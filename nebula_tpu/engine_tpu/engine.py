"""TpuGraphEngine: the device-side query hot path.

The opt-in per-space TPU storage engine (BASELINE.json north star): GO
multi-hop expansion and FIND SHORTEST PATH run as compiled XLA programs
over CSR snapshots instead of per-hop storage RPCs. The query engine
consults `can_serve` per statement — anything unsupported falls back to
the CPU scatter/gather path, and materialized results flow through the
exact same yield-evaluation machinery (`_emit_go_rows`) so result sets
are identical by construction wherever both paths can serve.

Snapshot lifecycle: built lazily from the KV store on first use, keyed
to the engine's write_version + catalog version. Committed writes no
longer rebuild: the engine pulls the storage-side change feed
(kvstore/changelog.py) and PATCHES the live snapshot — delta adds into
an ELL buffer the hop kernel unions with the base CSR, deletes as
device tombstone point-updates, prop updates into the host mirrors
(delta.py; SURVEY.md §7 hard-part (a), §2.10 P6). When the delta fills,
a background repack folds it into a fresh base while queries keep
serving; a failed apply poisons the snapshot so CPU fallback serves
until the repack swaps in.

Freshness model (remote topology): the token rides a push-fed watch
cache, not per-query probes. Writes through THIS graphd are strictly
read-your-writes (the client's local write seq is part of the token);
writes through ANOTHER graphd become visible within one watch push
(~50-150ms) — the same staleness class as the reference's 1s cached
topology pull (MetaClient.cpp:120-193). A local write currently
invalidates twice (seq bump now, version push later); cheap once
invalidation is a delta apply instead of a rebuild.
"""
from __future__ import annotations

import atexit
import logging
import threading
import time
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common.cache import (CacheRung, plan_stage_enabled,
                            result_stage_enabled)
from ..common import consistency as _consistency
from ..common import heat as _heat
from ..common import ledger as _ledger
from ..common.faults import CircuitBreaker, faults
from ..common import profiler as _profiler
from ..common.flight import recorder as _flight
from ..common.flags import graph_flags
from ..common.qos import LANE_BULK, LANE_INTERACTIVE, OverloadShed
from ..common.stats import stats as global_stats
from ..common.threads import traced_thread
from ..common.tracing import tracer as _tr
from ..common import writepath as _writepath
from ..common.status import ErrorCode, Status, StatusOr
from ..filter.expressions import (Expression, InputPropExpr,
                                  VariablePropExpr, encode_expression)
from ..parser import ast
from ..storage.types import BoundResponse, EdgeData, PartResult, VertexData
from . import fused, materialize, traverse
from .csr import CsrSnapshot
from .filter_compile import FilterCompiler

_LOG = logging.getLogger("nebula_tpu.engine_tpu")

# daemon prewarm threads issue XLA compiles; the interpreter killing
# one mid-compile during finalization segfaults the process. atexit
# runs BEFORE daemon threads are reaped: stop new compile launches and
# join the stragglers (bounded) while the runtime is still whole.
_PREWARM_SHUTDOWN = threading.Event()


@atexit.register
def _drain_prewarm_threads() -> None:
    _PREWARM_SHUTDOWN.set()
    for t in threading.enumerate():
        if t.name.startswith("csr-prewarm-"):
            t.join(timeout=10.0)


DEFAULT_MAX_EDGES_PER_VERTEX = 10000


def _snap_bytes(snap) -> int:
    """Device bytes resident for a snapshot (0 when the walk declines)
    — the write-path lifecycle ledger's device-mem delta source."""
    try:
        return int(snap.device_mem().get("bytes", 0))
    except Exception:
        return 0


class _BudgetExceeded(Exception):
    """Pull-mode edge budget ran out: fall to the dense device path."""


class _GoReq:
    """One session's plain GO parked at the cross-session dispatcher.
    `done` flips exactly once (via _mark_done, under the dispatcher
    condition var), after `result` is written; the owning thread
    re-reads it under the same condition var. `claimed` means a group
    leader drained this request into its window — the owner waits for
    `done` instead of trying to lead. A device failure never carries
    an error back: `result` stays None and the owner re-serves on the
    CPU pipe (docs/manual/9-robustness.md). `dkey` is the statement's
    version-free identity for in-window dedupe (cache_mode=full):
    identical same-key requests inside one window collapse to a single
    lane and fan the rows out to every waiter; None = never deduped.
    `followers` (set by the window leader) are the collapsed twins —
    _mark_done clones this request's result into them BEFORE flipping
    its own `done`, the only point where the owner provably isn't yet
    finalizing/mutating the shared result."""
    __slots__ = ("ctx", "s", "starts", "edge_types", "alias_map",
                 "name_by_type", "key", "yield_cols", "result",
                 "done", "claimed", "t_enq", "tctx", "dkey",
                 "followers", "lane", "ledger")

    def __init__(self, ctx, s, starts, edge_types, alias_map,
                 name_by_type, key, yield_cols, dkey=None):
        self.ctx = ctx
        self.s = s
        self.starts = starts
        self.edge_types = edge_types
        self.alias_map = alias_map
        self.name_by_type = name_by_type
        self.key = key
        self.yield_cols = yield_cols
        self.result = None
        self.done = False
        self.claimed = False
        self.t_enq = 0.0
        self.dkey = dkey
        self.followers: Optional[List["_GoReq"]] = None
        # QoS lane ("interactive" | "bulk"): set at enqueue from the
        # ctx (graph-layer classification / overrides) or the engine's
        # own statement-shape fallback; drives weighted-fair round
        # selection and watermark shedding (docs/manual/14-qos.md)
        self.lane = LANE_INTERACTIVE
        # the owner's trace context (None unsampled): whoever serves
        # this request — its own thread or a group leader — records
        # spans into the OWNER's trace via tracer.use (tracing.py)
        self.tctx = None
        # the owner's cost ledger (None when accounting is off): the
        # serving thread charges the OWNER's ledger via ledger.use,
        # same discipline as tctx (common/ledger.py)
        self.ledger = None


def _uses_input_refs(exprs: List[Expression]) -> bool:
    for e in exprs:
        for node in e.walk():
            if isinstance(node, (InputPropExpr, VariablePropExpr)):
                return True
    return False


class TpuGraphEngine:
    def __init__(self, auto_refresh: bool = True, enabled: bool = True,
                 mesh=None):
        """mesh: optional jax.sharding.Mesh over the partition axis —
        snapshots whose part count divides the mesh get sharded kernels
        and traversals run distributed (all_to_all frontier exchange,
        ref role: StorageClient scatter/gather, StorageClient.inl:73-160).
        """
        self.auto_refresh = auto_refresh
        self.enabled = enabled
        self.mesh = mesh
        self._snapshots: Dict[int, CsrSnapshot] = {}
        self._provider = None
        self._sm = None
        self._meta = None
        # serializes snapshot lifecycle + host-mirror reads: delta
        # applies mutate shard mirrors in place, so queries and applies
        # must not interleave (rebuild swaps were immutable; deltas are
        # not). Contention-profiled (common/profiler.py): acquire
        # waits feed the nebula_lock_wait_us_engine_snapshot histogram
        # + the /profile?locks=1 table
        self._lock = _profiler.profiled_rlock("engine_snapshot")
        # write-path observatory: /snapshots + the flight "writepath"
        # collector read per-space lifecycle status via weak registry
        _writepath.register_engine(self)
        # tiny leaf lock for counters bumped OUTSIDE the engine lock
        # (pre-lock decline paths, off-lock window encode): dict-int
        # += is read-add-store and loses increments under thread
        # interleaving. Never held while acquiring any other lock.
        self._stats_lock = threading.Lock()
        self._repacking: Dict[int, bool] = {}
        self._prewarming: Dict[int, bool] = {}
        self._prewarm_threads: Dict[int, threading.Thread] = {}
        # cross-session dispatcher (group commit): concurrent plain GOs
        # queue here; one thread becomes leader PER (space, steps,
        # edge_types) GROUP and serves that group's window in one
        # batched device program. Groups are independent rounds:
        # `_disp_serving` maps each in-flight group key to its round
        # owner, so an unrelated slow group neither delays nor is
        # delayed by this one (group-complete scheduling), while
        # same-key arrivals still pile up behind the in-flight round
        # and coalesce into the next window (the group-commit batching
        # pressure). `MAX_CONCURRENT_ROUNDS` bounds device/queue
        # pressure from many distinct keys.
        # contention-profiled cv lock: waiter re-acquires after
        # notify_all are the dispatcher's real convoy signal
        # (nebula_lock_wait_us_dispatcher_cv)
        self._disp_cv = threading.Condition(
            _profiler.profiled_rlock("dispatcher_cv"))
        self._disp_queue: List["_GoReq"] = []
        self._disp_serving: Dict[Tuple, "_GoReq"] = {}
        # QoS priority lanes (docs/manual/14-qos.md): per-lane
        # in-flight round counts + weighted-fair virtual time — the
        # scheduler state _lane_may_lead_locked consults so bulk scans
        # cannot monopolize the MAX_CONCURRENT_ROUNDS slots. All
        # mutated under _disp_cv. Weights/cap are instance attrs so
        # benches and tests can tighten them.
        self.lane_weights = dict(self.LANE_WEIGHTS)
        self.bulk_max_rounds = self.BULK_MAX_ROUNDS
        self._lane_rounds = {LANE_INTERACTIVE: 0, LANE_BULK: 0}
        self._lane_vtime = {LANE_INTERACTIVE: 0.0, LANE_BULK: 0.0}
        # unclaimed queued requests per lane (enqueue +1, claim/balk
        # -1): the O(1) early-out for _eligible_waiter_locked — the
        # common no-cross-lane-contention case must not pay an
        # O(queue) scan inside the cv wait predicate
        self._lane_queued = {LANE_INTERACTIVE: 0, LANE_BULK: 0}
        # recent group waits (ms) feeding the shed watermark's p95 —
        # bounded sample window appended under _disp_cv in _mark_done
        from collections import deque
        self._wait_samples = deque(maxlen=self.WAIT_SAMPLE_WINDOW)
        # per-reason / per-space shed tallies (the /tpu_stats qos
        # block's per-tenant slices); bumped under _stats_lock
        self.qos_shed_reasons: Dict[str, int] = {}
        self.qos_shed_by_space: Dict[int, int] = {}
        # pull-mode budget: frontiers whose cumulative edge visits stay
        # under this run on host mirrors; larger ones amortize the dense
        # device dispatch (direction-optimized execution). The engine-
        # wide value is a PRE-CALIBRATION placeholder only (a modeled
        # v5e/SNB estimate): every served space gets a measured
        # per-space fit from calibrate_sparse_budget(), run
        # automatically by the prewarm hook on first USE (round-4
        # verdict item 4 — production engines used to keep this
        # default, 48x off the measured crossover). EXPLICIT assignment
        # to `sparse_edge_budget` pins routing (tests/operators) and
        # disables auto-calibration — see the property below.
        self._sparse_edge_budget = 1 << 22
        self._budget_pinned = False
        self._space_budgets: Dict[int, int] = {}
        # space -> calibration record (exposed via /get_stats as
        # tpu_engine.sparse_budget_fit samples)
        self.sparse_budget_calibrations: Dict[int, Dict[str, Any]] = {}
        # space -> measured lane-vs-vmapped batched-kernel pick (the
        # sparse-budget discipline applied to kernel CHOICE: the
        # lane-matrix layout is TPU-optimal, but fallback backends can
        # execute the vmapped variant several times faster — route
        # windows by measurement, once per snapshot)
        self.batched_kernel_calibrations: Dict[int, Dict[str, Any]] = {}
        self.stats = {"go_served": 0, "path_served": 0, "rebuilds": 0,
                      "fallbacks": 0, "sharded_queries": 0,
                      "fast_materialize": 0, "slow_materialize": 0,
                      "delta_applies": 0, "delta_edges": 0,
                      "bg_repacks": 0, "sparse_served": 0,
                      "host_filter_vectorized": 0, "repack_failures": 0,
                      "agg_served": 0, "agg_sparse_served": 0,
                      "agg_declined": 0, "batched_dispatches": 0,
                      "batched_queries": 0, "batched_max_window": 0,
                      "batched_lane_rounds": 0,
                      # dispatcher window lifecycle (docs/manual/
                      # 7-dispatcher.md): per-group rounds, early
                      # waiter releases, cross-group leader handoffs,
                      # and the native batch row-encode counters
                      "disp_rounds": 0, "disp_group_keys": 0,
                      "early_releases": 0, "leader_handoffs": 0,
                      "native_encode_rows": 0, "encode_fallback_rows": 0,
                      "group_wait_us_total": 0, "group_wait_count": 0,
                      "group_wait_us_max": 0, "path_declined": 0,
                      "budget_recalibrations": 0,
                      # degradation ladder (docs/manual/9-robustness.md):
                      # breaker lifecycle, queries sent to the CPU pipe
                      # because a breaker was open or a device serve
                      # failed, per-query deadline-budget bailouts,
                      # poisoned snapshots, mesh -> single-device
                      # demotions
                      "breaker_trips": 0, "breaker_recoveries": 0,
                      "degraded_serves": 0, "deadline_exceeded": 0,
                      "snapshot_poisoned": 0, "mesh_demotions": 0,
                      # in-window request dedupe (cache_mode=full;
                      # docs/manual/11-caching.md): requests that rode
                      # a twin's lane instead of their own, and windows
                      # where at least one collapse happened
                      "dedup_collapsed": 0, "dedup_rounds": 0,
                      # device-resident fused serve loop (fused.py;
                      # docs/manual/13-device-speed.md): launches of
                      # the fused window/aggregate programs, and
                      # windows that mixed more distinct compiled
                      # WHERE masks than one program fuses
                      "fused_launches": 0, "fused_declined": 0,
                      # multi-tenant QoS (docs/manual/14-qos.md):
                      # rounds granted per priority lane, and admitted
                      # work shed at a watermark (typed E_OVERLOAD)
                      # before it could queue toward its deadline
                      "lane_rounds_interactive": 0,
                      "lane_rounds_bulk": 0, "qos_shed": 0,
                      # cluster scatter/gather v2 (cluster.py;
                      # docs/manual/13-device-speed.md): GO windows
                      # served from per-storaged device partials
                      "cluster_served": 0, "cluster_declined": 0,
                      "cluster_hops": 0, "cluster_fallback_parts": 0,
                      # device-resident secondary indexes (index.py;
                      # docs/manual/16-indexes.md): per-snapshot sorted
                      # property arrays serving LOOKUP, plus the
                      # GET SUBGRAPH frontier-expansion verb
                      "index_builds": 0, "index_bytes": 0,
                      "index_searches": 0, "index_hits": 0,
                      "index_declined": 0, "index_invalidations": 0,
                      "lookup_served": 0, "subgraph_served": 0}
        # mesh execution service (mesh_exec.py): device-served queries
        # on SHARDED snapshots, per feature — the decline matrix the
        # round-5 verdict flagged (batched windows / aggregation / ALL
        # paths used to switch off exactly when the mesh showed up).
        # mesh_decline_reasons nests {feature: {reason: count}};
        # both surface in /tpu_stats ("mesh") and /get_stats as
        # tpu_engine.mesh_served.<feature> / mesh_declined.<f>.<r>.
        self.mesh_served: Dict[str, int] = {}
        self.mesh_decline_reasons: Dict[str, Dict[str, int]] = {}
        # why device path serving declined before lock/snapshot, by
        # reason (mirrors agg_decline_reasons; /tpu_stats + /get_stats
        # tpu_engine.path_declined.<reason>)
        self.path_decline_reasons: Dict[str, int] = {}
        # why a device index serve (LOOKUP / GET SUBGRAPH) declined,
        # by reason (/tpu_stats "index" block + /get_stats
        # tpu_engine.index.declined.<reason>)
        self.index_decline_reasons: Dict[str, int] = {}
        # why aggregate pushdown declined, by reason (round-4 verdict:
        # the decline path was invisible — 0/3 bench queries served
        # with no stat saying why); mirrored into the global stats
        # manager as tpu_engine.agg_declined.<reason> for /get_stats
        self.agg_decline_reasons: Dict[str, int] = {}
        # space -> (consecutive failures, earliest next attempt): a
        # persistently failing background repack backs off instead of
        # spinning, and every failure is logged + counted
        self._repack_backoff: Dict[int, Tuple[int, float]] = {}
        # sparse-budget staleness (VERDICT weak #5): per-space snapshot
        # churn (rebuilds + delta applies) since process start; a
        # budget fitted BUDGET_RECAL_CHURN versions ago re-fits in the
        # background (honoring the explicit pin lock)
        self._space_churn: Dict[int, int] = {}
        self._recalibrating: set = set()
        # degradation ladder (docs/manual/9-robustness.md): one
        # circuit breaker per device feature ("go" / "agg" / "path" /
        # "mesh"); N consecutive device failures trip the feature to
        # CPU fallback, exponential-backoff half-open probes re-admit
        # it, and a tripped MESH breaker first demotes the space to
        # single-device serving before CPU. Threshold/backoff are
        # instance attrs so chaos harnesses can tighten them.
        self._breakers: Dict[str, CircuitBreaker] = {}
        self.breaker_threshold = 3
        self.breaker_base_s = 0.5
        self.breaker_max_s = 30.0
        # spaces demoted off the mesh (mesh breaker tripped):
        # _build_fresh skips sharding for them until a half-open probe
        # re-admits the mesh (see _mesh_failed / _snapshot_locked)
        self._mesh_demoted: set = set()
        # per-query device-path deadline; None -> the
        # tpu_query_deadline_ms graphd flag
        self.query_deadline_ms: Optional[int] = None
        # per-query stage breakdown of the LAST device-served query
        # (snapshot check / kernel / materialize — ref role: per-stage
        # latency in responses, ExecutionPlan.cpp:57) + a serial so the
        # query layer knows whether a given query was the one served
        self.last_profile: Optional[Dict[str, Any]] = None
        self.profile_seq = 0
        self._tracing = False
        # snapshot-versioned cache rungs (common/cache.py; docs/manual/
        # 11-caching.md; cache_mode=full). Result keys embed the
        # provider's freshness token + the catalog version, so a write
        # or schema change makes old entries structurally unreachable —
        # and a cache hit is served BEFORE the breaker gate (an open
        # breaker degrades to a warm cache, not straight to the CPU
        # pipe). Negative rung: structural decline decisions (agg
        # pre-checks / path routing) keyed by catalog version.
        self.result_cache = CacheRung(
            "tpu_engine.cache.result", 512,
            stats_prefix="tpu_engine.cache.result")
        self.negative_cache = CacheRung(
            "tpu_engine.cache.negative", 256,
            stats_prefix="tpu_engine.cache.negative")
        # per-snapshot compiled-filter-plan rung counters (the plans
        # themselves live on each snapshot — see _plan_filter); bumped
        # under the engine lock, every _plan_filter caller holds it
        self.filter_plan_counters = {"hits": 0, "misses": 0,
                                     "evictions": 0, "invalidations": 0}
        # fused-program registry (fused.py; docs/manual/13-device-
        # speed.md): per-snapshot program dicts live on each snapshot
        # (_fused_entry), these are the engine-lifetime counters — the
        # signature set is the recompile-bound contract the tier-1
        # guard asserts (tests/test_fused.py)
        self._fused_counters = {"hits": 0, "misses": 0}
        self._fused_signatures: set = set()
        # guards the per-snapshot program dicts: the off-lock
        # calibration probe and a launching leader can resolve the
        # same signature concurrently
        self._fused_reg_lock = threading.Lock()
        # two-slot donated-buffer H2D staging for window frontier
        # stacks (double-buffering: window N+1's transfer overlaps
        # window N's kernel)
        self.frontier_pool = fused.FrontierPool()
        # cluster scatter/gather v2 (cluster.py): lazily built when
        # the provider is remote and cluster_device_serve is on
        self._cluster = None

    # results bigger than this never enter the result cache (a handful
    # of supernode answers must not evict the whole working set)
    RESULT_CACHE_MAX_ROWS = 100_000

    def cache_stats(self) -> Dict[str, Any]:
        """The /tpu_stats "cache" block: per-rung counters + the live
        cache_mode (docs/manual/11-caching.md)."""
        from ..common.cache import mode_of
        with self._stats_lock:
            dedupe = {"collapsed": self.stats["dedup_collapsed"],
                      "rounds": self.stats["dedup_rounds"]}
        return {"mode": mode_of(graph_flags),
                "result": self.result_cache.stats(),
                "negative": self.negative_cache.stats(),
                "filter_plan": dict(self.filter_plan_counters),
                "dedupe": dedupe}

    # ------------------------------------------------------------------
    # fused device programs (fused.py; docs/manual/13-device-speed.md)
    # ------------------------------------------------------------------
    def _fused_entry(self, snap, sig: Tuple, make):
        """One fused program per (snapshot, signature): the per-
        snapshot dict next to the PR 5 compiled-filter rung binds the
        layout statics once; the signature set + hit/miss counters
        make recompile behavior observable (`fused_programs` in
        /tpu_stats). Thread-safe on its own (`_fused_reg_lock`) — the
        calibration probe resolves entries OFF the engine lock while
        leaders resolve them inside the launch phase; make() only
        binds statics (jit compiles at call time), so holding the
        registry lock across it is cheap."""
        with self._fused_reg_lock:
            reg = getattr(snap, "_fused_programs", None)
            if reg is None:
                reg = snap._fused_programs = {}
            fn = reg.get(sig)
            miss = fn is None
            if miss:
                # XLA compile accounting (common/profiler.py): the
                # FIRST launch of a fresh signature pays trace +
                # compile — timed into the tpu_engine.compile_us
                # histogram and the /profile?compiles=1 table
                fn = reg[sig] = _profiler.compiles.timed_first_call(
                    make(), str(sig))
        with self._stats_lock:
            if miss:
                self._fused_counters["misses"] += 1
                self._fused_signatures.add(sig)
            else:
                self._fused_counters["hits"] += 1
        if miss:
            global_stats.add_value("tpu_engine.fused.misses",
                                   kind="counter")
            # a compile is a latency cliff worth remembering: the ring
            # shows whether a p99 burn lined up with a signature miss
            _flight.record("fused_compile", signature=str(sig))
        return fn

    def fused_stats(self) -> Dict[str, Any]:
        """The /tpu_stats "fused_programs" block: program-registry
        hits/misses, the distinct-signature gauge (the recompile-bound
        contract), the REAL XLA compile-cache entry count across the
        fused entry points, and fused launches."""
        with self._stats_lock:
            out: Dict[str, Any] = dict(self._fused_counters)
            out["launches"] = self.stats["fused_launches"]
            out["declined"] = self.stats["fused_declined"]
        out["signatures"] = len(self._fused_signatures)
        out["xla_cache_entries"] = fused.compile_cache_size()
        return out

    def prefetch_stats(self) -> Dict[str, int]:
        """The /tpu_stats "frontier_prefetch" block: H2D stages,
        prefetch hits/misses, kernel-overlapped transfers + the wall
        time they had to hide, and donation fallbacks."""
        return self.frontier_pool.snapshot()

    def device_mem_stats(self) -> Dict[str, Any]:
        """The per-snapshot device-memory ledger (docs/manual/
        10-observability.md, "Continuous profiling"): live CSR bytes
        by dtype width per served space, plus the FrontierPool's
        cumulative staged frontier bytes — the MEASURED companion of
        bench's modeled tier1_hbm_model, scraped as
        tpu_engine.device_mem.* gauges."""
        spaces: Dict[str, Dict[str, int]] = {}
        total = 0
        by_width: Dict[str, int] = {}
        with self._lock:
            snaps = dict(self._snapshots)
        for space_id, snap in snaps.items():
            try:
                mem = snap.device_mem()
            except Exception:
                continue     # a snapshot mid-poison must not 500 /profile
            spaces[str(space_id)] = mem
            total += mem.get("bytes", 0)
            for k, v in mem.items():
                if k.startswith("bytes."):
                    w = k[len("bytes."):]
                    by_width[w] = by_width.get(w, 0) + v
        return {"snapshots": len(spaces), "bytes": total,
                "frontier_h2d_bytes":
                    self.frontier_pool.snapshot()["h2d_bytes"],
                "by_width": by_width, "spaces": spaces}

    @property
    def sparse_edge_budget(self) -> int:
        """Engine-wide pull-vs-push crossover (pre-calibration
        fallback; per-space fits in `_space_budgets` take precedence).
        SETTING it is an explicit routing pin: per-space fits are
        dropped and prewarm's auto-calibration stops, so a test or
        operator that forces the dense (0) or sparse (huge) path keeps
        that routing."""
        return self._sparse_edge_budget

    @sparse_edge_budget.setter
    def sparse_edge_budget(self, v: int) -> None:
        # under the engine lock so a pin can't interleave with an
        # auto-calibration install (calibrate_sparse_budget checks
        # _budget_pinned and installs under the same lock): an
        # explicit pin always wins, whatever the ordering
        with self._lock:
            self._sparse_edge_budget = int(v)
            self._budget_pinned = True
            self._space_budgets.clear()

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def _record_profile(self, mode: str, t_snap: float, t_kernel: float,
                        t_mat: float, snap=None) -> None:
        self.last_profile = {
            "mode": mode,
            "snapshot_us": int(t_snap * 1e6),
            "kernel_us": int(t_kernel * 1e6),
            "materialize_us": int(t_mat * 1e6),
            "delta_edges": (snap.delta.edge_count
                            if snap is not None and snap.delta else 0),
        }
        self.profile_seq += 1
        # every device-served query ends here with its stage timings —
        # the one hook that turns them into trace spans (backdated;
        # no-ops when the query is unsampled) and into the native
        # stage histograms (exemplars carry the live trace id, so a
        # bad bucket on /metrics links straight to a span tree)
        global_stats.add_value("tpu_engine.kernel_us",
                               t_kernel * 1e6, kind="histogram")
        global_stats.add_value("tpu_engine.materialize_us",
                               t_mat * 1e6, kind="histogram")
        # cost ledger: device compute attributed to the query being
        # served (the caller re-points the ledger ContextVar at the
        # owner for window requests, like the trace context). Sparse
        # modes are host pulls — no device launch to charge.
        led = _ledger.current()
        if led is not None and "sparse" not in mode:
            led.device_us += int(t_kernel * 1e6)
            led.launches += 1
        if "sparse" not in mode:
            # per-part heat: device time attributed to the parts the
            # serving query's start vids noted at the engine entry
            # (common/heat.py — coalesced-window riders land on the
            # leader's parts, the ledger's attributed-time discipline)
            _heat.charge_device(t_kernel * 1e6)
        if _tr.active():
            _tr.tag_root("mode", mode)
            _tr.add_span("snapshot", t_snap * 1e6)
            _tr.add_span("kernel", t_kernel * 1e6, mode=mode)
            _tr.add_span("materialize", t_mat * 1e6)

    def start_trace(self, trace_dir: str) -> bool:
        """Opt-in XLA/JAX profiler trace of the device path; view with
        TensorBoard or xprof. One trace at a time — returns False (and
        keeps the active trace) when one is already running."""
        import jax
        with self._lock:
            if self._tracing:
                return False
            jax.profiler.start_trace(trace_dir)
            self._tracing = True
            return True

    def stop_trace(self) -> bool:
        import jax
        with self._lock:
            if not self._tracing:
                return False
            self._tracing = False   # never wedge: cleared even on error
            jax.profiler.stop_trace()
            return True

    # ------------------------------------------------------------------
    def attach(self, cluster) -> None:
        from .provider import LocalStoreProvider
        self._provider = LocalStoreProvider(cluster.store, cluster.sm)
        self._sm = cluster.sm
        self._meta = cluster.meta
        _consistency.register_audit(self.audit_snapshots)

    def attach_raw(self, store, sm, meta=None) -> None:
        from .provider import LocalStoreProvider
        self._provider = LocalStoreProvider(store, sm)
        self._sm = sm
        self._meta = meta
        _consistency.register_audit(self.audit_snapshots)

    def attach_provider(self, provider, sm, meta=None) -> None:
        """Arbitrary snapshot feed — the RemoteStorageProvider path for
        the real 3-daemon topology (graphd --tpu)."""
        self._provider = provider
        self._sm = sm
        self._meta = meta
        _consistency.register_audit(self.audit_snapshots)

    # ------------------------------------------------------------------
    # device-snapshot audit (consistency observatory; docs/manual/
    # 10-observability.md "Consistency observatory")
    # ------------------------------------------------------------------
    def _record_store_digest(self, snap) -> None:
        """Record the store digest this snapshot's content came from
        (build or delta apply). Only recorded when the provider can
        name a digest at EXACTLY the snapshot's version — anything
        else leaves None and the auditor skips (counted), never
        guesses."""
        snap.store_digest = None
        fn = getattr(self._provider, "store_digest", None)
        if fn is None or not _consistency.enabled():
            return
        try:
            d = fn(snap.space_id)
        except Exception:
            return
        if d is not None and d[1] == snap.write_version:
            snap.store_digest = d[0]

    def audit_snapshots(self) -> Dict[str, Any]:
        """Cross-check every live snapshot's lineage digest against
        the CURRENT engine digest: when the version token says nothing
        changed, the content digest must agree — a mismatch is the
        delta-overrun / silent-store-mutation class (flight event
        ``snapshot_audit_mismatch``, rides the replica_divergence
        trigger). Cheap (per space: one version read + a fold over
        part digests); runs on the consistency audit cadence and on
        demand (/consistency?audit=1)."""
        out = {"checked": 0, "mismatches": 0, "skipped": 0}
        if self._provider is None or not _consistency.enabled():
            return out
        fn = getattr(self._provider, "store_digest", None)
        with self._lock:
            snaps = list(self._snapshots.items())
        for space_id, snap in snaps:
            recorded = getattr(snap, "store_digest", None)
            if fn is None or recorded is None or snap.stale:
                out["skipped"] += 1
                continue
            try:
                cur = fn(space_id)
            except Exception:
                cur = None
            if cur is None or cur[1] != snap.write_version:
                # writes in flight / version moved: a rebuild or delta
                # apply is the judge, not this round
                out["skipped"] += 1
                continue
            out["checked"] += 1
            global_stats.add_value("consistency.audit_checks",
                                   kind="counter")
            if cur[0] != recorded:
                out["mismatches"] += 1
                global_stats.add_value("consistency.audit_mismatch",
                                       kind="counter")
                _flight.record(
                    "snapshot_audit_mismatch", space=space_id,
                    version=str(snap.write_version),
                    recorded=_consistency.hex_digest(recorded),
                    engine=_consistency.hex_digest(cur[0]))
        self._audit_last = {**out, "ts": time.time()}
        return out

    def audit_state(self) -> Dict[str, Any]:
        """The graphd /consistency audit block: last audit outcome +
        per-space snapshot lineage."""
        with self._lock:
            snaps = {
                str(sid): {
                    "write_version": str(snap.write_version),
                    "store_digest": _consistency.hex_digest(
                        getattr(snap, "store_digest", None)),
                    "stale": bool(snap.stale),
                }
                for sid, snap in self._snapshots.items()}
        return {"last": getattr(self, "_audit_last", None),
                "snapshots": snaps}

    def snapshots_status(self) -> Dict[str, Any]:
        """Per-space live snapshot status for the write-path
        observatory's /snapshots body (common/writepath.py): version,
        staleness, delta occupancy, repack-in-flight and approximate
        device residency — the instantaneous complement of the
        lifecycle ledger's event history."""
        with self._lock:
            spaces = {}
            for sid, snap in self._snapshots.items():
                d = snap.delta
                spaces[str(sid)] = {
                    "write_version": str(snap.write_version),
                    "stale": bool(snap.stale),
                    "sharded": getattr(snap, "sharded_kernel",
                                       None) is not None,
                    "delta_edges": 0 if d is None else d.edge_count,
                    "delta_tombs": 0 if d is None else d.tomb_count,
                    "device_bytes": _snap_bytes(snap),
                    "repacking": bool(self._repacking.get(sid)),
                }
        with self._stats_lock:
            counters = {k: self.stats[k] for k in
                        ("rebuilds", "bg_repacks", "delta_applies",
                         "snapshot_poisoned", "repack_failures")}
        return {"spaces": spaces, "counters": counters}

    # ------------------------------------------------------------------
    # snapshot lifecycle
    # ------------------------------------------------------------------
    def _catalog_version(self) -> int:
        v = getattr(self._meta, "catalog_version", 0) if self._meta else 0
        return v() if callable(v) else v

    def refresh(self, space_id: int) -> Optional[CsrSnapshot]:
        # Serve-path callers hold the engine lock. A REPLACEMENT
        # refresh (the space already has a snapshot: failover,
        # incompatible token) must FAIL FAST — retry sleeps
        # (storage-client KV backoff, transport reconnect pacing) are
        # suppressed for this context, the miss degrades to the old
        # snapshot/CPU pipe, and a background repack (own pacing,
        # off-lock) converges. The lock-order witness caught the
        # un-suppressed form blocking every query on the engine lock
        # for the backoff duration during `bench --cluster` failover
        # (docs/manual/15-static-analysis.md). FIRST-TOUCH keeps the
        # historical paced build only on a LOCAL provider: the space
        # cannot device-serve until the snapshot exists, so blocking
        # its first query through the transient (topology watch lag
        # on a fresh space) is the better trade. A cluster-capable
        # REMOTE provider inverts that trade — queries device-serve
        # via per-storaged partials (cluster.py) with no local
        # snapshot at all, so the first local build typically happens
        # mid-failover (the cluster path just declined) and pacing
        # its scan retries would block every query on the engine lock
        # through an election (lock-witness finding during
        # `bench --partition` nemesis phases).
        from ..common.faults import no_retry_sleep
        replacement = self._snapshots.get(space_id) is not None
        remote = getattr(self._provider, "_client", None) is not None
        fail_fast = replacement or remote
        token = no_retry_sleep.set(True) if fail_fast else None
        t0 = time.perf_counter()
        try:
            snap = self._build_fresh(space_id)
        finally:
            if token is not None:
                no_retry_sleep.reset(token)
        if snap is None:
            if fail_fast:
                # converge off-lock: the repack ladder retries with its
                # own backoff while queries keep the previous snapshot
                # (or, remote, the cluster/CPU ladder)
                self._kick_repack(space_id, cause="refresh_failed")
            return None
        old = self._snapshots.get(space_id)
        self._snapshots[space_id] = snap
        self.stats["rebuilds"] += 1
        self._space_churn[space_id] = \
            self._space_churn.get(space_id, 0) + 1
        # lifecycle ledger + watermark: a fresh build makes every write
        # at or below its capture token device-visible (runs under the
        # engine lock — counter-class records only, the read_fence
        # precedent; no spans here)
        build_us = int((time.perf_counter() - t0) * 1e6)
        _writepath.snapshots.note(
            space_id, "build", dur_us=build_us,
            cause="replace" if replacement else "first_touch",
            device_bytes=_snap_bytes(snap),
            device_bytes_delta=_snap_bytes(snap) - (
                _snap_bytes(old) if old is not None else 0))
        _writepath.watermark.note_visible(
            space_id, getattr(snap, "delta_cursor", None), cause="build")
        self._maybe_recalibrate(space_id, snap)
        return snap

    # snapshot versions a budget fit survives before it re-fits: the
    # walk rate and dense dispatch cost both move with graph shape, so
    # a budget calibrated against version K is a modeled constant again
    # by version K+N (VERDICT round-5 weak #5)
    BUDGET_RECAL_CHURN = 8

    def _maybe_recalibrate(self, space_id: int, snap
                           ) -> Optional[threading.Thread]:
        """Drop + background-refit a sparse-budget calibration whose
        space has churned BUDGET_RECAL_CHURN snapshot versions
        (rebuilds + delta applies) since the fit. Counted
        (`budget_recalibrations`, /tpu_stats + /get_stats); an
        explicitly pinned budget is never touched (the pin lock from
        PR 1 — calibrate_sparse_budget re-checks under the engine
        lock, so a pin landing mid-refit still wins). Returns the
        refit thread for tests; None when nothing is stale."""
        if self._budget_pinned or self._provider is None:
            return None
        rec = self.sparse_budget_calibrations.get(space_id)
        if rec is None or space_id in self._recalibrating:
            return None
        churn = self._space_churn.get(space_id, 0)
        if churn - rec.get("churn_at_fit", 0) < self.BUDGET_RECAL_CHURN:
            return None
        self.stats["budget_recalibrations"] += 1
        global_stats.add_value("tpu_engine.budget_recalibrations", kind="counter")
        # the stale record stays installed until the refit OVERWRITES
        # it: popping first would make one failed/empty refit disable
        # recalibration for the space forever (rec is None above), and
        # would blank the /tpu_stats fit record meanwhile
        self._recalibrating.add(space_id)

        def run():
            try:
                # roots/etypes scans are O(E log E) numpy over the host
                # mirrors — computed HERE, never in the caller's thread
                # (refresh/delta-apply callers hold the engine lock on
                # the query path). Mirrors of the captured snapshot
                # object are safe to scan off-lock: delta applies never
                # touch sharded snapshots, and an unsharded apply
                # racing this probe only skews the measured rate
                roots = _calibration_roots(snap)
                etypes = sorted({int(t) for s in snap.shards
                                 for t in np.unique(s.edge_etype)
                                 if t > 0}) or [1]
                if roots:
                    self.calibrate_sparse_budget(
                        space_id, roots,
                        etypes[:traverse.MAX_EDGE_TYPES_PER_QUERY],
                        auto=True, _snap=snap)
            except Exception:
                _LOG.exception("budget recalibration of space %d "
                               "failed", space_id)
            finally:
                # a successful refit stamped a fresh churn_at_fit; a
                # FAILED/empty one advances the anchor on the stale
                # record instead, so the next attempt waits another
                # BUDGET_RECAL_CHURN versions (natural backoff) rather
                # than re-scanning the graph on every write batch
                with self._lock:
                    rec2 = self.sparse_budget_calibrations.get(space_id)
                    if rec2 is not None:
                        rec2.setdefault("churn_at_fit", 0)
                        if rec2["churn_at_fit"] < \
                                self._space_churn.get(space_id, 0):
                            rec2["churn_at_fit"] = \
                                self._space_churn.get(space_id, 0)
                    self._recalibrating.discard(space_id)

        # nlint: disable=NL002 -- shared background refit outlives any
        # one request; adopting a caller's trace would pin a dead trace
        t = threading.Thread(target=run, daemon=True,
                             name=f"csr-recal-{space_id}")
        t.start()
        return t

    # ------------------------------------------------------------------
    # mesh serving counters (mesh_exec.py; satellite of ISSUE 2)
    # ------------------------------------------------------------------
    def _mesh_served(self, feature: str, n: int = 1) -> None:
        """Count device-served queries on a SHARDED snapshot, per
        feature (go_batched / agg / path_all). May run off the engine
        lock, hence the stats leaf lock."""
        with self._stats_lock:
            self.mesh_served[feature] = \
                self.mesh_served.get(feature, 0) + n
        global_stats.add_value("tpu_engine.mesh_served." + feature,
                               kind="counter")
        # a successful meshed serve is the mesh breaker's probe
        # success: a half-open mesh closes and stays re-admitted
        self._device_ok("mesh")

    def _mesh_decline(self, feature: str, reason: str) -> None:
        """Count one meshed-serving decline by (feature, reason) — the
        decline matrix in docs/manual/8-mesh.md stays observable."""
        with self._stats_lock:
            d = self.mesh_decline_reasons.setdefault(feature, {})
            d[reason] = d.get(reason, 0) + 1
        global_stats.add_value(
            f"tpu_engine.mesh_declined.{feature}.{reason}",
            kind="counter")

    # ------------------------------------------------------------------
    # degradation ladder: per-feature circuit breakers + deadline
    # budget (docs/manual/9-robustness.md)
    # ------------------------------------------------------------------
    def _breaker(self, feature: str) -> CircuitBreaker:
        b = self._breakers.get(feature)
        if b is None:
            with self._stats_lock:
                b = self._breakers.get(feature)
                if b is None:
                    b = CircuitBreaker(self.breaker_threshold,
                                       self.breaker_base_s,
                                       self.breaker_max_s)
                    self._breakers[feature] = b
        return b

    def _device_admit(self, feature: str, ctx=None) -> bool:
        """Ladder gate at the top of every device entry point: an OPEN
        breaker sends the query straight to the CPU pipe (counted in
        `degraded_serves`); an admitted query gets its deadline budget
        stamped on the ctx (threaded through dispatcher wait + kernel
        + materialize via _deadline_exceeded)."""
        if not self._breaker(feature).allow():
            with self._stats_lock:
                self.stats["degraded_serves"] += 1
            global_stats.add_value("tpu_engine.degraded_serves."
                                   + feature, kind="counter")
            # every open-breaker degrade is a flight event: the armed
            # aftermath after a trip would otherwise be silent (the
            # degraded queries carry their trace ids here — the ring
            # shows WHO served on the CPU pipe while the device was
            # fenced, and the ids join the histogram exemplars)
            _flight.record("breaker_open_serve", feature=feature)
            _tr.tag_root("degraded", "breaker_open:" + feature)
            return False
        if ctx is not None:
            ms = self.query_deadline_ms
            if ms is None:
                ms = graph_flags.get("tpu_query_deadline_ms", 0) or 0
            ctx._tpu_deadline = (time.monotonic() + ms / 1e3) \
                if ms else None
        return True

    def _device_ok(self, feature: str) -> None:
        b = self._breaker(feature)
        r0 = b.recoveries
        b.record_success()
        if b.recoveries != r0:
            with self._stats_lock:
                self.stats["breaker_recoveries"] += 1
            global_stats.add_value("tpu_engine.breaker_recoveries", kind="counter")
            _flight.record("breaker_recovered", feature=feature)
            _LOG.info("device path %r recovered: half-open probe "
                      "succeeded, breaker closed", feature)

    def _device_failed(self, feature: str, exc: Exception):
        """One device-path failure: counted against the feature's
        breaker; the query is NOT errored — callers return None so
        the CPU pipe re-serves it (failure isolation: the client
        never sees a device-infrastructure error). Returns None for
        `return self._device_failed(...)` convenience.

        Data-dependent evaluation errors are NOT infrastructure: the
        CPU pipe raises the identical error for the same query, so a
        client retrying one bad query must not trip the breaker and
        degrade every other session's traffic — the query still
        re-serves (and errors) on the CPU pipe, without breaker
        impact."""
        from ..filter.expressions import EvalError
        if isinstance(exc, EvalError):
            with self._stats_lock:
                self.stats["degraded_serves"] += 1
            return None
        tripped = self._breaker(feature).record_failure()
        if tripped:
            with self._stats_lock:
                self.stats["breaker_trips"] += 1
            global_stats.add_value("tpu_engine.breaker_trips",
                                   kind="counter")
            # the flight recorder's breaker_open trigger: a trip dumps
            # a bundle + arms aftermath sampling (common/flight.py)
            _flight.record("breaker_trip", feature=feature,
                           error=repr(exc))
        else:
            _flight.record("device_failure", feature=feature,
                           error=repr(exc))
        with self._stats_lock:
            self.stats["degraded_serves"] += 1
        global_stats.add_value("tpu_engine.device_failures." + feature,
                               kind="counter")
        # the degraded serve is visibly degraded in its own trace
        # (leaders serving a waiter's request are re-pointed at the
        # waiter's trace via tracer.use, so the tag lands correctly)
        _tr.tag_root("degraded", "cpu_retry:" + feature)
        if tripped:
            _tr.tag_root("breaker_tripped", feature)
        _LOG.warning(
            "device path %r failed, query retried on the CPU pipe%s: "
            "%r", feature,
            " (breaker tripped: CPU fallback until a half-open probe "
            "succeeds)" if tripped else "", exc)
        return None

    def _deadline_exceeded(self, ctx, where: str) -> bool:
        """Has this query's device-path budget run out? Checked at the
        phase seams (dispatcher claim, kernel launch, materialize);
        True sends the query to the CPU pipe and counts it."""
        dl = getattr(ctx, "_tpu_deadline", None)
        if dl is None or time.monotonic() < dl:
            return False
        with self._stats_lock:
            self.stats["deadline_exceeded"] += 1
        global_stats.add_value("tpu_engine.deadline_exceeded." + where,
                               kind="counter")
        _flight.record("deadline_balk", where=where)
        _tr.tag_root("degraded", "deadline:" + where)
        return True

    def _mesh_failed(self, feature: str, exc: Exception, snap) -> None:
        """Mesh rung of the ladder: a failed sharded collective counts
        against the "mesh" breaker; while the breaker is not closed
        the space DEMOTES to single-device serving — the sharded
        snapshot is poisoned and the background repack rebuilds it
        unsharded (_build_fresh skips sharding for demoted spaces).
        Half-open probes re-admit the mesh via _snapshot_locked."""
        self._mesh_decline(feature, "exec_error")
        _tr.tag_root("degraded", "mesh_failed:" + feature)
        b = self._breaker("mesh")
        tripped = b.record_failure()
        if tripped:
            with self._stats_lock:
                self.stats["breaker_trips"] += 1
            global_stats.add_value("tpu_engine.breaker_trips",
                                   kind="counter")
        _LOG.warning("meshed %s serve failed%s: %r", feature,
                     " (mesh breaker tripped)" if tripped else "", exc)
        if (tripped or b.state != CircuitBreaker.CLOSED) and \
                getattr(snap, "sharded_kernel", None) is not None:
            with self._lock:
                first = snap.space_id not in self._mesh_demoted
                self._mesh_demoted.add(snap.space_id)
                snap.stale = True
            self._purge_space_cache(snap.space_id)   # demotion poison
            if first:
                with self._stats_lock:
                    self.stats["mesh_demotions"] += 1
                global_stats.add_value("tpu_engine.mesh_demotions", kind="counter")
                _flight.record("mesh_demotion", space=snap.space_id,
                               feature=feature)
                _LOG.warning(
                    "space %d demoted to single-device serving "
                    "(unsharded rebuild kicked; half-open mesh probes "
                    "re-admit)", snap.space_id)
            self._kick_repack(snap.space_id, cause="mesh_demotion")

    def breaker_states(self) -> Dict[str, str]:
        with self._stats_lock:   # _breaker() inserts concurrently
            breakers = dict(self._breakers)
        return {f: b.state for f, b in breakers.items()}

    def robustness_stats(self) -> Dict[str, Any]:
        """The /tpu_stats "robustness" block (also embedded in the
        bench tier-2/3 JSON): ladder counters + live breaker states +
        injected-fault counts."""
        with self._stats_lock:
            keys = ("breaker_trips", "breaker_recoveries",
                    "degraded_serves", "deadline_exceeded",
                    "snapshot_poisoned", "mesh_demotions")
            out: Dict[str, Any] = {k: self.stats[k] for k in keys}
        out["breaker_state"] = self.breaker_states()
        out["faults_injected"] = faults.counts()
        return out

    def _build_fresh(self, space_id: int) -> Optional[CsrSnapshot]:
        """Build (but don't install) a fresh snapshot — lock-free, so
        the background repack can scan while queries keep serving.
        Spaces demoted off the mesh (mesh breaker) build UNSHARDED
        until a half-open probe re-admits them."""
        faults.fire("csr.build")
        catalog = self._catalog_version()
        snap = self._provider.build(space_id)
        if snap is None:
            return None
        snap.catalog_version = catalog
        # consistency observatory: remember the store digest this
        # build scanned, so the auditor can later prove the snapshot's
        # lineage still matches the engine at the same version
        self._record_store_digest(snap)
        # secondary indexes ride the same off-lock build: every
        # cataloged (tag, leading field) gets its sorted device array
        # now, so the first LOOKUP never pays the sort under the lock
        self._prebuild_indexes(space_id, snap)
        if (self.mesh is not None and self.mesh.devices.size > 1
                and snap.num_parts % self.mesh.devices.size == 0
                and space_id not in self._mesh_demoted):
            from .distributed import shard_snapshot_arrays
            shard_snapshot_arrays(self.mesh, snap)
        return snap

    def snapshot(self, space_id: int) -> Optional[CsrSnapshot]:
        if self._provider is None:
            return None
        with self._lock:
            return self._snapshot_locked(space_id)

    def prewarm(self, space_id: int, block: bool = False,
                _retry: bool = True) -> None:
        """Build the space's snapshot and compile the hot traversal
        kernels OFF the query path: on a fresh process the first dense
        dispatch pays ~20-40s of XLA compile, which would otherwise
        land on whoever runs the first big query. Fired on USE when
        the engine serves the space (no reference analogue — compile
        warmup is an accelerator concern). Idempotent; at most one
        warmup per space at a time."""
        if not (self.enabled and self._provider is not None):
            return

        def run():
            try:
                # a live fresh snapshot means kernels are already
                # compiled — skip straight to calibration (repeat USEs
                # used to rebuild a throwaway snapshot every time)
                snap = None
                with self._lock:
                    cur = self._snapshots.get(space_id)
                    if (cur is not None and not cur.stale
                            and cur.write_version ==
                            self._version_nosleep(space_id)
                            and getattr(cur, "catalog_version", -1) ==
                            self._catalog_version()):
                        snap = cur
                import jax.numpy as jnp
                if snap is None:
                    # build OFF TO THE SIDE (like the background
                    # repack) so a space that's still being bulk-loaded
                    # never gets a soon-stale snapshot installed under
                    # live queries
                    snap = self._build_fresh(space_id)
                if snap is None:
                    return
                if getattr(snap, "sharded_kernel", None) is not None:
                    # meshed kernels compile per-query shapes; the one
                    # warmable piece is the LIVE snapshot's per-device
                    # window layout (a private build would be dropped)
                    if snap is cur:
                        from . import mesh_exec
                        mesh_exec.ensure_sharded_aligned(self.mesh,
                                                         snap)
                    return
                etypes = sorted({int(t) for s in snap.shards
                                 for t in np.unique(s.edge_etype)
                                 if t > 0}) or [1]
                if _PREWARM_SHUTDOWN.is_set():
                    return
                if snap is not cur:
                    req = jnp.asarray(traverse.pad_edge_types(
                        etypes[:traverse.MAX_EDGE_TYPES_PER_QUERY]))
                    f0 = jnp.zeros((snap.num_parts, snap.cap_v), bool)
                    _, a = traverse.multi_hop(f0, jnp.int32(2),
                                              snap.kernel, req)
                    a.block_until_ready()
                    traverse.bfs_dist(f0, jnp.int32(2), snap.kernel,
                                      req).block_until_ready()
                    # batched lane-matrix layout for the dispatcher —
                    # built HERE (private snapshot, no lock needed)
                    # because the query path never pays the build —
                    # plus a compile of BOTH dispatcher bucket shapes
                    # of the FUSED window program (the entry the serve
                    # loop actually launches) at EVERY filter arity
                    # (unfiltered, nf=1, nf=MAX — filter_bucket admits
                    # no others), so production windows, filtered or
                    # not, never hit a cold XLA compile (20-40s on
                    # first chip contact) under the launch lock. On
                    # the host-CPU fallback backend a compile is
                    # ~100ms, not worth tripling the warmup: filtered
                    # variants compile on first use there
                    try:
                        import jax
                        nf_variants = (0,) \
                            if jax.default_backend() == "cpu" \
                            else (0, 1, fused.MAX_WINDOW_FILTERS)
                        snap.aligned_kernel()
                        al = snap.aligned_ready()
                        if al is not None:
                            ak_w, c_w, g_w = al
                            cap = self._dispatch_cap(snap)
                            for b in sorted({min(self.SMALL_BUCKET, cap),
                                             cap}):
                                for nf in nf_variants:
                                    if _PREWARM_SHUTDOWN.is_set():
                                        return
                                    fb = jnp.zeros(
                                        (b, snap.num_parts, snap.cap_v),
                                        bool)
                                    fm = None if nf == 0 else jnp.zeros(
                                        (nf, snap.num_parts, snap.cap_e),
                                        bool)
                                    fs = None if nf == 0 else jnp.full(
                                        (b,), -1, jnp.int32)
                                    fused.window_lane(
                                        fb, jnp.int32(2), ak_w,
                                        snap.kernel, req, fm, fs,
                                        chunk=c_w, group=g_w
                                    ).block_until_ready()
                    except Exception:
                        pass
                    # install only if still current and nothing else
                    # served the space meanwhile — otherwise the
                    # compile-cache warmup was the whole point and the
                    # build is dropped
                    with self._lock:
                        # never install an EMPTY snapshot: a space
                        # being USE'd right before a bulk load would
                        # get a zero-content snapshot whose later
                        # delta pull exceeds the change ring
                        # (poison -> background repack -> transient
                        # declines at first query); an empty install
                        # has no serving value anyway
                        if space_id not in self._snapshots and \
                                snap.total_edges > 0 and \
                                self._provider is not None and \
                                self._version_nosleep(space_id) == \
                                snap.write_version:
                            self._snapshots[space_id] = snap
                        else:
                            # a query installed its own snapshot while
                            # we built: GRAFT the aligned layout onto
                            # it only when both are PRISTINE builds of
                            # the same committed state (equal
                            # write_version, NO delta buffer on either
                            # side — any apply history, even vertex
                            # adds or tombstones with edge_count 0,
                            # can shift slot assignment vs a fresh
                            # scan and the layout's slot numbering
                            # would silently mismatch)
                            cur2 = self._snapshots.get(space_id)
                            if (cur2 is not None
                                    and snap._aligned is not None
                                    and cur2._aligned is None
                                    and cur2.delta is None
                                    and snap.delta is None
                                    and cur2.write_version ==
                                    snap.write_version):
                                cur2._aligned = snap._aligned
                elif snap._aligned is None and \
                        (snap.delta is None or
                         (snap.delta.edge_count == 0
                          and snap.delta.tomb_count == 0)):
                    # live snapshot lacks the layout: build OFF the
                    # engine lock from the mutable mirrors, then graft
                    # only if no delta apply raced the build (applies
                    # hold the lock and bump write_version after
                    # mutating, so an unchanged version proves the
                    # arrays were stable throughout)
                    with self._lock:
                        v0 = snap.write_version
                    try:
                        built = snap.build_aligned_off_side()
                    except Exception:
                        built = None
                    if built is not None:
                        with self._lock:
                            if snap.write_version == v0 and \
                                    (snap.delta is None or
                                     snap.delta.edge_count == 0) and \
                                    snap._aligned is None:
                                snap._aligned = built
                # measured pull-vs-push crossover for THIS space: the
                # fitted budget replaces the modeled default everywhere
                # the engine serves, not just inside bench.py (round-4
                # verdict item 4)
                if not self._budget_pinned and \
                        space_id not in self.sparse_budget_calibrations:
                    roots = _calibration_roots(snap)
                    if roots:
                        self.calibrate_sparse_budget(
                            space_id, roots,
                            etypes[:traverse.MAX_EDGE_TYPES_PER_QUERY],
                            auto=True, _snap=snap)
            except Exception:
                _LOG.exception("prewarm of space %d failed", space_id)
            finally:
                self._prewarming[space_id] = False

        if block:
            # traced_thread (NL002): a `block`ing caller joins this
            # warmup from inside its own statement, so the caller's
            # live trace rightfully owns the spans recorded here
            t = traced_thread(run, name=f"csr-prewarm-{space_id}")
        else:
            # nlint: disable=NL002 -- fire-and-forget warmup (USE
            # path) outlives the kicking request; adopting its context
            # would pin a finished trace and ship dead trace ctx on
            # every warmup RPC
            t = threading.Thread(target=run, daemon=True,
                                 name=f"csr-prewarm-{space_id}")
        # check-then-set AND handle store under one lock hold: two
        # concurrent USEs must not both start warmups, and a blocking
        # caller that loses the race must find the WINNER's thread
        # handle (flag-before-handle left a window where join was
        # silently skipped — review finding, round 5)
        with self._lock:
            if self._prewarming.get(space_id):
                already = self._prewarm_threads.get(space_id)
            else:
                self._prewarming[space_id] = True
                self._prewarm_threads[space_id] = t
                t.start()   # started under the lock: a loser can
                already = None   # never join an unstarted thread
        if already is not None:
            if block:
                already.join()   # wait out the in-flight warmup
                # the joined warmup may have started BEFORE the space
                # had data (USE fires prewarm at connect time): one
                # more blocking pass calibrates against current data.
                # Bounded — the retry pass runs with _retry=False.
                if _retry and not self._budget_pinned and \
                        space_id not in self.sparse_budget_calibrations:
                    self.prewarm(space_id, block=True, _retry=False)
            return
        if block:
            t.join()

    def _version_nosleep(self, space_id: int):
        """provider.version from a section HOLDING the engine lock:
        suppress the shared retry sleeps (transport reconnect pacing
        on a just-died host) — a miss fails fast into the decline/CPU
        ladder instead of holding the lock for the backoff duration
        (lock-witness finding during `bench --cluster` failover)."""
        from ..common.faults import no_retry_sleep
        tok = no_retry_sleep.set(True)
        try:
            return self._provider.version(space_id)
        finally:
            no_retry_sleep.reset(tok)

    def _snapshot_locked(self, space_id: int) -> Optional[CsrSnapshot]:
        if self._mesh_demoted and space_id in self._mesh_demoted \
                and self.mesh is not None:
            # mesh re-admission probe: once the mesh breaker's open
            # window elapses, kick a SHARDED rebuild off the query
            # path; the single-device snapshot keeps serving until the
            # swap, and the first meshed serve's outcome closes or
            # re-opens the breaker. The demotion flag is dropped only
            # when the repack actually STARTS — _kick_repack no-ops
            # while the demotion's own (unsharded) rebuild is still in
            # flight or backed off, and dropping the flag then would
            # leave the space single-device with no future trigger.
            b = self._breakers.get("mesh")
            if b is not None and b.allow():
                self._mesh_demoted.discard(space_id)
                if not self._kick_repack(space_id, cause="mesh_readmit"):
                    self._mesh_demoted.add(space_id)   # retry later
        token = self._version_nosleep(space_id)
        if token is None:
            return None
        snap = self._snapshots.get(space_id)
        catalog = self._catalog_version()
        fresh = (snap is not None and not snap.stale
                 and snap.write_version == token
                 and getattr(snap, "catalog_version", -1) == catalog)
        if fresh:
            return snap
        if self._repacking.get(space_id):
            # a background repack is folding the delta / replacing a
            # poisoned snapshot: decline (CPU serves) rather than start
            # a racing synchronous rebuild under the engine lock
            return None
        if not self.auto_refresh:
            # operator controls rebuild timing; a stale snapshot must not
            # serve (results would be wrong) — decline so CPU path runs
            return None
        # incremental path: patch the live snapshot from the committed-
        # write feed instead of rebuilding (SURVEY §7 hard-part (a))
        if (snap is not None and not snap.stale
                and getattr(snap, "catalog_version", -1) == catalog
                and getattr(snap, "sharded_kernel", None) is None
                and self._token_compatible(snap, token)):
            if self._try_apply_deltas(snap, token):
                return snap
            # apply failed mid-way (capacity / barrier): the snapshot may
            # be partially patched — poison it, rebuild off the query
            # path, serve via CPU fallback until the swap. The poison
            # hits ONLY this snapshot (counted: snapshot_poisoned) — a
            # later refresh()/repack rebuilds cleanly.
            snap.stale = True
            self.stats["snapshot_poisoned"] += 1
            global_stats.add_value("tpu_engine.snapshot_poisoned", kind="counter")
            # the provider stamped WHY the pull declined (ring overrun /
            # barrier / pull failure) — the poison event and lifecycle
            # ledger carry that cause so overrun -> poison -> repack
            # reads as one attributed chain, not three counters
            cause = getattr(self._provider, "last_decline",
                            None) or "apply_failed"
            _flight.record("snapshot_poisoned", space=space_id,
                           cause=cause)
            _writepath.snapshots.note(space_id, "poison", cause=cause)
            # poison hygiene: drop the space's cached results/declines
            # alongside the snapshot (entries are already version-
            # orphaned; this frees them and counts the purge) — and the
            # poisoned snapshot's secondary indexes, exactly like the
            # CSR caches (the repack's fresh build re-creates them)
            self._invalidate_prop_indexes(snap)
            self._purge_space_cache(space_id)
            self._kick_repack(space_id, cause=cause)
            return None
        return self.refresh(space_id)

    # compiled-filter plans kept per snapshot (bounded dict, LRU-ish by
    # insertion since the working set is a handful of WHERE shapes)
    FILTER_PLAN_CAP = 64

    def _plan_filter(self, ctx, s, snap, use_delta, name_by_type,
                     alias_map, edge_types):
        """(device_mask, local_filter) for a WHERE clause: try the
        device compile; fall back to host evaluation. With delta edges
        in play a compiled mask would cover only canonical edges —
        evaluate on the host for ALL rows so both row sources stay
        consistent.

        Compiled plans are cached ON THE SNAPSHOT keyed by
        (write_version, filter bytes, edge types, aliases) — the
        per-snapshot rung of docs/manual/11-caching.md. This is the
        hoisted form of the old per-window `filter_cache` in
        _serve_group: a WHERE shape compiled for window N is reused by
        window N+1 (and by the single-query path) until a delta apply
        bumps write_version — prop patches mutate the host mirrors the
        compiler read, so the version is the correctness boundary.
        Declined compiles are cached too (the decline is deterministic
        per key). Every caller holds the engine lock (the compiler
        reads delta-mutable mirrors), so the per-snapshot dict and the
        engine-level counters need no extra lock."""
        if s.where is None:
            return None, None
        if use_delta:
            return None, s.where.filter
        key = None
        cache = None
        if plan_stage_enabled(graph_flags):
            try:
                key = (snap.write_version,
                       encode_expression(s.where.filter),
                       tuple(edge_types),
                       tuple(sorted(alias_map.items())))
            except Exception:
                key = None
            if key is not None:
                cache = getattr(snap, "_filter_plans", None)
                if cache is None:
                    cache = snap._filter_plans = {}
                plan = cache.get(key)
                if plan is not None:
                    self.filter_plan_counters["hits"] += 1
                    global_stats.add_value(
                        "tpu_engine.cache.filter_plan.hit",
                        kind="counter")
                    return plan
                self.filter_plan_counters["misses"] += 1
        fc = FilterCompiler(snap, self._sm, ctx.space_id(), name_by_type,
                            alias_map, edge_types)
        device_mask = fc.compile(s.where.filter)
        plan = (None, s.where.filter) if device_mask is None \
            else (device_mask, None)
        if key is not None and cache is not None:
            # entries keyed to a superseded write_version are dead the
            # moment the version moved — drop them (counted) before the
            # cap check so stale plans never crowd out live ones
            stale = [k for k in cache if k[0] != snap.write_version]
            for k in stale:
                del cache[k]
            self.filter_plan_counters["invalidations"] += len(stale)
            while len(cache) >= self.FILTER_PLAN_CAP:
                cache.pop(next(iter(cache)))
                self.filter_plan_counters["evictions"] += 1
            cache[key] = plan
        return plan

    @staticmethod
    def _token_compatible(snap, token) -> bool:
        """Deltas can only patch a snapshot whose routing still matches
        (remote tokens carry part->leader routing; a moved part means
        scans would come from a different host — rebuild). Likewise a
        LEADERSHIP change on any routed host (its per-space version
        element carries a leadership signature): the change ring of a
        deposed replica stops receiving the new leader's writes, so
        patching from it would freeze the snapshot at deposal time —
        rebuild through leader-routed scans instead, which re-resolves
        the real leaders as a side effect."""
        old = snap.write_version
        if isinstance(token, tuple) and isinstance(old, tuple):
            if len(token) != 3 or len(old) != 3 or token[1] != old[1]:
                return False
            sig = {h: v[1] for h, v in token[0] if isinstance(v, tuple)}
            old_sig = {h: v[1] for h, v in old[0] if isinstance(v, tuple)}
            return sig == old_sig
        return not isinstance(token, tuple) and not isinstance(old, tuple)

    def _try_apply_deltas(self, snap, token) -> bool:
        cs = getattr(self._provider, "changes_since", None)
        cursor = getattr(snap, "delta_cursor", None)
        if cs is None or cursor is None:
            return False
        # the pull runs under the engine lock: suppress retry sleeps
        # (transport reconnect pacing on a just-died host) for this
        # context — a failed pull already degrades cleanly (poison ->
        # CPU pipe -> background repack). Same invariant as refresh().
        from ..common.faults import no_retry_sleep
        _tok = no_retry_sleep.set(True)
        t0 = time.perf_counter()
        try:
            entries, new_cursor = cs(snap.space_id, cursor)
        finally:
            no_retry_sleep.reset(_tok)
        if entries is None:
            return False
        if entries:
            from .delta import apply_entries
            try:
                faults.fire("csr.delta_apply")
                ok = apply_entries(snap, self._sm, entries, time.time())
            except Exception:
                # an apply that RAISES is handled like one that
                # declines: the snapshot may be partially patched, so
                # the caller poisons it and the repack rebuilds — the
                # query itself serves on the CPU pipe, never errors
                _LOG.exception("delta apply onto space %d snapshot "
                               "raised; poisoning", snap.space_id)
                ok = False
            if not ok:
                return False
            # tombstones/patches mutate the canonical arrays the
            # batched aligned layout was built from
            snap.invalidate_aligned()
            # ... and the host prop columns the secondary indexes were
            # sorted from: drop them now (the write-version key already
            # orphans them structurally; the next LOOKUP rebuilds lazily)
            self._invalidate_prop_indexes(snap)
            self.stats["delta_applies"] += 1
            self._space_churn[snap.space_id] = \
                self._space_churn.get(snap.space_id, 0) + 1
            self._maybe_recalibrate(snap.space_id, snap)
        snap.delta_cursor = new_cursor
        snap.write_version = token
        # the snapshot now claims version `token`: re-anchor its
        # lineage digest at that version (None when a write raced —
        # the auditor then skips until the next build/apply)
        self._record_store_digest(snap)
        # write-path observatory: the whole apply ran under
        # `engine_snapshot`, so this extent IS the lock-hold cost the
        # ROADMAP item 2 delta-compaction work optimizes; the cursor
        # advance makes every write at or below it device-visible
        us = int((time.perf_counter() - t0) * 1e6)
        _writepath.stage("delta_apply", us)
        if entries:
            _writepath.snapshots.note(
                snap.space_id, "delta_apply", dur_us=us, lock_us=us,
                entries=len(entries))
        _writepath.watermark.note_visible(snap.space_id, new_cursor,
                                          cause="delta")
        d = snap.delta
        if d is not None:
            self.stats["delta_edges"] = d.edge_count
            if d.edge_count + d.tomb_count > 0.75 * d.max_edges:
                # fold the delta into a fresh base while still serving
                self._kick_repack(snap.space_id, cause="delta_full")
        return True

    def _kick_repack(self, space_id: int, cause: str = "kick") -> bool:
        """Rebuild off the query path; queries keep serving the current
        snapshot (or CPU fallback when poisoned) until the swap.
        Returns True when a rebuild thread actually started (False: one
        is already in flight, or the failure backoff hasn't elapsed —
        the mesh re-admission gate keys off this).

        A failed build is never silent (ref role: every background
        path in the reference logs, kvstore/raftex/RaftPart.cpp
        throughout): it's logged with the traceback, counted in both
        the engine stats (`repack_failures`) and the global stats
        manager (`tpu_engine.repack_failures`, visible via
        /get_stats), and retried with exponential backoff on the next
        kick — meanwhile queries keep the previous snapshot."""
        if self._repacking.get(space_id):
            return False
        fails, not_before = self._repack_backoff.get(space_id, (0, 0.0))
        if time.time() < not_before:
            return False
        self._repacking[space_id] = True

        def run():
            t0 = time.perf_counter()
            try:
                snap = self._build_fresh(space_id)   # scan without lock
                if snap is not None:
                    if getattr(snap, "sharded_kernel", None) is None:
                        try:        # dispatcher layout, still off-lock
                            snap.aligned_kernel()
                        except Exception:
                            pass
                    else:
                        # meshed twin: per-device aligned blocks for
                        # the sharded window kernel, also off-lock
                        # (first window otherwise pays the build under
                        # the engine lock)
                        from . import mesh_exec
                        mesh_exec.ensure_sharded_aligned(self.mesh, snap)
                    t_lock = time.perf_counter()
                    with self._lock:                 # swap under lock
                        old = self._snapshots.get(space_id)
                        self._snapshots[space_id] = snap
                        # a repack swap is a snapshot version like any
                        # other: it counts toward the budget-staleness
                        # churn (refresh/delta applies do the same)
                        self._space_churn[space_id] = \
                            self._space_churn.get(space_id, 0) + 1
                        self._maybe_recalibrate(space_id, snap)
                    self.stats["rebuilds"] += 1
                    self.stats["bg_repacks"] += 1
                    self._repack_backoff.pop(space_id, None)
                    # observatory: the repack folded every committed
                    # write up to the build's capture token into the
                    # served snapshot — record the full-rebuild cost
                    # (stage histogram), lifecycle event (with swap
                    # lock-hold + device-mem delta) and watermark
                    # advance, all OFF the engine lock
                    us = int((time.perf_counter() - t0) * 1e6)
                    _writepath.stage("repack", us, trace_id="")
                    _writepath.snapshots.note(
                        space_id, "repack", dur_us=us, cause=cause,
                        lock_us=int((time.perf_counter() - t_lock)
                                    * 1e6),
                        device_bytes=_snap_bytes(snap),
                        device_bytes_delta=_snap_bytes(snap) - (
                            _snap_bytes(old) if old is not None
                            else 0))
                    _writepath.watermark.note_visible(
                        space_id, getattr(snap, "delta_cursor", None),
                        cause="repack")
            except Exception:
                n = fails + 1
                delay = min(2.0 ** (n - 1), 60.0)
                self._repack_backoff[space_id] = (n, time.time() + delay)
                self.stats["repack_failures"] += 1
                global_stats.add_value("tpu_engine.repack_failures", kind="counter")
                _writepath.snapshots.note(
                    space_id, "repack_failed", cause=cause,
                    consecutive=n, retry_in_s=round(delay, 1))
                _LOG.exception(
                    "background repack of space %d failed (consecutive "
                    "failure %d, next attempt in %.0fs); continuing to "
                    "serve the previous snapshot", space_id, n, delay)
            finally:
                self._repacking[space_id] = False

        # nlint: disable=NL002 -- background repack serves every later
        # query, not the one that happened to trip it; no trace adoption
        threading.Thread(target=run, daemon=True,
                         name=f"csr-repack-{space_id}").start()
        return True

    # ------------------------------------------------------------------
    # serve decisions
    # ------------------------------------------------------------------
    def can_serve(self, space_id: int, s: ast.GoSentence) -> bool:
        if not (self.enabled and self._provider is not None):
            return False
        if _consistency.is_shadow():
            # shadow-read re-execution (common/consistency.py): the
            # whole point is an independent CPU-pipe twin — decline
            return False
        exprs = [c.expr for c in (s.yield_.columns if s.yield_ else [])]
        if s.where:
            exprs.append(s.where.filter)
        if _uses_input_refs(exprs) and s.step.upto:
            # per-root frontiers x per-step masks in one program is the
            # rare combination we leave to the CPU loop
            return False
        return True

    def can_serve_path(self, space_id: int, s: ast.FindPathSentence) -> bool:
        """Structural routing for FIND PATH, decided BEFORE the engine
        lock and snapshot are taken (mirroring the aggregation
        pre-checks): a query the device path would decline anyway must
        cost schema-free checks only, not a lock + snapshot check +
        discarded walk. Every decline is counted by reason
        (`path_decline_reasons`; /tpu_stats + /get_stats
        tpu_engine.path_declined.<reason>)."""
        if not (self.enabled and self._provider is not None):
            return False
        if _consistency.is_shadow():
            return False    # shadow runs take the CPU pipe by design
        if not s.shortest:
            # ALL/NOLOOP paths serve meshed AND unmeshed: sharded
            # snapshots take the per-step sharded expansion
            # (mesh_exec.multi_hop_steps_sharded) with the same
            # host-side enumeration; only the bounded-steps form runs
            # on device either way.
            #
            # Deliberately NOT negative-cached: this verdict is one
            # integer range check against a class constant — a locked
            # LRU probe plus a streamed counter costs strictly more
            # than the check it would skip. The negative rung carries
            # the verdicts that DO skip real work (the aggregation
            # pre-check's per-spec schema walk).
            if not 1 <= int(s.step.steps) <= self.MAX_DEVICE_STEPS:
                return self._path_decline("all_paths_steps_out_of_range")
        return True

    def _path_decline(self, reason: str) -> bool:
        """Count one FIND PATH device-path decline (engine stats +
        /get_stats) and return False so the CPU path serves — without
        a snapshot ever being touched. Runs pre-lock on concurrent
        session threads, hence the stats lock."""
        with self._stats_lock:
            self.stats["path_declined"] += 1
            self.path_decline_reasons[reason] = \
                self.path_decline_reasons.get(reason, 0) + 1
        global_stats.add_value("tpu_engine.path_declined." + reason,
                               kind="counter")
        return False

    # ------------------------------------------------------------------
    # secondary indexes: LOOKUP / GET SUBGRAPH on device (index.py;
    # docs/manual/16-indexes.md)
    # ------------------------------------------------------------------
    def _index_decline(self, reason: str):
        """Count one index/subgraph device decline by reason and return
        None so the storaged CPU scan serves — a failed or refused
        device index search is never a client error."""
        with self._stats_lock:
            self.stats["index_declined"] += 1
            self.index_decline_reasons[reason] = \
                self.index_decline_reasons.get(reason, 0) + 1
        global_stats.add_value("tpu_engine.index.declined." + reason,
                               kind="counter")
        return None

    def _index_specs(self, space_id: int) -> List[dict]:
        """Cataloged tag-index descriptors (metad DDL; edge indexes are
        catalog-only for now — LOOKUP ON edge serves via the CPU scan)."""
        if self._sm is None:
            return []
        try:
            return [d for d in self._sm.list_indexes(space_id)
                    if not d.get("is_edge")]
        except Exception:
            return []

    def _prebuild_indexes(self, space_id: int, snap) -> None:
        """Eagerly build every cataloged tag index on a fresh snapshot —
        the same off-lock build path the CSR arrays ride; a failed
        build degrades that (tag, prop) to the CPU scan, it never
        fails the snapshot build."""
        cache = getattr(snap, "prop_indexes", None)
        if cache is None:
            cache = snap.prop_indexes = {}
        for spec in self._index_specs(space_id):
            fields = spec.get("fields") or []
            if not fields:
                continue
            # device search covers the index's LEADING field (the
            # composite tail is catalog metadata only)
            key = (spec["schema_id"], fields[0])
            if key not in cache:
                cache[key] = self._build_one_index(snap, key[0], key[1])

    def _build_one_index(self, snap, tag_id: int, prop: str):
        from . import index as secindex
        try:
            faults.fire("index.build")
            idx = secindex.build_tag_index(snap, tag_id, prop)
        except Exception:
            _LOG.exception(
                "device index build for (tag %d, %r) on space %d "
                "failed; LOOKUP serves via the storaged CPU scan",
                tag_id, prop, snap.space_id)
            return None
        if idx is not None:
            with self._stats_lock:
                self.stats["index_builds"] += 1
                self.stats["index_bytes"] += idx.nbytes
            global_stats.add_value("tpu_engine.index.builds",
                                   kind="counter")
        return idx

    def _get_index_locked(self, snap, tag_id: int, prop: str):
        """Per-snapshot index, building lazily when the eager pass
        missed it (index created after the snapshot, or a delta apply
        dropped it). Caller holds the engine lock — the build reads
        the delta-mutable host columns. A None entry is sticky for the
        snapshot's current write_version (the decline is deterministic
        for these mirrors); a version-orphaned survivor rebuilds."""
        cache = getattr(snap, "prop_indexes", None)
        if cache is None:
            cache = snap.prop_indexes = {}
        key = (tag_id, prop)
        if key in cache:
            idx = cache[key]
            if idx is None or idx.matches_snapshot(snap):
                return idx
        idx = cache[key] = self._build_one_index(snap, tag_id, prop)
        return idx

    def _invalidate_prop_indexes(self, snap) -> None:
        """Delta applies / poison: drop the snapshot's secondary
        indexes (prop patches mutate the host columns they were sorted
        from). The write-version key already makes stale ones
        structurally unreachable; this frees the device arrays now and
        counts the purge."""
        cache = getattr(snap, "prop_indexes", None)
        if not cache:
            return
        n = len(cache)
        cache.clear()
        with self._stats_lock:
            self.stats["index_invalidations"] += n
        global_stats.add_value("tpu_engine.index.invalidations", n,
                               kind="counter")

    def index_stats(self) -> Dict[str, Any]:
        """The /tpu_stats "index" block (flattened to Prometheus as
        tpu_engine.index.*): build/serve lifecycle of the device
        secondary indexes."""
        with self._stats_lock:
            out = {"builds": self.stats["index_builds"],
                   "bytes": self.stats["index_bytes"],
                   "searches": self.stats["index_searches"],
                   "hits": self.stats["index_hits"],
                   "declines": self.stats["index_declined"],
                   "invalidations": self.stats["index_invalidations"],
                   "lookup_served": self.stats["lookup_served"],
                   "subgraph_served": self.stats["subgraph_served"],
                   "decline_reasons": dict(self.index_decline_reasons)}
        return out

    def can_serve_lookup(self, space_id: int) -> bool:
        """Structural pre-check for LOOKUP device serving (the executor
        already verified a catalog index exists — E_INDEX_NOT_FOUND
        is a client error, not a routing decision)."""
        if not (self.enabled and self._provider is not None):
            return False
        if _consistency.is_shadow():
            return False    # shadow runs take the CPU pipe by design
        return True

    def execute_lookup(self, ctx, tag_id: int, prop: str,
                       op: Optional[str], value,
                       yield_props: List[Tuple[str, str]]):
        """Serve LOOKUP ON tag WHERE prop OP value via the device
        sorted-array index. `yield_props` are (column name, prop name)
        plain-prop yields the executor pre-resolved — anything richer
        declined upstream. Returns StatusOr(InterimResult) with rows
        sorted by VertexID, or None so the storaged scan twin serves.

        Same ladder/cache shape as GO: result-cache hit BEFORE the
        "index" breaker gate; any device failure feeds the breaker and
        degrades to the CPU scan, never a client error."""
        space = ctx.space_id()
        ck = None
        try:
            if result_stage_enabled(graph_flags):
                token = self._provider.version(space)
                if token is not None:
                    ck = ("lookup", space, int(tag_id), token,
                          self._catalog_version(), prop, op, value,
                          tuple(yield_props))
        except Exception:
            ck = None    # unkeyable literal: skip the rung
        if ck is not None:
            hit = self._result_cache_get(ck)
            if hit is not None:
                return hit
        if not self._device_admit("index", ctx):
            return None
        try:
            r = self._execute_lookup_inner(space, tag_id, prop, op,
                                           value, yield_props)
        except Exception as e:
            return self._device_failed("index", e)
        if r is not None:
            self._device_ok("index")
            with self._stats_lock:
                self.stats["lookup_served"] += 1
                self.stats["index_hits"] += 1
            global_stats.add_value("tpu_engine.index.hits",
                                   kind="counter")
            if ck is not None:
                self._result_cache_put(ck, r)
        return r

    def _execute_lookup_inner(self, space, tag_id, prop, op, value,
                              yield_props):
        from . import index as secindex
        with self._lock:
            snap = self._snapshot_locked(space)
            if snap is None:
                return self._index_decline("no_snapshot")
            with self._stats_lock:
                self.stats["index_searches"] += 1
            global_stats.add_value("tpu_engine.index.searches",
                                   kind="counter")
            faults.fire("index.search")
            idx = self._get_index_locked(snap, tag_id, prop)
            if idx is None:
                return self._index_decline("unindexable_prop")
            if op is None:
                # no-WHERE dump form: null-prop rows are absent from
                # the index but present in the scan — CPU serves
                return self._index_decline("no_where")
            if idx.is_str:
                if op != "==":
                    return self._index_decline("string_order_compare")
                if not isinstance(value, str):
                    return self._index_decline("type_mismatch")
                vids = secindex.search(idx, op,
                                       snap.str_code("t", prop, value))
            else:
                if isinstance(value, str):
                    return self._index_decline("type_mismatch")
                vids = secindex.search(idx, op, value)
            if vids is None:
                return self._index_decline("unsupported_op")
            rows = self._materialize_lookup_rows(snap, tag_id,
                                                 np.sort(vids),
                                                 yield_props)
            if rows is None:
                return self._index_decline("unmaterializable_yield")
        from ..graph.interim import InterimResult
        cols = ["VertexID"] + [n for n, _ in yield_props]
        return StatusOr.of(InterimResult(cols, rows))

    def _materialize_lookup_rows(self, snap, tag_id, vids, yield_props):
        """Rows for the matched vids from the snapshot host mirrors —
        the same decoded values the storaged scan twin returns. None
        (decline) when any needed cell can't be read with identical
        semantics (absent column / schema-version-missing cells /
        nulls whose CPU reading is schema-dependent). Caller holds the
        engine lock (mirrors are delta-mutable)."""
        from .csr import host_item
        rows = []
        for vid in vids:
            loc = snap.locate(int(vid))
            if loc is None:
                return None
            p0, local = loc
            row = [int(vid)]
            for _, pname in yield_props:
                col = snap.shards[p0].tag_props.get(tag_id, {}).get(pname)
                if col is None or col.missing is not None:
                    return None
                if col.present is not None and not col.present[local]:
                    return None
                row.append(host_item(col, local))
            rows.append(row)
        return rows

    def can_serve_subgraph(self, space_id: int, steps: int) -> bool:
        if not (self.enabled and self._provider is not None):
            return False
        if _consistency.is_shadow():
            return False    # shadow runs take the CPU pipe by design
        return 1 <= int(steps) <= self.MAX_DEVICE_STEPS

    def execute_subgraph(self, ctx, steps: int, starts: List[int],
                         edge_types: List[int],
                         name_by_type: Dict[int, str]):
        """GET SUBGRAPH: bounded frontier expansion with edge capture
        over the per-step device masks (traverse.multi_hop_steps /
        the sharded twin). Rows (Step, SrcVID, EdgeName, Ranking,
        DstVID), sorted; None -> the CPU expansion twin serves."""
        space = ctx.space_id()
        heat_tok = self._heat_note_query(ctx, starts)
        try:
            ck = None
            try:
                if result_stage_enabled(graph_flags):
                    token = self._provider.version(space)
                    if token is not None:
                        ck = ("subgraph", space, int(steps), token,
                              self._catalog_version(),
                              tuple(edge_types), tuple(starts))
            except Exception:
                ck = None
            if ck is not None:
                hit = self._result_cache_get(ck)
                if hit is not None:
                    return hit
            if not self._device_admit("subgraph", ctx):
                return None
            try:
                r = self._execute_subgraph_inner(space, steps, starts,
                                                 edge_types,
                                                 name_by_type)
            except Exception as e:
                return self._device_failed("subgraph", e)
            if r is not None:
                self._device_ok("subgraph")
                with self._stats_lock:
                    self.stats["subgraph_served"] += 1
                if ck is not None:
                    self._result_cache_put(ck, r)
            return r
        finally:
            _heat.restore(heat_tok)

    def _execute_subgraph_inner(self, space, steps, starts, edge_types,
                                name_by_type):
        import jax.numpy as jnp
        if not edge_types:
            return self._index_decline("no_edge_types")
        if len(edge_types) > traverse.MAX_EDGE_TYPES_PER_QUERY:
            return self._index_decline("too_many_edge_types")
        with self._lock:
            snap = self._snapshot_locked(space)
            if snap is None:
                return self._index_decline("no_snapshot")
            if snap.delta is not None and snap.delta.edge_count > 0:
                # delta-added edges live outside the canonical kernel;
                # the per-step capture below would miss them (tombstones
                # alone are fine — they point-update the valid masks)
                return self._index_decline("delta_edges")
            f0 = jnp.asarray(
                snap.frontier_from_vids([int(v) for v in starts]))
            req = jnp.asarray(traverse.pad_edge_types(list(edge_types)))
            if getattr(snap, "sharded_kernel", None) is not None:
                from . import mesh_exec
                try:
                    masks = mesh_exec.multi_hop_steps_sharded(
                        self.mesh, f0, snap.sharded_kernel, req,
                        int(steps))
                except Exception as e:
                    self._mesh_failed("subgraph", e, snap)
                    return None
                self.stats["sharded_queries"] += 1
                self._mesh_served("subgraph")
            else:
                masks = traverse.multi_hop_steps(f0, snap.kernel, req,
                                                 steps=int(steps))
            v0 = snap.write_version
        # device wait OFF the engine lock (jax releases the GIL);
        # materialize re-takes it and declines if a delta apply moved
        # the snapshot under the fetch — the CPU pipe serves instead
        masks_np = np.asarray(masks)
        with self._lock:
            if snap.stale or snap.write_version != v0:
                return self._index_decline("snapshot_moved")
            rows = self._materialize_subgraph_rows(snap, masks_np,
                                                   name_by_type)
        rows.sort()
        from ..graph.interim import InterimResult
        return StatusOr.of(InterimResult(
            ["Step", "SrcVID", "EdgeName", "Ranking", "DstVID"],
            [list(t) for t in rows]))

    def _materialize_subgraph_rows(self, snap, masks_np, name_by_type):
        """(step, src, edge name, rank, dst) tuples from the per-step
        active masks + host mirrors; caller holds the engine lock."""
        rows = []
        for si in range(masks_np.shape[0]):
            for p0, shard in enumerate(snap.shards):
                for e in np.nonzero(masks_np[si, p0])[0]:
                    et = int(shard.edge_etype[e])
                    name = name_by_type.get(et)
                    src = snap.vid_of_slot(p0, int(shard.edge_src[e]))
                    if name is None or src is None:
                        continue
                    rows.append((si + 1, int(src), name,
                                 int(shard.edge_rank[e]),
                                 int(shard.edge_dst_vid[e])))
        return rows

    # ------------------------------------------------------------------
    # GO on device
    # ------------------------------------------------------------------
    def execute_go(self, ctx, s: ast.GoSentence, starts: List[int],
                   edge_types: List[int], alias_map: Dict[str, str],
                   name_by_type: Dict[int, str]):
        """Returns executors.Result, or None to fall back to CPU.

        Ladder wrapper: an open "go" breaker declines straight to the
        CPU pipe, and any device-path exception is converted to a CPU
        retry (counted + fed to the breaker) — a client never sees a
        device-infrastructure error (docs/manual/9-robustness.md).

        Result-cache rung (cache_mode=full): a plain-form GO whose
        (statement shape, starts, snapshot token, catalog version) key
        hits serves from the cache BEFORE the breaker gate — a tripped
        device degrades to a warm cache, not straight to the CPU pipe.
        Keys embed the freshness token, so staleness is structural:
        any committed write moves the token and orphans old entries."""
        # workload observatory: charge read heat to the start-vid
        # parts, feed the hot-vertex sketch, and note the parts for
        # device-time attribution (one flag read when disarmed)
        heat_tok = self._heat_note_query(ctx, starts)
        try:
            return self._execute_go_outer(ctx, s, starts, edge_types,
                                          alias_map, name_by_type)
        finally:
            _heat.restore(heat_tok)

    def _heat_note_query(self, ctx, starts):
        try:
            space = ctx.space_id()
            return _heat.observe_query(space, starts,
                                       ctx.sm.num_parts(space))
        except Exception:
            return None    # telemetry must never fail a query

    def _execute_go_outer(self, ctx, s, starts, edge_types, alias_map,
                          name_by_type):
        ck, yield_cols = self._go_cache_key(ctx, s, starts, edge_types,
                                            alias_map, name_by_type)
        if ck is not None:
            hit = self._result_cache_get(ck)
            if hit is not None:
                return hit
        if not self._device_admit("go", ctx):
            return None
        try:
            r = self._execute_go_routed(ctx, s, starts, edge_types,
                                        alias_map, name_by_type,
                                        dkey=None if ck is None
                                        else ck[:3] + ck[5:],
                                        yield_cols=yield_cols)
        except OverloadShed:
            # a shed is NOT a device failure: it must surface as the
            # typed, retryable overload signal — feeding it to the
            # breaker or the CPU pipe would either degrade everyone
            # for load that is working as intended, or move the
            # overload onto the slower path. It propagates AS the
            # exception so the graph layer can build the E_OVERLOAD
            # response with the machine-readable retry_after_ms hint
            # intact — the same contract admission denials keep
            # (docs/manual/14-qos.md)
            raise
        except Exception as e:
            return self._device_failed("go", e)
        if r is not None:
            self._device_ok("go")
            if ck is not None:
                self._result_cache_put(ck, r)
        return r

    # ------------------------------------------------------------------
    # device result cache (rung 2 of docs/manual/11-caching.md)
    # ------------------------------------------------------------------
    def _go_cache_key(self, ctx, s, starts, edge_types, alias_map,
                      name_by_type):
        """-> (key, yield_cols): the result-cache key for a plain-form
        GO (None when the rung is off or the statement shape is
        uncacheable — UPTO / input refs depend on per-session state)
        plus the resolved yield columns so the serve path downstream
        reuses them instead of re-deriving. Key layout: (kind, space,
        steps, token, catalog, etypes, starts, aliases, where bytes,
        yield bytes, distinct) — space at [1] anchors per-space
        purges; token/catalog at [3]/[4] so the version-free dedupe
        identity is ck[:3] + ck[5:]."""
        if not result_stage_enabled(graph_flags) or \
                self._provider is None or not self.enabled:
            return None, None
        from ..graph import executors as ex
        yield_cols = None
        try:
            yield_cols = ex._go_yield_columns(s, ctx, name_by_type)
            exprs = [c.expr for c in yield_cols]
            if s.where is not None:
                exprs.append(s.where.filter)
            if s.step.upto or _uses_input_refs(exprs):
                return None, yield_cols
            space = ctx.space_id()
            token = self._provider.version(space)
            if token is None:
                return None, yield_cols
            where_enc = encode_expression(s.where.filter) \
                if s.where is not None else None
            yenc = tuple((c.name(), encode_expression(c.expr))
                         for c in yield_cols)
        except Exception:
            # unkeyable statements simply skip the rung
            return None, yield_cols
        return (("go", space, int(s.step.steps), token,
                 self._catalog_version(), tuple(edge_types),
                 tuple(starts), tuple(sorted(alias_map.items())),
                 where_enc, yenc,
                 bool(s.yield_ and s.yield_.distinct)), yield_cols)

    def _result_cache_get(self, ck):
        v = self.result_cache.get(ck)
        if v is None:
            return None
        cols, rows = v
        from ..graph.interim import InterimResult
        _tr.tag_root("cache_hit", "result")
        return StatusOr.of(InterimResult(list(cols), list(rows)))

    def _result_cache_put(self, ck, r) -> None:
        """Store one finalized device result — ONLY when the space's
        freshness token still equals the key's token: a delta apply
        landing mid-serve (the snapshot-version redo check re-served
        the request) moves the token, and publishing the pre-write
        rows under the pre-write key would hand a later same-token
        reader a result the redo already superseded. Rows are stored
        as an immutable tuple; hits box a fresh InterimResult, so a
        downstream ORDER BY/LIMIT can never mutate the cached copy."""
        try:
            if not r.ok():
                return
        except AttributeError:
            return
        v = r.value()
        rows = getattr(v, "rows", None)
        if rows is None or len(rows) > self.RESULT_CACHE_MAX_ROWS:
            return
        if getattr(v, "_tpu_deferred", None) is not None:
            return    # not boxed yet (defensive; callers finalize first)
        if getattr(v, "_tpu_no_cache", False):
            return    # cluster-served partials may be bounded-stale
            # (follower fence / shard budget): publishing them under
            # the FRESH token would hand later readers stale rows the
            # token says are current
        if getattr(v, "_tpu_dedupe_clone", False):
            return    # a deduped window wakes N owners with one shared
            # payload: the representative's put is the only one needed
            # — N-1 re-puts of identical tuples would just burn copies
            # and inflate `stores`
        space, token = ck[1], ck[3]
        if self._provider is None or \
                self._provider.version(space) != token or \
                self._catalog_version() != ck[4]:
            return
        self.result_cache.put(ck, (tuple(v.columns), tuple(rows)))

    def _purge_space_cache(self, space_id: int) -> int:
        """Drop every cached result/decline of a space — the poison
        hygiene rung: a poisoned snapshot's entries are already
        unreachable (the token moved past them), this frees the memory
        NOW and makes the purge observable (`invalidations`)."""
        n = self.result_cache.invalidate_where(
            lambda k: len(k) > 1 and k[1] == space_id)
        n += self.negative_cache.invalidate_where(
            lambda k: len(k) > 1 and k[1] == space_id)
        return n

    @staticmethod
    def _clone_result(r):
        """An independent Result over the same immutable payload — the
        in-window dedupe fan-out: every follower gets its OWN
        InterimResult (downstream executors may sort/mutate rows in
        place) while sharing the window-encoded blob (EncodedRows
        decode is pure) or the row tuples."""
        if r is None:
            return None
        try:
            if not r.ok():
                return r
        except AttributeError:
            return r
        v = r.value()
        from ..graph.interim import InterimResult
        out = InterimResult(list(v.columns))
        enc = getattr(v, "_tpu_deferred", None)
        if enc is not None:
            out._tpu_deferred = enc
        else:
            out.rows = list(v.rows)
        out._tpu_dedupe_clone = True   # _result_cache_put skips clones
        return StatusOr.of(out)

    def _execute_go_routed(self, ctx, s: ast.GoSentence,
                           starts: List[int], edge_types: List[int],
                           alias_map: Dict[str, str],
                           name_by_type: Dict[int, str], dkey=None,
                           yield_cols=None):
        """Route one GO to the dispatcher or the single-query path.

        Plain-form GO (no UPTO, no input refs, unmeshed) goes through
        the cross-session dispatcher: concurrent sessions' traversals
        coalesce into ONE batched device program per round (group
        commit — see _go_via_dispatcher), the fix PARITY.md's
        concurrency sweep prescribed for the flat-QPS GIL ceiling.
        Everything else takes the single-query path unchanged."""
        from ..graph import executors as ex
        if len(edge_types) > traverse.MAX_EDGE_TYPES_PER_QUERY:
            self.stats["fallbacks"] += 1
            return None
        if yield_cols is None:   # the cache-key step already resolved
            yield_cols = ex._go_yield_columns(s, ctx, name_by_type)
        exprs = [c.expr for c in yield_cols]
        if s.where is not None:
            exprs.append(s.where.filter)
        # meshed engines route through the dispatcher too: sharded
        # snapshots serve batched windows via mesh_exec (concurrent
        # sessions coalesce on the mesh exactly as single-chip)
        if not s.step.upto and not _uses_input_refs(exprs):
            # cluster scatter/gather v2 (cluster.py): a remote-provider
            # engine fans the window out to per-storaged device
            # partials instead of building/refreshing a graphd-local
            # snapshot from row scans (docs/manual/13-device-speed.md)
            cr = self._cluster_go(ctx, s, starts, edge_types, alias_map,
                                  name_by_type, ex, yield_cols)
            if cr is not None:
                return cr
            return self._go_via_dispatcher(ctx, s, starts, edge_types,
                                           alias_map, name_by_type, ex,
                                           yield_cols, dkey=dkey)
        with self._lock:   # delta applies mutate host mirrors in place
            r = self._execute_go_locked(ctx, s, starts, edge_types,
                                        alias_map, name_by_type, ex,
                                        yield_cols)
        return self._finalize_result(r)

    def _cluster_go(self, ctx, s, starts, edge_types, alias_map,
                    name_by_type, ex, yield_cols):
        """Serve a plain-form GO via the cluster device path (per-host
        storaged device partials; cluster.py) when the provider is
        remote and `cluster_device_serve` is on. None -> caller rides
        the dispatcher. Exceptions propagate to the outer breaker
        ladder like any device failure."""
        client = getattr(self._provider, "_client", None)
        if client is None or not graph_flags.get_or(
                "cluster_device_serve", True, bool):
            return None
        cl = self._cluster
        if cl is None or cl.client is not client:
            from .cluster import ClusterDeviceServe
            cl = self._cluster = ClusterDeviceServe(self, client)
        r = cl.serve_go(ctx, s, starts, edge_types, alias_map,
                        name_by_type, ex, yield_cols)
        with self._stats_lock:
            self.stats["cluster_hops"] = cl.stats["hops"]
            self.stats["cluster_declined"] = cl.stats["declined"]
            self.stats["cluster_fallback_parts"] = \
                cl.stats["fallback_parts"]
            if r is not None:
                self.stats["cluster_served"] += 1
                self.stats["go_served"] += 1
        return r

    MAX_ROOTS_ON_DEVICE = 64   # per-root frontier memory bound
    MAX_DEVICE_STEPS = 16      # per-step mask stacks are [N, P, cap_e]:
                               # unbounded N would unroll the trace and
                               # OOM the chip — huge-N queries fall back
                               # to the bounded-memory CPU loop
    MAX_DISPATCH_BATCH = 128   # queries coalesced per dispatcher round
                               # (= traverse.LANES, the frontier-matrix
                               # width — one full TPU lane row); the
                               # per-round memory cap still governs on
                               # big graphs (_dispatch_cap)
    MAX_CONCURRENT_ROUNDS = 4  # distinct (space, steps, edge_types)
                               # groups served at once: group-complete
                               # scheduling runs unrelated groups as
                               # independent rounds; this bounds the
                               # device/queue pressure when many keys
                               # mix (excess keys wait FIFO-ish on the
                               # dispatcher cv)
    SMALL_BUCKET = 8           # small-window pad size (see _serve_group)
    # per-root edge cap for the calibration walk probe — bounds the
    # engine-lock hold time on huge graphs (rate, not completion)
    CALIBRATION_PROBE_BUDGET = 1 << 18
    # ---- multi-tenant QoS (docs/manual/14-qos.md) ----
    # bulk-lane rounds may hold at most this many of the
    # MAX_CONCURRENT_ROUNDS slots, so interactive lanes always have
    # headroom no matter how many bulk scans queue
    BULK_MAX_ROUNDS = 2
    # weighted-fair round selection: a granted round advances its
    # lane's virtual time by 1/weight — with 4:1 the bulk lane wins
    # ~1 in 5 contended grants (and never more slots than its cap)
    LANE_WEIGHTS = {LANE_INTERACTIVE: 4, LANE_BULK: 1}
    # group-wait samples feeding the shed watermark's p95
    WAIT_SAMPLE_WINDOW = 64
    # minimum samples before the p95 watermark trusts the window
    WAIT_SAMPLE_MIN = 8

    # ------------------------------------------------------------------
    # cross-session batched dispatch (round-4 verdict item 3): the
    # graphd thread model is thread-per-connection Python, so under
    # concurrency the engine lock + GIL serialize per-query device
    # dispatches — PARITY.md's sweep measured aggregate QPS flat at
    # ~630 from N=2. Group commit fixes the device half: whichever
    # thread finds its (space, steps, edge_types) GROUP idle becomes
    # that group's LEADER, drains every queued same-key request, and
    # serves the whole window in ONE [N, P, cap_v] batched program
    # (multi_hop_roots — the hop kernel reads the edge block once per
    # hop no matter how many frontiers ride along, the reference's
    # bucket idiom, QueryBaseProcessor.inl:460-513). Same-key arrivals
    # during a round queue up for the next one — natural batching
    # under load, zero added latency when idle. UNRELATED keys elect
    # their own leaders concurrently (group-complete scheduling), so
    # no waiter's wall time is bounded by a slow group it doesn't
    # belong to; waiters wake the moment their own group's results
    # land, not at end-of-round (docs/manual/7-dispatcher.md).
    # ------------------------------------------------------------------
    def _go_via_dispatcher(self, ctx, s, starts, edge_types, alias_map,
                           name_by_type, ex, yield_cols, dkey=None):
        req = _GoReq(ctx, s, starts, edge_types, alias_map, name_by_type,
                     (ctx.space_id(), int(s.step.steps),
                      tuple(edge_types)), yield_cols, dkey=dkey)
        req.t_enq = time.monotonic()
        req.tctx = _tr.current_state()
        req.ledger = _ledger.current()
        lane = getattr(ctx, "qos_lane", None)
        if lane is None:
            lane = self._classify_lane(s, starts)
        elif lane == LANE_INTERACTIVE \
                and not getattr(ctx, "qos_lane_pinned", False) \
                and self._classify_lane(s, starts) == LANE_BULK:
            # shape-classified interactive at parse time, but the
            # RESOLVED start set is wide (e.g. a pipe fanned out
            # thousands of start vids the parser couldn't see):
            # upgrade to bulk so width-abuse can't ride the protected
            # lane. Explicit pins (session / plan lane=) are honored.
            lane = LANE_BULK
        req.lane = lane
        # load-shedding watermark (docs/manual/14-qos.md): admitted
        # work sheds HERE, before it queues — bulk first (1x), then
        # interactive (2x) — so by the time deadline balks engage the
        # queue has already stopped growing. A shed is a typed,
        # retryable E_OVERLOAD, never a CPU fallback (that would move
        # the overload, not shed it).
        self._maybe_shed(req)
        dl = getattr(ctx, "_tpu_deadline", None)
        with self._disp_cv:
            self._disp_queue.append(req)
            self._lane_queued[req.lane] += 1
        batch = None
        timed_out = False
        # dispatcher_wait: from enqueue until the owner either wakes
        # done (a leader served it) or becomes a leader itself — the
        # queueing stage of the span tree (no-op when unsampled)
        wait_sp = _tr.span("dispatcher.wait").open()
        waited = False
        while True:
            with self._disp_cv:
                while not req.done and (
                        req.claimed
                        or req.key in self._disp_serving
                        or len(self._disp_serving)
                        >= self.MAX_CONCURRENT_ROUNDS
                        or not self._lane_may_lead_locked(req)):
                    timeout = None
                    if dl is not None:
                        timeout = dl - time.monotonic()
                        if timeout <= 0 and not req.claimed:
                            # deadline: balk out of the queue and let
                            # the CPU pipe serve — an UNCLAIMED waiter
                            # never blocks past its deadline. (A
                            # claimed one is owned by an in-flight
                            # round whose failure isolation guarantees
                            # a prompt wake — _serve_batch marks every
                            # claimed request done on every path.)
                            self._disp_queue = [
                                r for r in self._disp_queue
                                if r is not req]
                            if self._lane_queued.get(req.lane, 0) > 0:
                                self._lane_queued[req.lane] -= 1
                            req.done = True
                            req.result = None
                            timed_out = True
                            break
                        timeout = max(timeout, 0.01)
                    self._disp_cv.wait(timeout)
                if req.done:
                    break
                # leader election for THIS key only: claim every queued
                # same-key request (the window); other keys' requests
                # stay queued for their own leaders
                if self._disp_serving:
                    self.stats["leader_handoffs"] += 1
                batch = [r for r in self._disp_queue
                         if r.key == req.key][:self.MAX_DISPATCH_BATCH]
                taken = set(map(id, batch))
                self._disp_queue = [r for r in self._disp_queue
                                    if id(r) not in taken]
                for r in batch:
                    r.claimed = True
                    # decrement by each request's ORIGINAL lane,
                    # before the owner-lane normalization below
                    if self._lane_queued.get(r.lane, 0) > 0:
                        self._lane_queued[r.lane] -= 1
                # the round is granted to THIS request's lane: pair
                # the accounting with the recorded owner (batch[0]) so
                # _release_round decrements the same lane it charges
                batch[0].lane = req.lane
                self._lane_rounds[req.lane] += 1
                other = LANE_BULK if req.lane == LANE_INTERACTIVE \
                    else LANE_INTERACTIVE
                w = max(self.lane_weights.get(req.lane, 1), 1)
                # weighted virtual time, deficit-bounded: an idle lane
                # can bank at most ~one round of credit, so a returning
                # lane gets priority without an exclusive burst
                self._lane_vtime[req.lane] = max(
                    self._lane_vtime[req.lane],
                    self._lane_vtime[other] - 1.0) + 1.0 / w
                self.stats["lane_rounds_" + req.lane] += 1
                self._disp_serving[req.key] = batch[0]
                self.stats["disp_rounds"] += 1
                self.stats["disp_group_keys"] += 1 + len(
                    {r.key for r in self._disp_queue
                     if r.key != req.key})
                # the grant itself can UNBLOCK a deferred waiter: the
                # eligible waiter another lane yielded to is now
                # claimed, and the vtime advance may flip the weighted
                # comparison — before lanes existed a grant only ever
                # tightened the wait predicate, so this notify is
                # newly load-bearing (a deferred thread must re-check
                # NOW, not when this round eventually releases)
                self._disp_cv.notify_all()
            if not waited:
                # elected leader: the wait is over — serving time is
                # accounted by the window/kernel/materialize spans
                wait_sp.close(role="leader")
                waited = True
            try:
                self._serve_batch(batch, ex)
            finally:
                self._release_round(req.key, batch[0])
            if req.done:
                break
        if not waited:
            wait_sp.close(role="waiter")
        if timed_out:
            with self._stats_lock:
                self.stats["deadline_exceeded"] += 1
            global_stats.add_value(
                "tpu_engine.deadline_exceeded.dispatch_wait",
                kind="counter")
            _flight.record("deadline_balk", where="dispatch_wait")
            _tr.tag_root("degraded", "deadline:dispatch_wait")
            return None
        if req.result is None:
            # the round failed/declined and this request re-serves on
            # the CPU pipe in its own session — visible in the owner's
            # trace (specific failure sites add their own tags; this
            # catch-all covers benign declines like a poisoned or
            # missing snapshot)
            _tr.tag_root("degraded", "cpu_fallback")
        return self._finalize_result(req.result)

    def _release_round(self, key, owner: "_GoReq") -> None:
        """End (or early-end) a group round: idempotent per owner, so
        the leader can hand the key back right after the window's last
        device launch — window N+1's leader then overlaps its dispatch
        with window N's materialization — and the round's `finally`
        stays a no-op."""
        with self._disp_cv:
            if self._disp_serving.get(key) is owner:
                del self._disp_serving[key]
                ln = owner.lane
                if self._lane_rounds.get(ln, 0) > 0:
                    self._lane_rounds[ln] -= 1
                self._disp_cv.notify_all()

    # ------------------------------------------------------------------
    # multi-tenant QoS: priority lanes + load shedding
    # (common/qos.py; docs/manual/14-qos.md)
    # ------------------------------------------------------------------
    def _classify_lane(self, s, starts) -> str:
        """Statement-shape fallback when the graph layer didn't set
        ctx.qos_lane (direct-engine callers) — the ONE shared rule,
        qos.bulk_shape, same as the graph-layer classifier."""
        from ..common.qos import bulk_shape
        if bulk_shape(int(s.step.steps), len(starts)):
            return LANE_BULK
        return LANE_INTERACTIVE

    def _lane_may_lead_locked(self, req: "_GoReq") -> bool:
        """May this request start a new round NOW? (under _disp_cv.)
        Two rules on top of the slot/key checks:

        - bulk cap: bulk rounds never hold more than bulk_max_rounds
          slots, so interactive work always has headroom;
        - weighted fairness: a lane whose virtual time is ahead yields
          the slot when the OTHER lane has an eligible waiter (an
          unclaimed request whose key is idle — an active thread that
          will take the slot the moment this one defers). Yielding to
          a waiter that could not lead would idle the slot, so
          eligibility is checked, not just presence."""
        lane = req.lane
        other = LANE_BULK if lane == LANE_INTERACTIVE \
            else LANE_INTERACTIVE
        if lane == LANE_BULK and \
                self._lane_rounds[LANE_BULK] >= max(self.bulk_max_rounds, 1):
            return False
        if self._lane_vtime[lane] > self._lane_vtime[other] and \
                self._eligible_waiter_locked(other):
            return False
        return True

    def _eligible_waiter_locked(self, lane: str) -> bool:
        if self._lane_queued.get(lane, 0) <= 0:
            return False    # O(1) common case: no cross-lane waiters
        if lane == LANE_BULK and \
                self._lane_rounds[LANE_BULK] >= max(self.bulk_max_rounds, 1):
            return False    # capped out: it could not take the slot
        for r in self._disp_queue:
            if not r.claimed and r.lane == lane \
                    and r.key not in self._disp_serving:
                return True
        return False

    def _wait_p95_ms_locked(self) -> float:
        """p95 of the recent group-wait window (ms); 0 until the
        window has WAIT_SAMPLE_MIN samples (a cold dispatcher must
        not shed on noise)."""
        n = len(self._wait_samples)
        if n < self.WAIT_SAMPLE_MIN:
            return 0.0
        xs = sorted(self._wait_samples)
        return xs[min(int(n * 0.95), n - 1)]

    def _maybe_shed(self, req: "_GoReq") -> None:
        """Watermark check at enqueue time — raises OverloadShed
        (converted to a typed E_OVERLOAD at the execute_go seam) when
        a shed watermark is crossed. Bulk sheds at 1x the watermark,
        interactive only at 2x: the lowest-priority admitted work goes
        first. Disabled (both flags 0) this is two flag reads."""
        qd = int(graph_flags.get("qos_shed_queue_depth", 0) or 0)
        wp = float(graph_flags.get("qos_shed_wait_p95_ms", 0) or 0)
        if qd <= 0 and wp <= 0:
            return
        mult = 1 if req.lane == LANE_BULK else 2
        with self._disp_cv:
            depth = len(self._disp_queue)
            p95 = self._wait_p95_ms_locked()
        reason = None
        if qd > 0 and depth >= qd * mult:
            reason = "queue_depth"
        elif wp > 0 and p95 >= wp * mult:
            reason = "wait_p95"
        if reason is None:
            return
        retry_ms = max(int(p95) or 0, 25)
        space_id = req.key[0]
        with self._stats_lock:
            self.stats["qos_shed"] += 1
            rk = f"{reason}:{req.lane}"
            self.qos_shed_reasons[rk] = \
                self.qos_shed_reasons.get(rk, 0) + 1
            self.qos_shed_by_space[space_id] = \
                self.qos_shed_by_space.get(space_id, 0) + 1
        global_stats.add_value("tpu_engine.qos.shed." + reason,
                               kind="counter")
        # retry-after distribution: the shape of overload pressure
        # (exemplars link a shed to the trace that was shed)
        global_stats.add_value("tpu_engine.qos.shed_retry_ms",
                               retry_ms, kind="histogram")
        _flight.record("shed", reason=reason, lane=req.lane,
                       space=space_id)
        _tr.tag_root("shed", f"{reason}:{req.lane}")
        raise OverloadShed(reason, retry_ms)

    def qos_stats(self) -> Dict[str, Any]:
        """The /tpu_stats "qos" dispatcher block: live lane occupancy,
        the shed watermark inputs, per-reason and per-space shed
        slices (docs/manual/14-qos.md)."""
        with self._disp_cv:
            depth = len(self._disp_queue)
            in_flight = dict(self._lane_rounds)
            queued = dict(self._lane_queued)
            p95 = self._wait_p95_ms_locked()
        with self._stats_lock:
            shed_reasons = dict(self.qos_shed_reasons)
            shed_by_space = {str(k): v for k, v in
                             self.qos_shed_by_space.items()}
            lanes = {
                LANE_INTERACTIVE:
                    self.stats["lane_rounds_interactive"],
                LANE_BULK: self.stats["lane_rounds_bulk"],
            }
            shed = self.stats["qos_shed"]
        return {
            "queue_depth": depth,
            "group_wait_p95_ms": round(p95, 2),
            "lane_rounds": lanes,
            "lane_rounds_in_flight": in_flight,
            "lane_queued": queued,
            "lane_weights": dict(self.lane_weights),
            "bulk_max_rounds": self.bulk_max_rounds,
            "shed": shed,
            "shed_reasons": shed_reasons,
            "shed_by_space": shed_by_space,
            "watermarks": {
                "queue_depth":
                    graph_flags.get("qos_shed_queue_depth", 0),
                "wait_p95_ms":
                    graph_flags.get("qos_shed_wait_p95_ms", 0),
            },
        }

    def _mark_done(self, reqs: List["_GoReq"], early: bool = False) -> None:
        """Flip `done` and wake the owners NOW — waiters wake on their
        own group's completion, never on an unrelated round's end.
        `early` counts waiters released before their round fully
        retired (sparse fast-outs, non-final chunks).

        Dedupe fan-out happens HERE, before the representative's
        `done` flips: its owner thread cannot wake (and start
        finalizing / letting downstream executors mutate the rows in
        place) until `done` is visible under this condition var, so
        cloning first is the one race-free point. Followers wake in
        the same notify as their representative — a deduped request
        never waits longer than the lane it rode."""
        now = time.monotonic()
        wait_hist: List[Tuple[int, Optional[str]]] = []
        with self._disp_cv:
            done_now: List["_GoReq"] = []
            seen = set()
            stack = list(reqs)
            while stack:
                r = stack.pop()
                if r.done or id(r) in seen:
                    continue
                seen.add(id(r))
                if r.followers:
                    for f in r.followers:
                        if f.done:
                            continue
                        try:
                            with _tr.use(f.tctx):
                                f.result = self._clone_result(r.result)
                                if f.result is not None:
                                    _tr.tag_root("cache_hit",
                                                 "window_dedupe")
                        except Exception:
                            f.result = None   # CPU pipe re-serves it
                        stack.append(f)
                done_now.append(r)
            for r in done_now:
                r.done = True
                w = int((now - r.t_enq) * 1e6)
                if r.ledger is not None:
                    # the waiter's own queue time (enqueue -> wake)
                    r.ledger.queue_wait_us += w
                self.stats["group_wait_us_total"] += w
                self.stats["group_wait_count"] += 1
                if w > self.stats["group_wait_us_max"]:
                    self.stats["group_wait_us_max"] = w
                # shed-watermark feed: recent per-request waits (ms)
                self._wait_samples.append(w / 1e3)
                # dispatcher-wait histogram fed OUTSIDE the cv below,
                # under each request's OWN trace id (the exemplar must
                # point at the waiter that waited, not the leader —
                # "" suppresses the exemplar for unsampled waiters
                # instead of falling back to the leader's ambient
                # trace, see StatsManager.add_value)
                wait_hist.append(
                    (w, r.tctx[0].trace_id if r.tctx else ""))
                if early:
                    self.stats["early_releases"] += 1
            self._disp_cv.notify_all()
        for w, tid in wait_hist:
            global_stats.add_value("tpu_engine.dispatcher_wait_us", w,
                                   kind="histogram", trace_id=tid)

    def _finalize_result(self, r):
        """Box a deferred (window-encoded) result into Python tuples in
        the OWNING session's thread — outside the dispatcher round and
        outside the engine lock (materialize.EncodedRows)."""
        if r is None:
            return None
        try:
            if not r.ok():
                return r
        except AttributeError:
            return r
        v = r.value()
        enc = getattr(v, "_tpu_deferred", None)
        if enc is not None:
            v.rows = enc.to_rows()
            v._tpu_deferred = None
        return r

    def _count_encode(self, n_rows: int, native_used: bool) -> None:
        # the window-level encode runs off the engine lock, where
        # concurrent rounds would race the increment
        with self._stats_lock:
            if native_used:
                self.stats["native_encode_rows"] += n_rows
            else:
                self.stats["encode_fallback_rows"] += n_rows

    def _serve_batch(self, batch: List["_GoReq"], ex) -> None:
        """One group's dispatcher round (every request shares one
        (space, steps, edge types) key); a request that fails
        individually degrades to a CPU-pipe retry in its own session
        (result stays None — device failures never carry errors back,
        docs/manual/9-robustness.md).

        In-window dedupe (cache_mode=full): identical requests inside
        the window — same version-free statement identity (`dkey`) —
        collapse to ONE served lane; the followers' rows fan out as
        independent clones over the shared encoded blob at the
        representative's own _mark_done (see there for why that is
        the race-free point). Tier-3-shaped load (sessions drawing
        from shared seed pools) stops paying per-duplicate kernel
        lanes and materialization. A fallen-through representative
        (exception below) fans out None and every follower re-serves
        on the CPU pipe in its own session, like a failed lane."""
        if len(batch) > 1:
            self.stats["batched_max_window"] = max(
                self.stats["batched_max_window"], len(batch))
        uniques = self._dedupe_window(batch)
        try:
            self._serve_group(uniques, ex)
        except Exception as e:   # defensive: never strand a waiter —
            # and never error one either: the failed round's requests
            # wake with result=None and re-serve on the CPU pipe in
            # their own sessions (failure isolation: other concurrent
            # groups and later windows are untouched)
            self._device_failed("go", e)
            for r in uniques:
                if not r.done:
                    r.result = None
                    with _tr.use(r.tctx):
                        _tr.tag_root("degraded", "window_failed")
            self._mark_done(uniques)

    def _dedupe_window(self, batch: List["_GoReq"]) -> List["_GoReq"]:
        """Collapse one claimed window to its unique representatives
        (first occurrence per dkey, preserving order — batch[0] stays
        first, so the round-ownership handoff in _serve_group is
        untouched); followers attach to their representative and are
        fanned out + woken by its _mark_done. Requests without a dkey
        (rung off, unkeyable) are always unique."""
        if len(batch) < 2:
            return batch
        uniques: List["_GoReq"] = []
        n_followers = 0
        rep_by_key: Dict[Any, "_GoReq"] = {}
        for r in batch:
            rep = rep_by_key.get(r.dkey) if r.dkey is not None else None
            if rep is None:
                if r.dkey is not None:
                    rep_by_key[r.dkey] = r
                uniques.append(r)
            else:
                if rep.followers is None:
                    rep.followers = []
                rep.followers.append(r)
                n_followers += 1
        if n_followers:
            with self._stats_lock:
                self.stats["dedup_collapsed"] += n_followers
                self.stats["dedup_rounds"] += 1
            global_stats.add_value("tpu_engine.dedup_collapsed",
                                   n_followers, kind="counter")
        return uniques

    def _serve_group(self, group: List["_GoReq"], ex) -> None:
        """Serve one group window in three phases: (1) snapshot +
        per-query routing + device launch under the engine lock, (2)
        device wait OFF the lock — after the window's last launch the
        round is released early, so the NEXT window's leader overlaps
        its dispatch with this window's materialization, (3)
        materialize under the lock (host mirrors are delta-mutable),
        with the whole window's deferred rows encoded in ONE native
        GIL-released call off-lock at the end. A delta apply landing
        between phases bumps snap.write_version; affected requests
        redo through the single-query path."""
        import jax.numpy as jnp
        owner = group[0]
        multi = len(group) > 1
        if not multi:
            r = group[0]
            try:
                # the solo round is still a dispatcher window (of 1):
                # PROFILE of an idle GO shows the same tree shape as a
                # coalesced one, just with window=1
                with _tr.use(r.tctx), _ledger.use(r.ledger), \
                        _tr.span("dispatcher.window", window=1):
                    with self._lock:
                        r.result = self._execute_go_locked(
                            r.ctx, r.s, r.starts, r.edge_types,
                            r.alias_map, r.name_by_type, ex,
                            r.yield_cols)
            except Exception as e:
                self._device_failed("go", e)
                r.result = None    # owner re-serves on the CPU pipe
            self._mark_done([r])
            return
        space_id, steps, etypes = group[0].key
        dense: List[Tuple[_GoReq, np.ndarray, list, list]] = []
        mesh_aligned = None
        with self._lock:
            t0 = time.monotonic()
            snap = self._snapshot_locked(space_id)
            t_snap = time.monotonic() - t0
            if snap is None:
                # no snapshot: the single path handles each (CPU falls
                # back per request); the engine lock is already held,
                # so _serve_singles' per-request re-acquire is nested
                self._serve_singles(group, ex)
                self._mark_done(group)
                return
            meshed = getattr(snap, "sharded_kernel", None) is not None
            v0 = snap.write_version
            # per-query routing first, identical to the single path:
            # small frontiers serve from the host pull; only the ones
            # that exceed the budget ride the shared dense dispatch.
            # Sparse-served waiters are released IMMEDIATELY — they box
            # their deferred rows in their own threads while the leader
            # is still driving the dense half. Meshed snapshots skip
            # the sparse probe (routing parity with the meshed
            # single-query path) — every live frontier rides the
            # sharded window dispatch.
            for r in group:
                # spans recorded while serving THIS request belong to
                # its owner's trace, not the leader's (and its charges
                # to the owner's ledger)
                with _tr.use(r.tctx), _ledger.use(r.ledger):
                    try:
                        if self._deadline_exceeded(r.ctx,
                                                   "dispatch_claim"):
                            r.result = None    # CPU pipe serves it
                            self._mark_done([r], early=True)
                            continue
                        yield_cols = r.yield_cols
                        columns = [c.name() for c in yield_cols]
                        frontier0 = snap.frontier_from_vids(r.starts)
                        if not frontier0.any():
                            r.result = StatusOr.of(
                                ex.InterimResult(columns))
                            self._mark_done([r], early=True)
                            continue
                        if not meshed:
                            t1 = time.monotonic()
                            sparse = self._sparse_expand(
                                snap, r.starts, r.edge_types, steps)
                            t_walk = time.monotonic() - t1
                            if sparse is not None:
                                r.result = self._emit_sparse(
                                    r.ctx, r.s, snap, sparse, yield_cols,
                                    columns, r.alias_map, r.name_by_type,
                                    ex, r.edge_types, t_snap, t_walk)
                                self._mark_done([r], early=True)
                                continue
                        dense.append((r, frontier0, yield_cols, columns))
                    except Exception as e:
                        self._device_failed("go", e)
                        r.result = None    # CPU pipe re-serves it
                        self._mark_done([r], early=True)
            if not dense:
                return
            use_delta = snap.delta is not None and snap.delta.edge_count > 0
            cap = self._dispatch_cap(snap)
            req_arr = jnp.asarray(traverse.pad_edge_types(list(etypes)))
            if meshed and not use_delta:
                # per-device aligned blocks for the window kernel:
                # NEVER built here — the locked phase must not pay an
                # O(E) build (the single-chip aligned_ready invariant).
                # A missing layout kicks an off-lock build and this
                # window serves per-request on the sharded kernel.
                from . import mesh_exec
                mesh_aligned = mesh_exec.sharded_aligned_ready(snap)
                if mesh_aligned is None and \
                        getattr(snap, "_sharded_aligned", None) is None:
                    self._kick_sharded_aligned(snap)
        # one device-filter compile per DISTINCT WHERE per round — and,
        # through _plan_filter's per-snapshot rung, per SNAPSHOT VERSION
        # across rounds (docs/manual/11-caching.md): the window dict
        # below is only an L0 memo that skips re-encoding the filter
        # for each request of the window; the compile itself is served
        # (and survives) in the snapshot's keyed plan cache. Compiles
        # run lazily UNDER the lock in phase 3 (FilterCompiler reads
        # host mirrors).
        filter_cache: Dict[Any, Tuple] = {}

        def plan_filter_cached(r):
            if r.s.where is None:
                key = (None, ())
            else:
                key = (encode_expression(r.s.where.filter),
                       tuple(sorted(r.alias_map.items())))
            if key not in filter_cache:
                filter_cache[key] = self._plan_filter(
                    r.ctx, r.s, snap, use_delta, r.name_by_type,
                    r.alias_map, r.edge_types)
            return filter_cache[key]
        n_chunks = (len(dense) + cap - 1) // cap
        if meshed:
            if mesh_aligned is None:
                # layout not ready yet (building off-lock), build
                # failed, or a delta is pending: each request still
                # serves on DEVICE through the per-query sharded
                # kernel — only the window coalescing is lost, and the
                # decline is visible in the mesh matrix
                if use_delta:
                    reason = "delta_pending"
                elif getattr(snap, "_sharded_aligned", None) == "failed":
                    reason = "aligned_build"
                else:
                    reason = "aligned_not_ready"
                self._mesh_decline("go_batched", reason)
                self._serve_singles([r for r, *_ in dense], ex)
                self._mark_done([r for r, *_ in dense])
                return
            self._serve_meshed_chunks(dense, cap, n_chunks, snap, v0,
                                      steps, req_arr, owner,
                                      plan_filter_cached, ex, t_snap,
                                      mesh_aligned)
            return
        self._serve_dense_chunks(dense, cap, n_chunks, snap, v0,
                                 steps, use_delta, req_arr, owner,
                                 plan_filter_cached, ex, t_snap)

    def _serve_dense_chunks(self, dense, cap, n_chunks, snap, v0, steps,
                            use_delta, req_arr, owner,
                            plan_filter_cached, ex, t_snap) -> None:
        import jax.numpy as jnp
        # OWNER-scoped kernel-calibration claim: only the round that
        # set "calibrating" may reset it (a concurrent round for
        # another key shares the snapshot object and must not wipe an
        # in-flight claim); reset covers every bail-out path — launch/
        # fetch error, stale redo — so a later window retries
        claimed = [False]
        try:
            self._serve_chunk_loop(dense, cap, n_chunks, snap, v0,
                                   steps, use_delta, req_arr, owner,
                                   plan_filter_cached, ex, t_snap,
                                   claimed)
        finally:
            if claimed[0] and getattr(snap, "batched_kernel_pick",
                                      None) == "calibrating":
                snap.batched_kernel_pick = None

    def _kick_sharded_aligned(self, snap) -> None:
        """Build the snapshot's per-device aligned blocks OFF the
        engine lock (background thread; at most one per snapshot).
        Windows landing before it completes serve per-request on the
        sharded kernel — the same never-build-on-the-query-path
        discipline as the single-chip aligned_ready."""
        if getattr(snap, "_sharded_aligned_kick", False):
            return
        snap._sharded_aligned_kick = True
        mesh = self.mesh

        def run():
            from . import mesh_exec
            mesh_exec.ensure_sharded_aligned(mesh, snap)

        # nlint: disable=NL002 -- one-shot shared layout build spanning
        # many windows; must not attach to the kicking window's trace
        threading.Thread(target=run, daemon=True,
                         name=f"mesh-aligned-{snap.space_id}").start()

    def _serve_singles(self, reqs: List["_GoReq"], ex) -> None:
        """Serve dispatcher requests through the exact single-query
        path — the shared fallback when no batch can carry them (no
        snapshot, snapshot moved under a round, meshed window without
        its layout). Caller marks done. A request that fails here
        degrades to the CPU pipe in its own session (result=None),
        never to a client error."""
        for r in reqs:
            with _tr.use(r.tctx), _ledger.use(r.ledger):
                try:
                    with self._lock:
                        r.result = self._execute_go_locked(
                            r.ctx, r.s, r.starts, r.edge_types,
                            r.alias_map, r.name_by_type, ex,
                            r.yield_cols)
                except Exception as e:
                    self._device_failed("go", e)
                    r.result = None

    def _encode_sink(self, sink: List[Tuple]) -> None:
        """The whole window's deferred rows in ONE native GIL-released
        batch encode, off the engine lock; waiters box their own
        tuples after wakeup. An encode failure degrades every owner to
        the CPU pipe (result=None) — never a silent empty result and
        never a client-visible error."""
        try:
            t0 = time.monotonic()
            encs, native_used = materialize.encode_window(
                [g for (_r, g, _t) in sink])
            enc_us = (time.monotonic() - t0) * 1e6
            self._count_encode(sum(len(e) for e in encs), native_used)
            for (r, _g, _t2), enc in zip(sink, encs):
                r.result.value()._tpu_deferred = enc
                # one shared native call encoded the whole window: each
                # owner's trace gets the span (same duration, tagged
                # with the window rows so the sharing is readable)
                with _tr.use(r.tctx):
                    _tr.add_span("encode", enc_us, rows=len(enc),
                                 native=native_used,
                                 window=len(sink))
        except Exception as e:
            self._device_failed("go", e)
            for r, _g, _t2 in sink:
                r.result = None
                with _tr.use(r.tctx):
                    _tr.tag_root("degraded", "encode_failed")

    def _serve_meshed_chunks(self, dense, cap, n_chunks, snap, v0,
                             steps, req_arr, owner, plan_filter_cached,
                             ex, t_snap, mesh_aligned) -> None:
        """Dispatcher window on a SHARDED snapshot — the mesh twin of
        _serve_chunk_loop: the whole window rides ONE sharded
        lane-matrix program (mesh_exec.multi_hop_masks_batch_sharded;
        per-hop pmax frontier merge shared across every lane), with
        the identical three-phase lifecycle — launch under the engine
        lock, device wait + early round release off the lock,
        materialize under the lock, window-level native encode off it.
        No delta branch (meshed snapshots rebuild instead of
        delta-patching) and no lane-vs-vmap calibration (there is no
        vmapped sharded window variant to race).

        KEEP IN SYNC with _serve_chunk_loop: the bucket/redo/stale2/
        early-release/encode phases are one lifecycle — a fix to
        either loop almost certainly belongs in the other."""
        import jax.numpy as jnp
        from . import mesh_exec
        ak_sh, a_chunk, a_group = mesh_aligned
        pool = self.frontier_pool
        for ci, c0 in enumerate(range(0, len(dense), cap)):
            chunk = dense[c0:c0 + cap]
            last_chunk = ci == n_chunks - 1
            launch_err = None
            fused_sel = None
            t_win0 = time.monotonic()
            t1 = time.monotonic()
            with self._lock:
                redo = snap.stale or snap.write_version != v0
                if not redo:
                    try:
                        faults.fire("kernel.launch")
                        # power-of-two buckets: meshed window programs
                        # are not precompiled by prewarm (meshed
                        # kernels compile per-query shapes), so smaller
                        # pads keep each first-seen compile cheap
                        bucket = self._window_bucket(len(chunk), cap,
                                                     False)
                        staged = pool.stage(
                            self._stack_frontiers(chunk, bucket))
                        f0s = staged.take()
                        # the window's compiled WHERE masks ride the
                        # sharded program too (one launch per chunk,
                        # no per-request host ANDs) — same fusion plan
                        # as the single-chip loop
                        fmasks, fsel = \
                            self._window_filter_plan(
                                chunk, bucket, plan_filter_cached)
                        fused_sel = fsel
                        t1 = time.monotonic()
                        masks = mesh_exec.multi_hop_masks_batch_sharded(
                            self.mesh, f0s, jnp.int32(steps), ak_sh,
                            snap.sharded_kernel, req_arr, a_chunk,
                            a_group, fmasks=fmasks,
                            fsel=None if fmasks is None
                            else jnp.asarray(fsel))
                        if fmasks is not None:
                            # an UNFILTERED meshed window runs the
                            # same program as pre-fusion — only count
                            # launches that actually fused WHERE masks
                            self.stats["fused_launches"] += 1
                        # the shard_map'd window does not take the
                        # donation (replicated operand) — expected
                        staged.after_launch(donate_expected=False)
                    except Exception as e:
                        launch_err = e
            if redo:
                # snapshot moved under the round: re-serve each through
                # the single-query path, which re-snapshots
                self._serve_singles([r for r, *_ in chunk], ex)
                self._mark_done([r for r, *_ in chunk],
                                early=not last_chunk)
                continue
            if launch_err is None:
                if last_chunk:
                    # window fully launched: hand the key back so
                    # window N+1's leader overlaps its dispatch with
                    # our wait
                    self._release_round(owner.key, owner)
                try:
                    pool.fetch_begin()
                    try:
                        masks_np = np.asarray(masks)   # wait OFF lock
                    finally:
                        pool.fetch_end()
                    # window D2H lands on the leader's query (module
                    # doc in common/ledger.py — solo windows exact)
                    _ledger.charge(d2h_bytes=masks_np.nbytes)
                except Exception as e:
                    launch_err = e
            if launch_err is not None:
                # mesh rung of the ladder: the failed window counts
                # against the mesh breaker (tripping it demotes the
                # space to single-device), and exactly this chunk's
                # requests retry — first per-request on the sharded
                # kernel, degrading to CPU in their own sessions if
                # that fails too
                self._mesh_failed("go_batched", launch_err, snap)
                self._serve_singles([r for r, *_ in chunk], ex)
                self._mark_done([r for r, *_ in chunk],
                                early=not last_chunk)
                continue
            t_kernel = time.monotonic() - t1
            sink: List[Tuple] = []
            served = 0
            with self._lock:
                self.stats["batched_dispatches"] += 1
                self.stats["batched_queries"] += len(chunk)
                stale2 = snap.stale or snap.write_version != v0
                win_us = (time.monotonic() - t_win0) * 1e6
                for i, entry in enumerate(chunk):
                    if self._serve_window_request(
                            entry, i, ci, len(chunk), stale2, win_us,
                            masks_np, None, plan_filter_cached, ex,
                            snap, t_snap, t_kernel, sink, meshed=True,
                            fused_sel=fused_sel):
                        served += 1
                # only queries the batched sharded dispatch actually
                # served — stale2 redos are charged by their own
                # single-query serve, never twice
                self.stats["sharded_queries"] += served
            if served:
                self._mesh_served("go_batched", served)
            if sink:
                self._encode_sink(sink)
            self._mark_done([r for r, *_ in chunk],
                            early=not last_chunk)

    def _window_bucket(self, n: int, cap: int, lane_path: bool) -> int:
        """Pad size of a window chunk's root axis, so XLA compiles FEW
        shapes, never past the memory-derived cap (the 1GiB mask
        budget must hold for the PADDED batch too); zero frontiers
        produce empty masks and carry no request.
        - lane path: exactly TWO buckets (small, cap) — both
          precompiled by prewarm, so no cold compile ever lands inside
          a round;
        - delta/vmapped/meshed rounds: power-of-two buckets (those
          programs compile per-seen shape — smaller pads keep each
          first-seen compile cheap)."""
        if lane_path:
            return min(self.SMALL_BUCKET, cap) \
                if n <= self.SMALL_BUCKET else cap
        bucket = 1
        while bucket < n:
            bucket *= 2
        return min(bucket, cap)

    @staticmethod
    def _stack_frontiers(chunk, bucket: int) -> np.ndarray:
        """One window chunk's [bucket, P, cap_v] host frontier stack
        (zero-padded) — the array the FrontierPool stages to device."""
        stack = [f for _, f, _, _ in chunk]
        if bucket > len(chunk):
            stack.extend([np.zeros_like(stack[0])]
                         * (bucket - len(chunk)))
        return np.stack(stack)

    def _window_filter_plan(self, chunk, bucket: int,
                            plan_filter_cached):
        """Per-lane compiled-WHERE fusion plan for one window chunk:
        -> (fmasks [NF, P, cap_e] device stack | None,
            fsel int32[bucket] | None).
        Distinct compiled device masks (by identity — the per-snapshot
        PR 5 rung dedupes equal WHERE shapes to one array) stack into
        the fused program's filter operand; each lane selects its own
        via fsel (-1 = no device filter). Runs under the engine lock
        (the filter compiler reads delta-mutable mirrors); a lane
        whose plan raises stays UNFUSED (fsel -1) and resolves per-
        request in phase 3 — fsel, not a window-wide flag, is what
        phase 3 consults, so a plan that raises here but succeeds
        there still ANDs its mask on the host. Windows mixing more
        shapes than MAX_WINDOW_FILTERS decline fusion wholesale
        (counted) so the operand bucket space stays bounded."""
        import jax.numpy as jnp
        distinct: List[Any] = []
        ids: Dict[int, int] = {}
        sel = np.full(bucket, -1, np.int32)
        for i, (r, *_rest) in enumerate(chunk):
            try:
                dm, _lf = plan_filter_cached(r)
            except Exception:
                continue   # phase 3 re-raises per-request
            if dm is None:
                continue
            j = ids.get(id(dm))
            if j is None:
                j = ids[id(dm)] = len(distinct)
                distinct.append(dm)
            sel[i] = j
        if not distinct:
            return None, None
        if len(distinct) > fused.MAX_WINDOW_FILTERS:
            self.stats["fused_declined"] += 1
            return None, None
        nf = fused.filter_bucket(len(distinct))
        pads = [distinct[0]] * (nf - len(distinct))
        return jnp.stack(list(distinct) + pads), sel

    def _serve_chunk_loop(self, dense, cap, n_chunks, snap, v0, steps,
                          use_delta, req_arr, owner, plan_filter_cached,
                          ex, t_snap, claimed) -> None:
        import jax.numpy as jnp
        pool = self.frontier_pool
        staged_next = None   # (chunk idx, _Staged): prefetched H2D
        lane_state = [not use_delta]   # bucket prediction for prefetch
        for ci, c0 in enumerate(range(0, len(dense), cap)):
            chunk = dense[c0:c0 + cap]
            last_chunk = ci == n_chunks - 1
            launch_err = None
            fused_sel = None
            host_stack = None
            kernel_cal = None
            t_win0 = time.monotonic()
            t1 = time.monotonic()
            with self._lock:
                redo = snap.stale or snap.write_version != v0
                if not redo:
                    try:
                        faults.fire("kernel.launch")
                        aligned = snap.aligned_ready() \
                            if not use_delta and steps >= 1 \
                            and len(chunk) > 1 else None
                        if aligned is not None and \
                                getattr(snap, "batched_kernel_pick",
                                        None) == "vmap":
                            # measured on THIS backend: the vmapped
                            # batch beats the lane-matrix layout
                            aligned = None
                        lane_state[0] = aligned is not None
                        bucket = self._window_bucket(
                            len(chunk), cap, aligned is not None)
                        host_stack = self._stack_frontiers(chunk,
                                                           bucket)
                        # double-buffered H2D: consume the transfer
                        # prefetched during the PREVIOUS chunk's
                        # kernel wait, or stage fresh
                        staged = None
                        if staged_next is not None:
                            pci, st = staged_next
                            staged_next = None
                            if pci == ci and st.shape == \
                                    host_stack.shape:
                                staged = st
                                pool.hit()
                            else:
                                pool.miss()
                        if staged is None:
                            staged = pool.stage(host_stack)
                        f0s = staged.take()
                        t1 = time.monotonic()
                        if use_delta:
                            # delta windows keep the unfused kernels:
                            # the compiled-filter rung declines with
                            # buffered adds in play (no device mask
                            # exists to fuse) and delta shapes vary
                            # with the buffer
                            masks, dmasks = \
                                traverse.multi_hop_roots_delta(
                                    f0s, jnp.int32(steps), snap.kernel,
                                    snap.delta.device(), req_arr)
                            staged.after_launch(donate_expected=False)
                        else:
                            # ONE fused launch per chunk: hop advance,
                            # final canonical gather and the window's
                            # compiled WHERE masks in a single device
                            # program — no per-request host filter
                            # ANDs, no intermediate sync
                            fmasks, fsel = \
                                self._window_filter_plan(
                                    chunk, bucket, plan_filter_cached)
                            fused_sel = fsel
                            fsel_op = None if fmasks is None \
                                else jnp.asarray(fsel)
                            nf = 0 if fmasks is None \
                                else int(fmasks.shape[0])
                            dmasks = None
                            if aligned is not None:
                                ak, a_chunk, a_group = aligned
                                if getattr(snap,
                                           "batched_kernel_pick",
                                           None) is None:
                                    # claim the one-shot lane-vs-
                                    # vmapped calibration; the timing
                                    # runs OFF the lock in phase 2
                                    snap.batched_kernel_pick = \
                                        "calibrating"
                                    claimed[0] = True
                                    kernel_cal = (ak, a_chunk,
                                                  a_group)
                                fn = self._fused_entry(
                                    snap,
                                    ("win_lane", bucket, nf, a_chunk,
                                     a_group),
                                    lambda: partial(
                                        fused.window_lane,
                                        chunk=a_chunk,
                                        group=a_group))
                                masks = fn(f0s, jnp.int32(steps), ak,
                                           snap.kernel, req_arr,
                                           fmasks, fsel_op)
                                self.stats["batched_lane_rounds"] += 1
                            else:
                                fn = self._fused_entry(
                                    snap, ("win_vmap", bucket, nf),
                                    lambda: fused.window_vmap)
                                masks = fn(f0s, jnp.int32(steps),
                                           snap.kernel, req_arr,
                                           fmasks, fsel_op)
                            self.stats["fused_launches"] += 1
                            # donation can only alias when the output
                            # matches the donated buffer's byte size
                            # (masks are [b,P,cap_e], the frontier
                            # [b,P,cap_v]) — audit a fallback only
                            # when aliasing was actually possible
                            staged.after_launch(
                                donate_expected=int(masks.nbytes) ==
                                int(np.prod(staged.shape)))
                    except Exception as e:
                        launch_err = e
            if redo:
                # snapshot moved under the round (delta apply /
                # poison): each request re-serves through the exact
                # single-query path, which re-snapshots
                self._serve_singles([r for r, *_ in chunk], ex)
                self._mark_done([r for r, *_ in chunk],
                                early=not last_chunk)
                continue
            if launch_err is None:
                if last_chunk:
                    # the window's device work is all launched: hand
                    # the key back NOW so window N+1's leader can claim
                    # and launch while we wait for masks + materialize
                    self._release_round(owner.key, owner)
                elif staged_next is None:
                    # prefetch slot: start the NEXT chunk's frontier
                    # H2D now, so the transfer rides under THIS
                    # chunk's kernel wait (the second slot of the
                    # donated-buffer pool)
                    try:
                        nxt = dense[c0 + cap:c0 + 2 * cap]
                        nb = self._window_bucket(len(nxt), cap,
                                                 lane_state[0])
                        staged_next = (ci + 1, pool.stage(
                            self._stack_frontiers(nxt, nb)))
                    except Exception:
                        staged_next = None
                # device wait OFF the engine lock (jax releases the
                # GIL): another group's round — or the next window of
                # this key — runs its host phases meanwhile. An async
                # dispatch error surfaces HERE at the fetch.
                try:
                    pool.fetch_begin()
                    try:
                        masks_np = np.asarray(masks)
                        dmasks_np = None if dmasks is None \
                            else np.asarray(dmasks)
                    finally:
                        pool.fetch_end()
                    _ledger.charge(d2h_bytes=masks_np.nbytes + (
                        dmasks_np.nbytes if dmasks_np is not None
                        else 0))
                except Exception as e:
                    launch_err = e
            if launch_err is not None:
                # failure isolation: exactly this chunk's waiters wake
                # with result=None and re-serve on the CPU pipe in
                # their own sessions — other groups, other chunks, and
                # later windows are untouched, and the round key is
                # handed back by the owner's finally
                self._device_failed("go", launch_err)
                for r, *_ in chunk:
                    if not r.done:
                        r.result = None
                        with _tr.use(r.tctx):
                            _tr.tag_root("degraded", "window_failed")
                self._mark_done([r for r, *_ in chunk],
                                early=not last_chunk)
                continue
            t_kernel = time.monotonic() - t1
            if kernel_cal is not None:
                # one-shot lane-vs-vmapped timing, also OFF the lock —
                # the extra dispatches never stall the engine, only
                # this first window's own materialization start. The
                # HOST stack is passed (the serving launch DONATED the
                # device buffer; the probe restages its own copies).
                self._calibrate_batched_kernel(snap, host_stack, steps,
                                               *kernel_cal, req_arr)
                claimed[0] = False   # resolved (or reset) by the call
            sink: List[Tuple] = []
            with self._lock:
                # counters under the lock: concurrent rounds would
                # otherwise race the read-add-store (lost increments)
                self.stats["batched_dispatches"] += 1
                self.stats["batched_queries"] += len(chunk)
                stale2 = snap.stale or snap.write_version != v0
                win_us = (time.monotonic() - t_win0) * 1e6
                for i, entry in enumerate(chunk):
                    self._serve_window_request(
                        entry, i, ci, len(chunk), stale2, win_us,
                        masks_np, dmasks_np, plan_filter_cached, ex,
                        snap, t_snap, t_kernel, sink, meshed=False,
                        fused_sel=fused_sel)
            if sink:
                self._encode_sink(sink)
            self._mark_done([r for r, *_ in chunk], early=not last_chunk)

    def _serve_window_request(self, entry, i, ci, window, stale2,
                              win_us, masks_np, dmasks_np,
                              plan_filter_cached, ex, snap, t_snap,
                              t_kernel, sink, meshed,
                              fused_sel=None) -> bool:
        """One request of a batched window, under the engine lock —
        the per-request tail SHARED by the meshed and single-chip
        chunk loops. Per-request spans (the shared window launch +
        this request's own materialize, via _record_profile) record
        into the OWNER's trace; a stale snapshot redoes through the
        single-query path and a failure degrades to the CPU pipe in
        the owner's session. Returns True only when the batched
        dispatch actually served the request (mesh accounting: stale2
        redos are charged by their own single-query serve)."""
        r, _f0, yield_cols, columns = entry
        with _tr.use(r.tctx), _ledger.use(r.ledger):
            try:
                if stale2:
                    r.result = self._execute_go_locked(
                        r.ctx, r.s, r.starts, r.edge_types,
                        r.alias_map, r.name_by_type, ex, r.yield_cols)
                    return False
                _tr.add_span("dispatcher.window", win_us,
                             window=window, chunk=ci, meshed=meshed)
                if r.ledger is not None:
                    # wall time of the shared window this request rode
                    # (the span twin above carries the same number)
                    r.ledger.window_share_us += int(win_us)
                device_mask, local_filter = plan_filter_cached(r)
                mask = masks_np[i]
                if device_mask is not None and \
                        (fused_sel is None or fused_sel[i] < 0):
                    # this LANE's mask was not fused (delta round, a
                    # window that mixed too many WHERE shapes, or a
                    # plan that raised at fusion time and only
                    # succeeded on this retry): the compiled mask
                    # still ANDs in here, per request, like pre-fusion
                    mask = mask & np.asarray(device_mask)
                d_mask = dmasks_np[i] if dmasks_np is not None else None
                r.result = self._go_emit_dense(
                    r.ctx, r.s, snap, mask, d_mask, local_filter,
                    yield_cols, columns, r.alias_map, r.name_by_type,
                    ex, r.edge_types, t_snap, t_kernel,
                    sink=sink, sink_req=r)
                return True
            except Exception as e:
                self._device_failed("go", e)
                r.result = None    # CPU pipe re-serves it
                return False

    def _calibrate_batched_kernel(self, snap, host_f0s, steps, ak,
                                  a_chunk, a_group, req_arr):
        """Measured lane-vs-vmapped routing for batched windows, once
        per snapshot: the lane-matrix kernel is the layout the TPU
        wants (edge/index streams read once per hop for the whole
        window), but fallback backends execute the plain vmapped batch
        several times faster — XLA:CPU measures ~5x on the SNB bench
        shape. Modeled preferences go stale; this is the
        calibrate_sparse_budget discipline applied to kernel choice.

        The probe times the FUSED window programs the dispatcher
        actually launches (the registry entries — window_lane served
        this very round, so its timing pass is warm), not the unfused
        kernels the pre-fusion probe measured: a pick made against the
        old cost model would pin the slower variant for the snapshot's
        whole life. Each timed call restages the frontier stack from
        the HOST copy (the serving launch donated the device buffer),
        so both variants pay the same per-window H2D production pays.

        Runs OFF the engine lock (kernel buffers are immutable device
        arrays) on the first window's live frontiers, compiles excluded
        from timing; a failure resets the claim so a later window
        retries."""
        import jax.numpy as jnp
        s32 = jnp.int32(steps)
        bucket = host_f0s.shape[0]
        try:
            lane_fn = self._fused_entry(
                snap, ("win_lane", bucket, 0, a_chunk, a_group),
                lambda: partial(fused.window_lane, chunk=a_chunk,
                                group=a_group))
            vmap_fn = self._fused_entry(
                snap, ("win_vmap", bucket, 0),
                lambda: fused.window_vmap)

            def lane():
                return lane_fn(jnp.asarray(host_f0s), s32, ak,
                               snap.kernel, req_arr, None, None)

            def vmap():
                return vmap_fn(jnp.asarray(host_f0s), s32,
                               snap.kernel, req_arr, None, None)

            # compiles outside timing: the lane program just served
            # the round (warm unless the round ran filtered — one
            # warm call makes both cases uniform), the vmapped one
            # compiles here
            lane().block_until_ready()
            vmap().block_until_ready()
            t0 = time.monotonic()
            lane().block_until_ready()
            lane_s = time.monotonic() - t0
            t0 = time.monotonic()
            vmap().block_until_ready()
            vmap_s = time.monotonic() - t0
        except Exception:
            # never fail the window over a calibration probe: keep the
            # lane default and let a later window retry
            snap.batched_kernel_pick = None
            _LOG.exception("batched kernel calibration failed "
                           "(space %d)", snap.space_id)
            return
        pick = "lane" if lane_s <= vmap_s else "vmap"
        snap.batched_kernel_pick = pick
        rec = {"lane_ms": round(lane_s * 1e3, 1),
               "vmap_ms": round(vmap_s * 1e3, 1), "pick": pick,
               "fused": True}
        self.batched_kernel_calibrations[snap.space_id] = rec
        global_stats.add_value("tpu_engine.batched_kernel_pick_" + pick,
                               kind="counter")
        _LOG.info("batched kernel calibrated (space %d): %s",
                  snap.space_id, rec)

    def _execute_go_locked(self, ctx, s, starts, edge_types, alias_map,
                           name_by_type, ex, yield_cols=None):
        t0 = time.monotonic()
        snap = self._snapshot_locked(ctx.space_id())
        t_snap = time.monotonic() - t0
        if snap is None:
            self.stats["fallbacks"] += 1
            return None

        if yield_cols is None:
            yield_cols = ex._go_yield_columns(s, ctx, name_by_type)
        columns = [c.name() for c in yield_cols]
        exprs = [c.expr for c in yield_cols]
        if s.where is not None:
            exprs.append(s.where.filter)
        needs_input = _uses_input_refs(exprs)
        upto = bool(s.step.upto)
        if (needs_input or upto) and \
                getattr(snap, "sharded_kernel", None) is not None:
            self.stats["fallbacks"] += 1
            return None   # mesh-sharded kernels serve the plain form only
        if upto and not 1 <= int(s.step.steps) <= self.MAX_DEVICE_STEPS:
            self.stats["fallbacks"] += 1
            return None   # 0 steps / huge N: the CPU loop serves exactly

        frontier0 = snap.frontier_from_vids(starts)
        if not frontier0.any():
            return StatusOr.of(ex.InterimResult(columns))
        import jax.numpy as jnp
        f0 = jnp.asarray(frontier0)
        _ledger.charge(h2d_bytes=frontier0.nbytes)
        req = jnp.asarray(traverse.pad_edge_types(edge_types))

        use_delta = snap.delta is not None and snap.delta.edge_count > 0
        if needs_input:
            return self._go_roots(ctx, s, starts, req, edge_types, snap,
                                  use_delta, yield_cols, columns, alias_map,
                                  name_by_type, ex, t_snap)
        if upto:
            return self._go_upto(ctx, s, f0, req, edge_types, snap,
                                 use_delta, yield_cols, columns, alias_map,
                                 name_by_type, ex, t_snap)
        # direction-optimized execution: a frontier that stays small is
        # served by a host-mirror pull over the snapshot (O(frontier
        # edges)) instead of the dense device dispatch (O(E) per hop) —
        # at SNB scale a selective 3-hop GO touches ~10^4 edges while
        # the dense path reads all 10^8 slots every hop
        if getattr(snap, "sharded_kernel", None) is None:
            t1 = time.monotonic()
            sparse = self._sparse_expand(snap, starts, edge_types,
                                         int(s.step.steps))
            t_kernel = time.monotonic() - t1
            if sparse is not None:
                return self._emit_sparse(ctx, s, snap, sparse, yield_cols,
                                         columns, alias_map, name_by_type,
                                         ex, edge_types, t_snap, t_kernel)
        if self._deadline_exceeded(ctx, "kernel"):
            self.stats["fallbacks"] += 1
            return None    # budget spent before the dense dispatch
        faults.fire("kernel.launch")
        device_mask, local_filter = self._plan_filter(
            ctx, s, snap, use_delta, name_by_type, alias_map, edge_types)

        d_active = None
        t1 = time.monotonic()
        if getattr(snap, "sharded_kernel", None) is not None:
            from . import distributed
            _, active = distributed.multi_hop_sharded(
                self.mesh, f0, jnp.int32(s.step.steps),
                snap.sharded_kernel, req)
            self.stats["sharded_queries"] += 1
        elif use_delta:
            _, active, d_active = traverse.multi_hop_delta(
                f0, s.step.steps, snap.kernel, snap.delta.device(), req)
        else:
            _, active = traverse.multi_hop(f0, s.step.steps, snap.kernel,
                                           req)
        if device_mask is not None:
            active = active & device_mask
        mask = np.asarray(active)
        t_kernel = time.monotonic() - t1
        d_mask = None if d_active is None else np.asarray(d_active)
        _ledger.charge(d2h_bytes=mask.nbytes + (
            d_mask.nbytes if d_mask is not None else 0))
        return self._go_emit_dense(ctx, s, snap, mask, d_mask,
                                   local_filter, yield_cols, columns,
                                   alias_map, name_by_type, ex, edge_types,
                                   t_snap, t_kernel)

    def _go_emit_dense(self, ctx, s, snap, mask, d_mask, local_filter,
                       yield_cols, columns, alias_map, name_by_type, ex,
                       edge_types, t_snap, t_kernel, sink=None,
                       sink_req=None):
        """Materialize one dense GO result from its final-hop numpy
        masks — the tail shared by the single-query path and the
        cross-session batched dispatcher (each batch member lands here
        with its own slice of the shared device dispatch).

        Deferred fast path: when every YIELD column has a typed form
        and no delta rows / per-row filter / DISTINCT are in play, the
        result rows stay COLUMNS here — encoded to row bytes by one
        native GIL-released call (materialize.encode_window) and boxed
        into Python tuples only in the owning session's thread
        (_finalize_result). With `sink` the typed gather is appended
        for the WINDOW-level encode instead of encoding per query."""
        if self._deadline_exceeded(ctx, "materialize"):
            return None    # budget spent: the CPU pipe serves it
        t2 = time.monotonic()
        # the device compile may have been declined (e.g. delta edges in
        # play, _plan_filter): still avoid the per-row Python walk over
        # the canonical rows with the vectorized host evaluator
        host_hf, local_filter, delta_rf = self._plan_host_filter(
            ctx, snap, local_filter, name_by_type, alias_map, edge_types)
        idx_per_part = None
        if host_hf is not None:
            idx_per_part = self._apply_host_filter(host_hf, snap, mask)
        d_any = d_mask is not None and d_mask.any()
        if local_filter is None and not d_any \
                and not (s.yield_ and s.yield_.distinct):
            gathered = materialize.gather_for_encode(
                ctx.sm, ctx.space_id(), snap, mask, yield_cols,
                alias_map, name_by_type, idx_per_part=idx_per_part)
            if gathered is not None:
                result = ex.InterimResult(columns)
                if sink is not None:
                    # _tpu_deferred is attached by the window-level
                    # encode in _serve_group (an encode failure errors
                    # the request — never a silent empty result)
                    sink.append((sink_req, gathered, t2))
                else:
                    t3 = time.monotonic()
                    encs, native_used = materialize.encode_window(
                        [gathered])
                    self._count_encode(len(encs[0]), native_used)
                    result._tpu_deferred = encs[0]
                    _tr.add_span("encode",
                                 (time.monotonic() - t3) * 1e6,
                                 rows=len(encs[0]), native=native_used)
                self.stats["fast_materialize"] += 1
                self.stats["go_served"] += 1
                self._record_profile("dense", t_snap, t_kernel,
                                     time.monotonic() - t2, snap)
                return StatusOr.of(result)
        rows: Optional[List[Tuple]] = None
        if local_filter is None:
            # columnar fast path: one numpy gather per YIELD column over
            # the host mirrors; declines (None) on any case whose CPU
            # semantics aren't a pure gather — identity by construction
            rows = materialize.emit_rows(snap, mask, ctx, yield_cols,
                                         alias_map, name_by_type,
                                         idx_per_part=idx_per_part)
        if rows is not None:
            self.stats["fast_materialize"] += 1
        else:
            self.stats["slow_materialize"] += 1
            resp = self._materialize(snap, mask, ctx, yield_cols, s,
                                     idx_per_part=idx_per_part)
            rows = []
            st = ex._emit_go_rows(ctx, resp, rows, yield_cols, local_filter,
                                  alias_map, name_by_type, roots={},
                                  input_index={}, needs_input=False,
                                  needs_dst=_needs_dst(yield_cols, s))
            if not st.ok():
                return StatusOr.from_status(st)
        if d_mask is not None and d_mask.any():
            # cap accounting must see the POST-filter base rows
            # (the CPU hot loop counts only filter-passing edges
            # toward max_edges_per_vertex, processors.py:235-244);
            # delta rows are likewise filtered (row_filter) BEFORE
            # cap counting, then emitted unfiltered
            base_for_cap = idx_per_part if idx_per_part is not None \
                else mask
            delta_resp = self._materialize_delta(snap, d_mask,
                                                 base_for_cap,
                                                 ctx, yield_cols, s,
                                                 row_filter=delta_rf)
            st = ex._emit_go_rows(ctx, delta_resp, rows, yield_cols,
                                  local_filter, alias_map, name_by_type,
                                  roots={}, input_index={},
                                  needs_input=False,
                                  needs_dst=_needs_dst(yield_cols, s))
            if not st.ok():
                return StatusOr.from_status(st)
        result = ex.InterimResult(columns, rows)
        if s.yield_ and s.yield_.distinct:
            result = result.distinct()
        self.stats["go_served"] += 1
        self._record_profile("dense", t_snap, t_kernel,
                             time.monotonic() - t2, snap)
        return StatusOr.of(result)

    # ------------------------------------------------------------------
    # GO | YIELD <aggregates> on device (bound_stats role on TPU)
    # ------------------------------------------------------------------
    def execute_go_aggregate(self, ctx, s: ast.GoSentence, specs,
                             out_cols: List[str], starts: List[int],
                             edge_types: List[int],
                             alias_map: Dict[str, str],
                             name_by_type: Dict[int, str],
                             group_layout: Optional[List] = None):
        """Ladder wrapper for the aggregation pushdown: an open "agg"
        breaker (or any device exception) degrades the query to the
        CPU pipe — counted, never client-visible (see execute_go).
        Aggregate results ride the snapshot-versioned result cache too
        (cache_mode=full; rows are tiny and the reductions are the
        expensive half of the stats surface) — checked BEFORE the
        breaker gate, same warm-cache-under-breaker rationale as GO."""
        heat_tok = self._heat_note_query(ctx, starts)
        try:
            return self._execute_go_aggregate_outer(
                ctx, s, specs, out_cols, starts, edge_types, alias_map,
                name_by_type, group_layout)
        finally:
            _heat.restore(heat_tok)

    def _execute_go_aggregate_outer(self, ctx, s, specs, out_cols,
                                    starts, edge_types, alias_map,
                                    name_by_type, group_layout):
        ck = self._agg_cache_key(ctx, s, specs, out_cols, starts,
                                 edge_types, alias_map, group_layout)
        if ck is not None:
            hit = self._result_cache_get(ck)
            if hit is not None:
                return hit
        if not self._device_admit("agg", ctx):
            return None
        try:
            r = self._execute_go_aggregate_checked(
                ctx, s, specs, out_cols, starts, edge_types, alias_map,
                name_by_type, group_layout)
        except Exception as e:
            return self._device_failed("agg", e)
        if r is not None:
            self._device_ok("agg")
            if ck is not None:
                self._result_cache_put(ck, r)
        return r

    def _agg_cache_key(self, ctx, s, specs, out_cols, starts,
                       edge_types, alias_map, group_layout):
        """Result-cache key for the aggregation pushdown (same layout
        contract as _go_cache_key: space at [1], token at [3],
        catalog at [4])."""
        if not result_stage_enabled(graph_flags) or \
                self._provider is None or not self.enabled:
            return None
        try:
            space = ctx.space_id()
            token = self._provider.version(space)
            if token is None:
                return None
            where_enc = encode_expression(s.where.filter) \
                if s.where is not None else None
            specs_sig = tuple(
                (fun, None if e is None else (e.edge, e.prop))
                for fun, e in specs)
        except Exception:
            return None
        return ("agg", space, int(s.step.steps), token,
                self._catalog_version(), tuple(edge_types),
                tuple(starts), tuple(sorted(alias_map.items())),
                where_enc, specs_sig, tuple(out_cols),
                None if group_layout is None else tuple(group_layout))

    def _execute_go_aggregate_checked(self, ctx, s: ast.GoSentence,
                                      specs, out_cols: List[str],
                                      starts: List[int],
                                      edge_types: List[int],
                                      alias_map: Dict[str, str],
                                      name_by_type: Dict[int, str],
                                      group_layout: Optional[List] = None):
        """Serve `GO … | YIELD <aggregates>` (and `GO … | GROUP BY
        $-.<dst> YIELD …`) as a masked device reduction over the
        final-hop edge block instead of materializing rows (ref role:
        QueryStatsProcessor / storage.thrift bound_stats :65-69;
        device math in aggregate.py). `specs` is
        [(fun, EdgePropExpr|None)]; without `group_layout` the result
        is one row aligned with `out_cols`; with it the reduction is
        segmented by the edge's dst and `group_layout` orders
        each row's cells: "key" emits the group's dst vid, an int
        emits that spec's aggregate. Returns a Result, or None to
        fall back to the CPU pipe — every declined case (non-
        vectorizable filter, non-int props, err cells the CPU
        would raise EvalError for) keeps CPU≡TPU identity by
        construction, and every decline is counted by reason
        (`agg_decline_reasons`; /get_stats
        `tpu_engine.agg_declined.<reason>`).

        Routing (round-4 verdict item 2): small frontiers are served
        by an exact host reduction over the SAME sparse pull the GO
        path uses (`_aggregate_sparse`) — the pulled edge set is
        reduced directly instead of being re-traversed and
        materialized through the CPU pipe; large frontiers take the
        masked device reduction. Structural declines (prop types,
        edge-type count) are decided BEFORE the engine lock and
        snapshot are taken, so a structurally-declined stats query
        costs schema lookups, not a snapshot check + discarded walk."""
        from ..graph import executors as ex
        if len(edge_types) > traverse.MAX_EDGE_TYPES_PER_QUERY:
            return self._agg_decline("too_many_edge_types")
        # pre-lock structural check: every non-COUNT spec must read an
        # int-typed edge prop (the exactness surface) — schema lookups
        # only, no snapshot / engine lock needed. The verdict is
        # NEGATIVE-CACHED per (specs, edge types, catalog version)
        # under cache_mode=full: the same declined stats query used to
        # re-walk the schema per execution; the per-query decline
        # COUNTERS still bump on every served query (the decline
        # matrix stays an accounting ledger).
        nk = None
        if result_stage_enabled(graph_flags):
            try:
                nk = ("aggpre", ctx.space_id(), self._catalog_version(),
                      tuple((fun, None if e is None else (e.edge, e.prop))
                            for fun, e in specs),
                      tuple(edge_types),
                      tuple(sorted(alias_map.items())))
            except Exception:
                nk = None
        verdict = self.negative_cache.get(nk) if nk is not None else None
        if verdict is None:
            verdict = self._agg_structural_reason(
                ctx, specs, edge_types, alias_map, name_by_type) or "ok"
            if nk is not None:
                self.negative_cache.put(nk, verdict)
        if verdict != "ok":
            return self._agg_decline(verdict)
        with self._lock:
            return self._go_aggregate_locked(ctx, s, specs, out_cols,
                                             starts, edge_types, alias_map,
                                             name_by_type, ex, group_layout)

    def _agg_structural_reason(self, ctx, specs, edge_types, alias_map,
                               name_by_type) -> Optional[str]:
        """The schema walk behind the aggregation pre-check: the
        decline reason, or None when the pushdown may proceed."""
        from ..codec.schema import PropType
        for fun, e in specs:
            if e is None:
                continue
            types = edge_types
            if e.edge is not None:
                canon = alias_map.get(e.edge, e.edge)
                types = [t for t in edge_types
                         if name_by_type.get(abs(t)) == canon]
                if not types:
                    return "prop_outside_over"
            seen = False
            for t in types:
                r = self._sm.edge_schema(ctx.space_id(), abs(t))
                ft = r.value().field_type(e.prop) if r.ok() else None
                if ft is None:
                    continue
                seen = True
                if ft in (PropType.DOUBLE, PropType.STRING, PropType.BOOL):
                    return "non_int_prop"
            if not seen:
                # no traversed type carries the prop: the CPU raises
                return "prop_not_found"
        return None

    @classmethod
    def _dispatch_cap(cls, snap) -> int:
        """Per-round root cap: the padded batch's [B, P, cap_e] masks
        must stay under a ~1GiB budget (and under the fixed lane
        width)."""
        return max(min(cls.MAX_DISPATCH_BATCH,
                       (1 << 30) // max(snap.num_parts * snap.cap_e, 1)),
                   1)

    def _agg_decline(self, reason: str):
        """Count one aggregation-pushdown decline (engine stats +
        /get_stats) and return None so the CPU pipe serves. The
        structural pre-checks call this before the engine lock, hence
        the stats lock."""
        with self._stats_lock:
            self.stats["agg_declined"] += 1
            self.agg_decline_reasons[reason] = \
                self.agg_decline_reasons.get(reason, 0) + 1
        global_stats.add_value("tpu_engine.agg_declined." + reason,
                               kind="counter")
        return None

    def _go_aggregate_locked(self, ctx, s, specs, out_cols, starts,
                             edge_types, alias_map, name_by_type, ex,
                             group_layout=None):
        from . import aggregate
        from .filter_compile import FilterCompiler, _Unsupported
        t0 = time.monotonic()
        snap = self._snapshot_locked(ctx.space_id())
        t_snap = time.monotonic() - t0
        if snap is None:
            self.stats["fallbacks"] += 1
            return self._agg_decline("no_snapshot")
        meshed = getattr(snap, "sharded_kernel", None) is not None

        def _decl(reason):
            # meshed declines also land in the mesh matrix, so the
            # operator can see WHICH features switch off on the mesh
            if meshed:
                self._mesh_decline("agg", reason)
            return self._agg_decline(reason)
        frontier0 = snap.frontier_from_vids(starts)
        if not frontier0.any():
            if group_layout is not None:   # GROUP BY of nothing: no rows
                return StatusOr.of(ex.InterimResult(out_cols))
            row = tuple(0 if f == "COUNT" else None for f, _ in specs)
            return StatusOr.of(ex.InterimResult(out_cols, [row]))
        # small frontiers: reduce the sparse pull directly — the same
        # pulled edge set the GO path would materialize, aggregated
        # exactly on the host without rows ever flowing through the
        # pipe (round-4 verdict: this case declined to the CPU pipe,
        # which re-traversed from scratch; 0/3 bench queries served)
        if getattr(snap, "sharded_kernel", None) is None:
            t1 = time.monotonic()
            sparse = self._sparse_expand(snap, starts, edge_types,
                                         int(s.step.steps))
            t_walk = time.monotonic() - t1
            if sparse is not None:
                return self._aggregate_sparse(
                    ctx, s, specs, out_cols, snap, sparse, edge_types,
                    alias_map, name_by_type, ex, group_layout, t_snap,
                    t_walk)
        if snap.delta is not None and snap.delta.edge_count > 0:
            # dense path only: buffered adds live outside the canonical
            # block the device reduction scans; the CPU pipe aggregates
            # them exactly (the sparse path above handles delta rows)
            return _decl("delta_adds")
        device_mask, local_filter = self._plan_filter(
            ctx, s, snap, False, name_by_type, alias_map, edge_types)
        if local_filter is not None:
            return _decl("filter_not_compilable")
        fc = FilterCompiler(snap, self._sm, ctx.space_id(), name_by_type,
                            alias_map, edge_types)
        # value columns for SUM/AVG/MIN/MAX — int-only (exactness)
        vals: Dict[Any, Any] = {}
        keyed_specs = []
        for fun, e in specs:
            if fun == "COUNT":
                keyed_specs.append((fun, None))
                continue
            key = (e.edge, e.prop)
            if key not in vals:
                try:
                    allowed = None
                    if e.edge is not None:
                        canon = alias_map.get(e.edge, e.edge)
                        allowed = [t for t in edge_types
                                   if name_by_type.get(abs(t)) == canon]
                        if not allowed:
                            return _decl("prop_outside_over")
                    v = fc._edge_prop_val(e.prop, allowed)
                except _Unsupported:
                    return _decl("prop_not_compilable")
                if v.kind != "num" or v.intlike is not True:
                    return _decl("non_int_prop")
                vals[key] = v
            keyed_specs.append((fun, key))
        # every LEFT yield column the CPU would evaluate per row can
        # raise EvalError on err cells — compile their err masks too
        # (underscore pseudo-props never err)
        from ..filter.expressions import (EdgeDstIdExpr, EdgePropExpr,
                                          EdgeRankExpr, EdgeSrcIdExpr,
                                          EdgeTypeExpr)
        err_masks = [v.err for v in vals.values()]
        for c in ex._go_yield_columns(s, ctx, name_by_type):
            e = c.expr
            if isinstance(e, (EdgeDstIdExpr, EdgeSrcIdExpr, EdgeRankExpr,
                              EdgeTypeExpr)):
                continue    # pseudo-props read key parts, never err
            if isinstance(e, EdgePropExpr) and e.prop.startswith("_"):
                continue
            try:
                err_masks.append(fc._compile(e).err)
            except _Unsupported:
                return _decl("yield_not_compilable")
        import jax.numpy as jnp
        import jax
        f0 = jnp.asarray(frontier0)
        req = jnp.asarray(traverse.pad_edge_types(edge_types))
        shape = (snap.num_parts, snap.cap_e)
        # fold every err mask into ONE program operand: the audit that
        # used to pay one jnp.any host sync PER mask rides the fused
        # program (fused.py; docs/manual/13-device-speed.md)
        err_comb = fused.combine_err_masks(err_masks, shape)
        faults.fire("kernel.launch")
        t1 = time.monotonic()
        if not meshed and group_layout is None:
            # fully fused ungrouped pushdown: traversal + compiled
            # WHERE + err audit + exact per-column partials in ONE
            # launch / ONE fetch (exactness identical to
            # aggregate.reduce_specs — see fused.agg_reduce)
            key_list = list(vals.keys())
            key_index = {k2: i for i, k2 in enumerate(key_list)}
            if key_list:
                values_op = jnp.stack([
                    jnp.broadcast_to(
                        jnp.asarray(vals[k2].value, jnp.int32), shape)
                    for k2 in key_list])
                nulls_op = jnp.stack([
                    jnp.broadcast_to(jnp.asarray(vals[k2].null, bool),
                                     shape)
                    for k2 in key_list])
            else:
                values_op = nulls_op = None
            cs = min(aggregate.SUM_CHUNK, max(snap.cap_e, 1))
            fn = self._fused_entry(
                snap, ("agg", len(key_list), device_mask is not None,
                       err_comb is not None, cs),
                lambda: partial(fused.agg_reduce, chunk_slots=cs))
            err_any, n_rows, parts = jax.device_get(
                fn(f0, jnp.int32(int(s.step.steps)), snap.kernel, req,
                   device_mask, err_comb, values_op, nulls_op))
            self.stats["fused_launches"] += 1
            t_kernel = time.monotonic() - t1
            if bool(err_any):
                # CPU raises EvalError for these rows
                return _decl("err_cells")
            row = fused.assemble_agg_row(keyed_specs, key_index,
                                         int(n_rows), parts)
            self.stats["agg_served"] += 1
            self._record_profile("aggregate", t_snap, t_kernel, 0.0,
                                 snap)
            return StatusOr.of(ex.InterimResult(out_cols, [tuple(row)]))
        if meshed:
            from . import distributed
            _, active = distributed.multi_hop_sharded(
                self.mesh, f0, jnp.int32(s.step.steps),
                snap.sharded_kernel, req)
            self.stats["sharded_queries"] += 1
            if device_mask is not None:
                active = active & device_mask
            if err_comb is not None and bool(jnp.any(active & err_comb)):
                # CPU raises EvalError for these rows
                return _decl("err_cells")
        else:
            # grouped unmeshed: fused traversal + filter + err audit
            # prologue — the active mask STAYS on device for the
            # grouped reduction, only the err_any scalar comes home
            fn = self._fused_entry(
                snap, ("agg_trav", device_mask is not None,
                       err_comb is not None),
                lambda: fused.traverse_filtered)
            active, err_any = fn(f0, jnp.int32(int(s.step.steps)),
                                 snap.kernel, req, device_mask,
                                 err_comb)
            self.stats["fused_launches"] += 1
            if bool(err_any):
                # CPU raises EvalError for these rows
                return _decl("err_cells")
        if group_layout is not None:
            if meshed:
                # distributed pushdown: per-shard scatter partials,
                # psum'd under the single-pass row bound / gathered +
                # host-int64-accumulated past it (mesh_exec preserves
                # every exactness bound of aggregate.py)
                from . import mesh_exec
                chunked0 = self.stats.get("agg_grouped_chunked", 0)
                try:
                    groups, cols = mesh_exec.mesh_grouped_reduce(
                        keyed_specs, active, vals, snap.d_edge_gidx,
                        snap.num_parts * snap.cap_v, self.mesh,
                        stats=self.stats)
                except Exception as e:
                    # mesh rung: count against the mesh breaker
                    # (tripping demotes to single-device); the CPU
                    # pipe serves this query
                    self._mesh_failed("agg", e, snap)
                    return self._agg_decline("exec_error")
                if self.stats.get("agg_grouped_chunked", 0) > chunked0:
                    global_stats.add_value(
                        "tpu_engine.agg_grouped_chunked",
                        kind="counter")
                self._mesh_served("agg")
            else:
                n_active = int(jnp.sum(active))
                if any(f in ("SUM", "AVG") for f, _ in keyed_specs) and \
                        n_active > aggregate.MAX_GROUPED_SUM_ROWS:
                    # beyond the single-pass digit bound the reduction
                    # switches to chunked scatter partials with host
                    # int64 accumulation (exact to ~2^55 rows) —
                    # counted, not declined (round-4 verdict weak #6)
                    self.stats["agg_grouped_chunked"] = \
                        self.stats.get("agg_grouped_chunked", 0) + 1
                    global_stats.add_value(
                        "tpu_engine.agg_grouped_chunked",
                        kind="counter")
                groups, cols = aggregate.grouped_reduce(
                    keyed_specs, active, vals, snap.d_edge_gidx,
                    snap.num_parts * snap.cap_v)
            # t1 spans traversal + reduction, like the ungrouped path
            t_kernel = time.monotonic() - t1
            t2 = time.monotonic()
            vids = snap.gidx_vids()[groups]
            rows = []
            for i in range(len(groups)):
                rows.append(tuple(
                    int(vids[i]) if cell == "key" else cols[cell][i]
                    for cell in group_layout))
            self.stats["agg_served"] += 1
            self._record_profile("aggregate-grouped", t_snap, t_kernel,
                                 time.monotonic() - t2, snap)
            return StatusOr.of(ex.InterimResult(out_cols, rows))
        # only the MESHED ungrouped reduction reaches here — the
        # unmeshed one returned from the fused program above
        from . import mesh_exec
        try:
            row = mesh_exec.mesh_reduce_specs(keyed_specs, active,
                                              vals, self.mesh)
        except Exception as e:
            self._mesh_failed("agg", e, snap)
            return self._agg_decline("exec_error")
        self._mesh_served("agg")
        t_kernel = time.monotonic() - t1
        if row is None:
            return _decl("exactness_bound")
        self.stats["agg_served"] += 1
        self._record_profile("aggregate", t_snap, t_kernel, 0.0, snap)
        return StatusOr.of(ex.InterimResult(out_cols, [tuple(row)]))

    def _aggregate_sparse(self, ctx, s, specs, out_cols, snap, sparse,
                          edge_types, alias_map, name_by_type, ex,
                          group_layout, t_snap, t_walk):
        """Exact host reduction over a sparse-pull edge set: the
        aggregation twin of `_emit_sparse` — same pulled indices, same
        filter/cap/err semantics, but the rows are REDUCED in place
        (vectorized hi/lo-split integer sums, exact at any int64
        magnitude) instead of materialized through the pipe. Delta-
        buffer rows are folded in as one extra value chunk, so unlike
        the dense device reduction this path serves with buffered adds
        in play. Declines mirror the CPU pipe's failure surface: a row
        the CPU would raise EvalError for declines the whole query."""
        from . import materialize
        from .filter_host import HostFilterCompiler
        from .filter_host import _Unsupported as _HostUnsupported
        from ..filter.expressions import (EdgeDstIdExpr, EdgePropExpr,
                                          EdgeRankExpr, EdgeSrcIdExpr,
                                          EdgeTypeExpr)
        act_idx, d_act = sparse
        local_filter = s.where.filter if s.where is not None else None
        host_hf, local_filter, delta_rf = self._plan_host_filter(
            ctx, snap, local_filter, name_by_type, alias_map, edge_types)
        if local_filter is not None:
            return self._agg_decline("filter_not_vectorizable")
        t2 = time.monotonic()
        if host_hf is not None and act_idx:
            act_idx = self._apply_host_filter_idx(host_hf, act_idx)
        # cap AFTER the filter (the CPU hot loop's count-after-filter
        # rule); the pre-cap filtered set stays the delta cap base,
        # exactly like _emit_sparse -> _materialize_delta
        filtered_idx = {p: idx for p, idx in act_idx.items() if idx.size}
        capped_idx = {p: materialize._apply_cap(snap.shards[p], idx)
                      for p, idx in filtered_idx.items()}
        hfc = HostFilterCompiler(snap, self._sm, ctx.space_id(),
                                 name_by_type, alias_map, edge_types)
        try:
            loaders: Dict[Any, Any] = {}
            for fun, e in specs:
                if e is None or (e.edge, e.prop) in loaders:
                    continue
                allowed = None
                if e.edge is not None:
                    canon = alias_map.get(e.edge, e.edge)
                    allowed = [t for t in edge_types
                               if name_by_type.get(abs(t)) == canon]
                    if not allowed:
                        return self._agg_decline("prop_outside_over")
                fn = hfc._edge_prop(e.prop, allowed)
                probe = fn(0, np.empty(0, np.int64))
                if probe.kind != "num" or probe.intlike is not True:
                    return self._agg_decline("non_int_prop")
                loaders[(e.edge, e.prop)] = fn
            # every left yield column the CPU would evaluate per row
            # can raise EvalError on err cells — audit them all.
            # Delta-buffer rows can't go through the vectorized fns:
            # edge-prop columns get a per-row props-dict audit below;
            # anything else (tag reads etc.) on a delta row would need
            # the exact per-row walk, so surviving delta rows decline
            # the query instead (delta_audit_strict).
            err_fns = []
            delta_audit: List[Tuple[Optional[str], str]] = []
            delta_audit_strict = False
            for c in ex._go_yield_columns(s, ctx, name_by_type):
                e = c.expr
                if isinstance(e, (EdgeDstIdExpr, EdgeSrcIdExpr,
                                  EdgeRankExpr, EdgeTypeExpr)):
                    continue    # pseudo-props read key parts, never err
                if isinstance(e, EdgePropExpr) and e.prop.startswith("_"):
                    continue
                if isinstance(e, EdgePropExpr):
                    delta_audit.append((e.edge, e.prop))
                    if (e.edge, e.prop) in loaders:
                        continue   # the loader's own err check covers it
                else:
                    delta_audit_strict = True
                fn = hfc._compile(e)
                fn(0, np.empty(0, np.int64))   # kind checks fail HERE,
                err_fns.append(fn)             # not mid-gather
        except _HostUnsupported:
            return self._agg_decline("yield_not_vectorizable")
        # gather per-part chunks: values + null masks per loader key,
        # dst vids for grouping
        n_rows = 0
        chunks: Dict[Any, List] = {k: [] for k in loaders}
        dst_chunks: List[np.ndarray] = []
        for p in sorted(capped_idx):
            idx = capped_idx[p]
            if not idx.size:
                continue
            n_rows += int(idx.size)
            for fn in err_fns:
                v = fn(p, idx)
                if np.any(v.err):
                    # CPU raises EvalError for these rows
                    return self._agg_decline("err_cells")
            for k, fn in loaders.items():
                v = fn(p, idx)
                if np.any(v.err):
                    # CPU raises EvalError for these rows (the loader
                    # doubles as its own column's err audit)
                    return self._agg_decline("err_cells")
                null = v.null if isinstance(v.null, np.ndarray) else \
                    np.full(idx.size, bool(v.null))
                chunks[k].append((np.asarray(v.value), null))
            if group_layout is not None:
                dst_chunks.append(snap.shards[p].edge_dst_vid[idx])
        # delta-buffer rows: one extra chunk built row-wise (few rows)
        if d_act:
            delta = snap.delta
            cap_counts: Dict[Tuple[int, int], int] = {}
            d_vals: Dict[Any, List] = {k: [] for k in loaders}
            d_dst: List[int] = []
            kept = 0
            for slot in d_act:
                info = delta.info.get(slot)
                if info is None:
                    continue
                if delta_rf is not None and not delta_rf(info):
                    continue
                src_vid, etype, rank, dst_vid, props = info
                ckey = (src_vid, etype)
                if ckey not in cap_counts:
                    cap_counts[ckey] = _base_active_count(
                        snap, filtered_idx, src_vid, etype)
                cap_counts[ckey] += 1
                if cap_counts[ckey] > DEFAULT_MAX_EDGES_PER_VERTEX:
                    continue
                if delta_audit_strict:
                    # a non-edge-prop yield column (tag read etc.)
                    # would need the exact per-row walk on this row
                    return self._agg_decline("delta_yield_audit")
                for edge, prop in delta_audit:
                    # the CPU evaluates EVERY left yield column per
                    # row — a version-missing key raises EvalError
                    # even when the column isn't an aggregate arg
                    if (edge is None or name_by_type.get(abs(etype)) ==
                            alias_map.get(edge, edge)) and \
                            prop not in props:
                        return self._agg_decline("err_cells")
                kept += 1
                d_dst.append(dst_vid)
                for (edge, prop), acc in d_vals.items():
                    if edge is not None and \
                            name_by_type.get(abs(etype)) != \
                            alias_map.get(edge, edge):
                        acc.append(None)    # other-type row: CPU None
                        continue
                    acc.append(props[prop])
            n_rows += kept
            if kept:
                for k, acc in d_vals.items():
                    vals = np.array([0 if x is None else x for x in acc],
                                    np.int64)
                    null = np.array([x is None for x in acc], bool)
                    chunks[k].append((vals, null))
                if group_layout is not None:
                    dst_chunks.append(np.asarray(d_dst, np.int64))
        if group_layout is not None:
            result = self._reduce_sparse_grouped(
                specs, out_cols, chunks, dst_chunks, group_layout, ex)
        else:
            row: List[Any] = []
            for fun, e in specs:
                if fun == "COUNT":
                    row.append(n_rows)
                    continue
                parts = chunks[(e.edge, e.prop)]
                row.append(_reduce_sparse_one(fun, parts))
            result = StatusOr.of(ex.InterimResult(out_cols, [tuple(row)]))
        self.stats["agg_served"] += 1
        self.stats["agg_sparse_served"] += 1
        self._record_profile("aggregate-sparse", t_snap, t_walk,
                             time.monotonic() - t2, snap)
        return result

    @staticmethod
    def _reduce_sparse_grouped(specs, out_cols, chunks, dst_chunks,
                               group_layout, ex):
        """Grouped twin of the sparse reduction: segment by dst vid
        with int64 scatter accumulators over hi/lo 32-bit halves (sums
        exact for any int64 values up to 2^31 rows — far above the
        pull budget). Rows emit in ascending dst-vid order (callers
        compare sorted; the CPU pipe's order is first-seen)."""
        if not dst_chunks:
            return StatusOr.of(ex.InterimResult(out_cols))
        dst = np.concatenate(dst_chunks)
        uniq, inv = np.unique(dst, return_inverse=True)
        counts = np.bincount(inv, minlength=len(uniq))
        cols: List[List] = []
        for fun, e in specs:
            if fun == "COUNT":
                cols.append([int(c) for c in counts])
                continue
            vals = np.concatenate(
                [np.asarray(v, np.int64) for v, _ in chunks[(e.edge,
                                                             e.prop)]])
            null = np.concatenate([n for _, n in chunks[(e.edge, e.prop)]])
            m = ~null
            nn = np.bincount(inv[m], minlength=len(uniq))
            if fun in ("MIN", "MAX"):
                ident = np.iinfo(np.int64).max if fun == "MIN" \
                    else np.iinfo(np.int64).min
                acc = np.full(len(uniq), ident, np.int64)
                op = np.minimum if fun == "MIN" else np.maximum
                op.at(acc, inv[m], vals[m])
                cols.append([int(x) if c else None
                             for x, c in zip(acc, nn)])
                continue
            u = vals[m].view(np.uint64) + np.uint64(1 << 63)
            lo = (u & np.uint64(0xFFFFFFFF)).astype(np.int64)
            hi = (u >> np.uint64(32)).astype(np.int64)
            acc_lo = np.zeros(len(uniq), np.int64)
            acc_hi = np.zeros(len(uniq), np.int64)
            np.add.at(acc_lo, inv[m], lo)
            np.add.at(acc_hi, inv[m], hi)
            sums = [(int(h) << 32) + int(l) - (int(c) << 63)
                    for h, l, c in zip(acc_hi, acc_lo, nn)]
            if fun == "SUM":
                cols.append([x if c else None for x, c in zip(sums, nn)])
            else:    # AVG: exact integer sum / count on the host
                cols.append([x / int(c) if c else None
                             for x, c in zip(sums, nn)])
        rows = []
        col_of = [None if cell == "key" else cell for cell in group_layout]
        for i in range(len(uniq)):
            rows.append(tuple(
                int(uniq[i]) if cell is None else cols[cell][i]
                for cell in col_of))
        return StatusOr.of(ex.InterimResult(out_cols, rows))

    def _compile_host_filter(self, ctx, snap, flt, name_by_type,
                             alias_map, edge_types):
        """Compile a WHERE filter to the vectorized host evaluator, or
        None when it's outside filter_host's surface (caller keeps the
        exact per-row Python walk). A ~10^6-edge result through the
        per-row walk costs seconds — the r3 bench's 12s p99 outlier."""
        from .filter_host import HostFilterCompiler
        hf = HostFilterCompiler(snap, self._sm, ctx.space_id(),
                                name_by_type, alias_map,
                                edge_types).compile(flt)
        if hf is not None:
            self.stats["host_filter_vectorized"] += 1
        return hf

    @staticmethod
    def _apply_host_filter(hf, snap, mask):
        """{part0: filtered ascending idx} over a dense [P, cap_e]
        active mask."""
        out = {}
        for p in range(snap.num_parts):
            idx = np.nonzero(mask[p])[0]
            if idx.size:
                out[p] = idx[hf.eval_part(p, idx)]
        return out

    def _plan_host_filter(self, ctx, snap, local_filter, name_by_type,
                          alias_map, edge_types):
        """The shared vectorize-or-keep decision: -> (host_hf,
        local_filter', delta_row_filter). When the filter compiles,
        canonical rows are pre-filtered (local_filter' is None) and
        delta rows get a per-row predicate evaluated DURING delta
        materialization — BEFORE cap counting, so the per-vertex cap
        sees only filter-passing rows on both row sources (the CPU hot
        loop's count-after-filter rule, processors.py:235-244)."""
        if local_filter is None:
            return None, None, None
        hf = self._compile_host_filter(ctx, snap, local_filter,
                                       name_by_type, alias_map, edge_types)
        if hf is None:
            # not vectorizable: callers keep the per-row walk, where cap
            # accounting remains pre-filter on the slow path (a known,
            # narrow divergence: >max_edges_per_vertex rows on one
            # (src, etype) AND a non-pushable filter)
            return None, local_filter, None
        flt = local_filter
        tag_refs = self._filter_tag_refs(flt)
        from ..graph.executors import make_tag_default_resolver
        tag_default = make_tag_default_resolver(ctx.sm, ctx.space_id())

        def delta_passes(info):
            return self._delta_row_passes(ctx, snap, flt, alias_map,
                                          name_by_type, info, tag_refs,
                                          tag_default)
        return hf, None, delta_passes

    @staticmethod
    def _filter_tag_refs(flt):
        """(src tag names, dst tag names) a filter references — the
        only vertex props _delta_row_passes needs to decode."""
        from ..filter.expressions import DestPropExpr, SourcePropExpr
        src, dst = set(), set()
        stack = [flt]
        while stack:
            e = stack.pop()
            if isinstance(e, SourcePropExpr):
                src.add(e.tag)
            elif isinstance(e, DestPropExpr):
                dst.add(e.tag)
            stack.extend(e.children())
        return src, dst

    def _delta_row_passes(self, ctx, snap, flt, alias_map, name_by_type,
                          info, tag_refs, tag_default) -> bool:
        """Evaluate a WHERE filter on one delta-buffer edge row with
        the executor's exact per-row semantics (EvalError drops the
        row). Only reachable for host-vectorizable filters, which never
        reference $-/$var, so no input row is needed; only the tags the
        filter actually references are decoded."""
        from ..graph.expr_context import EdgeRowExprContext
        src_vid, etype, rank, dst_vid, props = info
        space = ctx.space_id()
        src_tags, dst_tags = tag_refs

        def named_tag_props(vid, names):
            if not names:
                return {}
            loc = snap.locate(vid)
            if loc is None:
                return {}
            shard = snap.shards[loc[0]]
            out = {}
            for name in names:
                tid = ctx.sm.tag_id(space, name)
                if tid is None:
                    continue
                tp = _host_tag_props(shard, tid, loc[1])
                if tp is not None:
                    out[name] = tp
            return out

        ectx = EdgeRowExprContext(
            input_row=None, variables=None,
            src_props=named_tag_props(src_vid, src_tags), edge_props=props,
            edge_name=name_by_type.get(abs(etype), str(abs(etype))),
            alias_map=alias_map, src=src_vid, dst=dst_vid, rank=rank,
            dst_props=named_tag_props(dst_vid, dst_tags),
            tag_default=tag_default)
        from ..filter.expressions import EvalError
        try:
            return bool(flt.eval(ectx))
        except EvalError:
            return False

    @staticmethod
    def _apply_host_filter_idx(hf, idx_per_part):
        """{part0: filtered idx} over already-sparse active indices."""
        return {p: idx[hf.eval_part(p, idx)]
                for p, idx in idx_per_part.items()}

    def _materialize_delta(self, snap: CsrSnapshot, d_mask: np.ndarray,
                           base_mask: np.ndarray, ctx, yield_cols,
                           s, row_filter=None) -> BoundResponse:
        """Delta-buffer edges active in the final hop, in the same
        BoundResponse shape as _materialize — one host loop over the few
        delta edges, flowing through the identical yield machinery.
        The per-vertex edge cap counts BASE rows first (the CPU storage
        path truncates across all of a vertex's edges, ref
        FLAGS_max_edge_returned_per_vertex). `row_filter` applies the
        WHERE clause per row BEFORE cap counting (the CPU hot loop's
        count-after-filter rule) — callers then emit WITHOUT a filter."""
        resp = BoundResponse()
        src_tag_reqs, _, _ = _collect_src_tags(ctx, yield_cols, s)
        per_vertex: Dict[int, VertexData] = {}
        delta = snap.delta
        cap_counts: Dict[Tuple[int, int], int] = {}
        for gdst, lane in zip(*np.nonzero(d_mask)):
            info = delta.info.get((int(gdst), int(lane)))
            if info is None:
                continue
            if row_filter is not None and not row_filter(info):
                continue
            src_vid, etype, rank, dst_vid, props = info
            ckey = (src_vid, etype)
            if ckey not in cap_counts:
                cap_counts[ckey] = _base_active_count(snap, base_mask,
                                                      src_vid, etype)
            cap_counts[ckey] += 1
            if cap_counts[ckey] > DEFAULT_MAX_EDGES_PER_VERTEX:
                continue
            vd = per_vertex.get(src_vid)
            if vd is None:
                vd = VertexData(src_vid)
                loc = snap.locate(src_vid)
                if loc is not None:
                    shard = snap.shards[loc[0]]
                    for tid in src_tag_reqs:
                        tp = _host_tag_props(shard, tid, loc[1])
                        if tp is not None:
                            vd.tag_props[tid] = tp
                per_vertex[src_vid] = vd
            vd.edges.append(EdgeData(src_vid, etype, rank, dst_vid,
                                     dict(props)))
        for p in range(snap.num_parts):
            resp.results[p + 1] = PartResult()
        resp.vertices = list(per_vertex.values())
        return resp

    # ------------------------------------------------------------------
    def _materialize(self, snap: CsrSnapshot, mask: Optional[np.ndarray],
                     ctx, yield_cols, s,
                     idx_per_part: Optional[Dict[int, np.ndarray]] = None
                     ) -> BoundResponse:
        """Compact the active-edge mask into the same BoundResponse shape
        the CPU storage path returns, reading props from host mirrors.
        Active edges come from `mask` or sparse `idx_per_part`."""
        space = ctx.space_id()
        resp = BoundResponse()
        src_tag_reqs, _, _ = _collect_src_tags(ctx, yield_cols, s)
        per_vertex: Dict[int, VertexData] = {}
        cap_counts: Dict[Tuple[int, int], int] = {}
        for p in range(snap.num_parts):
            shard = snap.shards[p]
            if idx_per_part is not None:
                idxs = idx_per_part.get(p, np.empty(0, np.int64))
            else:
                idxs = np.nonzero(mask[p])[0]
            for i in idxs:
                i = int(i)
                src_vid = int(shard.vids[shard.edge_src[i]])
                et = int(shard.edge_etype[i])
                ckey = (src_vid, et)
                cap_counts[ckey] = cap_counts.get(ckey, 0) + 1
                if cap_counts[ckey] > DEFAULT_MAX_EDGES_PER_VERTEX:
                    continue
                vd = per_vertex.get(src_vid)
                if vd is None:
                    vd = VertexData(src_vid)
                    for tid in src_tag_reqs:
                        props = _host_tag_props(shard, tid,
                                                int(shard.edge_src[i]))
                        if props is not None:
                            vd.tag_props[tid] = props
                    per_vertex[src_vid] = vd
                props = _host_edge_props(shard, et, i)
                vd.edges.append(EdgeData(src_vid, et,
                                         int(shard.edge_rank[i]),
                                         int(shard.edge_dst_vid[i]), props))
            resp.results[p + 1] = PartResult()
        resp.vertices = list(per_vertex.values())
        return resp

    # ------------------------------------------------------------------
    # sparse (pull-mode) GO: host-mirror frontier advance for small
    # frontiers — the direction-optimized half of the engine
    # ------------------------------------------------------------------
    @staticmethod
    def _part_frontier_edges(shard, locals_, req, max_total=None):
        """Vectorized expansion of one part's frontier locals over the
        base CSR: -> (idx int64[], per_edge_row int64[] positions into
        `locals_`, raw_count) with validity+etype filtering applied.
        raw_count is the UNFILTERED segment total, computed from the
        indptr BEFORE any per-edge allocation; when it exceeds
        `max_total` the expansion is not materialized and (None, None,
        raw_count) returns — a supernode frontier must cost O(frontier)
        host work, not O(its edges), before the budget bails. Shared by
        the pull-mode GO walk and the pull-mode path expansion."""
        indptr = _shard_indptr(shard)
        lo, hi = indptr[locals_], indptr[locals_ + 1]
        counts = (hi - lo).astype(np.int64)
        total = int(counts.sum())
        if total == 0:
            return (np.empty(0, np.int64), np.empty(0, np.int64), 0)
        if max_total is not None and total > max_total:
            return (None, None, total)
        idx = (np.repeat(lo - np.pad(np.cumsum(counts), (1, 0))[:-1],
                         counts) + np.arange(total))
        rows = np.repeat(np.arange(len(locals_), dtype=np.int64), counts)
        ok = shard.edge_valid[idx] & np.isin(shard.edge_etype[idx],
                                             list(req))
        return idx[ok], rows[ok], total

    def calibrate_sparse_budget(self, space_id: int, roots: List[int],
                                edge_types: List[int], steps: int = 3,
                                auto: bool = False, _snap=None
                                ) -> Optional[Dict[str, Any]]:
        """Replace the modeled pull-vs-push breakeven with a MEASURED
        one (round-3 verdict: the 4M constant was never validated on
        hardware). Times one dense batch-1 dispatch and the sparse
        host walk over the given roots on THIS machine/chip, fits
        budget = dense_seconds * sparse_edges_per_second (x0.8
        margin), installs it as the SPACE's budget (and as the
        engine-wide fallback), and returns + caches the fit record
        (`sparse_budget_calibrations`; sampled into /get_stats as
        tpu_engine.sparse_budget_fit). Runs automatically from the
        prewarm hook on first USE; roots should be representative
        seeds (hubs included) so the walk rate reflects real
        frontiers. `auto` calls (the prewarm hook) defer to an
        explicitly pinned budget, never override it, and pass the
        warmup's own PRIVATE snapshot via `_snap` — calibration must
        not install snapshots itself (an install mid-bulk-load leaves
        a soon-stale snapshot whose next delta patch poisons it,
        declining the first real query — observed as a flaky
        first-query fallback)."""
        if auto and self._budget_pinned:
            return None
        snap = _snap
        if snap is None:
            with self._lock:
                snap = self._snapshot_locked(space_id)
        if snap is None:
            return None
        import jax.numpy as jnp
        # dense batch-1 timing: kernel buffers are immutable (delta
        # point-updates swap in new arrays), so one grabbed reference
        # is consistent without the engine lock
        kernel = snap.kernel
        req = jnp.asarray(traverse.pad_edge_types(edge_types))
        f0 = jnp.asarray(snap.frontier_from_vids(roots[:1]))
        _, a = traverse.multi_hop(f0, jnp.int32(steps), kernel,
                                  req)     # compile outside timing
        a.block_until_ready()
        t0 = time.monotonic()
        _, a = traverse.multi_hop(f0, jnp.int32(steps), kernel, req)
        a.block_until_ready()
        dense_s = time.monotonic() - t0
        # sparse rate over the sampled roots. The probe budget is
        # BOUNDED per root (review finding, round 5): the walk holds
        # the engine lock (host mirrors are delta-mutable), and an
        # unbounded hub walk on an SNB-scale graph would stall every
        # query for tens of seconds. A truncated walk still measures
        # the edges/sec rate — the fit needs rate, not completion.
        visited = 0
        t0 = time.monotonic()
        with self._lock:
            for r in roots:
                self._sparse_expand(snap, [r], edge_types, steps,
                                    budget=self.CALIBRATION_PROBE_BUDGET)
                visited += getattr(self, "_sparse_visited", 0)
        walk_s = max(time.monotonic() - t0, 1e-9)
        if visited == 0:
            return None
        rate = visited / walk_s
        fitted = max(1 << 14, int(dense_s * rate * 0.8))
        # pin check + install are ONE critical section (and the
        # sparse_edge_budget setter takes the same lock): a pin landing
        # mid-probe can no longer be overridden by the install racing
        # between the check and the assignments
        with self._lock:
            if auto and self._budget_pinned:
                return None   # pinned mid-probe: never override
            self._sparse_edge_budget = fitted   # not the property: no pin
            self._space_budgets[space_id] = fitted
        rec = {"dense_dispatch_ms": round(dense_s * 1e3, 2),
               "sparse_edges_per_sec": int(rate),
               "probe_roots": len(roots), "probe_edges": int(visited),
               "fitted_budget": fitted,
               # staleness anchor: _maybe_recalibrate re-fits once the
               # space churns BUDGET_RECAL_CHURN versions past this
               "churn_at_fit": self._space_churn.get(space_id, 0)}
        self.sparse_budget_calibrations[space_id] = rec
        # kind="timing": the fitted budget is a value distribution (a
        # gauge sampled per calibration), not a monotonic event count
        global_stats.add_value("tpu_engine.sparse_budget_fit", fitted,
                               kind="timing")
        _LOG.info("sparse budget calibrated (space %d): %s", space_id, rec)
        return rec

    def _budget_for(self, space_id: int) -> int:
        return self._space_budgets.get(space_id, self.sparse_edge_budget)

    def _sparse_expand(self, snap, starts, edge_types, steps,
                       budget: Optional[int] = None):
        """Advance the frontier over the snapshot's host mirrors,
        visiting only the frontier's own edges. Returns (final active
        canonical idx per part, final active delta slots) or None when
        the visited-edge budget is exceeded (the dense device dispatch
        amortizes better there). `self._sparse_visited` records the
        raw edges the walk touched (calibrate_sparse_budget's rate
        probe)."""
        req = set(edge_types)
        delta = snap.delta if (snap.delta is not None
                               and snap.delta.edge_count > 0) else None
        frontier: Dict[int, np.ndarray] = {}
        for v in set(starts):
            loc = snap.locate(v)
            if loc is not None:
                frontier.setdefault(loc[0], []).append(loc[1])
        frontier = {p: np.unique(np.asarray(ls, np.int64))
                    for p, ls in frontier.items()}
        if budget is None:
            budget = self._budget_for(snap.space_id)
        visited = 0
        for step in range(steps):
            final = step == steps - 1
            act_idx: Dict[int, np.ndarray] = {}
            d_act: List[Tuple[int, int]] = []
            nxt: Dict[int, List[np.ndarray]] = {}
            for p, locals_ in frontier.items():
                shard = snap.shards[p]
                base = locals_[locals_ < shard.num_vids_base]
                if base.size:
                    idx, _, raw = self._part_frontier_edges(
                        shard, base, req, max_total=budget - visited)
                    visited += raw
                    if visited > budget:
                        self._sparse_visited = visited
                        return None
                    if idx.size:
                        act_idx[p] = idx
                        if not final:
                            dp = shard.edge_dst_part[idx]
                            dl = shard.edge_dst_local[idx]
                            for q in np.unique(dp):
                                nxt.setdefault(int(q), []).append(
                                    dl[dp == q].astype(np.int64))
                if delta is not None:
                    for l in locals_:
                        gs = p * snap.cap_v + int(l)
                        for slot in delta.by_src.get(gs, ()):
                            if not delta.h_ok[slot]:
                                continue
                            info = delta.info.get(slot)
                            if info is None or info[1] not in req:
                                continue
                            visited += 1
                            if visited > budget:
                                self._sparse_visited = visited
                                return None
                            d_act.append(slot)
                            if not final:
                                q, dl = divmod(slot[0], snap.cap_v)
                                nxt.setdefault(q, []).append(
                                    np.asarray([dl], np.int64))
            if final:
                self._sparse_visited = visited
                return act_idx, d_act
            if not nxt:
                self._sparse_visited = visited
                return {}, []
            frontier = {q: np.unique(np.concatenate(ls))
                        for q, ls in nxt.items()}
        self._sparse_visited = visited
        return {}, []

    def _emit_sparse(self, ctx, s, snap, sparse, yield_cols, columns,
                     alias_map, name_by_type, ex, edge_types,
                     t_snap=0.0, t_kernel=0.0):
        t2 = time.monotonic()
        act_idx, d_act = sparse
        local_filter = s.where.filter if s.where is not None else None
        host_hf, local_filter, delta_rf = self._plan_host_filter(
            ctx, snap, local_filter, name_by_type, alias_map, edge_types)
        if host_hf is not None and act_idx:
            act_idx = self._apply_host_filter_idx(host_hf, act_idx)
        if local_filter is None and not d_act \
                and not (s.yield_ and s.yield_.distinct):
            # deferred fast path (see _go_emit_dense): typed columns +
            # one native GIL-released encode; the owning session boxes
            # tuples after wakeup, outside the lock and the dispatcher
            gathered = materialize.gather_for_encode(
                ctx.sm, ctx.space_id(), snap, None, yield_cols,
                alias_map, name_by_type, idx_per_part=act_idx)
            if gathered is not None:
                t3 = time.monotonic()
                encs, native_used = materialize.encode_window([gathered])
                self._count_encode(len(encs[0]), native_used)
                result = ex.InterimResult(columns)
                result._tpu_deferred = encs[0]
                _tr.add_span("encode", (time.monotonic() - t3) * 1e6,
                             rows=len(encs[0]), native=native_used)
                self.stats["fast_materialize"] += 1
                self.stats["go_served"] += 1
                self.stats["sparse_served"] += 1
                self._record_profile("sparse", t_snap, t_kernel,
                                     time.monotonic() - t2, snap)
                return StatusOr.of(result)
        rows: Optional[List[Tuple]] = None
        needs_dst = _needs_dst(yield_cols, s)
        if local_filter is None:
            rows = materialize.emit_rows(snap, None, ctx, yield_cols,
                                         alias_map, name_by_type,
                                         idx_per_part=act_idx)
        if rows is not None:
            self.stats["fast_materialize"] += 1
        else:
            self.stats["slow_materialize"] += 1
            resp = self._materialize(snap, None, ctx, yield_cols, s,
                                     idx_per_part=act_idx)
            rows = []
            st = ex._emit_go_rows(ctx, resp, rows, yield_cols, local_filter,
                                  alias_map, name_by_type, roots={},
                                  input_index={}, needs_input=False,
                                  needs_dst=needs_dst)
            if not st.ok():
                return StatusOr.from_status(st)
        if d_act:
            delta = snap.delta
            d_mask = np.zeros_like(delta.h_ok)
            for slot in d_act:
                d_mask[slot] = True
            dresp = self._materialize_delta(snap, d_mask, act_idx, ctx,
                                            yield_cols, s,
                                            row_filter=delta_rf)
            st = ex._emit_go_rows(ctx, dresp, rows, yield_cols, local_filter,
                                  alias_map, name_by_type, roots={},
                                  input_index={}, needs_input=False,
                                  needs_dst=needs_dst)
            if not st.ok():
                return StatusOr.from_status(st)
        result = ex.InterimResult(columns, rows)
        if s.yield_ and s.yield_.distinct:
            result = result.distinct()
        self.stats["go_served"] += 1
        self.stats["sparse_served"] += 1
        self._record_profile("sparse", t_snap, t_kernel,
                             time.monotonic() - t2, snap)
        return StatusOr.of(result)

    # ------------------------------------------------------------------
    # pull-mode adjacency for path queries (direction optimization)
    # ------------------------------------------------------------------
    def _mirror_adj(self, snap, frontier, edge_types, state):
        """{dst: [(src, etype, rank)]} for one expansion over the
        snapshot's host mirrors — the _expand contract without the
        storage RPC. The frontier walk is VECTORIZED (the budget check
        runs on raw segment sizes before any per-edge python), so a
        budget-exceeding frontier bails in numpy time instead of
        crawling millions of edges scalar-wise under the engine lock.
        Raises _BudgetExceeded past the pull budget (caller falls to
        the dense device path)."""
        budget = self._budget_for(snap.space_id)
        req = list(set(edge_types))
        delta = snap.delta if (snap.delta is not None
                               and snap.delta.edge_count > 0) else None
        out: Dict[int, list] = {}
        by_part: Dict[int, list] = {}
        delta_locs = []
        for vid in frontier:
            loc = snap.locate(vid)
            if loc is None:
                continue
            by_part.setdefault(loc[0], []).append((loc[1], vid))
            if delta is not None:
                delta_locs.append((loc[0], loc[1], vid))
        for p, pairs in by_part.items():
            shard = snap.shards[p]
            base = [(l, v) for l, v in pairs if l < shard.num_vids_base]
            if not base:
                continue
            locals_ = np.asarray([l for l, _ in base], np.int64)
            vids_ = np.asarray([v for _, v in base], np.int64)
            idx, rows, raw = self._part_frontier_edges(
                shard, locals_, req,
                max_total=budget - state["visited"])
            state["visited"] += raw
            if state["visited"] > budget:
                raise _BudgetExceeded()
            src_per_edge = vids_[rows]
            ets = shard.edge_etype[idx]
            ranks = shard.edge_rank[idx]
            dsts = shard.edge_dst_vid[idx]
            for j in range(len(idx)):     # survivors only
                out.setdefault(int(dsts[j]), []).append(
                    (int(src_per_edge[j]), int(ets[j]), int(ranks[j])))
        if delta is not None:
            req_set = set(req)
            for p, local, vid in delta_locs:
                gs = p * snap.cap_v + local
                for slot in delta.by_src.get(gs, ()):
                    info = delta.info.get(slot)
                    if info is None or not delta.h_ok[slot]:
                        continue
                    _, et, rank, dst_vid, _props = info
                    if et not in req_set:
                        continue
                    state["visited"] += 1
                    if state["visited"] > budget:
                        raise _BudgetExceeded()
                    out.setdefault(dst_vid, []).append((vid, et, rank))
        return out

    # ------------------------------------------------------------------
    # FIND ALL/NOLOOP PATH: per-level device adjacency, host enumeration
    # (ref FindPathExecutor.cpp:218-290 — the join stays on CPU, the
    # per-hop storage expansion moves on-chip)
    # ------------------------------------------------------------------
    def _find_all_paths(self, ctx, s, sources, targets, edge_types,
                        name_by_type, snap, ex):
        if not 1 <= int(s.step.steps) <= self.MAX_DEVICE_STEPS:
            return None   # pre-checked by can_serve_path; defense only
        import jax.numpy as jnp
        meshed = getattr(snap, "sharded_kernel", None) is not None
        upto = int(s.step.steps)
        f0 = jnp.asarray(snap.frontier_from_vids(sources))
        req = jnp.asarray(traverse.pad_edge_types(edge_types))
        use_delta = snap.delta is not None and snap.delta.edge_count > 0
        if meshed:
            if use_delta:
                # defensive only: sharded snapshots rebuild instead of
                # delta-patching, so a pending delta means a racing
                # apply — the CPU pipe serves exactly
                self._mesh_decline("path_all", "delta_pending")
                return None
            from . import mesh_exec
            try:
                # per-step sharded expansion (all_to_all exchange per
                # hop); enumeration below reads the same mask stack it
                # reads single-chip
                masks = mesh_exec.multi_hop_steps_sharded(
                    self.mesh, f0, snap.sharded_kernel, req, upto)
            except Exception as e:
                # mesh rung of the ladder: count against the mesh
                # breaker (tripping demotes the space to single-
                # device); the CPU pipe serves this query meanwhile
                self._mesh_failed("path_all", e, snap)
                _LOG.exception("sharded ALL-path expansion failed "
                               "(space %d)", snap.space_id)
                return None
            dmasks = None
            self.stats["sharded_queries"] += 1
            self._mesh_served("path_all")
        elif use_delta:
            masks, dmasks = traverse.multi_hop_steps_delta(
                f0, snap.kernel, snap.delta.device(), req, steps=upto)
        else:
            masks = traverse.multi_hop_steps(f0, snap.kernel, req,
                                             steps=upto)
            dmasks = None
        masks = np.asarray(masks)
        dmasks = None if dmasks is None else np.asarray(dmasks)
        delta = snap.delta

        def expand_fn(_frontier, depth):
            """ALL edges active at this level, indexed by src vid — a
            superset of the enumeration loop's path-end lookups (the
            device frontier never prunes by path like NOLOOP does).
            The per-(src, etype) cap matches the CPU path's
            max_edges_per_vertex truncation in get_neighbors."""
            from .materialize import _apply_cap
            by_src: Dict[int, list] = {}
            cap_counts: Dict[Tuple[int, int], int] = {}
            mask = masks[depth]
            for p, shard in enumerate(snap.shards):
                idx = np.nonzero(mask[p])[0]
                if idx.size == 0:
                    continue
                idx = _apply_cap(shard, idx)
                svids = shard.vids[shard.edge_src[idx]]
                for i, sv in zip(idx, svids):
                    sv, et = int(sv), int(shard.edge_etype[i])
                    cap_counts[(sv, et)] = cap_counts.get((sv, et), 0) + 1
                    by_src.setdefault(sv, []).append(
                        (int(shard.edge_dst_vid[i]), et,
                         int(shard.edge_rank[i])))
            if dmasks is not None:
                for gdst, lane in zip(*np.nonzero(dmasks[depth])):
                    info = delta.info.get((int(gdst), int(lane)))
                    if info is None:
                        continue
                    src_vid, etype, rank, dst_vid, _props = info
                    ck = (src_vid, etype)
                    cap_counts[ck] = cap_counts.get(ck, 0) + 1
                    if cap_counts[ck] > DEFAULT_MAX_EDGES_PER_VERTEX:
                        continue
                    by_src.setdefault(src_vid, []).append(
                        (dst_vid, etype, rank))
            return by_src

        paths = ex._all_paths(ctx, ctx.space_id(), sources, targets,
                              edge_types, upto, name_by_type,
                              noloop=s.noloop, expand_fn=expand_fn)
        self.stats["path_served"] += 1
        return StatusOr.of(ex.InterimResult(["_path_"],
                                            [(p,) for p in paths]))

    # ------------------------------------------------------------------
    # GO UPTO: per-step masks (one row per (edge, step), ref upto
    # emission in the CPU loop / GoExecutor union semantics)
    # ------------------------------------------------------------------
    def _go_upto(self, ctx, s, f0, req, edge_types, snap, use_delta,
                 yield_cols, columns, alias_map, name_by_type, ex,
                 t_snap=0.0):
        from . import materialize
        steps = int(s.step.steps)
        device_mask, local_filter = self._plan_filter(
            ctx, s, snap, use_delta, name_by_type, alias_map, edge_types)
        t1 = time.monotonic()   # kernel time = device dispatch only
        if use_delta:
            masks, dmasks = traverse.multi_hop_steps_delta(
                f0, snap.kernel, snap.delta.device(), req, steps=steps)
        else:
            masks = traverse.multi_hop_steps(f0, snap.kernel, req,
                                             steps=steps)
            dmasks = None
        dm_np = None if device_mask is None else np.asarray(device_mask)
        t_kernel = time.monotonic() - t1
        t2 = time.monotonic()
        rows: List[Tuple] = []
        needs_dst = _needs_dst(yield_cols, s)
        # vectorized host filter, compiled ONCE for all steps
        host_hf, local_filter, delta_rf = self._plan_host_filter(
            ctx, snap, local_filter, name_by_type, alias_map, edge_types)
        for si in range(steps):
            mask = np.asarray(masks[si])
            if dm_np is not None:
                mask = mask & dm_np
            idx_pp = None
            if host_hf is not None:
                idx_pp = self._apply_host_filter(host_hf, snap, mask)
            step_rows = None
            if local_filter is None:
                step_rows = materialize.emit_rows(snap, mask, ctx,
                                                  yield_cols, alias_map,
                                                  name_by_type,
                                                  idx_per_part=idx_pp)
            if step_rows is not None:
                self.stats["fast_materialize"] += 1
                rows.extend(step_rows)
            else:
                self.stats["slow_materialize"] += 1
                resp = self._materialize(snap, mask, ctx, yield_cols, s,
                                         idx_per_part=idx_pp)
                st = ex._emit_go_rows(ctx, resp, rows, yield_cols,
                                      local_filter, alias_map, name_by_type,
                                      roots={}, input_index={},
                                      needs_input=False, needs_dst=needs_dst)
                if not st.ok():
                    return StatusOr.from_status(st)
            if dmasks is not None:
                d_mask = np.asarray(dmasks[si])
                if d_mask.any():
                    base_for_cap = idx_pp if idx_pp is not None else mask
                    dresp = self._materialize_delta(snap, d_mask,
                                                    base_for_cap, ctx,
                                                    yield_cols, s,
                                                    row_filter=delta_rf)
                    st = ex._emit_go_rows(ctx, dresp, rows, yield_cols,
                                          local_filter, alias_map,
                                          name_by_type, roots={},
                                          input_index={}, needs_input=False,
                                          needs_dst=needs_dst)
                    if not st.ok():
                        return StatusOr.from_status(st)
        result = ex.InterimResult(columns, rows)
        if s.yield_ and s.yield_.distinct:
            result = result.distinct()
        self.stats["go_served"] += 1
        self._record_profile("upto", t_snap, t_kernel,
                             time.monotonic() - t2, snap)
        return StatusOr.of(result)

    # ------------------------------------------------------------------
    # input-ref GO: one frontier per root so result rows join back to
    # the input rows of the root that reached them (the device form of
    # VertexBackTracker, ref GoExecutor.cpp:1067-1075)
    # ------------------------------------------------------------------
    def _go_roots(self, ctx, s, starts, req, edge_types, snap, use_delta,
                  yield_cols, columns, alias_map, name_by_type, ex,
                  t_snap=0.0):
        import jax.numpy as jnp
        roots = sorted(set(starts))
        # [R, P, cap_e] masks materialize on device AND host: bound the
        # root count by a ~1GB mask budget, not just the fixed cap
        mask_budget = (1 << 30) // max(snap.num_parts * snap.cap_e, 1)
        if len(roots) > min(self.MAX_ROOTS_ON_DEVICE, max(mask_budget, 1)):
            self.stats["fallbacks"] += 1
            return None
        # input/var refs are evaluated per joined input row on the host;
        # filters WITHOUT input refs vectorize (the compiler declines
        # $-/$var nodes, so this can't skip input-dependent filters)
        local_filter = s.where.filter if s.where is not None else None
        host_hf, local_filter, delta_rf = self._plan_host_filter(
            ctx, snap, local_filter, name_by_type, alias_map, edge_types)
        f0s = jnp.asarray(np.stack(
            [snap.frontier_from_vids([r]) for r in roots]))
        t1 = time.monotonic()   # kernel time = device dispatch only
        if use_delta:
            masks, dmasks = traverse.multi_hop_roots_delta(
                f0s, s.step.steps, snap.kernel, snap.delta.device(), req)
        else:
            masks = traverse.multi_hop_roots(f0s, s.step.steps, snap.kernel,
                                             req)
            dmasks = None
        masks = np.asarray(masks)
        dmasks = None if dmasks is None else np.asarray(dmasks)
        t_kernel = time.monotonic() - t1
        t2 = time.monotonic()
        keep = None
        if host_hf is not None:
            # evaluate the filter ONCE over the union of root masks —
            # overlapping root frontiers would otherwise re-gather the
            # same edges per root; per root below it's one boolean index
            keep = np.zeros((snap.num_parts, snap.cap_e), bool)
            union = masks.any(axis=0)
            for p, idx in self._apply_host_filter(host_hf, snap,
                                                  union).items():
                keep[p][idx] = True
        input_index = ex.build_input_index(ctx, s)
        input_var = s.from_.ref.var \
            if isinstance(s.from_.ref, VariablePropExpr) else None
        needs_dst = _needs_dst(yield_cols, s)
        rows: List[Tuple] = []
        for i, root in enumerate(roots):
            mask = masks[i]
            d_mask = dmasks[i] if dmasks is not None else None
            if not mask.any() and (d_mask is None or not d_mask.any()):
                continue
            idx_pp = None
            if keep is not None:
                kept = mask & keep
                idx_pp = {p: idx for p in range(snap.num_parts)
                          if (idx := np.nonzero(kept[p])[0]).size}
            resp = self._materialize(snap, mask, ctx, yield_cols, s,
                                     idx_per_part=idx_pp)
            if d_mask is not None and d_mask.any():
                # delta rows are row_filter-ed (pre-cap) during
                # materialization, so one merged emit serves both
                base_for_cap = idx_pp if idx_pp is not None else mask
                dresp = self._materialize_delta(snap, d_mask, base_for_cap,
                                                ctx, yield_cols, s,
                                                row_filter=delta_rf)
                _merge_bound_resp(resp, dresp)
            roots_map = {v.vid: {root} for v in resp.vertices}
            st = ex._emit_go_rows(ctx, resp, rows, yield_cols, local_filter,
                                  alias_map, name_by_type, roots=roots_map,
                                  input_index=input_index, needs_input=True,
                                  needs_dst=needs_dst, input_var=input_var)
            if not st.ok():
                return StatusOr.from_status(st)
        result = ex.InterimResult(columns, rows)
        if s.yield_ and s.yield_.distinct:
            result = result.distinct()
        self.stats["go_served"] += 1
        self._record_profile("roots", t_snap, t_kernel,
                             time.monotonic() - t2, snap)
        return StatusOr.of(result)

    # ------------------------------------------------------------------
    # FIND SHORTEST PATH on device
    # ------------------------------------------------------------------
    def execute_find_path(self, ctx, s: ast.FindPathSentence,
                          sources: List[int], targets: List[int],
                          edge_types: List[int],
                          name_by_type: Dict[int, str]):
        """Ladder wrapper (see execute_go): an open "path" breaker or
        a device exception degrades to the CPU pipe, counted."""
        from ..graph import executors as ex
        if len(edge_types) > traverse.MAX_EDGE_TYPES_PER_QUERY:
            self._path_decline("too_many_edge_types")
            return None
        if not self._device_admit("path", ctx):
            return None
        heat_tok = self._heat_note_query(ctx, sources)
        try:
            with self._lock:   # delta applies mutate mirrors in place
                r = self._execute_find_path_locked(ctx, s, sources,
                                                   targets, edge_types,
                                                   name_by_type, ex)
        except Exception as e:
            return self._device_failed("path", e)
        finally:
            _heat.restore(heat_tok)
        if r is not None:
            self._device_ok("path")
        return r

    def _execute_find_path_locked(self, ctx, s, sources, targets,
                                  edge_types, name_by_type, ex):
        t0 = time.monotonic()
        snap = self._snapshot_locked(ctx.space_id())
        t_snap = time.monotonic() - t0
        if snap is None or not sources or not targets:
            if snap is None:
                return None
            return StatusOr.of(ex.InterimResult(["_path_"]))
        if not s.shortest:
            return self._find_all_paths(ctx, s, sources, targets,
                                        edge_types, name_by_type, snap, ex)
        # direction optimization: a short path on a big graph touches a
        # handful of edges — run the CPU bidirectional join over the
        # snapshot mirrors under the pull budget before paying the
        # dense O(E)-per-hop device BFS
        if getattr(snap, "sharded_kernel", None) is None:
            state = {"visited": 0}
            t1 = time.monotonic()
            try:
                paths = ex._shortest_paths(
                    ctx, ctx.space_id(), sources, targets, edge_types,
                    int(s.step.steps), name_by_type,
                    expand_fn=lambda f, t: self._mirror_adj(snap, f, t,
                                                            state))
            except _BudgetExceeded:
                pass
            else:
                self.stats["path_served"] += 1
                self.stats["sparse_served"] += 1
                self._record_profile("path-sparse", t_snap,
                                     time.monotonic() - t1, 0.0, snap)
                return StatusOr.of(ex.InterimResult(
                    ["_path_"], [(p,) for p in paths]))
        import jax.numpy as jnp
        f_src = snap.frontier_from_vids(sources)
        f_dst = snap.frontier_from_vids(targets)
        if not f_src.any() or not f_dst.any():
            return StatusOr.of(ex.InterimResult(["_path_"]))
        req_f = jnp.asarray(traverse.pad_edge_types(edge_types))
        req_b = jnp.asarray(traverse.pad_edge_types([-t for t in edge_types]))
        upto = s.step.steps
        use_delta = snap.delta is not None and snap.delta.edge_count > 0
        # halved-depth bidirectional sweep (ref: FindPathExecutor :155)
        steps_f = (upto + 1) // 2
        steps_b = upto - steps_f
        t1 = time.monotonic()
        if getattr(snap, "sharded_kernel", None) is not None:
            from . import distributed
            dist_f = np.asarray(distributed.bfs_dist_sharded(
                self.mesh, jnp.asarray(f_src), jnp.int32(steps_f),
                snap.sharded_kernel, req_f))
            dist_b = np.asarray(distributed.bfs_dist_sharded(
                self.mesh, jnp.asarray(f_dst), jnp.int32(max(steps_b, 0)),
                snap.sharded_kernel, req_b))
            self.stats["sharded_queries"] += 1
        elif use_delta:
            dk = snap.delta.device()
            dist_f = np.asarray(traverse.bfs_dist_delta(
                jnp.asarray(f_src), steps_f, snap.kernel, dk, req_f))
            dist_b = np.asarray(traverse.bfs_dist_delta(
                jnp.asarray(f_dst), max(steps_b, 0), snap.kernel, dk, req_b))
        else:
            dist_f = np.asarray(traverse.bfs_dist(
                jnp.asarray(f_src), steps_f, snap.kernel, req_f))
            dist_b = np.asarray(traverse.bfs_dist(
                jnp.asarray(f_dst), max(steps_b, 0), snap.kernel, req_b))
        t2 = time.monotonic()
        paths = _reconstruct_shortest(snap, dist_f, dist_b, sources, targets,
                                      edge_types, upto, name_by_type)
        self.stats["path_served"] += 1
        self._record_profile("path", t_snap, t2 - t1,
                             time.monotonic() - t2, snap)
        return StatusOr.of(ex.InterimResult(["_path_"], [(p,) for p in paths]))


# ---------------------------------------------------------------------------
# host-side helpers
# ---------------------------------------------------------------------------

def _calibration_roots(snap, k: int = 16) -> List[int]:
    """Representative seeds for the budget probe: each shard's top-
    degree vids (hub walks dominate the sparse cost) plus a couple of
    evenly-spaced ordinary vids per shard."""
    roots: List[int] = []
    for shard in snap.shards:
        n = shard.num_vids_base
        if n == 0:
            continue
        deg = np.diff(_shard_indptr(shard))[:n]
        if deg.size:
            order = np.argsort(deg)
            roots.extend(int(shard.vids[i]) for i in order[-2:])
        step = max(n // 2, 1)
        roots.extend(int(shard.vids[i]) for i in range(0, n, step)[:2])
    return list(dict.fromkeys(roots))[:k]


def _exact_int_sum_np(a: np.ndarray) -> int:
    """Exact Python-int sum of an int array of ANY magnitude: split
    each bias-shifted uint64 into 32-bit halves whose int64 partial
    sums cannot overflow below 2^31 elements (the pull budget is far
    smaller), then reassemble in Python ints — the host twin of
    aggregate.exact_int_sum's digit discipline."""
    if a.size == 0:
        return 0
    if a.dtype == object:
        return sum(int(x) for x in a.tolist())
    a = np.ascontiguousarray(a, np.int64)
    u = a.view(np.uint64) + np.uint64(1 << 63)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.int64)
    hi = (u >> np.uint64(32)).astype(np.int64)
    return ((int(hi.sum()) << 32) + int(lo.sum())) - (len(a) << 63)


def _reduce_sparse_one(fun: str, parts) -> Any:
    """One ungrouped aggregate over [(values, null_mask)] chunks with
    the CPU's _agg_apply semantics: nulls excluded, None when no
    non-null values, AVG = exact integer sum / count (Python int/int
    division, float result identical to the pipe's sum()/len())."""
    vals_l = [np.asarray(v)[~n] for v, n in parts]
    total_n = sum(int(x.size) for x in vals_l)
    if total_n == 0:
        return None
    if fun == "MIN":
        return min(int(np.min(x)) for x in vals_l if x.size)
    if fun == "MAX":
        return max(int(np.max(x)) for x in vals_l if x.size)
    s = sum(_exact_int_sum_np(x) for x in vals_l)
    return s if fun == "SUM" else s / total_n


def _collect_src_tags(ctx, yield_cols, s):
    from ..graph.executors import _collect_prop_requirements
    exprs = [c.expr for c in yield_cols]
    if s.where is not None:
        exprs.append(s.where.filter)
    return _collect_prop_requirements(exprs, ctx)


def _needs_dst(yield_cols, s) -> bool:
    from ..filter.expressions import DestPropExpr
    exprs = [c.expr for c in yield_cols]
    if s.where is not None:
        exprs.append(s.where.filter)
    for e in exprs:
        for node in e.walk():
            if isinstance(node, DestPropExpr):
                return True
    return False


def _merge_bound_resp(resp: BoundResponse, other: BoundResponse) -> None:
    """Merge `other`'s vertices into resp (same shape the CPU client's
    collectResponse produces for one host) — delta rows join base rows
    under their shared source vertex."""
    by_vid = {v.vid: v for v in resp.vertices}
    for v in other.vertices:
        mine = by_vid.get(v.vid)
        if mine is None:
            resp.vertices.append(v)
            by_vid[v.vid] = v
        else:
            mine.edges.extend(v.edges)
            for tid, props in v.tag_props.items():
                mine.tag_props.setdefault(tid, props)


def _base_active_count(snap, base, src_vid: int, etype: int) -> int:
    """Active base edges of (src, etype) in the final hop — the
    starting point for the per-vertex cap over delta rows. `base` is a
    dense [P, cap_e] bool mask OR a sparse {part0: ascending idx} dict
    (the pull-mode form)."""
    loc = snap.locate(src_vid)
    if loc is None:
        return 0
    p, local = loc
    shard = snap.shards[p]
    if local >= shard.num_vids_base:
        return 0    # delta vertex: no canonical rows
    indptr = _shard_indptr(shard)
    lo, hi = int(indptr[local]), int(indptr[local + 1])
    if lo >= hi:
        return 0
    if isinstance(base, dict):
        idx = base.get(p)
        if idx is None or idx.size == 0:
            return 0
        sel = idx[np.searchsorted(idx, lo):np.searchsorted(idx, hi)]
        return int((shard.edge_etype[sel] == etype).sum())
    seg = slice(lo, hi)
    return int((base[p, seg]
                & (shard.edge_etype[seg] == etype)).sum())


def _host_tag_props(shard, tag_id: int, local: int) -> Optional[Dict[str, Any]]:
    """Tag-row props dict for the slow (VertexData) path, or None when
    the vertex has no row for the tag. Keys the row's schema version
    doesn't carry are OMITTED — downstream expression eval then raises
    EvalError exactly like the CPU path's getters."""
    from .csr import host_item
    cols = shard.tag_props.get(tag_id)
    if cols is None:
        return None
    out: Dict[str, Any] = {}
    has_any = False
    for name, col in cols.items():
        if col.missing is not None:
            if col.missing[local]:
                continue
            has_any = True
            out[name] = host_item(col, local)
        else:
            # fast-build column: ~present means no row (nulls are not
            # reachable through current writes)
            if col.present is not None and not col.present[local]:
                continue
            has_any = True
            out[name] = host_item(col, local)
    return out if has_any else None


def _host_edge_props(shard, etype: int, edge_idx: int) -> Dict[str, Any]:
    """Edge-row props for the slow path; version-missing keys omitted
    (the CPU walk raises for them — see _host_tag_props)."""
    from .csr import host_item
    cols = shard.edge_props.get(etype)
    if not cols:
        return {}
    return {name: host_item(col, edge_idx) for name, col in cols.items()
            if col.missing is None or not col.missing[edge_idx]}


def _shard_indptr(shard) -> np.ndarray:
    """Lazy CSR indptr over the sorted edge_src array."""
    if not hasattr(shard, "_indptr"):
        nv = len(shard.vids)
        shard._indptr = np.searchsorted(shard.edge_src[:shard.num_edges],
                                        np.arange(nv + 1))
    return shard._indptr


def _reconstruct_shortest(snap: CsrSnapshot, dist_f: np.ndarray,
                          dist_b: np.ndarray, sources, targets,
                          edge_types: List[int], upto: int,
                          name_by_type: Dict[int, str]) -> List[str]:
    """Host-side path reconstruction from the two device BFS depth maps.

    Meet vertices minimize dist_f + dist_b; predecessor edges are found
    through the reverse-copy rows stored in each vertex's own partition
    (edge u->v of type t is stored at v as (v, -t, rank, u))."""
    both = (dist_f >= 0) & (dist_b >= 0)
    if not both.any():
        return []
    total = np.where(both, dist_f + dist_b, np.iinfo(np.int32).max)
    best = int(total.min())
    if best > upto:
        return []
    meets = np.argwhere(total == best)
    type_set = set(edge_types)
    rev_set = {-t for t in edge_types}

    def neighbors_at(vid: int, want_types, dist_map, level: int):
        """Vertices u adjacent to vid (through edges of want_types as seen
        FROM vid's partition rows) with dist_map[u] == level; returns
        (u, etype_seen, rank). Covers base CSR rows (skipping delta
        tombstones) plus delta-buffer rows whose row-src is vid."""
        loc = snap.locate(vid)
        if loc is None:
            return
        p, local = loc
        shard = snap.shards[p]
        if local < shard.num_vids_base:
            indptr = _shard_indptr(shard)
            for i in range(indptr[local], indptr[local + 1]):
                if not shard.edge_valid[i]:
                    continue   # tombstoned after build
                et = int(shard.edge_etype[i])
                if et not in want_types:
                    continue
                u = int(shard.edge_dst_vid[i])
                uloc = snap.locate(u)
                if uloc is None:
                    continue
                if dist_map[uloc[0], uloc[1]] == level:
                    yield u, et, int(shard.edge_rank[i])
        d = snap.delta
        if d is not None:
            gslot = p * snap.cap_v + local
            for slot in d.by_src.get(gslot, ()):
                info = d.info.get(slot)
                if info is None or not d.h_ok[slot]:
                    continue
                _, et, rank, u, _props = info
                if et not in want_types:
                    continue
                uloc = snap.locate(u)
                if uloc is None:
                    continue
                if dist_map[uloc[0], uloc[1]] == level:
                    yield u, et, rank

    # path entry = (vid, etype_into_vid, rank_into_vid); entry 0 carries
    # no edge info
    out = set()
    for p, local in meets:
        mid = snap.vid_of_slot(int(p), int(local))
        if mid is None:
            continue
        df = int(dist_f[p, local])
        db = int(dist_b[p, local])
        prefixes = [((mid, 0, 0),)]
        for level in range(df - 1, -1, -1):
            nxt = []
            for path in prefixes:
                v = path[0][0]
                # predecessor u -> v of forward type t is stored at v's
                # partition as the reverse row (v, -t, rank, u)
                for u, et_seen, rank in neighbors_at(v, rev_set, dist_f, level):
                    fixed_head = (v, -et_seen, rank)
                    nxt.append(((u, 0, 0), fixed_head) + path[1:])
            prefixes = nxt
            if not prefixes:
                break
        suffixes = [((mid, 0, 0),)]
        for level in range(db - 1, -1, -1):
            nxt = []
            for path in suffixes:
                v = path[-1][0]
                # successor v -> w: the forward row (v, t, rank, w) at v
                for w, et_seen, rank in neighbors_at(v, type_set, dist_b, level):
                    nxt.append(path + ((w, et_seen, rank),))
            suffixes = nxt
            if not suffixes:
                break
        for pre in prefixes:
            for suf in suffixes:
                full = pre + suf[1:]
                vids = [e[0] for e in full]
                steps = [(e[1], e[2]) for e in full[1:]]
                out.add(traverse_format(vids, steps, name_by_type))
    return sorted(out)


def traverse_format(vids, steps, name_by_type) -> str:
    parts = [str(vids[0])]
    for (et, rank), vid in zip(steps, vids[1:]):
        name = name_by_type.get(abs(et), str(abs(et)))
        parts.append(f"<{name},{rank}>{vid}")
    return "".join(parts)
