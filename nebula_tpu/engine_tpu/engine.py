"""TpuGraphEngine: the device-side query hot path.

The opt-in per-space TPU storage engine (BASELINE.json north star): GO
multi-hop expansion and FIND SHORTEST PATH run as compiled XLA programs
over CSR snapshots instead of per-hop storage RPCs. The query engine
consults `can_serve` per statement — anything unsupported falls back to
the CPU scatter/gather path, and materialized results flow through the
exact same yield-evaluation machinery (`_emit_go_rows`) so result sets
are identical by construction wherever both paths can serve.

Snapshot lifecycle: built lazily from the KV store on first use, keyed
to the engine's write_version + catalog version; stale snapshots are
rebuilt transparently (auto_refresh) — the Phase-6 upgrade path is
delta buffers + periodic repack (SURVEY.md §7 hard-part (a)).

Freshness model (remote topology): the token rides a push-fed watch
cache, not per-query probes. Writes through THIS graphd are strictly
read-your-writes (the client's local write seq is part of the token);
writes through ANOTHER graphd become visible within one watch push
(~50-150ms) — the same staleness class as the reference's 1s cached
topology pull (MetaClient.cpp:120-193). A local write currently
invalidates twice (seq bump now, version push later); cheap once
invalidation is a delta apply instead of a rebuild.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common.status import ErrorCode, Status, StatusOr
from ..filter.expressions import (Expression, InputPropExpr, VariablePropExpr)
from ..parser import ast
from ..storage.types import BoundResponse, EdgeData, PartResult, VertexData
from . import traverse
from .csr import CsrSnapshot
from .filter_compile import FilterCompiler

DEFAULT_MAX_EDGES_PER_VERTEX = 10000


def _uses_input_refs(exprs: List[Expression]) -> bool:
    for e in exprs:
        for node in e.walk():
            if isinstance(node, (InputPropExpr, VariablePropExpr)):
                return True
    return False


class TpuGraphEngine:
    def __init__(self, auto_refresh: bool = True, enabled: bool = True,
                 mesh=None):
        """mesh: optional jax.sharding.Mesh over the partition axis —
        snapshots whose part count divides the mesh get sharded kernels
        and traversals run distributed (all_to_all frontier exchange,
        ref role: StorageClient scatter/gather, StorageClient.inl:73-160).
        """
        self.auto_refresh = auto_refresh
        self.enabled = enabled
        self.mesh = mesh
        self._snapshots: Dict[int, CsrSnapshot] = {}
        self._provider = None
        self._sm = None
        self._meta = None
        self.stats = {"go_served": 0, "path_served": 0, "rebuilds": 0,
                      "fallbacks": 0, "sharded_queries": 0,
                      "fast_materialize": 0, "slow_materialize": 0}

    # ------------------------------------------------------------------
    def attach(self, cluster) -> None:
        from .provider import LocalStoreProvider
        self._provider = LocalStoreProvider(cluster.store, cluster.sm)
        self._sm = cluster.sm
        self._meta = cluster.meta

    def attach_raw(self, store, sm, meta=None) -> None:
        from .provider import LocalStoreProvider
        self._provider = LocalStoreProvider(store, sm)
        self._sm = sm
        self._meta = meta

    def attach_provider(self, provider, sm, meta=None) -> None:
        """Arbitrary snapshot feed — the RemoteStorageProvider path for
        the real 3-daemon topology (graphd --tpu)."""
        self._provider = provider
        self._sm = sm
        self._meta = meta

    # ------------------------------------------------------------------
    # snapshot lifecycle
    # ------------------------------------------------------------------
    def _catalog_version(self) -> int:
        v = getattr(self._meta, "catalog_version", 0) if self._meta else 0
        return v() if callable(v) else v

    def refresh(self, space_id: int) -> Optional[CsrSnapshot]:
        catalog = self._catalog_version()
        snap = self._provider.build(space_id)
        if snap is None:
            return None
        snap.catalog_version = catalog
        if (self.mesh is not None and self.mesh.devices.size > 1
                and snap.num_parts % self.mesh.devices.size == 0):
            from .distributed import shard_snapshot_arrays
            shard_snapshot_arrays(self.mesh, snap)
        self._snapshots[space_id] = snap
        self.stats["rebuilds"] += 1
        return snap

    def snapshot(self, space_id: int) -> Optional[CsrSnapshot]:
        if self._provider is None:
            return None
        token = self._provider.version(space_id)
        if token is None:
            return None
        snap = self._snapshots.get(space_id)
        fresh = (snap is not None
                 and snap.write_version == token
                 and getattr(snap, "catalog_version", -1) == self._catalog_version())
        if fresh:
            return snap
        if not self.auto_refresh:
            # operator controls rebuild timing; a stale snapshot must not
            # serve (results would be wrong) — decline so CPU path runs
            return None
        return self.refresh(space_id)

    # ------------------------------------------------------------------
    # serve decisions
    # ------------------------------------------------------------------
    def can_serve(self, space_id: int, s: ast.GoSentence) -> bool:
        if not (self.enabled and self._provider is not None):
            return False
        exprs = [c.expr for c in (s.yield_.columns if s.yield_ else [])]
        if s.where:
            exprs.append(s.where.filter)
        if _uses_input_refs(exprs):
            return False  # $-/$var back-references need CPU root tracking
        if s.step.upto:
            # UPTO emits one row per (edge, step); the device union mask
            # loses that multiplicity — CPU path serves it exactly
            return False
        return True

    def can_serve_path(self, space_id: int, s: ast.FindPathSentence) -> bool:
        return bool(self.enabled and self._provider is not None
                    and s.shortest)

    # ------------------------------------------------------------------
    # GO on device
    # ------------------------------------------------------------------
    def execute_go(self, ctx, s: ast.GoSentence, starts: List[int],
                   edge_types: List[int], alias_map: Dict[str, str],
                   name_by_type: Dict[int, str]):
        """Returns executors.Result, or None to fall back to CPU."""
        from ..graph import executors as ex
        if len(edge_types) > traverse.MAX_EDGE_TYPES_PER_QUERY:
            self.stats["fallbacks"] += 1
            return None
        snap = self.snapshot(ctx.space_id())
        if snap is None:
            self.stats["fallbacks"] += 1
            return None

        yield_cols = ex._go_yield_columns(s, ctx, name_by_type)
        columns = [c.name() for c in yield_cols]

        frontier0 = snap.frontier_from_vids(starts)
        if not frontier0.any():
            return StatusOr.of(ex.InterimResult(columns))
        import jax.numpy as jnp
        f0 = jnp.asarray(frontier0)
        req = jnp.asarray(traverse.pad_edge_types(edge_types))

        # filter: try device compile; else host-side at materialization
        device_mask = None
        local_filter = None
        if s.where is not None:
            fc = FilterCompiler(snap, self._sm, ctx.space_id(), name_by_type,
                                alias_map, edge_types)
            device_mask = fc.compile(s.where.filter)
            if device_mask is None:
                local_filter = s.where.filter

        if getattr(snap, "sharded_kernel", None) is not None:
            from . import distributed
            _, active = distributed.multi_hop_sharded(
                self.mesh, f0, jnp.int32(s.step.steps),
                snap.sharded_kernel, req)
            self.stats["sharded_queries"] += 1
        else:
            _, active = traverse.multi_hop(f0, s.step.steps, snap.kernel,
                                           req)
        if device_mask is not None:
            active = active & device_mask
        mask = np.asarray(active)

        rows: Optional[List[Tuple]] = None
        if local_filter is None:
            # columnar fast path: one numpy gather per YIELD column over
            # the host mirrors; declines (None) on any case whose CPU
            # semantics aren't a pure gather — identity by construction
            from . import materialize
            rows = materialize.emit_rows(snap, mask, ctx, yield_cols,
                                         alias_map, name_by_type)
        if rows is not None:
            self.stats["fast_materialize"] += 1
        else:
            self.stats["slow_materialize"] += 1
            resp = self._materialize(snap, mask, ctx, yield_cols, s)
            rows = []
            st = ex._emit_go_rows(ctx, resp, rows, yield_cols, local_filter,
                                  alias_map, name_by_type, roots={},
                                  input_index={}, needs_input=False,
                                  needs_dst=_needs_dst(yield_cols, s))
            if not st.ok():
                return StatusOr.from_status(st)
        result = ex.InterimResult(columns, rows)
        if s.yield_ and s.yield_.distinct:
            result = result.distinct()
        self.stats["go_served"] += 1
        return StatusOr.of(result)

    # ------------------------------------------------------------------
    def _materialize(self, snap: CsrSnapshot, mask: np.ndarray, ctx,
                     yield_cols, s) -> BoundResponse:
        """Compact the active-edge mask into the same BoundResponse shape
        the CPU storage path returns, reading props from host mirrors."""
        space = ctx.space_id()
        resp = BoundResponse()
        src_tag_reqs, _, _ = _collect_src_tags(ctx, yield_cols, s)
        per_vertex: Dict[int, VertexData] = {}
        cap_counts: Dict[Tuple[int, int], int] = {}
        for p in range(snap.num_parts):
            shard = snap.shards[p]
            idxs = np.nonzero(mask[p])[0]
            for i in idxs:
                i = int(i)
                src_vid = int(shard.vids[shard.edge_src[i]])
                et = int(shard.edge_etype[i])
                ckey = (src_vid, et)
                cap_counts[ckey] = cap_counts.get(ckey, 0) + 1
                if cap_counts[ckey] > DEFAULT_MAX_EDGES_PER_VERTEX:
                    continue
                vd = per_vertex.get(src_vid)
                if vd is None:
                    vd = VertexData(src_vid)
                    for tid in src_tag_reqs:
                        props = _host_tag_props(shard, tid,
                                                int(shard.edge_src[i]))
                        if props is not None:
                            vd.tag_props[tid] = props
                    per_vertex[src_vid] = vd
                props = _host_edge_props(shard, et, i)
                vd.edges.append(EdgeData(src_vid, et,
                                         int(shard.edge_rank[i]),
                                         int(shard.edge_dst_vid[i]), props))
            resp.results[p + 1] = PartResult()
        resp.vertices = list(per_vertex.values())
        return resp

    # ------------------------------------------------------------------
    # FIND SHORTEST PATH on device
    # ------------------------------------------------------------------
    def execute_find_path(self, ctx, s: ast.FindPathSentence,
                          sources: List[int], targets: List[int],
                          edge_types: List[int],
                          name_by_type: Dict[int, str]):
        from ..graph import executors as ex
        if len(edge_types) > traverse.MAX_EDGE_TYPES_PER_QUERY:
            return None
        snap = self.snapshot(ctx.space_id())
        if snap is None or not sources or not targets:
            if snap is None:
                return None
            return StatusOr.of(ex.InterimResult(["_path_"]))
        import jax.numpy as jnp
        f_src = snap.frontier_from_vids(sources)
        f_dst = snap.frontier_from_vids(targets)
        if not f_src.any() or not f_dst.any():
            return StatusOr.of(ex.InterimResult(["_path_"]))
        req_f = jnp.asarray(traverse.pad_edge_types(edge_types))
        req_b = jnp.asarray(traverse.pad_edge_types([-t for t in edge_types]))
        upto = s.step.steps
        # halved-depth bidirectional sweep (ref: FindPathExecutor :155)
        steps_f = (upto + 1) // 2
        steps_b = upto - steps_f
        if getattr(snap, "sharded_kernel", None) is not None:
            from . import distributed
            dist_f = np.asarray(distributed.bfs_dist_sharded(
                self.mesh, jnp.asarray(f_src), jnp.int32(steps_f),
                snap.sharded_kernel, req_f))
            dist_b = np.asarray(distributed.bfs_dist_sharded(
                self.mesh, jnp.asarray(f_dst), jnp.int32(max(steps_b, 0)),
                snap.sharded_kernel, req_b))
            self.stats["sharded_queries"] += 1
        else:
            dist_f = np.asarray(traverse.bfs_dist(
                jnp.asarray(f_src), steps_f, snap.kernel, req_f))
            dist_b = np.asarray(traverse.bfs_dist(
                jnp.asarray(f_dst), max(steps_b, 0), snap.kernel, req_b))
        paths = _reconstruct_shortest(snap, dist_f, dist_b, sources, targets,
                                      edge_types, upto, name_by_type)
        self.stats["path_served"] += 1
        return StatusOr.of(ex.InterimResult(["_path_"], [(p,) for p in paths]))


# ---------------------------------------------------------------------------
# host-side helpers
# ---------------------------------------------------------------------------

def _collect_src_tags(ctx, yield_cols, s):
    from ..graph.executors import _collect_prop_requirements
    exprs = [c.expr for c in yield_cols]
    if s.where is not None:
        exprs.append(s.where.filter)
    return _collect_prop_requirements(exprs, ctx)


def _needs_dst(yield_cols, s) -> bool:
    from ..filter.expressions import DestPropExpr
    exprs = [c.expr for c in yield_cols]
    if s.where is not None:
        exprs.append(s.where.filter)
    for e in exprs:
        for node in e.walk():
            if isinstance(node, DestPropExpr):
                return True
    return False


def _host_tag_props(shard, tag_id: int, local: int) -> Optional[Dict[str, Any]]:
    cols = shard.tag_props.get(tag_id)
    if cols is None:
        return None
    first = next(iter(cols.values()), None)
    if first is None or (first.present is not None and not first.present[local]):
        # vertex has no row for this tag
        has_any = any(c.present is not None and c.present[local]
                      for c in cols.values())
        if not has_any:
            return None
    return {name: col.host[local] for name, col in cols.items()}


def _host_edge_props(shard, etype: int, edge_idx: int) -> Dict[str, Any]:
    cols = shard.edge_props.get(etype)
    if not cols:
        return {}
    return {name: col.host[edge_idx] for name, col in cols.items()}


def _shard_indptr(shard) -> np.ndarray:
    """Lazy CSR indptr over the sorted edge_src array."""
    if not hasattr(shard, "_indptr"):
        nv = len(shard.vids)
        shard._indptr = np.searchsorted(shard.edge_src[:shard.num_edges],
                                        np.arange(nv + 1))
    return shard._indptr


def _reconstruct_shortest(snap: CsrSnapshot, dist_f: np.ndarray,
                          dist_b: np.ndarray, sources, targets,
                          edge_types: List[int], upto: int,
                          name_by_type: Dict[int, str]) -> List[str]:
    """Host-side path reconstruction from the two device BFS depth maps.

    Meet vertices minimize dist_f + dist_b; predecessor edges are found
    through the reverse-copy rows stored in each vertex's own partition
    (edge u->v of type t is stored at v as (v, -t, rank, u))."""
    both = (dist_f >= 0) & (dist_b >= 0)
    if not both.any():
        return []
    total = np.where(both, dist_f + dist_b, np.iinfo(np.int32).max)
    best = int(total.min())
    if best > upto:
        return []
    meets = np.argwhere(total == best)
    type_set = set(edge_types)
    rev_set = {-t for t in edge_types}

    def neighbors_at(vid: int, want_types, dist_map, level: int):
        """Vertices u adjacent to vid (through edges of want_types as seen
        FROM vid's partition rows) with dist_map[u] == level; returns
        (u, etype_seen, rank)."""
        loc = snap.locate(vid)
        if loc is None:
            return
        p, local = loc
        shard = snap.shards[p]
        indptr = _shard_indptr(shard)
        for i in range(indptr[local], indptr[local + 1]):
            et = int(shard.edge_etype[i])
            if et not in want_types:
                continue
            u = int(shard.edge_dst_vid[i])
            uloc = snap.locate(u)
            if uloc is None:
                continue
            if dist_map[uloc[0], uloc[1]] == level:
                yield u, et, int(shard.edge_rank[i])

    # path entry = (vid, etype_into_vid, rank_into_vid); entry 0 carries
    # no edge info
    out = set()
    for p, local in meets:
        mid = int(snap.shards[p].vids[local])
        df = int(dist_f[p, local])
        db = int(dist_b[p, local])
        prefixes = [((mid, 0, 0),)]
        for level in range(df - 1, -1, -1):
            nxt = []
            for path in prefixes:
                v = path[0][0]
                # predecessor u -> v of forward type t is stored at v's
                # partition as the reverse row (v, -t, rank, u)
                for u, et_seen, rank in neighbors_at(v, rev_set, dist_f, level):
                    fixed_head = (v, -et_seen, rank)
                    nxt.append(((u, 0, 0), fixed_head) + path[1:])
            prefixes = nxt
            if not prefixes:
                break
        suffixes = [((mid, 0, 0),)]
        for level in range(db - 1, -1, -1):
            nxt = []
            for path in suffixes:
                v = path[-1][0]
                # successor v -> w: the forward row (v, t, rank, w) at v
                for w, et_seen, rank in neighbors_at(v, type_set, dist_b, level):
                    nxt.append(path + ((w, et_seen, rank),))
            suffixes = nxt
            if not suffixes:
                break
        for pre in prefixes:
            for suf in suffixes:
                full = pre + suf[1:]
                vids = [e[0] for e in full]
                steps = [(e[1], e[2]) for e in full[1:]]
                out.add(traverse_format(vids, steps, name_by_type))
    return sorted(out)


def traverse_format(vids, steps, name_by_type) -> str:
    parts = [str(vids[0])]
    for (et, rank), vid in zip(steps, vids[1:]):
        name = name_by_type.get(abs(et), str(abs(et)))
        parts.append(f"<{name},{rank}>{vid}")
    return "".join(parts)
