"""Expression → vectorized device mask compiler.

The TPU answer to the reference's per-row filter closures (ref:
storage/QueryBaseProcessor.inl:415-443 binds getters to KV iterators,
evaluated edge-by-edge): instead of evaluating the expression tree per
edge, compile it once into jnp operations producing a bool mask over
the whole [P, cap_e] edge block (SURVEY.md §7 hard-part (c)).

Supported on device: literals; edge props; `$^` source-vertex props
(gathered through edge_src); `$$` dest-vertex props (gathered through
the dst global index); arithmetic / relational / logical operators;
string equality via dictionary codes. Anything else (functions, $-,
$var, _rank/_src/_dst literals, casts) returns None — the engine then
runs the traversal unfiltered on device and applies the filter on the
host during materialization, preserving exact semantics.

Null semantics mirror the CPU path: comparisons against a missing
property are false (tracked with presence masks; DOUBLE uses NaN which
is naturally false in comparisons).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..codec.schema import PropType
from ..filter.expressions import (ArithmeticExpr, DestPropExpr, EdgePropExpr,
                                  Expression, Literal, LogicalExpr,
                                  RelationalExpr, SourcePropExpr, UnaryExpr)


class _Unsupported(Exception):
    pass


class _Val:
    """A compiled sub-expression: device value + presence + kind."""

    __slots__ = ("kind", "value", "present", "str_meta")

    def __init__(self, kind: str, value, present, str_meta=None):
        self.kind = kind          # 'num' | 'bool' | 'strcode' | 'strlit'
        self.value = value        # jnp array or python scalar
        self.present = present    # jnp bool array or None (always present)
        self.str_meta = str_meta  # (kind, schema_id, prop) for strcode


class FilterCompiler:
    def __init__(self, snapshot, sm, space_id: int,
                 name_by_type: Dict[int, str], alias_map: Dict[str, str],
                 edge_types: List[int]):
        self.snap = snapshot
        self.sm = sm
        self.space_id = space_id
        self.name_by_type = name_by_type
        self.alias_map = alias_map
        self.edge_types = edge_types

    def compile(self, expr: Expression) -> Optional[jnp.ndarray]:
        """-> bool mask [P, cap_e], or None if not device-compilable."""
        try:
            v = self._compile(expr)
            if v.kind != "bool":
                return None
            mask = v.value
            if v.present is not None:
                mask = mask & v.present
            return mask
        except _Unsupported:
            return None

    # ------------------------------------------------------------------
    def _edge_prop_val(self, prop: str,
                       allowed_types: Optional[List[int]] = None) -> _Val:
        """Value of an edge prop, selected per edge by its stored etype.

        `allowed_types` restricts which edge types the reference is
        valid for (a qualified `e1.prop` must evaluate as absent on
        edges of other types, mirroring the CPU path's EvalError)."""
        snap = self.snap
        types = allowed_types if allowed_types is not None else self.edge_types
        acc = None
        present = jnp.zeros(snap.d_edge_etype.shape, dtype=bool)
        is_string = None
        for et in types:
            col = snap.device_edge_prop(et, prop)
            if col is None:
                continue
            # column dtype tells us the prop kind for this etype
            col_is_string = self._edge_prop_type(et, prop) == PropType.STRING
            if is_string is None:
                is_string = col_is_string
            elif is_string != col_is_string:
                raise _Unsupported()
            sel = snap.d_edge_etype == et
            pres = sel & self._edge_prop_present(et, prop)
            if acc is None:
                acc = jnp.where(sel, col, 0 if col.dtype != jnp.float32
                                else jnp.float32(jnp.nan))
            else:
                acc = jnp.where(sel, col, acc)
            present = present | pres
        if acc is None:
            raise _Unsupported()
        if is_string:
            return _Val("strcode", acc, present, ("e", prop))
        if acc.dtype == jnp.bool_:
            return _Val("bool", acc, present)
        return _Val("num", acc, present)

    def _edge_prop_type(self, et: int, prop: str) -> Optional[PropType]:
        r = self.sm.edge_schema(self.space_id, et)
        return r.value().field_type(prop) if r.ok() else None

    def _edge_prop_present(self, et: int, prop: str) -> jnp.ndarray:
        cols = []
        for s in self.snap.shards:
            col = s.edge_props.get(et, {}).get(prop)
            if col is None or col.present is None:
                cols.append(np.zeros(self.snap.cap_e, bool))
            else:
                cols.append(col.present)
        return jnp.asarray(np.stack(cols))

    def _src_prop_val(self, tag: str, prop: str) -> _Val:
        tid = self.sm.tag_id(self.space_id, tag)
        if tid is None:
            raise _Unsupported()
        col = self.snap.device_tag_prop(tid, prop)
        if col is None:
            raise _Unsupported()
        ptype = self.sm.tag_schema(self.space_id, tid).value().field_type(prop)
        pres_np = np.stack([
            s.tag_props.get(tid, {}).get(prop).present
            if s.tag_props.get(tid, {}).get(prop) is not None
            else np.zeros(self.snap.cap_v, bool)
            for s in self.snap.shards])
        # gather per-edge source values: [P, cap_v] -> [P, cap_e]
        vals = jnp.take_along_axis(col, self.snap.d_edge_src, axis=1)
        pres = jnp.take_along_axis(jnp.asarray(pres_np),
                                   self.snap.d_edge_src, axis=1)
        if ptype == PropType.STRING:
            return _Val("strcode", vals, pres, ("t", prop))
        if col.dtype == jnp.bool_:
            return _Val("bool", vals, pres)
        return _Val("num", vals, pres)

    def _dst_prop_val(self, tag: str, prop: str) -> _Val:
        tid = self.sm.tag_id(self.space_id, tag)
        if tid is None:
            raise _Unsupported()
        col = self.snap.device_tag_prop(tid, prop)
        if col is None:
            raise _Unsupported()
        ptype = self.sm.tag_schema(self.space_id, tid).value().field_type(prop)
        pres_np = np.stack([
            s.tag_props.get(tid, {}).get(prop).present
            if s.tag_props.get(tid, {}).get(prop) is not None
            else np.zeros(self.snap.cap_v, bool)
            for s in self.snap.shards])
        # flatten [P, cap_v] -> [P*cap_v] + dump slot, gather by global idx
        flat = jnp.concatenate([col.reshape(-1),
                                jnp.zeros((1,), col.dtype)])
        flat_p = jnp.concatenate([jnp.asarray(pres_np).reshape(-1),
                                  jnp.zeros((1,), jnp.bool_)])
        vals = flat[self.snap.d_edge_gidx]
        pres = flat_p[self.snap.d_edge_gidx]
        if ptype == PropType.STRING:
            return _Val("strcode", vals, pres, ("t", prop))
        if col.dtype == jnp.bool_:
            return _Val("bool", vals, pres)
        return _Val("num", vals, pres)

    # ------------------------------------------------------------------
    def _compile(self, e: Expression) -> _Val:
        if isinstance(e, Literal):
            v = e.value
            if isinstance(v, bool):
                return _Val("bool", v, None)
            if isinstance(v, (int, float)):
                return _Val("num", v, None)
            if isinstance(v, str):
                return _Val("strlit", v, None)
            raise _Unsupported()
        if isinstance(e, EdgePropExpr):
            allowed = None
            if e.edge is not None:
                canon = self.alias_map.get(e.edge, e.edge)
                allowed = [t for t in self.edge_types
                           if self.name_by_type.get(abs(t)) == canon]
                if not allowed:
                    raise _Unsupported()
            return self._edge_prop_val(e.prop, allowed)
        if isinstance(e, SourcePropExpr):
            return self._src_prop_val(e.tag, e.prop)
        if isinstance(e, DestPropExpr):
            return self._dst_prop_val(e.tag, e.prop)
        if isinstance(e, UnaryExpr):
            v = self._compile(e.operand)
            if e.op == "!" and v.kind == "bool":
                return _Val("bool", ~v.value if hasattr(v.value, "dtype")
                            else (not v.value), v.present)
            if e.op == "-" and v.kind == "num":
                return _Val("num", -v.value, v.present)
            if e.op == "+" and v.kind == "num":
                return v
            raise _Unsupported()
        if isinstance(e, ArithmeticExpr):
            l = self._compile(e.left)
            r = self._compile(e.right)
            if l.kind != "num" or r.kind != "num":
                raise _Unsupported()
            pres = _and_present(l.present, r.present)
            if e.op == "+":
                return _Val("num", l.value + r.value, pres)
            if e.op == "-":
                return _Val("num", l.value - r.value, pres)
            if e.op == "*":
                return _Val("num", l.value * r.value, pres)
            if e.op == "/":
                return _Val("num", l.value / r.value, pres)
            if e.op == "%":
                return _Val("num", l.value % r.value, pres)
            raise _Unsupported()
        if isinstance(e, RelationalExpr):
            l = self._compile(e.left)
            r = self._compile(e.right)
            pres = _and_present(l.present, r.present)
            # string comparisons: only == / != via dict codes
            if "strcode" in (l.kind, r.kind):
                if e.op not in ("==", "!="):
                    raise _Unsupported()
                code_side, lit_side = (l, r) if l.kind == "strcode" else (r, l)
                if lit_side.kind != "strlit":
                    raise _Unsupported()
                kind, prop = code_side.str_meta
                code = self.snap.str_code(kind, prop, lit_side.value)
                m = code_side.value == code
                if e.op == "!=":
                    m = ~m
                return _Val("bool", m, pres)
            if l.kind == "strlit" or r.kind == "strlit":
                raise _Unsupported()
            if l.kind == "bool" and r.kind == "bool" and e.op in ("==", "!="):
                m = (l.value == r.value) if e.op == "==" else (l.value != r.value)
                return _Val("bool", m, pres)
            if l.kind != "num" or r.kind != "num":
                raise _Unsupported()
            ops = {"==": lambda a, b: a == b, "!=": lambda a, b: a != b,
                   "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
                   ">": lambda a, b: a > b, ">=": lambda a, b: a >= b}
            if e.op not in ops:
                raise _Unsupported()
            return _Val("bool", ops[e.op](l.value, r.value), pres)
        if isinstance(e, LogicalExpr):
            l = self._compile(e.left)
            r = self._compile(e.right)
            if l.kind != "bool" or r.kind != "bool":
                raise _Unsupported()
            lv = l.value if l.present is None else (l.value & l.present)
            rv = r.value if r.present is None else (r.value & r.present)
            if e.op == "&&":
                return _Val("bool", lv & rv, None)
            if e.op == "||":
                return _Val("bool", lv | rv, None)
            return _Val("bool", lv ^ rv, None)
        raise _Unsupported()


def _and_present(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b
