"""Expression → vectorized device mask compiler.

The TPU answer to the reference's per-row filter closures (ref:
storage/QueryBaseProcessor.inl:146-167 decodes the pushed expression,
:415-443 binds getters to KV iterators, evaluated edge-by-edge):
instead of evaluating the expression tree per edge, compile it once
into jnp operations producing a bool mask over the whole [P, cap_e]
edge block (SURVEY.md §7 hard-part (c)).

Exact-semantics discipline — each node tracks THREE states per edge
slot, identical to filter_host.py (see its module doc for the rules):
value / null (explicit NULL, CPU relational null rules) / err (the CPU
walk raises EvalError: prop missing from the row's schema version,
vertex without the referenced tag, division by zero). err propagation
follows CPU evaluation order including && / || short-circuit. The
final mask is `truthy(value) & ~null & ~err`.

Supported on device: literals; edge props; `$^` source-vertex props
(gathered through edge_src); `$$` dest-vertex props (gathered through
the dst global index); arithmetic / relational / logical operators
(int/int division C-style); string equality via dictionary codes.
Anything else (functions, $-, $var, casts) returns None — the engine
then runs the traversal unfiltered on device and applies the filter on
the host during materialization, preserving exact semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..codec.schema import PropType
from ..filter.expressions import (ArithmeticExpr, DestPropExpr, EdgePropExpr,
                                  Expression, Literal, LogicalExpr,
                                  RelationalExpr, SourcePropExpr, UnaryExpr)


class _Unsupported(Exception):
    pass


# numpy, not jnp: a module-level jnp constant would initialize the JAX
# backend at import time, before callers (bench.py, __graft_entry__)
# get a chance to force the platform — on a host whose accelerator
# relay is down that hangs every import. np.bool_ composes with jnp
# arrays identically (`~`, `&`, `|`, jnp.where all accept it).
_F = np.bool_(False)


class _Val:
    """A compiled sub-expression: device value + null/err masks."""

    __slots__ = ("kind", "value", "null", "err", "str_meta", "intlike")

    def __init__(self, kind: str, value, null=_F, err=_F, str_meta=None,
                 intlike=None):
        self.kind = kind          # 'num' | 'bool' | 'strcode' | 'strlit'
        self.value = value        # jnp array or python scalar
        self.null = null          # jnp bool array/scalar
        self.err = err            # jnp bool array/scalar
        self.str_meta = str_meta  # (kind, prop) for strcode
        self.intlike = intlike    # num only: True=int, False=float


def _truthy(v: _Val):
    """CPU _truthy over (value, null): null is falsy; num != 0."""
    if v.kind == "bool":
        t = v.value
    elif v.kind == "num":
        t = v.value != 0
    else:
        raise _Unsupported()
    return t & ~v.null


class FilterCompiler:
    def __init__(self, snapshot, sm, space_id: int,
                 name_by_type: Dict[int, str], alias_map: Dict[str, str],
                 edge_types: List[int]):
        self.snap = snapshot
        self.sm = sm
        self.space_id = space_id
        self.name_by_type = name_by_type
        self.alias_map = alias_map
        self.edge_types = edge_types

    def compile(self, expr: Expression) -> Optional[jnp.ndarray]:
        """-> bool mask [P, cap_e] (True = row passes), or None if not
        device-compilable."""
        try:
            v = self._compile(expr)
            if v.kind not in ("bool", "num"):
                return None
            return _truthy(v) & ~v.err
        except _Unsupported:
            return None

    # ------------------------------------------------------------------
    def _col_states(self, kind: str, sid: int, prop: str, cap: int):
        """Per-shard (null, err) stacks for a column, [P, cap] device
        arrays (filter_host._leaf_states, stacked): with a `missing`
        mask err = missing, null = ~present & ~missing; without one
        ~present means no-row/expired which the CPU path raises for."""
        nulls, errs = [], []
        for s in self.snap.shards:
            store = s.edge_props if kind == "e" else s.tag_props
            col = store.get(sid, {}).get(prop)
            if col is None:
                nulls.append(np.zeros(cap, bool))
                errs.append(np.ones(cap, bool))
                continue
            pres = col.present if col.present is not None \
                else np.ones(cap, bool)
            if col.missing is not None:
                errs.append(col.missing)
                nulls.append(~pres & ~col.missing)
            else:
                errs.append(~pres)
                nulls.append(np.zeros(cap, bool))
        return jnp.asarray(np.stack(nulls)), jnp.asarray(np.stack(errs))

    def _edge_prop_val(self, prop: str,
                       allowed_types: Optional[List[int]] = None) -> _Val:
        """Value of an edge prop, selected per edge by its stored etype.

        `allowed_types` restricts which edge types the reference is
        valid for (a qualified `e1.prop` must evaluate as absent on
        edges of other types, mirroring the CPU path's EvalError)."""
        snap = self.snap
        types = allowed_types if allowed_types is not None else self.edge_types
        acc = None
        # slots whose requested type has no column for this prop: the
        # CPU getter raises "prop not found"
        null = jnp.zeros(snap.d_edge_etype.shape, dtype=bool)
        err = jnp.ones(snap.d_edge_etype.shape, dtype=bool)
        is_string = None
        intlike = None
        kind = None
        for et in types:
            col = snap.device_edge_prop(et, prop)
            if col is None:
                continue
            ptype = self._edge_prop_type(et, prop)
            if ptype == PropType.DOUBLE:
                # the device mirror is float32 — comparing through it
                # diverges from the CPU's exact float64 compare; the
                # host vectorized evaluator serves doubles instead
                raise _Unsupported()
            k = ("strcode" if ptype == PropType.STRING else
                 "bool" if ptype == PropType.BOOL else "num")
            col_is_string = k == "strcode"
            if kind is None:
                kind = k
                is_string = col_is_string
                intlike = True
            elif kind != k:
                # a bool/int mix would silently promote bools to
                # numbers in jnp.where — CPU treats the kinds as
                # incomparable per row; fall back
                raise _Unsupported()
            sel = snap.d_edge_etype == et
            cn, ce = self._col_states("e", et, prop, snap.cap_e)
            if acc is None:
                acc = jnp.where(sel, col, 0)
            else:
                acc = jnp.where(sel, col, acc)
            null = jnp.where(sel, cn, null)
            err = jnp.where(sel, ce, err)
        if acc is None:
            raise _Unsupported()
        if is_string:
            return _Val("strcode", acc, null, err, ("e", prop))
        if acc.dtype == jnp.bool_:
            return _Val("bool", acc, null, err)
        return _Val("num", acc, null, err, intlike=intlike)

    def _edge_prop_type(self, et: int, prop: str) -> Optional[PropType]:
        r = self.sm.edge_schema(self.space_id, et)
        return r.value().field_type(prop) if r.ok() else None

    def _tag_prop_val(self, tag: str, prop: str, dest: bool) -> _Val:
        """$^ (gather through edge_src) or $$ (gather through the dst
        global index) tag prop as per-edge values.

        Tag-prop semantics (ref VertexHolder::get → getDefaultProp,
        GoExecutor.cpp:1009-1018): a vertex with NO tag row reads as
        the schema default — its device cell already encodes the type
        default (0 / False; strings get the interned ""-code patched
        in). Outside the exact surface (DOUBLE, explicit defaults,
        nullable, columns with missing-version masks — which mix
        "no row" with "version lacks the prop") the host walk serves."""
        snap = self.snap
        tid = self.sm.tag_id(self.space_id, tag)
        if tid is None:
            raise _Unsupported()
        col = snap.device_tag_prop(tid, prop)
        if col is None:
            raise _Unsupported()
        r = self.sm.tag_schema(self.space_id, tid)
        f = r.value().field(prop) if r.ok() else None
        if f is None or f.type == PropType.DOUBLE or \
                f.default is not None or f.nullable:
            raise _Unsupported()
        ptype = f.type
        is_string = ptype == PropType.STRING
        patches = []
        for s in snap.shards:
            c = s.tag_props.get(tid, {}).get(prop)
            if c is None:
                if is_string:
                    patches.append(np.ones(snap.cap_v, bool))
                continue
            if c.version_missing and c.missing is not None \
                    and c.missing.any():
                raise _Unsupported()
            if is_string:
                patches.append(~c.present if c.present is not None
                               else np.zeros(snap.cap_v, bool))
        if is_string:
            sd = snap.str_dicts.setdefault(("t", prop), {})
            default_code = sd.setdefault("", len(sd))
            patch_v = jnp.asarray(np.stack(patches))
            col = jnp.where(patch_v, jnp.int32(default_code), col)
        # numeric/bool device cells already hold the type default at
        # absent slots (0 / False)
        if dest:
            # the dump slot (invalid edges) reads as default too — such
            # edges are masked out of `active` before the filter lands
            flat = jnp.concatenate([col.reshape(-1),
                                    jnp.zeros((1,), col.dtype)])
            vals = flat[snap.d_edge_gidx]
        else:
            vals = jnp.take_along_axis(col, snap.d_edge_src, axis=1)
        if ptype == PropType.STRING:
            return _Val("strcode", vals, _F, _F, ("t", prop))
        if col.dtype == jnp.bool_:
            return _Val("bool", vals, _F, _F)
        return _Val("num", vals, _F, _F, intlike=True)

    # ------------------------------------------------------------------
    def _compile(self, e: Expression) -> _Val:
        if isinstance(e, Literal):
            v = e.value
            if isinstance(v, bool):
                return _Val("bool", v)
            if isinstance(v, (int, float)):
                return _Val("num", v, intlike=isinstance(v, int))
            if isinstance(v, str):
                return _Val("strlit", v)
            raise _Unsupported()
        if isinstance(e, EdgePropExpr):
            allowed = None
            if e.edge is not None:
                canon = self.alias_map.get(e.edge, e.edge)
                allowed = [t for t in self.edge_types
                           if self.name_by_type.get(abs(t)) == canon]
                if not allowed:
                    raise _Unsupported()
            return self._edge_prop_val(e.prop, allowed)
        if isinstance(e, SourcePropExpr):
            return self._tag_prop_val(e.tag, e.prop, dest=False)
        if isinstance(e, DestPropExpr):
            return self._tag_prop_val(e.tag, e.prop, dest=True)
        if isinstance(e, UnaryExpr):
            v = self._compile(e.operand)
            if e.op == "!" and v.kind in ("bool", "num"):
                t = _truthy(v)
                return _Val("bool", ~t if hasattr(t, "dtype") else (not t),
                            _F, v.err)
            if e.op == "-" and v.kind == "num":
                # CPU: -None is _require_num -> EvalError
                return _Val("num", -v.value, _F, v.err | v.null,
                            intlike=v.intlike)
            if e.op == "+" and v.kind == "num":
                return _Val("num", v.value, _F, v.err | v.null,
                            intlike=v.intlike)
            raise _Unsupported()
        if isinstance(e, ArithmeticExpr):
            # device int arithmetic runs in int32 and would WRAP where
            # the CPU's python ints don't (age * 10^8 flips sign) —
            # arithmetic filters go to the vectorized int64 host
            # evaluator instead
            raise _Unsupported()
        if isinstance(e, RelationalExpr):
            # CPU null rules (expressions.py RelationalExpr.eval): the
            # result is never null — null==null is True, null!=x is
            # True iff exactly one side is null, null under an ordering
            # operator is False.
            l = self._compile(e.left)
            r = self._compile(e.right)
            err = l.err | r.err
            both = ~l.null & ~r.null
            # string comparisons: only == / != via dict codes
            if "strcode" in (l.kind, r.kind):
                if e.op not in ("==", "!="):
                    raise _Unsupported()
                code_side, lit_side = (l, r) if l.kind == "strcode" else (r, l)
                if lit_side.kind != "strlit":
                    raise _Unsupported()
                kind, prop = code_side.str_meta
                code = self.snap.str_code(kind, prop, lit_side.value)
                if e.op == "==":
                    return _Val("bool", (code_side.value == code) & both,
                                _F, err)
                return _Val("bool",
                            jnp.where(both, code_side.value != code, True),
                            _F, err)
            if l.kind == "strlit" or r.kind == "strlit":
                raise _Unsupported()
            eq_kinds = (l.kind == "bool" and r.kind == "bool") or \
                (l.kind == "num" and r.kind == "num")
            if not eq_kinds:
                raise _Unsupported()
            for side in (l, r):
                if isinstance(side.value, float):
                    # a float literal against the int32 device mirror
                    # would compare in float32; CPU compares in exact
                    # float64 — host evaluator serves it
                    raise _Unsupported()
                if isinstance(side.value, int) and not isinstance(
                        side.value, bool) and not (
                        -(1 << 31) <= side.value < (1 << 31)):
                    raise _Unsupported()  # literal outside int32 range
            ops = {"==": lambda a, b: a == b, "!=": lambda a, b: a != b,
                   "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
                   ">": lambda a, b: a > b, ">=": lambda a, b: a >= b}
            if e.op not in ops:
                raise _Unsupported()
            m = ops[e.op](l.value, r.value)
            if e.op == "==":
                return _Val("bool", jnp.where(both, m, l.null & r.null),
                            _F, err)
            if e.op == "!=":
                return _Val("bool", jnp.where(both, m, l.null ^ r.null),
                            _F, err)
            return _Val("bool", jnp.asarray(m) & both, _F, err)
        if isinstance(e, LogicalExpr):
            # err follows CPU evaluation order: left always evaluates;
            # right only when && sees a truthy left / || sees a falsy
            # left (short-circuit)
            l = self._compile(e.left)
            r = self._compile(e.right)
            lv, rv = _truthy(l), _truthy(r)
            if e.op == "&&":
                return _Val("bool", lv & rv, _F, l.err | (lv & r.err))
            if e.op == "||":
                return _Val("bool", lv | rv, _F, l.err | (~lv & r.err))
            return _Val("bool", lv ^ rv, _F, l.err | r.err)
        raise _Unsupported()
