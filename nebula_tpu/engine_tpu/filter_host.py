"""Expression → vectorized HOST mask evaluator for pull-mode queries.

The sparse (pull-mode) half of the engine previously evaluated WHERE
filters through the executor's per-row expression walk — a Python loop
that turns a 10^6-edge sparse result into seconds of host time (the
round-3 bench's 12s p99 outlier). This module is the host-mirror twin
of `filter_compile.FilterCompiler`: the same expression surface,
compiled to NUMPY gathers over the snapshot's per-shard host mirrors
and evaluated only at the ACTIVE edge indices the sparse walk produced
— O(active edges) vectorized, no per-row Python.

Exact-semantics discipline (the identity north star): every node
tracks THREE states per row, mirroring the CPU walk
(filter/expressions.py + the _StorageExprContext getters):

  value  — the computed value
  null   — the value is an SQL-ish NULL (explicit null bit in the row);
           relational ops have special null rules
           (expressions.py RelationalExpr.eval), _truthy(None) is False
  err    — evaluating this cell RAISES EvalError on the CPU path
           (prop missing from the row's schema version, vertex without
           the referenced tag, division by zero, $^ prop of an edge
           type that lacks it): the row is dropped from WHERE results

err propagation follows CPU evaluation order, including && / ||
short-circuit: `true || r.missing` keeps the row, `r.missing && x`
drops it.

Role parity: the reference evaluates pushed-down filters per edge row
inside the storage hot loop (storage/QueryBaseProcessor.inl:415-443);
here the pull path evaluates them as one vectorized pass per part.

Anything outside the supported surface (functions, $-, $var, casts,
string ordering, int/float-mixed division) returns None from `compile`
and the engine keeps the exact per-row Python walk.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..codec.schema import PropType
from ..filter.expressions import (ArithmeticExpr, DestPropExpr, EdgePropExpr,
                                  Expression, Literal, LogicalExpr,
                                  RelationalExpr, SourcePropExpr, UnaryExpr)

_F = np.False_


class _Unsupported(Exception):
    pass


class _Val:
    __slots__ = ("kind", "value", "null", "err", "intlike")

    def __init__(self, kind, value, null=_F, err=_F, intlike=None):
        self.kind = kind          # 'num' | 'bool' | 'strcode' | 'strlit'
        self.value = value        # np array or python scalar
        self.null = null          # bool mask / np scalar
        self.err = err            # bool mask / np scalar
        self.intlike = intlike    # num only: True=int, False=float
                                  # (drives C-style division semantics)


def _truthy(v: _Val):
    """CPU _truthy over (value, null): null is falsy; num != 0."""
    if v.kind == "bool":
        t = v.value
    elif v.kind == "num":
        t = np.asarray(v.value != 0)
    else:
        raise _Unsupported()
    return t & ~v.null


def _leaf_states(col, ii: np.ndarray):
    """(values, null, err) of a PropColumn at host indices ii.

    Three-state decode (PropColumn doc): with a `missing` mask, err =
    missing and null = ~present & ~missing. Without one (the fast
    single-version build), ~present can only mean no-row/expired cells
    — the CPU path raises for those, so err = ~present and null never
    fires (explicit nulls are not reachable through current nGQL
    writes; nullable isn't expressible in CREATE)."""
    pres = col.present[ii] if col.present is not None else \
        np.ones(len(ii), bool)
    if col.missing is not None:
        err = col.missing[ii]
        null = ~pres & ~err
    else:
        err = ~pres
        null = np.zeros(len(ii), bool)
    if col.ptype == PropType.STRING:
        if col.device_vals is None:
            raise _Unsupported()
        return col.device_vals[ii], null, err
    if col.host.dtype != object:
        return col.host[ii], null, err
    if col.ptype == PropType.DOUBLE:
        # object-host double column (python build path): the only
        # numeric mirror is float32 device_vals — comparing through it
        # diverges from the CPU's exact float64 compare; fall back
        raise _Unsupported()
    if col.device_vals is None or not col.device_ok:
        raise _Unsupported()
    return col.device_vals[ii], null, err


_ZERO_DT = {"strcode": np.int32, "bool": np.bool_, "num": np.float64}


class HostFilter:
    """Compiled filter: `eval_part(part0, idx) -> bool[len(idx)]` over
    canonical edge indices of one shard (True = row passes)."""

    def __init__(self, fn):
        self._fn = fn

    def eval_part(self, p0: int, idx: np.ndarray) -> np.ndarray:
        v = self._fn(p0, np.asarray(idx, np.int64))
        keep = _truthy(v) & ~v.err
        if not isinstance(keep, np.ndarray):
            keep = np.full(len(idx), bool(keep))
        return keep


class HostFilterCompiler:
    """Mirror of FilterCompiler over host mirrors (see module doc)."""

    def __init__(self, snapshot, sm, space_id: int,
                 name_by_type: Dict[int, str], alias_map: Dict[str, str],
                 edge_types: List[int]):
        self.snap = snapshot
        self.sm = sm
        self.space_id = space_id
        self.name_by_type = name_by_type
        self.alias_map = alias_map
        self.edge_types = edge_types

    def compile(self, expr: Expression) -> Optional[HostFilter]:
        try:
            fn = self._compile(expr)

            def root(p0, idx):
                v = fn(p0, idx)
                if v.kind not in ("bool", "num"):
                    raise _Unsupported()
                return v
            # probe once on an empty index set so unsupported shapes
            # fail at compile time, not mid-query
            root(0, np.empty(0, np.int64))
            return HostFilter(root)
        except _Unsupported:
            return None

    # -- leaf accessors ------------------------------------------------
    def _check_cols(self, kind: str, sid: int, prop: str) -> None:
        """Compile-time guard: every shard that has the column must be
        able to serve it vectorized (device encoding or numeric host)."""
        found = False
        for s in self.snap.shards:
            store = s.edge_props if kind == "e" else s.tag_props
            col = store.get(sid, {}).get(prop)
            if col is None:
                continue
            found = True
            if col.ptype == PropType.STRING:
                if col.device_vals is None:
                    raise _Unsupported()
            elif col.host.dtype == object and (
                    col.ptype == PropType.DOUBLE
                    or col.device_vals is None or not col.device_ok):
                raise _Unsupported()
        if not found and kind == "e":
            raise _Unsupported()

    @staticmethod
    def _kind_of(t: PropType) -> str:
        if t == PropType.STRING:
            return "strcode"
        if t == PropType.BOOL:
            return "bool"
        return "num"

    def _edge_prop(self, prop: str, allowed: Optional[List[int]]):
        types = allowed if allowed is not None else self.edge_types
        kind = None
        intlike = None
        for et in types:
            r = self.sm.edge_schema(self.space_id, abs(et))
            t = r.value().field_type(prop) if r.ok() else None
            if t is None:
                continue
            k = self._kind_of(t)
            if kind is None:
                kind = k
                intlike = t != PropType.DOUBLE
            elif kind != k:
                raise _Unsupported()
            elif intlike != (t != PropType.DOUBLE):
                # int/float mix across edge types: np.where would
                # upcast the int64 accumulator to float64, so compares
                # on ints beyond 2^53 could diverge from the CPU's
                # exact compare — per-row walk serves it (same
                # treatment as the bool/num mix above)
                raise _Unsupported()
        if kind is None:
            raise _Unsupported()
        for et in types:
            self._check_cols("e", et, prop)
        snap = self.snap

        def fn(p0, idx):
            shard = snap.shards[p0]
            ets = shard.edge_etype[idx]
            n = len(idx)
            acc = None
            null = np.zeros(n, bool)
            # rows whose requested type has no column for this prop:
            # the CPU getter raises "prop not found"
            err = np.ones(n, bool)
            for et in types:
                col = shard.edge_props.get(et, {}).get(prop)
                if col is None:
                    continue
                vals, cn, ce = _leaf_states(col, idx)
                sel = ets == et
                if acc is None:
                    acc = np.zeros(n, vals.dtype)
                acc = np.where(sel, vals, acc)
                null = np.where(sel, cn, null)
                err = np.where(sel, ce, err)
            if acc is None:
                acc = np.zeros(n, _ZERO_DT[kind])
            return _Val(kind, acc, null, err, intlike)
        fn._str_key = ("e", prop) if kind == "strcode" else None
        return fn

    def _tag_prop_fn(self, tag: str, prop: str):
        """-> (kind, intlike, per-(shard, local-idx) gather closure).

        Tag-prop semantics (ref VertexHolder::get → getDefaultProp,
        GoExecutor.cpp:1009-1018): a vertex with NO tag row — incl.
        TTL-expired, and shards where no vertex carries the tag —
        evaluates to the schema default; a row whose VERSION lacks the
        prop stays err (CPU raises). Fields with an explicit default
        are outside this vectorized surface (mirrors encode type
        defaults at absent cells) — per-row walk serves them."""
        tid = self.sm.tag_id(self.space_id, tag)
        if tid is None:
            raise _Unsupported()
        r = self.sm.tag_schema(self.space_id, tid)
        f = r.value().field(prop) if r.ok() else None
        if f is None or f.default is not None or f.nullable:
            # explicit defaults aren't encoded in the mirrors, and
            # explicit NULLs aren't defaults — per-row walk serves both
            raise _Unsupported()
        t = f.type
        self._check_cols("t", tid, prop)
        for s in self.snap.shards:
            c = s.tag_props.get(tid, {}).get(prop)
            if c is not None and c.version_missing and \
                    c.missing is not None and c.missing.any():
                # a multi-version mask mixes "no row" (default) with
                # "version lacks the prop" (CPU raises) — the per-row
                # walk separates them exactly. Delta-materialized
                # masks (tombstones) are pure no-row: default cells.
                raise _Unsupported()
        snap = self.snap
        kind = self._kind_of(t)
        intlike = t != PropType.DOUBLE if kind == "num" else None

        empty_code = None
        if kind == "strcode":
            # "" must have ONE consistent code everywhere — intern it
            # into the global (kind, prop) dict the columns share
            sd = snap.str_dicts.setdefault(("t", prop), {})
            empty_code = sd.setdefault("", len(sd))

        def gather(p0, locals_):
            """-> (vals | None, null, err); vals None = every cell is
            the type default (no column in this shard; numeric/bool —
            strings fill the interned ""-code instead). Absent cells
            (no tag row; the missing-mask case was declined above)
            read as the type default — 0/False already encoded in the
            mirrors."""
            n = len(locals_)
            no_null = np.zeros(n, bool)
            col = snap.shards[p0].tag_props.get(tid, {}).get(prop)
            if col is None:
                if kind == "strcode":
                    return (np.full(n, empty_code, np.int32),
                            no_null, no_null)
                return None, no_null, no_null
            vals, _null, _err = _leaf_states(col, locals_)
            if kind == "strcode" and col.present is not None:
                absent = ~col.present[locals_]
                if absent.any():
                    vals = np.where(absent, np.int32(empty_code), vals)
            return vals, no_null, no_null
        return kind, intlike, gather

    # -- expression walk ----------------------------------------------
    def _compile(self, e: Expression):
        snap = self.snap
        if isinstance(e, Literal):
            v = e.value
            if isinstance(v, bool):
                return lambda p0, idx: _Val("bool", v)
            if isinstance(v, (int, float)):
                il = isinstance(v, int)
                return lambda p0, idx: _Val("num", v, intlike=il)
            if isinstance(v, str):
                return lambda p0, idx: _Val("strlit", v)
            raise _Unsupported()
        if isinstance(e, EdgePropExpr):
            allowed = None
            if e.edge is not None:
                canon = self.alias_map.get(e.edge, e.edge)
                allowed = [t for t in self.edge_types
                           if self.name_by_type.get(abs(t)) == canon]
                if not allowed:
                    raise _Unsupported()
            return self._edge_prop(e.prop, allowed)
        if isinstance(e, (SourcePropExpr, DestPropExpr)):
            kind, intlike, gather = self._tag_prop_fn(e.tag, e.prop)
            prop = e.prop
            if isinstance(e, SourcePropExpr):
                def sfn(p0, idx):
                    shard = snap.shards[p0]
                    vals, null, err = gather(p0, shard.edge_src[idx])
                    if vals is None:
                        vals = np.zeros(len(idx), _ZERO_DT[kind])
                    return _Val(kind, vals, null, err, intlike)
                sfn._str_key = ("t", prop) if kind == "strcode" else None
                return sfn

            def dfn(p0, idx):
                shard = snap.shards[p0]
                dp = shard.edge_dst_part[idx]
                dl = shard.edge_dst_local[idx].astype(np.int64)
                n = len(idx)
                # value buffer adopts the first real column's dtype —
                # forcing float64 would silently round int64 tag props
                vals = None
                null = np.zeros(n, bool)
                err = np.ones(n, bool)
                for q in np.unique(dp):
                    sel = dp == q
                    v, cn, ce = gather(int(q), dl[sel])
                    null[sel] = cn
                    err[sel] = ce
                    if v is None:
                        continue      # all-err shard: values unused
                    if vals is None:
                        vals = np.zeros(n, v.dtype)
                    elif vals.dtype != v.dtype:
                        vals = vals.astype(np.result_type(vals.dtype,
                                                          v.dtype))
                    vals[sel] = v
                if vals is None:
                    vals = np.zeros(n, _ZERO_DT[kind])
                return _Val(kind, vals, null, err, intlike)
            dfn._str_key = ("t", prop) if kind == "strcode" else None
            return dfn
        if isinstance(e, UnaryExpr):
            f = self._compile(e.operand)
            op = e.op

            def ufn(p0, idx):
                v = f(p0, idx)
                if op == "!" and v.kind in ("bool", "num"):
                    t = _truthy(v)
                    nv = ~t if isinstance(t, np.ndarray) else (not t)
                    return _Val("bool", nv, _F, v.err)
                if op == "-" and v.kind == "num":
                    # CPU: -None is _require_num -> EvalError
                    return _Val("num", -v.value, _F, v.err | v.null,
                                v.intlike)
                if op == "+" and v.kind == "num":
                    return _Val("num", v.value, _F, v.err | v.null,
                                v.intlike)
                raise _Unsupported()
            return ufn
        if isinstance(e, ArithmeticExpr):
            lf, rf = self._compile(e.left), self._compile(e.right)
            op = e.op
            if op not in ("+", "-", "*", "/", "%"):
                raise _Unsupported()

            def afn(p0, idx):
                l, r = lf(p0, idx), rf(p0, idx)
                if l.kind != "num" or r.kind != "num":
                    raise _Unsupported()
                # CPU _require_num(None) raises -> null operands err
                err = l.err | r.err | l.null | r.null
                a, b = l.value, r.value
                both_int = l.intlike and r.intlike
                if op == "+":
                    return _Val("num", a + b, _F, err, both_int)
                if op == "-":
                    return _Val("num", a - b, _F, err, both_int)
                if op == "*":
                    return _Val("num", a * b, _F, err, both_int)
                # CPU: x/0 and x%0 raise EvalError; int/int divides
                # C-style — via float64 exactly like python's int(l/r);
                # a static int/float mix can't vectorize either branch
                if l.intlike is None or r.intlike is None:
                    raise _Unsupported()
                zero = np.asarray(b == 0)
                err = err | zero
                safe_b = np.where(zero, 1, b)
                with np.errstate(divide="ignore", invalid="ignore"):
                    if op == "/":
                        q = np.asarray(a) / safe_b
                        if both_int:
                            q = np.trunc(q).astype(np.int64)
                        return _Val("num", q, _F, err, both_int)
                    if not both_int:
                        raise _Unsupported()  # CPU: % requires integers
                    return _Val("num", np.fmod(np.asarray(a), safe_b),
                                _F, err, True)
            return afn
        if isinstance(e, RelationalExpr):
            lf, rf = self._compile(e.left), self._compile(e.right)
            op = e.op

            def rfn(p0, idx):
                # CPU null rules (expressions.py RelationalExpr.eval):
                # the result is never null — null==null is True,
                # null!=x is True iff exactly one side is null, null
                # under an ordering operator is False
                l, r = lf(p0, idx), rf(p0, idx)
                err = l.err | r.err
                both = ~l.null & ~r.null
                if "strcode" in (l.kind, r.kind):
                    if op not in ("==", "!="):
                        raise _Unsupported()
                    code_side, lit_side = (l, r) if l.kind == "strcode" \
                        else (r, l)
                    if lit_side.kind != "strlit":
                        raise _Unsupported()
                    code_fn = lf if l.kind == "strcode" else rf
                    kind, prop = code_fn._str_key
                    code = snap.str_code(kind, prop, lit_side.value)
                    if op == "==":
                        return _Val("bool",
                                    (code_side.value == code) & both,
                                    _F, err)
                    return _Val("bool",
                                np.where(both, code_side.value != code,
                                         True), _F, err)
                if l.kind == "strlit" or r.kind == "strlit":
                    raise _Unsupported()
                eq_kinds = (l.kind == "bool" and r.kind == "bool") or \
                    (l.kind == "num" and r.kind == "num")
                if not eq_kinds:
                    raise _Unsupported()
                ops = {"==": np.equal, "!=": np.not_equal, "<": np.less,
                       "<=": np.less_equal, ">": np.greater,
                       ">=": np.greater_equal}
                if op not in ops:
                    raise _Unsupported()
                m = ops[op](l.value, r.value)
                if op == "==":
                    return _Val("bool", np.where(both, m, l.null & r.null),
                                _F, err)
                if op == "!=":
                    return _Val("bool", np.where(both, m, l.null ^ r.null),
                                _F, err)
                return _Val("bool", np.asarray(m) & both, _F, err)
            return rfn
        if isinstance(e, LogicalExpr):
            lf, rf = self._compile(e.left), self._compile(e.right)
            op = e.op

            def lfn(p0, idx):
                # err follows CPU evaluation order: left always
                # evaluates; right only when && sees a truthy left /
                # || sees a falsy left (short-circuit)
                l, r = lf(p0, idx), rf(p0, idx)
                lv, rv = _truthy(l), _truthy(r)
                if op == "&&":
                    return _Val("bool", lv & rv, _F,
                                l.err | (lv & r.err))
                if op == "||":
                    return _Val("bool", lv | rv, _F,
                                l.err | (~lv & r.err))
                return _Val("bool", lv ^ rv, _F, l.err | r.err)
            return lfn
        raise _Unsupported()
