"""Device-resident fused serve programs: one launch per dispatcher
chunk, one fetch per result (docs/manual/13-device-speed.md).

BENCH_r05 measured tier1_hbm_util_vs_peak at 0.01 with dispatcher_wait
+ kernel dominating the tier-3 span breakdown — the chip idles between
host-synchronized stages. This module closes those seams:

1. FUSED WINDOW PROGRAMS — the hop advance (traverse._masks_batch_core
   / the vmapped multi_hop), the compiled-WHERE lane filters
   (filter_compile device masks), and the final canonical gather run
   as ONE jitted program. Per-request `mask & np.asarray(device_mask)`
   host ANDs (a D2H transfer of the full [P, cap_e] mask PER REQUEST
   per window) disappear: the window's distinct compiled masks ride
   along as a stacked [NF, P, cap_e] operand and each lane selects its
   own (`fsel`, -1 = unfiltered lane).

2. FUSED AGGREGATE PROGRAMS — the aggregation pushdown's traversal,
   filter, err-cell audit (previously one `jnp.any` host sync PER err
   mask) and the exact per-column partials (non-null count, MIN/MAX
   lattice, the 8-bit digit-chunk sums of aggregate.exact_int_sum)
   return as one pytree in one fetch. Exactness discipline is
   byte-identical to aggregate.py: int32 digit partials over chunks of
   SUM_CHUNK slots, host reassembly in Python ints.

3. FRONTIER DOUBLE-BUFFERING (FrontierPool) — window N+1's frontier
   stack H2D transfer is staged asynchronously (jax.device_put) while
   window N's kernel is still in flight; the fused window programs
   DONATE the frontier argument (donate_argnums=0) so XLA may recycle
   the staged buffer for outputs. The pool alternates conceptual slots
   by construction: each staged buffer is consumed (donated) by
   exactly one launch, and the next window stages into fresh memory
   while the previous launch still owns its slot. The launch-site
   audit counts `donation_fallbacks` only when aliasing was actually
   POSSIBLE (output byte size matches the donated buffer) yet the
   backend left the input alive — size-mismatched launches (the
   normal cap_e != cap_v case) and no-aliasing backends are expected
   non-donations, never counted, never warned per launch.

Program SIGNATURES: (kind, batch bucket, filter arity bucket, layout
statics). `steps` and the requested edge types are traced operands —
varying them NEVER compiles a new program; WHERE shapes collapse to
the filter-arity bucket because compiled filters are mask OPERANDS,
not program structure. The per-snapshot registry
(TpuGraphEngine._fused_entry) binds snapshot arrays per signature and
counts hits/misses/signatures so recompile behavior is observable and
bounded (tests/test_fused.py asserts the bound).

Every fused entry point stays behind the PR 3 ladder: callers fire
`faults.fire("kernel.launch")` immediately before the launch and wrap
the call in the per-feature breaker, so chaos runs trip and recover
through the fused loop exactly as through the old one.
"""
from __future__ import annotations

import threading
import time
import warnings
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import ledger as _ledger
from . import traverse
from .aggregate import SUM_CHUNK, _BIAS

# distinct compiled WHERE masks fused into one window program; windows
# mixing more shapes than this fall back to the per-request host AND
# (counted as fused_declined — the signature space stays bounded)
MAX_WINDOW_FILTERS = 8

# donation fallbacks are COUNTED (FrontierPool), not warned per launch
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def filter_bucket(n_filters: int) -> int:
    """Pad the distinct-filter count to exactly TWO operand arities —
    1 (the common single-WHERE-shape window) or MAX_WINDOW_FILTERS —
    so prewarm can compile EVERY filtered lane-program shape up front
    and no filtered window ever pays a cold XLA compile under the
    engine lock. The multi-shape pad wastes some operand bytes on
    windows mixing 2..MAX-1 shapes; those windows are rare, cold
    compiles under the launch lock are 20-40s on first chip contact."""
    return 1 if n_filters <= 1 else MAX_WINDOW_FILTERS


def _apply_lane_filters(masks: jnp.ndarray, fmasks: jnp.ndarray,
                        fsel: jnp.ndarray) -> jnp.ndarray:
    """AND each lane's compiled WHERE mask into the window masks ON
    DEVICE: fsel[b] indexes the stacked distinct masks; -1 marks an
    unfiltered lane (its mask passes through untouched)."""
    sel = fmasks[jnp.maximum(fsel, 0)]           # [B, P, cap_e]
    return masks & ((fsel < 0)[:, None, None] | sel)


@partial(jax.jit, static_argnames=("chunk", "group"), donate_argnums=(0,))
def window_lane(f0s: jnp.ndarray, steps: jnp.ndarray, ak, k,
                req_types: jnp.ndarray, fmasks, fsel, *,
                chunk: int, group: int) -> jnp.ndarray:
    """Fused lane-matrix dispatcher window: hop advance + final
    canonical gather + per-lane compiled WHERE filters in ONE program.
    fmasks/fsel None -> unfiltered (a distinct trace, not a distinct
    operand shape). The frontier stack is DONATED."""
    masks = traverse._masks_batch_core(f0s, steps, ak, k, req_types,
                                       chunk, group)
    if fmasks is None:
        return masks
    return _apply_lane_filters(masks, fmasks, fsel)


@partial(jax.jit, donate_argnums=(0,))
def window_vmap(f0s: jnp.ndarray, steps: jnp.ndarray, k,
                req_types: jnp.ndarray, fmasks, fsel) -> jnp.ndarray:
    """Fused vmapped window — the variant backends that lower vmap
    efficiently pick via the batched-kernel calibration. Identical
    semantics to multi_hop_roots + per-lane filter AND."""
    masks = jax.vmap(
        lambda f: traverse.multi_hop(f, steps, k, req_types)[1])(f0s)
    if fmasks is None:
        return masks
    return _apply_lane_filters(masks, fmasks, fsel)


@jax.jit
def traverse_filtered(f0: jnp.ndarray, steps: jnp.ndarray, k,
                      req_types: jnp.ndarray, fmask, err_mask):
    """Fused prologue of the GROUPED aggregation pushdown: traversal +
    compiled WHERE + err-cell audit in one program. -> (active mask
    [P, cap_e] — stays on device for grouped_reduce — and the single
    err_any scalar that used to cost one host sync per err mask)."""
    _, active = traverse.multi_hop(f0, steps, k, req_types)
    if fmask is not None:
        active = active & fmask
    err_any = jnp.zeros((), bool) if err_mask is None \
        else jnp.any(active & err_mask)
    return active, err_any


@partial(jax.jit, static_argnames=("chunk_slots",))
def agg_reduce(f0: jnp.ndarray, steps: jnp.ndarray, k,
               req_types: jnp.ndarray, fmask, err_mask, values, nulls,
               *, chunk_slots: int):
    """Fused UNGROUPED aggregation pushdown: traversal + filter + err
    audit + exact per-column partials, one launch / one fetch.

    values int32[NV, P, cap_e], nulls bool[NV, P, cap_e] (NV = distinct
    aggregate value columns; None when only COUNT is requested).
    Returns (err_any bool, n_rows int32, None | (nn int32[NV],
    mn int32[NV], mx int32[NV], digits int32[NV, 4, P, n_chunks])).

    Exactness is aggregate.py's, unchanged: n_rows/nn are int32 row
    counts (cap_e < 2^31), MIN/MAX are int32 lattice ops under the
    mask, and SUM rides bias-shifted 8-bit digit partials summed in
    int32 over chunks of `chunk_slots <= SUM_CHUNK` slots (chunk_sum
    <= chunk_slots * 255 < 2^30) — the host reassembles Python ints.
    """
    _, active = traverse.multi_hop(f0, steps, k, req_types)
    if fmask is not None:
        active = active & fmask
    err_any = jnp.zeros((), bool) if err_mask is None \
        else jnp.any(active & err_mask)
    n_rows = jnp.sum(active)                     # int32, like reduce_specs
    if values is None:
        return err_any, n_rows, None
    m = active[None] & ~nulls                    # [NV, P, cap_e]
    nn = m.sum(axis=(1, 2), dtype=jnp.int32)
    mn = jnp.min(jnp.where(m, values, jnp.int32(2**31 - 1)), axis=(1, 2))
    mx = jnp.max(jnp.where(m, values, jnp.int32(-(2**31))), axis=(1, 2))
    u = values.astype(jnp.uint32) + jnp.uint32(_BIAS)
    NV, P, cap = u.shape
    pad = (-cap) % chunk_slots
    if pad:
        u = jnp.pad(u, ((0, 0), (0, 0), (0, pad)))
        m = jnp.pad(m, ((0, 0), (0, 0), (0, pad)))
    u = u.reshape(NV, P, -1, chunk_slots)
    m4 = m.reshape(NV, P, -1, chunk_slots)
    digits = []
    for kd in range(4):
        d = ((u >> jnp.uint32(8 * kd)) & jnp.uint32(0xFF)).astype(jnp.int32)
        digits.append(jnp.sum(jnp.where(m4, d, 0), axis=-1))
    return err_any, n_rows, (nn, mn, mx, jnp.stack(digits, axis=1))


def assemble_agg_row(keyed_specs: List[Tuple[str, Any]],
                     key_index: Dict[Any, int], n_rows: int,
                     parts) -> List:
    """Host tail of agg_reduce: the exact result row, value-identical
    to aggregate.reduce_specs (Python ints/floats/None only)."""
    row: List = []
    if parts is not None:
        nn, mn, mx, digits = (np.asarray(a) for a in parts)
    for fun, key in keyed_specs:
        if fun == "COUNT":
            row.append(int(n_rows))
            continue
        i = key_index[key]
        c = int(nn[i])
        if c == 0:
            row.append(None)                     # CPU: no non-null values
            continue
        if fun == "MIN":
            row.append(int(mn[i]))
        elif fun == "MAX":
            row.append(int(mx[i]))
        else:
            total = 0
            for kd in range(4):
                # object-dtype accumulation: chunk partials are exact
                # int32, their Python-int sum is exact at any scale
                total += int(digits[i, kd].astype(object).sum()) << (8 * kd)
            total -= c * _BIAS
            row.append(total if fun == "SUM" else total / c)
    return row


def combine_err_masks(err_masks: List, shape: Tuple[int, int]):
    """Fold the compiled err masks into the single program operand:
    None (nothing can err), or a [P, cap_e] bool device array. Scalar
    leaves (filter_compile's np.bool_ False literals) fold away; a
    degenerate scalar-True err errs everywhere, like the CPU walk."""
    comb = None
    for em in err_masks:
        comb = em if comb is None else comb | em
    if comb is None:
        return None
    if not hasattr(comb, "shape") or comb.shape == ():
        if not bool(comb):
            return None
        return jnp.ones(shape, bool)
    return comb


def compile_cache_size() -> int:
    """Total XLA compile-cache entries across the fused entry points —
    the real recompile count the signature registry's misses upper-
    bound (the jit cache shares across snapshots of equal shapes)."""
    n = 0
    for fn in (window_lane, window_vmap, traverse_filtered, agg_reduce):
        try:
            n += fn._cache_size()
        except Exception:
            pass
    return n


class _Staged:
    """One staged frontier-stack H2D transfer (see FrontierPool)."""

    __slots__ = ("buf", "shape", "t0", "overlapped", "epoch0", "_pool",
                 "_donated")

    def __init__(self, buf, shape, t0: float, overlapped: bool,
                 epoch0: int, pool):
        self.buf = buf
        self.shape = shape
        self.t0 = t0
        self.overlapped = overlapped
        self.epoch0 = epoch0
        self._pool = pool
        self._donated = False

    def take(self):
        """Hand the device buffer to a launch. A transfer counts as
        overlapped if a kernel fetch was in flight when it was staged
        OR began between stage and take — the serve loop stages chunk
        N+1's prefetch just BEFORE its own fetch of chunk N's masks,
        so the overlap it creates is only visible at take time (the
        fetch epoch moved). Overlapped takes credit the wall time the
        transfer had to hide behind the kernel (`h2d_overlap_us`)."""
        with self._pool._lock:
            if not self.overlapped \
                    and self._pool._fetch_epoch > self.epoch0:
                self.overlapped = True
                self._pool.stats["overlapped"] += 1
            if self.overlapped:
                dt = int((time.monotonic() - self.t0) * 1e6)
                self._pool.stats["h2d_overlap_us"] += dt
        return self.buf

    def after_launch(self, donate_expected: bool = False) -> None:
        """Post-launch donation audit: if the launch was expected to
        donate the buffer (caller verified output/input byte sizes
        permit aliasing) but it survived, the backend fell back to a
        copy — counted, so HBM-pressure regressions are visible
        without drowning the counter in expected non-donations."""
        if self._donated:
            return
        self._donated = True
        if donate_expected:
            try:
                alive = not self.buf.is_deleted()
            except Exception:
                alive = False
            if alive:
                with self._pool._lock:
                    self._pool.stats["donation_fallbacks"] += 1


class FrontierPool:
    """Two-slot donated-buffer staging for window frontier stacks.

    stage() starts the H2D transfer immediately (jax.device_put is
    asynchronous); the caller launches later with take(). The serve
    loops stage chunk N+1 (and, via the dispatcher's early round
    release, window N+1's leader stages its first chunk) while chunk
    N's kernel wait (`fetch_begin`/`fetch_end` bracket the blocking
    np.asarray) is in flight — a stage during an active fetch, or one
    whose take() observes a fetch that began after it (the loop's own
    prefetch lands just before it blocks on the current chunk), counts
    as `overlapped`, and `h2d_overlap_us` accumulates the wall time
    each overlapped transfer had to hide. Donation (the launch consuming the buffer)
    keeps the pool at two live slots: the in-flight kernel owns one
    staged buffer, the prefetched window owns the other."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fetches = 0
        # bumped on every fetch_begin: lets take() detect a fetch that
        # STARTED after its stage (the serve loop's own prefetch lands
        # just before the loop blocks on the current chunk's masks)
        self._fetch_epoch = 0
        self.stats = {"stages": 0, "prefetch_hits": 0,
                      "prefetch_misses": 0, "overlapped": 0,
                      "h2d_overlap_us": 0, "donation_fallbacks": 0,
                      "h2d_bytes": 0}

    def fetch_begin(self) -> None:
        with self._lock:
            self._fetches += 1
            self._fetch_epoch += 1

    def fetch_end(self) -> None:
        with self._lock:
            self._fetches -= 1

    def stage(self, arr: np.ndarray) -> _Staged:
        with self._lock:
            self.stats["stages"] += 1
            self.stats["h2d_bytes"] += arr.nbytes
            overlapped = self._fetches > 0
            if overlapped:
                self.stats["overlapped"] += 1
            epoch0 = self._fetch_epoch
        # per-query cost ledger (common/ledger.py): the staging
        # thread's query carries the transfer — exact for solo windows
        # (the PROFILE case); a coalesced window's H2D lands on its
        # leader's query (see the ledger module doc)
        led = _ledger.current()
        if led is not None:
            led.h2d_bytes += arr.nbytes
        return _Staged(jax.device_put(arr), arr.shape, time.monotonic(),
                       overlapped, epoch0, self)

    def hit(self) -> None:
        with self._lock:
            self.stats["prefetch_hits"] += 1

    def miss(self) -> None:
        with self._lock:
            self.stats["prefetch_misses"] += 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.stats)
