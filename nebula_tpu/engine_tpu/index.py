"""Device-resident secondary property indexes (ROADMAP item 4).

Role parity with the reference's storage-side index scans
(`storage/index/LookUpProcessor` next to the KVStore): LOOKUP ON tag
WHERE prop OP value resolves through a named index instead of a full
scan. Here the index is a per-snapshot SORTED property array living on
device: one `(tag_id, prop)` pair -> values sorted ascending plus the
matching global vertex slots, binary-searched on device
(jnp.searchsorted is a lax-friendly O(log n) ladder) and gathered into
a vid set / frontier.

Design points, mirroring the CSR discipline (csr.py):

- Built on the same off-lock per-snapshot build path CSR uses
  (`TpuGraphEngine._build_fresh` builds cataloged indexes eagerly;
  anything missed builds lazily under the engine lock) and keyed by
  the snapshot's PR 5 write-version token — a committed write moves
  the token and structurally orphans the index (delta applies also
  clear the per-snapshot dict, poison purges it like the CSR caches).
- Values ride the narrow-width packing ladder: int columns re-pack to
  int8/int16 when their range allows (NEBULA_TPU_WIDE_CSR=1 pins
  int32, same switch as the edge arrays); the global slot array packs
  via `edge_index_dtype`. Query constants outside the packed range
  resolve host-side to all/nothing before touching the device.
- Byte-identity with the CPU scan twin is exact, not approximate:
  integer/bool/string-code searches are exact by construction; float
  columns are searched in the device's f32 encoding and only the
  equality BAND [lo, hi) — where f32 rounding could disagree with the
  host's f64 compare — is re-verified against the full-fidelity host
  mirror (f32 rounding is monotone, so everything outside the band is
  provably on the right side).
- String props are dictionary codes on device (csr.str_code):
  equality only; ordered string compares decline to the CPU scan.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..codec.schema import PropType
from .csr import (FORCE_WIDE_DTYPES, CsrSnapshot, PropColumn,
                  edge_index_dtype, host_item)

# ops a device index search can serve; "!=" walks the whole array and
# is better off on the CPU scan
SUPPORTED_OPS = ("==", "<", "<=", ">", ">=")


@dataclass
class PropIndex:
    """One (tag, prop) sorted-array index over one snapshot."""
    space_id: int
    tag_id: int
    prop: str
    ptype: PropType
    write_version: Any
    values_d: Any                 # device, sorted ascending (codes for str)
    gidx_d: Any                   # device, int16|int32 global slot per entry
    vids_sorted: np.ndarray       # host int64, parallel to values_d
    host_vals: np.ndarray         # host full-fidelity, parallel (band verify)
    is_float: bool
    is_str: bool
    count: int
    nbytes: int

    def matches_snapshot(self, snap: CsrSnapshot) -> bool:
        return self.write_version == snap.write_version


def _slot_vid(shard, local: int, delta_rev: Dict[int, int]) -> Optional[int]:
    if local < shard.num_vids_base:
        return int(shard.vids[local])
    return delta_rev.get(local)


def build_tag_index(snap: CsrSnapshot, tag_id: int,
                    prop: str) -> Optional[PropIndex]:
    """Build the sorted device index for (tag_id, prop) from the
    snapshot's host mirrors. None = this prop can't host a device
    index (no device-encodable column) — the CPU scan serves.
    Runs off-lock at build time or under the engine lock for lazy
    (post-delta) rebuilds; either way the caller owns installation."""
    import jax.numpy as jnp
    vals_parts: List[np.ndarray] = []
    host_parts: List[np.ndarray] = []
    gidx_parts: List[np.ndarray] = []
    vid_parts: List[np.ndarray] = []
    ptype: Optional[PropType] = None
    any_col = False
    for p0, shard in enumerate(snap.shards):
        col: Optional[PropColumn] = shard.tag_props.get(tag_id, {}).get(prop)
        if col is None:
            continue
        any_col = True
        if not col.device_ok or col.device_vals is None:
            return None
        if col.missing is not None:
            # mixed no-row / version-missing cells: the CPU's
            # schema-default-vs-error semantics can't be mirrored from
            # the present mask alone — the scan twin serves this prop
            return None
        ptype = col.ptype
        present = col.present
        if present is None:
            present = np.ones(len(col.device_vals), dtype=bool)
        slots = np.nonzero(present)[0]
        if len(slots) == 0:
            continue
        delta_rev = {loc: vid for vid, loc in shard.delta_vids.items()}
        vids = np.empty(len(slots), np.int64)
        keep = np.ones(len(slots), bool)
        for i, local in enumerate(slots):
            v = _slot_vid(shard, int(local), delta_rev)
            if v is None:
                keep[i] = False
            else:
                vids[i] = v
        slots = slots[keep]
        vids = vids[keep]
        if len(slots) == 0:
            continue
        vals_parts.append(col.device_vals[slots])
        hv = col.host[slots]
        host_parts.append(hv if hv.dtype != object else hv)
        gidx_parts.append(p0 * snap.cap_v + slots.astype(np.int64))
        vid_parts.append(vids)
    if not any_col or not vals_parts:
        # tag/prop exists but no rows: an EMPTY index still serves
        # (zero matches) as long as the column itself was indexable
        if not any_col:
            return None
        vals = np.zeros(0, np.int32)
        host_vals = np.zeros(0, np.int64)
        gidx = np.zeros(0, np.int64)
        vids = np.zeros(0, np.int64)
    else:
        vals = np.concatenate(vals_parts)
        host_vals = np.concatenate(host_parts)
        gidx = np.concatenate(gidx_parts)
        vids = np.concatenate(vid_parts)
    order = np.lexsort((vids, vals))
    vals = vals[order]
    host_vals = host_vals[order]
    gidx = gidx[order]
    vids = vids[order]
    is_float = vals.dtype.kind == "f"
    if is_float and len(vals) and np.isnan(vals.astype(np.float64)).any():
        # NaN sorts to the tail, so the ">" exact region would include
        # entries every python compare rejects — scan twin serves
        return None
    is_str = ptype == PropType.STRING if ptype is not None else False
    # narrow-width packing for int values (PR 7 ladder): int8/int16
    # when the value range allows, int32 fallback; the env pin wins
    if vals.dtype.kind == "i" and not FORCE_WIDE_DTYPES and len(vals):
        lo, hi = int(vals.min()), int(vals.max())
        for dt in (np.int8, np.int16):
            ii = np.iinfo(dt)
            if ii.min <= lo and hi <= ii.max:
                vals = vals.astype(dt)
                break
    gdt = edge_index_dtype(snap.num_parts * snap.cap_v)
    gidx_packed = gidx.astype(gdt)
    import jax.numpy as jnp
    values_d = jnp.asarray(vals)
    gidx_d = jnp.asarray(gidx_packed)
    return PropIndex(space_id=snap.space_id, tag_id=tag_id, prop=prop,
                     ptype=ptype or PropType.INT,
                     write_version=snap.write_version,
                     values_d=values_d, gidx_d=gidx_d,
                     vids_sorted=vids, host_vals=host_vals,
                     is_float=is_float, is_str=is_str,
                     count=len(vids),
                     nbytes=int(vals.nbytes + gidx_packed.nbytes))


def _py_cmp(op: str, a, b) -> bool:
    if a is None:
        return False
    if op == "==":
        return a == b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    return a >= b


def _cast_query(idx: PropIndex, op: str, value):
    """Map the python query constant into the packed dtype. Returns
    ("all",), ("none",) for range-resolved constants, ("val", v) to
    search, or ("decline",) when the comparison can't be exact."""
    dt = idx.values_d.dtype
    if idx.is_str:
        return ("val", value)    # caller passes the dict code already
    if dt.kind == "b":
        # bool column: python `True == 5` is False, so only a bool
        # constant compares exactly against the packed bool array
        if not isinstance(value, bool):
            return ("decline",)
        return ("val", np.asarray(value, dt), op)
    if isinstance(value, bool):
        value = int(value)
    if not isinstance(value, (int, float)):
        return ("decline",)
    if dt.kind == "i":
        if isinstance(value, float):
            if value != int(value):
                # fractional constant vs int column: resolve by shifting
                # to the neighbouring integer, exactly like the CPU's
                # mixed-type compare
                if op == "==":
                    return ("none",)
                if op in ("<", "<="):
                    value = int(np.floor(value))
                    op = "<="
                else:
                    value = int(np.ceil(value))
                    op = ">="
            else:
                value = int(value)
        info = np.iinfo(dt)
        if value > info.max:
            return ("all",) if op in ("<", "<=") else ("none",)
        if value < info.min:
            return ("all",) if op in (">", ">=") else ("none",)
        return ("val", np.asarray(value, dt), op)
    # float column: searches run in f32; the band re-verifies
    return ("val", np.asarray(float(value), dt), op)


def search(idx: PropIndex, op: str, value,
           query_value=None) -> Optional[np.ndarray]:
    """Device binary search -> matching vids (int64, unsorted).
    `value`: the device-comparable constant (dict code for strings);
    `query_value`: the original python constant for float band
    verification (defaults to `value`). None = decline (CPU serves)."""
    if op not in SUPPORTED_OPS:
        return None
    if idx.is_str and op != "==":
        return None              # dict codes aren't lexicographic
    if query_value is None:
        query_value = value
    if idx.count == 0:
        return np.zeros(0, np.int64)
    cast = _cast_query(idx, op, value)
    if cast[0] == "decline":
        return None
    if cast[0] == "all":
        return idx.vids_sorted.copy()
    if cast[0] == "none":
        return np.zeros(0, np.int64)
    v = cast[1]
    if len(cast) > 2:
        op = cast[2]
    import jax.numpy as jnp
    # the device part: O(log n) searchsorted ladder over the resident
    # sorted array (eager ops execute on the backend device)
    lo = int(jnp.searchsorted(idx.values_d, v, side="left"))
    hi = int(jnp.searchsorted(idx.values_d, v, side="right"))
    n = idx.count
    if op == "==":
        exact_sl: List[slice] = []
        band = slice(lo, hi)
    elif op == "<":
        exact_sl = [slice(0, lo)]
        band = slice(lo, hi)
    elif op == "<=":
        exact_sl = [slice(0, lo)]
        band = slice(lo, hi)
    elif op == ">":
        exact_sl = [slice(hi, n)]
        band = slice(lo, hi)
    else:  # ">="
        exact_sl = [slice(hi, n)]
        band = slice(lo, hi)
    out = [idx.vids_sorted[s] for s in exact_sl]
    if band.stop > band.start:
        if idx.is_float:
            # f32-equal band: re-verify against the f64 host mirror
            bh = idx.host_vals[band]
            keep = np.fromiter(
                (_py_cmp(op, (x.item() if isinstance(x, np.generic) else x),
                         query_value) for x in bh),
                dtype=bool, count=len(bh))
            out.append(idx.vids_sorted[band][keep])
        elif op in ("==", "<=", ">="):
            out.append(idx.vids_sorted[band])
        # for exact dtypes "<" / ">" exclude the equality band entirely
    if not out:
        return np.zeros(0, np.int64)
    return np.concatenate(out) if len(out) > 1 else out[0].copy()


def search_frontier(snap: CsrSnapshot, idx: PropIndex, op: str, value,
                    query_value=None) -> Optional[np.ndarray]:
    """Like search() but gathers the matched global slots into a
    bool[P, cap_v] frontier (the LOOKUP-seeded GO / MATCH entry)."""
    vids = search(idx, op, value, query_value)
    if vids is None:
        return None
    return snap.frontier_from_vids([int(v) for v in vids])
