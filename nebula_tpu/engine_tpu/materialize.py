"""Columnar GO-result materialization — the device-path answer to the
reference's in-scan row emission (ref storage/QueryBaseProcessor.inl:
380-458 emits encoded rows inside the storage hot loop).

The traversal kernel emits a bool edge mask; this module turns it into
result rows WITHOUT per-edge Python: the mask compacts to index arrays
(np.nonzero), every YIELD column compiles to one numpy gather over the
snapshot's host prop mirrors, and rows assemble with a single zip.

Identity discipline: each column planner handles only cases whose CPU
semantics are a pure per-row gather; ANYTHING else — unsupported
expression kinds, a row whose edge type mismatches a named prop ref
(CPU raises), a source/dst vertex missing a referenced tag (CPU
raises) — returns None and the engine falls back to the slow
VertexData path, which reproduces CPU behavior exactly. So the fast
path can only produce rows the slow path would have produced.
"""
from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..filter.expressions import (DestPropExpr, EdgeDstIdExpr, EdgePropExpr,
                                  EdgeRankExpr, EdgeSrcIdExpr, EdgeTypeExpr,
                                  Literal, SourcePropExpr)

DEFAULT_MAX_EDGES_PER_VERTEX = 10000

# PropType wire values (codec/schema.py) — materialize avoids importing
# the enum in the hot path
_PT_BOOL, _PT_INT, _PT_DOUBLE, _PT_STRING = 1, 2, 5, 6


class _PartEnv:
    """Shared per-part gathered arrays, built lazily once per column
    that needs them."""

    __slots__ = ("snap", "shard", "p0", "idx", "_cache")

    def __init__(self, snap, shard, p0: int, idx: np.ndarray):
        self.snap = snap
        self.shard = shard
        self.p0 = p0
        self.idx = idx
        self._cache: Dict[str, np.ndarray] = {}

    def _get(self, name: str, fn) -> np.ndarray:
        a = self._cache.get(name)
        if a is None:
            a = fn()
            self._cache[name] = a
        return a

    def src_local(self):
        return self._get("src_local", lambda: self.shard.edge_src[self.idx])

    def src_vid(self):
        return self._get("src_vid",
                         lambda: self.shard.vids[self.src_local()])

    def dst_vid(self):
        return self._get("dst_vid",
                         lambda: self.shard.edge_dst_vid[self.idx])

    def rank(self):
        return self._get("rank", lambda: self.shard.edge_rank[self.idx])

    def etype(self):
        return self._get("etype", lambda: self.shard.edge_etype[self.idx])


def _alias_match(env: _PartEnv, alias_name: str,
                 name_by_type: Dict[int, str]) -> np.ndarray:
    """bool[n]: rows whose edge name equals alias_name (the CPU
    _check_edge / _eval_yield None-masking rule)."""
    ets = env.etype()
    out = np.zeros(len(ets), bool)
    for t in np.unique(ets):
        if name_by_type.get(abs(int(t))) == alias_name:
            out |= ets == t
    return out


def _masked_object(vals: np.ndarray, match: np.ndarray) -> np.ndarray:
    out = vals.astype(object)
    out[~match] = None
    return out


def _plan(expr, sm, space: int, alias_map: Dict[str, str],
          name_by_type: Dict[int, str]
          ) -> Optional[Callable[[_PartEnv], Optional[np.ndarray]]]:
    """Compile one YIELD expression to a per-part column evaluator.
    None = not vectorizable (caller falls back to the slow path).

    KEEP IN SYNC with _plan_typed below: the deferred (encoded) path
    mirrors these per-case fallback rules with typed outputs — a
    semantic change here (alias-mismatch raise, missing-prop raise,
    version-missing fallback, tag default fill, nullable exclusion)
    must be mirrored there or the two fast paths diverge."""
    if isinstance(expr, Literal):
        v = expr.value
        return lambda env: np.full(len(env.idx), v, dtype=object)

    if isinstance(expr, (EdgeDstIdExpr, EdgeSrcIdExpr, EdgeRankExpr)):
        src = {EdgeDstIdExpr: _PartEnv.dst_vid, EdgeSrcIdExpr: _PartEnv.src_vid,
               EdgeRankExpr: _PartEnv.rank}[type(expr)]
        if expr.edge is None:
            return lambda env: src(env).astype(object)
        alias_name = alias_map.get(expr.edge, expr.edge)

        def named(env):
            # rows of another edge type yield None (the _eval_yield rule)
            return _masked_object(src(env),
                                  _alias_match(env, alias_name, name_by_type))
        return named

    if isinstance(expr, EdgeTypeExpr):
        def type_name(env):
            ets = env.etype()
            out = np.empty(len(ets), object)
            for t in np.unique(ets):
                out[ets == t] = name_by_type.get(abs(int(t)),
                                                 str(abs(int(t))))
            return out
        return type_name

    if isinstance(expr, EdgePropExpr):
        alias_name = (alias_map.get(expr.edge, expr.edge)
                      if expr.edge is not None else None)
        prop = expr.prop

        def edge_prop(env):
            ets = env.etype()
            out = np.empty(len(ets), object)
            for t in np.unique(ets):
                t = int(t)
                name = name_by_type.get(abs(t))
                if alias_name is not None and name != alias_name:
                    return None  # CPU raises on mismatched rows: fallback
                cols = env.shard.edge_props.get(t)
                if cols is None or prop not in cols:
                    return None  # CPU raises "prop not found": fallback
                sel = ets == t
                col = cols[prop]
                if col.missing is not None \
                        and col.missing[env.idx[sel]].any():
                    # a row's schema version lacks the prop: CPU raises
                    return None
                from .csr import host_gather
                out[sel] = host_gather(col, env.idx[sel]).tolist()
            return out
        return edge_prop

    if isinstance(expr, (SourcePropExpr, DestPropExpr)):
        # tag-prop semantics (ref VertexHolder::get → getDefaultProp,
        # GoExecutor.cpp:1009-1018): a vertex with NO tag row yields
        # the schema default; a row whose VERSION lacks the prop is a
        # CPU-raise (fallback); unknown tag/prop is a query error
        # (fallback: the slow path raises it exactly)
        tid = sm.tag_id(space, expr.tag)
        if tid is None:
            return None
        r = sm.tag_schema(space, tid)
        if not r.ok() or not r.value().has_field(expr.prop):
            return None           # unknown prop: CPU raises
        if r.value().field(expr.prop).nullable:
            return None    # explicit NULLs aren't defaults: slow path
        dflt = r.value().default_value(expr.prop)
        prop = expr.prop

        def tag_vals(shard, locals_):
            """column values at local slots with default fill, or None
            to fall back (version-missing cells)."""
            cols = shard.tag_props.get(tid)
            if cols is None or prop not in cols:
                return np.full(len(locals_), dflt, object)
            col = cols[prop]
            if col.version_missing and col.missing is not None \
                    and col.missing[locals_].any():
                return None       # version lacks the prop: CPU raises
            vals = col.host[locals_]
            if col.present is not None:
                pres = col.present[locals_]
                if not pres.all():
                    vals = np.where(pres, vals.astype(object), dflt)
            return vals

        if isinstance(expr, SourcePropExpr):
            def src_prop(env):
                return tag_vals(env.shard, env.src_local())
            return src_prop

        def dst_prop(env):
            dparts = env.shard.edge_dst_part[env.idx]
            dlocals = env.shard.edge_dst_local[env.idx]
            out = np.empty(len(env.idx), object)
            for q in np.unique(dparts):
                sel = dparts == q
                vals = tag_vals(env.snap.shards[int(q)], dlocals[sel])
                if vals is None:
                    return None
                out[sel] = np.asarray(vals, object)
            return out
        return dst_prop

    return None   # FunctionCall / arithmetic / $- refs: slow path


def _apply_cap(shard, idx: np.ndarray,
               cap: int = DEFAULT_MAX_EDGES_PER_VERTEX) -> np.ndarray:
    """Per-(src, etype) edge cap over ACTIVE edges — identical to the
    slow path's cap_counts (ref FLAGS_max_edge_returned_per_vertex).
    Active indices are ascending and canonical order groups (src,
    etype) contiguously, so within-group rank is positional."""
    if len(idx) <= cap:
        return idx
    grp_change = np.ones(len(idx), bool)
    src = shard.edge_src[idx]
    et = shard.edge_etype[idx]
    grp_change[1:] = (src[1:] != src[:-1]) | (et[1:] != et[:-1])
    starts = np.nonzero(grp_change)[0]
    counts = np.diff(np.append(starts, len(idx)))
    rank = np.arange(len(idx)) - np.repeat(starts, counts)
    return idx[rank < cap]


def emit_rows(snap, mask: Optional[np.ndarray], ctx, yield_cols, alias_map,
              name_by_type,
              idx_per_part: Optional[Dict[int, np.ndarray]] = None
              ) -> Optional[List[Tuple]]:
    """Fully-columnar GO row emission. None = fall back to the slow
    (VertexData) path. Only call when no CPU-side filter or input
    back-references remain (can_serve already excludes $-/$var).
    Active edges come from `mask` (dense [P, cap_e] bool) or
    `idx_per_part` (sparse: part0 -> ascending canonical indices)."""
    sm = ctx.sm
    space = ctx.space_id()
    plans = []
    for c in yield_cols:
        p = _plan(c.expr, sm, space, alias_map, name_by_type)
        if p is None:
            return None
        plans.append(p)

    rows: List[Tuple] = []
    for p0, shard in enumerate(snap.shards):
        if idx_per_part is not None:
            idx = idx_per_part.get(p0)
            if idx is None:
                continue
        else:
            idx = np.nonzero(mask[p0])[0]
        if idx.size == 0:
            continue
        idx = _apply_cap(shard, idx)
        env = _PartEnv(snap, shard, p0, idx)
        cols = []
        for plan in plans:
            col = plan(env)
            if col is None:
                return None
            cols.append(col)
        rows.extend(zip(*(c.tolist() for c in cols)))
    return rows


# ---------------------------------------------------------------------------
# deferred (encoded) materialization — the dispatcher-window fast path
# ---------------------------------------------------------------------------
# The leader gathers TYPED numpy columns (no per-row Python objects),
# encodes the whole window's rows in ONE GIL-released native call
# (nbc_encode_rows; python fallback is byte-identical), and hands each
# waiter an EncodedRows slice. The waiter boxes its own tuples on
# wakeup — outside the dispatcher round and outside the engine lock —
# so the serialized serve path pays numpy gathers + one native call
# instead of a per-row Python loop per waiter. Typed plans cover only
# cases whose classic (emit_rows) boxing is a pure typed gather; any
# other column falls the whole request back to emit_rows, keeping
# identity by construction.

class EncodedRows:
    """One request's slice of a window-encoded row blob. `to_rows()`
    decodes to the exact tuples emit_rows would have produced."""

    __slots__ = ("field_types", "blob", "row_off", "row_len")

    def __init__(self, field_types, blob, row_off, row_len):
        self.field_types = field_types
        self.blob = blob
        self.row_off = row_off
        self.row_len = row_len

    def __len__(self) -> int:
        return len(self.row_off)

    def to_rows(self) -> List[Tuple]:
        n = len(self.row_off)
        if n == 0:
            return []
        from .. import native
        try:
            v64, vf, so, sl, nulls, _ = native.decode_rows(
                self.field_types, self.blob, self.row_off, self.row_len,
                np.arange(n, dtype=np.int32), n)
        except Exception:
            return _decode_rows_py(self.field_types, self.blob,
                                   self.row_off, self.row_len)
        cols = []
        for f, t in enumerate(self.field_types):
            if t == _PT_DOUBLE:
                col = vf[f].tolist()
            elif t == _PT_BOOL:
                col = [bool(x) for x in v64[f].tolist()]
            elif t == _PT_STRING:
                col = [self.blob[o:o + g].decode("utf-8")
                       for o, g in zip(so[f].tolist(), sl[f].tolist())]
            else:
                col = v64[f].tolist()
            nf = nulls[f]
            if nf.any():
                col = [None if z else v
                       for v, z in zip(col, nf.tolist())]
            cols.append(col)
        return list(zip(*cols))


def _decode_rows_py(field_types, blob, row_off, row_len) -> List[Tuple]:
    """struct-based decode of the fixed-slot layout (no native lib)."""
    n_fields = len(field_types)
    null_bytes = (n_fields + 7) // 8
    slot_offs, off = [], 0
    for t in field_types:
        slot_offs.append(off)
        off += 1 if t == _PT_BOOL else 8
    rows = []
    for ro, rl in zip(row_off.tolist(), row_len.tolist()):
        row = blob[ro:ro + rl]
        ver_len = row[0]
        null_off = 1 + ver_len
        slot_off = null_off + null_bytes
        var_off = slot_off + off
        vals = []
        for f, t in enumerate(field_types):
            if row[null_off + (f >> 3)] & (1 << (f & 7)):
                vals.append(None)
                continue
            o = slot_off + slot_offs[f]
            if t == _PT_BOOL:
                vals.append(row[o] != 0)
            elif t == _PT_DOUBLE:
                vals.append(struct.unpack_from("<d", row, o)[0])
            elif t == _PT_STRING:
                so, sl = struct.unpack_from("<II", row, o)
                vals.append(row[var_off + so:var_off + so + sl]
                            .decode("utf-8"))
            else:
                vals.append(struct.unpack_from("<q", row, o)[0])
        rows.append(tuple(vals))
    return rows


def _plan_typed(expr, sm, space: int, alias_map: Dict[str, str],
                name_by_type: Dict[int, str]):
    """Compile one YIELD expression to (ptype, evaluator) where
    evaluator(env) -> (vals ndarray, null bool ndarray) or None (fall
    back to the classic object path at runtime). Returns None when the
    expression has no typed form. Only cases whose emit_rows boxing is
    a pure typed gather are covered — identity by construction.

    KEEP IN SYNC with _plan above: every fallback rule here is the
    typed mirror of the corresponding _plan case (see its docstring);
    when in doubt return None — the classic path is always correct."""
    if isinstance(expr, Literal):
        v = expr.value
        if v is None:
            return _PT_INT, lambda env: (
                np.zeros(len(env.idx), np.int64),
                np.ones(len(env.idx), bool))
        if isinstance(v, bool):
            return _PT_BOOL, lambda env: (
                np.full(len(env.idx), int(v), np.int64),
                np.zeros(len(env.idx), bool))
        if isinstance(v, int):
            if not -(1 << 63) <= v < (1 << 63):
                return None     # beyond int64: classic object path
            return _PT_INT, lambda env: (
                np.full(len(env.idx), v, np.int64),
                np.zeros(len(env.idx), bool))
        if isinstance(v, float):
            return _PT_DOUBLE, lambda env: (
                np.full(len(env.idx), v, np.float64),
                np.zeros(len(env.idx), bool))
        return None     # string literals: classic path

    if isinstance(expr, (EdgeDstIdExpr, EdgeSrcIdExpr, EdgeRankExpr)):
        src = {EdgeDstIdExpr: _PartEnv.dst_vid,
               EdgeSrcIdExpr: _PartEnv.src_vid,
               EdgeRankExpr: _PartEnv.rank}[type(expr)]
        if expr.edge is None:
            return _PT_INT, lambda env: (
                src(env).astype(np.int64, copy=False),
                np.zeros(len(env.idx), bool))
        alias_name = alias_map.get(expr.edge, expr.edge)

        def named(env):
            # other-type rows yield None (the _eval_yield rule) —
            # encoded as null cells
            match = _alias_match(env, alias_name, name_by_type)
            return src(env).astype(np.int64, copy=False), ~match
        return _PT_INT, named

    if isinstance(expr, EdgePropExpr):
        alias_name = (alias_map.get(expr.edge, expr.edge)
                      if expr.edge is not None else None)
        prop = expr.prop

        def edge_prop(env):
            from .csr import host_gather
            ets = env.etype()
            vals = None
            null = np.zeros(len(ets), bool)
            for t in np.unique(ets):
                t = int(t)
                name = name_by_type.get(abs(t))
                if alias_name is not None and name != alias_name:
                    return None  # CPU raises on mismatched rows
                cols = env.shard.edge_props.get(t)
                if cols is None or prop not in cols:
                    return None  # CPU raises "prop not found"
                sel = ets == t
                col = cols[prop]
                if col.missing is not None \
                        and col.missing[env.idx[sel]].any():
                    return None  # version lacks the prop: CPU raises
                part = np.asarray(host_gather(col, env.idx[sel]))
                if not _typed_ok(part):
                    return None
                if vals is None:
                    vals = np.zeros(len(ets), _widen(part.dtype))
                elif vals.dtype != _widen(part.dtype):
                    return None  # mixed dtypes across types: classic
                vals[sel] = part
            if vals is None:     # no rows at all (idx empty per type)
                vals = np.zeros(len(ets), np.int64)
            return vals, null
        # declared ptype depends on the mirror dtype, resolved per
        # part at runtime: report via a mutable probe on first gather
        return ("edge_prop", edge_prop)

    if isinstance(expr, (SourcePropExpr, DestPropExpr)):
        tid = sm.tag_id(space, expr.tag)
        if tid is None:
            return None
        r = sm.tag_schema(space, tid)
        if not r.ok() or not r.value().has_field(expr.prop):
            return None          # unknown prop: CPU raises
        if r.value().field(expr.prop).nullable:
            return None          # explicit NULLs aren't defaults
        dflt = r.value().default_value(expr.prop)
        prop = expr.prop
        if isinstance(dflt, bool) or not isinstance(dflt, (int, float)):
            return None          # string/None defaults: classic path

        def tag_vals(shard, locals_):
            cols = shard.tag_props.get(tid)
            if cols is None or prop not in cols:
                return np.full(len(locals_), dflt), None
            col = cols[prop]
            if col.version_missing and col.missing is not None \
                    and col.missing[locals_].any():
                return None, None    # version lacks the prop: CPU raises
            vals = np.asarray(col.host[locals_])
            if not _typed_ok(vals):
                return None, None
            if col.present is not None:
                pres = col.present[locals_]
                if not pres.all():
                    vals = np.where(pres, vals, dflt)
            return vals, None

        if isinstance(expr, SourcePropExpr):
            def src_prop(env):
                vals, _ = tag_vals(env.shard, env.src_local())
                if vals is None:
                    return None
                return vals, np.zeros(len(env.idx), bool)
            return ("tag_prop", src_prop)

        def dst_prop(env):
            dparts = env.shard.edge_dst_part[env.idx]
            dlocals = env.shard.edge_dst_local[env.idx]
            out = None
            for q in np.unique(dparts):
                sel = dparts == q
                vals, _ = tag_vals(env.snap.shards[int(q)], dlocals[sel])
                if vals is None:
                    return None
                if out is None:
                    out = np.zeros(len(env.idx), _widen(vals.dtype))
                elif out.dtype != _widen(vals.dtype):
                    return None
                out[sel] = vals
            if out is None:
                out = np.zeros(len(env.idx), np.int64)
            return out, np.zeros(len(env.idx), bool)
        return ("tag_prop", dst_prop)

    return None      # EdgeTypeExpr / functions / $- refs: classic path


def _typed_ok(a: np.ndarray) -> bool:
    return a.dtype.kind in "ifb" or a.dtype == np.int64


def _widen(dt: np.dtype) -> np.dtype:
    if dt.kind == "b":
        return np.dtype(bool)
    if dt.kind == "f":
        return np.dtype(np.float64)
    return np.dtype(np.int64)


def _ptype_of(vals: np.ndarray) -> int:
    if vals.dtype.kind == "b":
        return _PT_BOOL
    if vals.dtype.kind == "f":
        return _PT_DOUBLE
    return _PT_INT


def plan_typed_columns(sm, space: int, yield_cols, alias_map,
                       name_by_type):
    """Typed plans for every YIELD column, or None when any column has
    no typed form (callers use the classic emit_rows path)."""
    plans = []
    for c in yield_cols:
        p = _plan_typed(c.expr, sm, space, alias_map, name_by_type)
        if p is None:
            return None
        plans.append(p)
    return plans


def gather_typed(snap, mask, plans,
                 idx_per_part: Optional[Dict[int, np.ndarray]] = None):
    """Evaluate typed plans over the active edges -> (field_types,
    [(vals, null)] per column) with all parts concatenated, or None
    (fall back to emit_rows). Row order is identical to emit_rows."""
    per_col: List[List[Tuple[np.ndarray, np.ndarray]]] = \
        [[] for _ in plans]
    for p0, shard in enumerate(snap.shards):
        if idx_per_part is not None:
            idx = idx_per_part.get(p0)
            if idx is None:
                continue
        else:
            idx = np.nonzero(mask[p0])[0]
        if idx.size == 0:
            continue
        idx = _apply_cap(shard, idx)
        env = _PartEnv(snap, shard, p0, idx)
        for ci, (kind, fn) in enumerate(plans):
            out = fn(env)
            if out is None:
                return None
            per_col[ci].append(out)
    field_types = []
    cols = []
    for ci, (kind, _fn) in enumerate(plans):
        chunks = per_col[ci]
        if not chunks:
            vals = np.zeros(0, np.int64)
            null = np.zeros(0, bool)
        else:
            dts = {_widen(v.dtype) for v, _ in chunks}
            if len(dts) > 1:
                return None      # per-part dtype drift: classic path
            vals = np.concatenate([v for v, _ in chunks])
            null = np.concatenate([n for _, n in chunks])
        if isinstance(kind, str) and kind in ("edge_prop", "tag_prop"):
            field_types.append(_ptype_of(vals))
        else:
            field_types.append(kind)
        cols.append((vals, null))
    return field_types, cols


def encode_window(requests):
    """Encode a WINDOW of gathered column sets into row blobs — one
    native (GIL-released) nbc_encode_rows call per distinct field
    signature, usually exactly one for a homogeneous window.

    requests: [(field_types, cols)] from gather_typed. Returns
    ([EncodedRows per request], native_used: bool)."""
    from .. import native
    out: List[Optional[EncodedRows]] = [None] * len(requests)
    native_used = True
    by_sig: Dict[Tuple[int, ...], List[int]] = {}
    for i, (ft, _cols) in enumerate(requests):
        by_sig.setdefault(tuple(ft), []).append(i)
    for sig, members in by_sig.items():
        n_fields = len(sig)
        counts = [len(requests[i][1][0][0]) if n_fields else 0
                  for i in members]
        total = sum(counts)
        vals_i64 = np.zeros((n_fields, total), np.int64)
        vals_f64 = np.zeros((n_fields, total), np.float64)
        nulls = np.zeros((n_fields, total), bool)
        pos = 0
        for i, cnt in zip(members, counts):
            _ft, cols = requests[i]
            for f, (vals, null) in enumerate(cols):
                if sig[f] == _PT_DOUBLE:
                    vals_f64[f, pos:pos + cnt] = vals
                else:
                    vals_i64[f, pos:pos + cnt] = vals
                nulls[f, pos:pos + cnt] = null
            pos += cnt
        try:
            blob, row_off, row_len = native.encode_rows(
                list(sig), vals_i64, vals_f64, nulls)
        except Exception:
            native_used = False
            blob, row_off, row_len = native.encode_rows_py(
                list(sig), vals_i64, vals_f64, nulls)
        pos = 0
        for i, cnt in zip(members, counts):
            out[i] = EncodedRows(list(sig), blob,
                                 row_off[pos:pos + cnt],
                                 row_len[pos:pos + cnt])
            pos += cnt
    return out, native_used


def gather_for_encode(sm, space, snap, mask, yield_cols, alias_map,
                      name_by_type,
                      idx_per_part: Optional[Dict[int, np.ndarray]] = None
                      ):
    """Plan + gather one request's typed columns for the deferred
    (encoded) path — the shared front half of both engine call sites
    (single query and dispatcher window). Returns gather_typed's
    (field_types, cols) or None (callers use emit_rows)."""
    plans = plan_typed_columns(sm, space, yield_cols, alias_map,
                               name_by_type)
    if plans is None:
        return None
    return gather_typed(snap, mask, plans, idx_per_part=idx_per_part)
