"""Columnar GO-result materialization — the device-path answer to the
reference's in-scan row emission (ref storage/QueryBaseProcessor.inl:
380-458 emits encoded rows inside the storage hot loop).

The traversal kernel emits a bool edge mask; this module turns it into
result rows WITHOUT per-edge Python: the mask compacts to index arrays
(np.nonzero), every YIELD column compiles to one numpy gather over the
snapshot's host prop mirrors, and rows assemble with a single zip.

Identity discipline: each column planner handles only cases whose CPU
semantics are a pure per-row gather; ANYTHING else — unsupported
expression kinds, a row whose edge type mismatches a named prop ref
(CPU raises), a source/dst vertex missing a referenced tag (CPU
raises) — returns None and the engine falls back to the slow
VertexData path, which reproduces CPU behavior exactly. So the fast
path can only produce rows the slow path would have produced.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..filter.expressions import (DestPropExpr, EdgeDstIdExpr, EdgePropExpr,
                                  EdgeRankExpr, EdgeSrcIdExpr, EdgeTypeExpr,
                                  Literal, SourcePropExpr)

DEFAULT_MAX_EDGES_PER_VERTEX = 10000


class _PartEnv:
    """Shared per-part gathered arrays, built lazily once per column
    that needs them."""

    __slots__ = ("snap", "shard", "p0", "idx", "_cache")

    def __init__(self, snap, shard, p0: int, idx: np.ndarray):
        self.snap = snap
        self.shard = shard
        self.p0 = p0
        self.idx = idx
        self._cache: Dict[str, np.ndarray] = {}

    def _get(self, name: str, fn) -> np.ndarray:
        a = self._cache.get(name)
        if a is None:
            a = fn()
            self._cache[name] = a
        return a

    def src_local(self):
        return self._get("src_local", lambda: self.shard.edge_src[self.idx])

    def src_vid(self):
        return self._get("src_vid",
                         lambda: self.shard.vids[self.src_local()])

    def dst_vid(self):
        return self._get("dst_vid",
                         lambda: self.shard.edge_dst_vid[self.idx])

    def rank(self):
        return self._get("rank", lambda: self.shard.edge_rank[self.idx])

    def etype(self):
        return self._get("etype", lambda: self.shard.edge_etype[self.idx])


def _alias_match(env: _PartEnv, alias_name: str,
                 name_by_type: Dict[int, str]) -> np.ndarray:
    """bool[n]: rows whose edge name equals alias_name (the CPU
    _check_edge / _eval_yield None-masking rule)."""
    ets = env.etype()
    out = np.zeros(len(ets), bool)
    for t in np.unique(ets):
        if name_by_type.get(abs(int(t))) == alias_name:
            out |= ets == t
    return out


def _masked_object(vals: np.ndarray, match: np.ndarray) -> np.ndarray:
    out = vals.astype(object)
    out[~match] = None
    return out


def _plan(expr, sm, space: int, alias_map: Dict[str, str],
          name_by_type: Dict[int, str]
          ) -> Optional[Callable[[_PartEnv], Optional[np.ndarray]]]:
    """Compile one YIELD expression to a per-part column evaluator.
    None = not vectorizable (caller falls back to the slow path)."""
    if isinstance(expr, Literal):
        v = expr.value
        return lambda env: np.full(len(env.idx), v, dtype=object)

    if isinstance(expr, (EdgeDstIdExpr, EdgeSrcIdExpr, EdgeRankExpr)):
        src = {EdgeDstIdExpr: _PartEnv.dst_vid, EdgeSrcIdExpr: _PartEnv.src_vid,
               EdgeRankExpr: _PartEnv.rank}[type(expr)]
        if expr.edge is None:
            return lambda env: src(env).astype(object)
        alias_name = alias_map.get(expr.edge, expr.edge)

        def named(env):
            # rows of another edge type yield None (the _eval_yield rule)
            return _masked_object(src(env),
                                  _alias_match(env, alias_name, name_by_type))
        return named

    if isinstance(expr, EdgeTypeExpr):
        def type_name(env):
            ets = env.etype()
            out = np.empty(len(ets), object)
            for t in np.unique(ets):
                out[ets == t] = name_by_type.get(abs(int(t)),
                                                 str(abs(int(t))))
            return out
        return type_name

    if isinstance(expr, EdgePropExpr):
        alias_name = (alias_map.get(expr.edge, expr.edge)
                      if expr.edge is not None else None)
        prop = expr.prop

        def edge_prop(env):
            ets = env.etype()
            out = np.empty(len(ets), object)
            for t in np.unique(ets):
                t = int(t)
                name = name_by_type.get(abs(t))
                if alias_name is not None and name != alias_name:
                    return None  # CPU raises on mismatched rows: fallback
                cols = env.shard.edge_props.get(t)
                if cols is None or prop not in cols:
                    return None  # CPU raises "prop not found": fallback
                sel = ets == t
                col = cols[prop]
                if col.missing is not None \
                        and col.missing[env.idx[sel]].any():
                    # a row's schema version lacks the prop: CPU raises
                    return None
                from .csr import host_gather
                out[sel] = host_gather(col, env.idx[sel]).tolist()
            return out
        return edge_prop

    if isinstance(expr, (SourcePropExpr, DestPropExpr)):
        # tag-prop semantics (ref VertexHolder::get → getDefaultProp,
        # GoExecutor.cpp:1009-1018): a vertex with NO tag row yields
        # the schema default; a row whose VERSION lacks the prop is a
        # CPU-raise (fallback); unknown tag/prop is a query error
        # (fallback: the slow path raises it exactly)
        tid = sm.tag_id(space, expr.tag)
        if tid is None:
            return None
        r = sm.tag_schema(space, tid)
        if not r.ok() or not r.value().has_field(expr.prop):
            return None           # unknown prop: CPU raises
        if r.value().field(expr.prop).nullable:
            return None    # explicit NULLs aren't defaults: slow path
        dflt = r.value().default_value(expr.prop)
        prop = expr.prop

        def tag_vals(shard, locals_):
            """column values at local slots with default fill, or None
            to fall back (version-missing cells)."""
            cols = shard.tag_props.get(tid)
            if cols is None or prop not in cols:
                return np.full(len(locals_), dflt, object)
            col = cols[prop]
            if col.version_missing and col.missing is not None \
                    and col.missing[locals_].any():
                return None       # version lacks the prop: CPU raises
            vals = col.host[locals_]
            if col.present is not None:
                pres = col.present[locals_]
                if not pres.all():
                    vals = np.where(pres, vals.astype(object), dflt)
            return vals

        if isinstance(expr, SourcePropExpr):
            def src_prop(env):
                return tag_vals(env.shard, env.src_local())
            return src_prop

        def dst_prop(env):
            dparts = env.shard.edge_dst_part[env.idx]
            dlocals = env.shard.edge_dst_local[env.idx]
            out = np.empty(len(env.idx), object)
            for q in np.unique(dparts):
                sel = dparts == q
                vals = tag_vals(env.snap.shards[int(q)], dlocals[sel])
                if vals is None:
                    return None
                out[sel] = np.asarray(vals, object)
            return out
        return dst_prop

    return None   # FunctionCall / arithmetic / $- refs: slow path


def _apply_cap(shard, idx: np.ndarray,
               cap: int = DEFAULT_MAX_EDGES_PER_VERTEX) -> np.ndarray:
    """Per-(src, etype) edge cap over ACTIVE edges — identical to the
    slow path's cap_counts (ref FLAGS_max_edge_returned_per_vertex).
    Active indices are ascending and canonical order groups (src,
    etype) contiguously, so within-group rank is positional."""
    if len(idx) <= cap:
        return idx
    grp_change = np.ones(len(idx), bool)
    src = shard.edge_src[idx]
    et = shard.edge_etype[idx]
    grp_change[1:] = (src[1:] != src[:-1]) | (et[1:] != et[:-1])
    starts = np.nonzero(grp_change)[0]
    counts = np.diff(np.append(starts, len(idx)))
    rank = np.arange(len(idx)) - np.repeat(starts, counts)
    return idx[rank < cap]


def emit_rows(snap, mask: Optional[np.ndarray], ctx, yield_cols, alias_map,
              name_by_type,
              idx_per_part: Optional[Dict[int, np.ndarray]] = None
              ) -> Optional[List[Tuple]]:
    """Fully-columnar GO row emission. None = fall back to the slow
    (VertexData) path. Only call when no CPU-side filter or input
    back-references remain (can_serve already excludes $-/$var).
    Active edges come from `mask` (dense [P, cap_e] bool) or
    `idx_per_part` (sparse: part0 -> ascending canonical indices)."""
    sm = ctx.sm
    space = ctx.space_id()
    plans = []
    for c in yield_cols:
        p = _plan(c.expr, sm, space, alias_map, name_by_type)
        if p is None:
            return None
        plans.append(p)

    rows: List[Tuple] = []
    for p0, shard in enumerate(snap.shards):
        if idx_per_part is not None:
            idx = idx_per_part.get(p0)
            if idx is None:
                continue
        else:
            idx = np.nonzero(mask[p0])[0]
        if idx.size == 0:
            continue
        idx = _apply_cap(shard, idx)
        env = _PartEnv(snap, shard, p0, idx)
        cols = []
        for plan in plans:
            col = plan(env)
            if col is None:
                return None
            cols.append(col)
        rows.extend(zip(*(c.tolist() for c in cols)))
    return rows
