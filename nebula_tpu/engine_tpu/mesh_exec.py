"""Mesh execution service: the full device query surface on sharded
snapshots.

distributed.py gives plain GO and SHORTEST a scatter/gather analogue of
the reference's StorageClient::collectResponse fan-out
(StorageClient.inl:73-160): per-device partition blocks, one
`all_to_all` frontier exchange per hop. This module generalizes that
per-shard-compute -> collective-merge pipeline to the REST of the
device surface, so a sharded snapshot serves exactly what a
single-chip one does:

1. Batched dispatcher windows (`multi_hop_masks_batch_sharded`): the
   cross-session group-commit window rides ONE replicated
   [n_slots+1, LANES] packed frontier matrix; each device advances it
   over its OWN aligned edge block (traverse._packed_hits) and the
   per-hop merge is one elementwise `pmax` — the OR across devices,
   the same collective shape as the sharded flagship counter. The
   final hop gathers each device's CANONICAL edge block against the
   lane matrix, so the output is the familiar [B, P, cap_e] mask
   stack, partition-sharded over the mesh.

2. Distributed aggregation pushdown (`mesh_reduce_specs`,
   `mesh_grouped_reduce`): per-shard masked partials — COUNT,
   non-null counts, MIN/MAX lattice partials, and the 8-bit
   digit-chunk SUM partials of aggregate.py — computed inside
   shard_map and combined with `psum` (grouped sums under the
   single-pass row bound) or gathered per device (`out_specs
   P(AXIS)`) and reassembled in host Python ints. Every exactness
   bound in aggregate.py is preserved: device partials stay int32
   under the same chunk sizes, and cross-device accumulation happens
   in host int64/Python ints, never in a wrapping dtype.

3. ALL/NOLOOP path expansion (`multi_hop_steps_sharded`): per-step
   canonical edge masks over the sharded kernel — the sharded twin of
   traverse.multi_hop_steps — with the per-hop frontier exchange of
   distributed.py; path enumeration stays on the host
   (engine._find_all_paths), reading the same mask stack it reads
   single-chip.

Everything here is provable on a host-emulated mesh
(`JAX_PLATFORMS=cpu` + `XLA_FLAGS=--xla_force_host_platform_device_
count=N`, see docs/manual/8-mesh.md) — results must be identical to
the CPU pipe by construction, which the mesh tests assert.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

import threading

from ..common.faults import faults
from . import aggregate
from .fused import _apply_lane_filters
from .distributed import AXIS, _exchange, shard_aligned_blocks
from .shard_compat import shard_map
from .traverse import (LANES, _edge_ok, _init_lanes, _packed_hits,
                       _packed_src_eff, hop_hits)

_BIAS = 1 << 31

# serializes sharded aligned-block builds: prewarm, repack and the
# dispatcher's kick thread can all reach ensure_sharded_aligned for
# the same fresh snapshot; one O(E) build + device_put is plenty
_aligned_build_lock = threading.Lock()


# ---------------------------------------------------------------------------
# sharded aligned layout cache (the dispatcher window's edge streams)
# ---------------------------------------------------------------------------

def sharded_aligned_ready(snap):
    """The cached per-device aligned blocks, or None — NEVER builds
    (the dispatcher's locked phase must not pay an O(E) build; the
    single-chip path keeps the same invariant via aligned_ready)."""
    cached = getattr(snap, "_sharded_aligned", None)
    return None if cached in (None, "failed") else cached


def ensure_sharded_aligned(mesh, snap):
    """The snapshot's per-device aligned blocks for batched windows,
    built once and cached on the snapshot (meshed snapshots rebuild on
    every version change, so the cache never goes stale mid-life).
    Returns (AlignedKernel[D, ...], chunk, group) or None when the
    layout can't be built; a failed build is cached as a decline so a
    hot dispatcher never retries a doomed build per window."""
    cached = getattr(snap, "_sharded_aligned", None)
    if cached is not None:
        return None if cached == "failed" else cached
    with _aligned_build_lock:
        cached = getattr(snap, "_sharded_aligned", None)   # lost race
        if cached is not None:
            return None if cached == "failed" else cached
        try:
            built = shard_aligned_blocks(mesh, snap)
        except Exception:
            snap._sharded_aligned = "failed"
            return None
        snap._sharded_aligned = built
        return built


# ---------------------------------------------------------------------------
# 1. batched dispatcher windows on the mesh
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _batch_masks_fn(mesh, num_devices: int, parts_per_dev: int,
                    cap_v: int, cap_e: int, n_slots: int, chunk: int,
                    group: int, batch: int, filtered: bool):
    """shard_map'd window kernel: replicated packed frontier matrix,
    per-device aligned-block advance, pmax merge per hop, one
    canonical gather per device block for the final masks. With
    `filtered` the window's stacked compiled WHERE masks ([NF, P,
    cap_e], partition-sharded like the output) AND in per lane INSIDE
    the same program (fsel[b] = that lane's mask index, -1 =
    unfiltered) — the sharded twin of fused.window_lane's filter
    fusion."""
    in_specs = (None, None, P(AXIS), P(AXIS), None)
    if filtered:
        in_specs = in_specs + (P(None, AXIS), None)

    @partial(shard_map, mesh=mesh, in_specs=in_specs,
             out_specs=P(None, AXIS))
    def run(frontiers0, steps_, ak_, kern_, req, *filt):
        ak = jax.tree.map(lambda a: a[0], ak_)   # this device's block
        k = jax.tree.map(lambda a: a[0], kern_)
        # lane matrix built ON DEVICE from the replicated [B, P, cap_v]
        # frontiers (traverse._init_lanes, the single-chip prologue):
        # a host-built [n_slots+1, LANES] matrix would mean a ~P*cap_v
        # x128 byte alloc + transfer per window, under the engine lock
        F0 = _init_lanes(frontiers0, n_slots)
        src_eff = _packed_src_eff(ak, req, n_slots, chunk, group)
        g_idx = ak.cbound // group
        j_idx = ak.cbound % group

        def body(_, f):
            hits = _packed_hits(f, src_eff, g_idx, j_idx, n_slots,
                                chunk, group).astype(jnp.int8)
            # OR across devices; the merged matrix is identical
            # everywhere, so the loop carry stays axis-invariant (the
            # same collective shape as the sharded batched counter)
            merged = lax.pmax(hits, AXIS)
            return jnp.pad(merged, ((0, 1), (0, 0)))

        F = lax.fori_loop(0, jnp.maximum(steps_ - 1, 0), body, F0)
        # final hop: gather THIS block's canonical edges against the
        # lane matrix — active[b, p, e] = F[global_src(p, e), b] & ok
        d = lax.axis_index(AXIS)
        gsrc = ((d * parts_per_dev
                 + jnp.arange(parts_per_dev, dtype=jnp.int32))[:, None]
                * cap_v + k.src)                 # [bp, cap_e] global slot
        rows = F[:, :batch][gsrc.reshape(-1)]    # [bp*cap_e, B] int8
        ok_c = _edge_ok(k.etype, k.valid, req)
        masks = (rows.reshape(parts_per_dev, cap_e, batch) > 0) \
            & ok_c[..., None]
        masks = jnp.moveaxis(masks, 2, 0)        # [B, bp, cap_e]
        if filt:
            fmasks, fsel = filt                  # [NF, bp, cap_e] block
            masks = _apply_lane_filters(masks, fmasks, fsel)
        return masks

    return jax.jit(run)


def multi_hop_masks_batch_sharded(mesh, frontiers0, steps, ak, kern,
                                  req_types, chunk: int, group: int,
                                  fmasks=None, fsel=None) -> jnp.ndarray:
    """Distributed dispatcher window: final-hop active edge masks for a
    batch of GO queries in ONE sharded dispatch. frontiers0
    bool[B, P, cap_v]; ak from shard_aligned_blocks / kern the
    snapshot's sharded EdgeKernel (both leading-dim sharded over the
    mesh). -> bool[B, P, cap_e], partition-sharded over axis 1.
    Identical semantics to traverse.multi_hop_masks_batch; with
    fmasks/fsel the window's compiled WHERE masks apply per lane
    inside the program (fused.window_lane's filter contract)."""
    faults.fire("mesh.collective")
    B, num_parts, cap_v = frontiers0.shape
    if B > LANES:
        raise ValueError(f"batch {B} > {LANES} lanes per dispatch")
    D = mesh.devices.size
    assert num_parts % D == 0
    ns = num_parts * cap_v
    cap_e = int(kern.src.shape[-1])
    fn = _batch_masks_fn(mesh, D, num_parts // D, cap_v, cap_e, ns,
                         chunk, group, B, fmasks is not None)
    if fmasks is None:
        return fn(jnp.asarray(frontiers0), steps, ak, kern, req_types)
    return fn(jnp.asarray(frontiers0), steps, ak, kern, req_types,
              fmasks, fsel)


# ---------------------------------------------------------------------------
# 3. ALL/NOLOOP path: per-step canonical masks on the mesh
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _steps_masks_fn(mesh, num_devices: int, parts_per_dev: int,
                    cap_v: int, steps: int):
    local_block = parts_per_dev * cap_v

    @partial(shard_map, mesh=mesh,
             in_specs=(P(AXIS), P(AXIS), None),
             out_specs=P(None, AXIS))
    def run(frontier, kern_, req):
        k = jax.tree.map(lambda a: a[0], kern_)
        edge_ok = _edge_ok(k.etype, k.valid, req)
        ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req)
        masks = []
        f = frontier
        for _ in range(steps):
            masks.append(jnp.take_along_axis(f, k.src, axis=1) & edge_ok)
            hits, _n = hop_hits(f, k.src_sorted, ok_sorted,
                                k.seg_starts, k.seg_ends)
            f = _exchange(hits, num_devices, local_block).reshape(
                parts_per_dev, cap_v)
        return jnp.stack(masks)                  # [steps, bp, cap_e]

    return jax.jit(run)


def multi_hop_steps_sharded(mesh, frontier0, kern, req_types,
                            steps: int) -> jnp.ndarray:
    """Per-step active edge masks over the sharded kernel (the
    engine's ALL/NOLOOP path expansion input): `steps` is static, one
    trace per N, exactly like traverse.multi_hop_steps.
    -> bool[steps, P, cap_e], partition-sharded over axis 1."""
    faults.fire("mesh.collective")
    num_parts, cap_v = frontier0.shape
    D = mesh.devices.size
    assert num_parts % D == 0
    fn = _steps_masks_fn(mesh, D, num_parts // D, cap_v, int(steps))
    return fn(frontier0, kern, req_types)


# ---------------------------------------------------------------------------
# 2. distributed aggregation: per-shard partials, psum/gather merge
# ---------------------------------------------------------------------------

def _bcast_val(active, v):
    """Normalize a compiled _Val's (value, null) to full [P, cap_e]
    device arrays (filter_compile leaves scalars for literal-only
    nulls)."""
    value = jnp.broadcast_to(jnp.asarray(v.value, jnp.int32),
                             active.shape)
    null = jnp.broadcast_to(jnp.asarray(v.null, bool), active.shape)
    return value, null


@lru_cache(maxsize=64)
def _active_count_fn(mesh):
    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS),),
             out_specs=P(AXIS))
    def run(active):
        # per-device row count (int32 exact: a block holds < 2^31
        # slots); summed on the host in Python ints
        return active.sum(dtype=jnp.int32)[None]

    return jax.jit(run)


def mesh_active_count(mesh, active) -> int:
    """Exact COUNT over a sharded row mask: per-device int32 partials
    gathered and summed host-side."""
    parts = np.asarray(_active_count_fn(mesh)(active))
    return int(parts.astype(object).sum())


@lru_cache(maxsize=64)
def _reduce_partials_fn(mesh, n_chunks: int, chunk_slots: int):
    """Per-device partials for one value column: (count, nonnull,
    min, max, digit-chunk sums). Digit partials follow
    aggregate.exact_int_sum's discipline — int32 sums over chunks of
    `chunk_slots` (chunk_sum <= chunk_slots * 255 < 2^31) — but per
    DEVICE; the host reassembles across chunks AND devices in Python
    ints, so no cross-device dtype ever accumulates."""

    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS),) * 3,
             out_specs=(P(AXIS),) * 4)
    def run(value, null, active):
        m = active & ~null
        nn = m.sum(dtype=jnp.int32)
        mn = jnp.min(jnp.where(m, value, jnp.int32(2**31 - 1)))
        mx = jnp.max(jnp.where(m, value, jnp.int32(-(2**31))))
        u = (value.astype(jnp.uint32) + jnp.uint32(_BIAS)).reshape(-1)
        mf = m.reshape(-1)
        pad = n_chunks * chunk_slots - u.shape[0]
        u = jnp.pad(u, (0, pad)).reshape(n_chunks, chunk_slots)
        mf = jnp.pad(mf, (0, pad)).reshape(n_chunks, chunk_slots)
        digits = []
        for k in range(4):
            d = ((u >> jnp.uint32(8 * k)) & jnp.uint32(0xFF)) \
                .astype(jnp.int32)
            digits.append(jnp.sum(jnp.where(mf, d, 0), axis=-1))
        return (nn[None], mn[None], mx[None],
                jnp.stack(digits)[None])         # [1, 4, n_chunks]

    return jax.jit(run)


def _column_partials(mesh, active, v):
    """-> (nonnull int, min int|None raw, max raw, exact sum int) for
    one value column over the sharded active mask."""
    value, null = _bcast_val(active, v)
    loc_slots = (active.shape[0] // mesh.devices.size) * active.shape[1]
    chunk_slots = min(aggregate.SUM_CHUNK, max(loc_slots, 1))
    n_chunks = max(1, -(-loc_slots // chunk_slots))
    fn = _reduce_partials_fn(mesh, n_chunks, chunk_slots)
    nn_d, mn_d, mx_d, dig_d = fn(value, null, active)
    nn_d = np.asarray(nn_d)
    nonnull = int(nn_d.astype(object).sum())
    mn = int(np.asarray(mn_d).min())
    mx = int(np.asarray(mx_d).max())
    dig = np.asarray(dig_d)                      # [D, 4, n_chunks]
    total = 0
    for k in range(4):
        total += int(dig[:, k, :].astype(object).sum()) << (8 * k)
    total -= nonnull * _BIAS
    return nonnull, mn, mx, total


def mesh_reduce_specs(specs, active, vals, mesh) -> Optional[List]:
    """aggregate.reduce_specs over a SHARDED active mask: per-shard
    masked partials computed inside shard_map, gathered per device,
    reassembled exactly on the host. Same result-row contract (CPU-
    identical Python values); never hits reduce_specs' device-wide
    transfer of the full mask."""
    faults.fire("mesh.collective")
    n_rows = mesh_active_count(mesh, active)
    row: List = []
    cache: Dict = {}
    for fun, key in specs:
        if fun == "COUNT":
            row.append(n_rows)
            continue
        if key not in cache:
            cache[key] = _column_partials(mesh, active, vals[key])
        nonnull, mn, mx, total = cache[key]
        if nonnull == 0:
            row.append(None)
            continue
        if fun == "MIN":
            row.append(mn)
        elif fun == "MAX":
            row.append(mx)
        else:
            row.append(total if fun == "SUM" else total / nonnull)
    return row


# -- grouped (GROUP BY dst) --------------------------------------------------

@lru_cache(maxsize=64)
def _grouped_count_fn(mesh, n_groups: int, flat_len: int,
                      count_chunk: int):
    """Per-device masked scatter-counts into the global group bins,
    one int32 pass per `count_chunk` slots (each pass's bins < 2^31:
    a slot contributes <= 1) — the distributed form of
    aggregate._scatter_count_i64. Output [D, n_passes, n_groups]
    int32; the host accumulates across passes and devices in int64,
    keeping grouped COUNT exact to ~2^63 rows."""
    n_passes = max(1, -(-flat_len // count_chunk))

    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS),) * 2,
             out_specs=P(AXIS))
    def run(mask, gidx):
        mf = mask.reshape(-1)
        gf = gidx.reshape(-1)
        passes = []
        for c in range(0, max(flat_len, 1), count_chunk):
            part = (jnp.zeros(n_groups + 1, jnp.int32)
                    .at[gf[c:c + count_chunk]]
                    .add(mf[c:c + count_chunk].astype(jnp.int32)))
            passes.append(part[:n_groups])
        return jnp.stack(passes)[None]           # [1, n_passes, G]

    return jax.jit(run), n_passes


def _mesh_scatter_count(mesh, mask, gidx, n_groups: int) -> np.ndarray:
    """int64[n_groups] exact masked group counts over sharded inputs.
    The pass width follows aggregate.COUNT_CHUNK at call time (tests
    pin it small to exercise the chunk boundary)."""
    flat_len = (mask.shape[0] // mesh.devices.size) * mask.shape[1]
    fn, _ = _grouped_count_fn(mesh, n_groups, flat_len,
                              int(aggregate.COUNT_CHUNK))
    parts = np.asarray(fn(mask, gidx))           # [D, n_passes, G] i32
    return parts.astype(np.int64).sum(axis=(0, 1))


@lru_cache(maxsize=64)
def _grouped_digit_psum_fn(mesh, n_groups: int):
    """Single-pass grouped digit sums merged with psum ON DEVICE:
    exact while TOTAL masked rows <= MAX_GROUPED_SUM_ROWS (rows * 255
    < 2^31 across ALL devices' contributions — the identical bound the
    single-chip single-pass reduction enforces). out: replicated
    [4, n_groups] int32."""

    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS),) * 3,
             out_specs=P())
    def run(u, mask, gidx):
        mf = mask.reshape(-1)
        gf = gidx.reshape(-1)
        uf = u.reshape(-1)
        digits = []
        for k in range(4):
            d = ((uf >> jnp.uint32(8 * k)) & jnp.uint32(0xFF)) \
                .astype(jnp.int32)
            part = (jnp.zeros(n_groups + 1, jnp.int32)
                    .at[gf].add(jnp.where(mf, d, 0)))[:n_groups]
            digits.append(part)
        return lax.psum(jnp.stack(digits), AXIS)

    return jax.jit(run)


@lru_cache(maxsize=64)
def _grouped_digit_gather_fn(mesh, n_groups: int, flat_len: int,
                             sum_seg: int):
    """Chunked per-device grouped digit partials for beyond-bound sums:
    each SUM_SEG pass's int32 bins are exact (<= sum_seg * 255 < 2^31);
    out [D, n_segs, 4, n_groups] accumulated host-side in int64 —
    grouped SUM/AVG stays exact to ~2^55 rows on the mesh, the same
    bound as aggregate.grouped_reduce."""
    n_segs = max(1, -(-flat_len // sum_seg))

    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS),) * 3,
             out_specs=P(AXIS))
    def run(u, mask, gidx):
        mf = mask.reshape(-1)
        gf = gidx.reshape(-1)
        uf = u.reshape(-1)
        segs = []
        for c in range(0, max(flat_len, 1), sum_seg):
            digits = []
            for k in range(4):
                d = ((uf[c:c + sum_seg] >> jnp.uint32(8 * k))
                     & jnp.uint32(0xFF)).astype(jnp.int32)
                part = (jnp.zeros(n_groups + 1, jnp.int32)
                        .at[gf[c:c + sum_seg]]
                        .add(jnp.where(mf[c:c + sum_seg], d, 0))
                        )[:n_groups]
                digits.append(part)
            segs.append(jnp.stack(digits))
        return jnp.stack(segs)[None]             # [1, n_segs, 4, G]

    return jax.jit(run)


@lru_cache(maxsize=64)
def _grouped_minmax_fn(mesh, n_groups: int):
    @partial(shard_map, mesh=mesh, in_specs=(P(AXIS),) * 3,
             out_specs=(P(AXIS), P(AXIS)))
    def run(value, mask, gidx):
        gf = gidx.reshape(-1)
        lo = jnp.where(mask, value, jnp.int32(2**31 - 1)).reshape(-1)
        hi = jnp.where(mask, value, jnp.int32(-(2**31))).reshape(-1)
        mn = (jnp.full(n_groups + 1, 2**31 - 1, jnp.int32)
              .at[gf].min(lo))[:n_groups]
        mx = (jnp.full(n_groups + 1, -(2**31), jnp.int32)
              .at[gf].max(hi))[:n_groups]
        return mn[None], mx[None]

    return jax.jit(run)


def mesh_grouped_reduce(specs, active, vals, gidx, n_groups: int,
                        mesh, stats: Optional[Dict] = None
                        ) -> Tuple[np.ndarray, List[List]]:
    """aggregate.grouped_reduce over a SHARDED mask: same signature
    contract -> (sorted group slots, per-spec python-value columns).
    COUNT and non-null counts ride chunked per-device scatter passes
    (host int64 accumulation, exact to ~2^63 rows); SUM/AVG take the
    device psum fast path under the single-pass row bound and fall to
    chunked gathered partials past it (exact to ~2^55 rows, counted in
    `stats` as agg_grouped_chunked just like the single-chip path);
    MIN/MAX are per-device lattice partials combined on the host."""
    faults.fire("mesh.collective")
    counts = _mesh_scatter_count(mesh, active, gidx, n_groups)
    groups = np.nonzero(counts)[0]
    out: List[List] = []
    cache: Dict = {}
    chunked_counted = False
    loc_flat = (active.shape[0] // mesh.devices.size) * active.shape[1]
    for fun, key in specs:
        if fun == "COUNT":
            out.append([int(x) for x in counts[groups]])
            continue
        v = vals[key]
        if key not in cache:
            value, null = _bcast_val(active, v)
            mk = active & ~null
            nn = _mesh_scatter_count(mesh, mk, gidx, n_groups)
            cache[key] = (value, mk, nn)
        value, mk, nonnull = cache[key]
        nn = nonnull[groups]
        if fun in ("MIN", "MAX"):
            mn_d, mx_d = _grouped_minmax_fn(mesh, n_groups)(value, mk,
                                                            gidx)
            sel = (np.asarray(mn_d).min(axis=0) if fun == "MIN"
                   else np.asarray(mx_d).max(axis=0))[groups]
            out.append([int(x) if c else None for x, c in zip(sel, nn)])
            continue
        u = value.astype(jnp.uint32) + jnp.uint32(_BIAS)
        n_masked = int(nonnull.sum())
        if n_masked <= aggregate.MAX_GROUPED_SUM_ROWS:
            dig = np.asarray(_grouped_digit_psum_fn(mesh, n_groups)(
                u, mk, gidx)).astype(np.int64)   # [4, G], exact
            total = np.zeros(n_groups, np.int64)
            for k in range(4):
                total += dig[k] << (8 * k)
        else:
            if stats is not None and not chunked_counted:
                # once per QUERY, matching the single-chip counter
                chunked_counted = True
                stats["agg_grouped_chunked"] = \
                    stats.get("agg_grouped_chunked", 0) + 1
            fn = _grouped_digit_gather_fn(mesh, n_groups, loc_flat,
                                          int(aggregate.SUM_SEG))
            parts = np.asarray(fn(u, mk, gidx))  # [D, nS, 4, G] i32
            total = np.zeros(n_groups, np.int64)
            for k in range(4):
                total += parts[:, :, k, :].astype(np.int64) \
                    .sum(axis=(0, 1)) << (8 * k)
        total -= nonnull * _BIAS
        sel = total[groups]
        if fun == "SUM":
            out.append([int(x) if c else None for x, c in zip(sel, nn)])
        else:                      # AVG: exact integer sum / count
            out.append([int(x) / int(c) if c else None
                        for x, c in zip(sel, nn)])
    return groups, out
