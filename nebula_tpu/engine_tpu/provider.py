"""Snapshot providers: where the TPU engine's CSR builds come from.

The reference puts its storage-engine plugin seam below the storage
service (`FLAGS_store_type`, ref storage/StorageServer.cpp:32-55). The
TPU engine mirrors that seam from the consuming side: a provider hands
it (a) a freshness token that changes whenever the space's data or
routing changes, and (b) a full CSR build. Two implementations:

- LocalStoreProvider: graphd and storaged share a process (single-node
  deployment, the in-proc test cluster) — scans the local engine.
- RemoteStorageProvider: the real 3-daemon topology — pulls columnar
  part scans over the storage RPC boundary (scan_part_cols) with the
  same leader routing/retry discipline as every other storage read.

Ordering invariant: build() captures the token BEFORE scanning, so a
write racing the build bumps the live version past the snapshot's and
forces a rebuild — the snapshot can only ever be too fresh, never
stale.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..common import writepath as _writepath
from ..common.faults import InjectedFault, faults
from ..kvstore.scan import ScanCols
from .csr import CsrSnapshot, build_shards, build_snapshot

# ring.overrun (docs/manual/9-robustness.md): forces the next
# changes_since pull to decline exactly the way a truncated change
# ring does — the consumer must poison its snapshot and repack. The
# write bench/tier-1 tests use it to prove the overrun -> poison ->
# repack cause chain deterministically (a REAL overrun needs a write
# burst past the ring cap, which the churn phase also drives).
faults.register("ring.overrun",
                doc="decline a changes_since pull as if the change "
                    "ring had truncated past the consumer's cursor — "
                    "snapshot poison + full host repack follow")


class SnapshotBuildError(RuntimeError):
    """A partition scan failed mid-build (leader moved, host died)."""


class LocalStoreProvider:
    """Snapshot feed from an in-process GraphStore."""

    def __init__(self, store, sm):
        self._store = store
        self._sm = sm

    def version(self, space_id: int):
        engine = self._store.space_engine(space_id)
        return None if engine is None else engine.write_version

    def store_digest(self, space_id: int):
        """(content digest, write_version) of the space's parts — the
        snapshot-audit lineage source (common/consistency.py). None
        when the observatory is disarmed or a write raced the walk."""
        return self._store.space_digest(space_id)

    def build(self, space_id: int) -> Optional[CsrSnapshot]:
        if self._store.space_engine(space_id) is None:
            return None
        snap = build_snapshot(self._store, self._sm, space_id,
                              self._sm.num_parts(space_id))
        snap.delta_cursor = snap.write_version
        return snap

    def changes_since(self, space_id: int, cursor):
        """Committed writes since `cursor` as resolved logical deltas.
        -> (entries | None, new_cursor); None entries = rebuild (ring
        truncated or a barrier op). Declines stamp `last_decline` so
        the consumer's poison event carries the cause (overrun ->
        poison -> repack, one attributed chain)."""
        from ..kvstore.changelog import resolve_changes
        self.last_decline = None
        engine = self._store.space_engine(space_id)
        if engine is None or getattr(engine, "changes", None) is None:
            self.last_decline = "no_engine"
            return None, cursor
        try:
            faults.fire("ring.overrun")
        except InjectedFault:
            self.last_decline = "ring_overrun"
            _writepath.note_ring_overrun(space_id, cause="injected",
                                         cursor=cursor)
            return None, cursor
        t0 = time.perf_counter()
        now_v, raw = engine.changes_snapshot(cursor)
        if raw is None:
            self.last_decline = "ring_overrun"
            _writepath.note_ring_overrun(space_id, cause="truncated",
                                         cursor=cursor)
            return None, cursor
        entries = resolve_changes(engine, raw)
        if entries is None:
            self.last_decline = "barrier"
            _writepath.note_ring_barrier(space_id)
            return None, cursor
        _writepath.stage("ring_publish",
                         (time.perf_counter() - t0) * 1e6)
        return entries, now_v


class _RemoteScanSource:
    """ScanSource over the storage RPC boundary (one scan_part_cols
    round-trip per (part, kind), leader-routed)."""

    def __init__(self, client, space_id: int):
        self._client = client
        self._space = space_id

    def scan(self, part: int, kind: int) -> ScanCols:
        from ..common.status import ErrorCode
        resp = self._client.scan_part_cols(self._space, part, kind)
        if resp.result.code != ErrorCode.SUCCEEDED:
            raise SnapshotBuildError(
                f"scan of part {part} failed: {resp.result.code.name}")
        return ScanCols.from_blobs(resp.n, resp.keys_blob, resp.vals_blob,
                                   np.frombuffer(resp.vlens, np.int64),
                                   np.frombuffer(resp.klens, np.int64))


class RemoteStorageProvider:
    """Snapshot feed over the storage service boundary — the TPU engine
    in graphd serving queries against data held by remote storaged."""

    def __init__(self, client, sm):
        self._client = client
        self._sm = sm

    def version(self, space_id: int):
        return self._client.space_versions(space_id)

    def store_digest(self, space_id: int):
        """Remote stores don't expose a digest walk over the storage
        RPC boundary (yet) — the snapshot audit declines; replica
        divergence detection lives on the storaged tier's own digest
        exchange (kvstore/raftex)."""
        return None

    def build(self, space_id: int) -> Optional[CsrSnapshot]:
        token = self.version(space_id)   # BEFORE the scans (see module doc)
        if token is None:
            return None
        num_parts = self._sm.num_parts(space_id)
        try:
            shards, cap_v, cap_e, dicts = build_shards(
                _RemoteScanSource(self._client, space_id), self._sm,
                space_id, num_parts)
        except SnapshotBuildError:
            return None
        snap = CsrSnapshot(space_id, shards, cap_v, cap_e, token)
        snap.str_dicts = dicts
        # host -> engine write-version at build (the per-host token
        # element is (write_version, leader_sig); the change-ring
        # cursor wants the bare version)
        snap.delta_cursor = {h: (v[0] if isinstance(v, tuple) else v)
                             for h, v in token[0]}
        return snap

    def changes_since(self, space_id: int, cursor):
        """Pull resolved deltas from every host serving the space (one
        RPC per host per INVALIDATION, never per query). Every host is
        polled authoritatively — the cached watch versions can lag a
        local write by one push (~50ms), and trusting them here would
        stamp the snapshot fresh without that write.
        -> (entries | None, new_cursor)."""
        self.last_decline = None
        token = self.version(space_id)
        if token is None:
            self.last_decline = "no_version"
            return None, cursor
        if {h for h, _ in token[0]} != set(cursor):
            self.last_decline = "host_set_changed"
            return None, cursor          # host set changed: rebuild
        try:
            faults.fire("ring.overrun")
        except InjectedFault:
            self.last_decline = "ring_overrun"
            _writepath.note_ring_overrun(space_id, cause="injected",
                                         cursor=dict(cursor))
            return None, cursor
        t0 = time.perf_counter()
        entries = []
        new_cursor = dict(cursor)
        for host, since in cursor.items():
            try:
                now_v, es = self._client.host_changes_since(host, space_id,
                                                            since)
            except Exception:
                self.last_decline = "pull_failed"
                return None, cursor
            if es is None:
                # the serving host's ring truncated past our cursor
                # (or a barrier op — the host can't distinguish over
                # the wire; either way the consumer repacks)
                self.last_decline = "ring_overrun"
                _writepath.note_ring_overrun(space_id,
                                             cause="truncated",
                                             host=host, cursor=since)
                return None, cursor
            entries.extend(es)
            new_cursor[host] = now_v
        _writepath.stage("ring_publish",
                         (time.perf_counter() - t0) * 1e6)
        return entries, new_cursor
