"""JAX version-compat shim for the mesh execution path.

The distributed kernels (distributed.py, mesh_exec.py) are written
against the modern shard_map surface: `jax.shard_map` plus explicit
varying-manual-axes casts (`lax.pcast(..., to="varying")`) for loop
carries. Older jax releases ship shard_map under
`jax.experimental.shard_map` and have no vma typing at all — there the
pcast is semantically a no-op (the old check_rep machinery infers
replication instead of demanding explicit casts).

Every shard_map consumer in the engine imports from HERE so the
version probe happens in exactly one place. Resolution order:

  shard_map:  jax.shard_map  ->  jax.experimental.shard_map.shard_map
  pvary:      lax.pcast(to="varying")  ->  lax.pvary  ->  identity
"""
from __future__ import annotations

from jax import lax

try:                                    # modern surface (jax >= 0.6)
    from jax import shard_map
except ImportError:                     # legacy experimental location
    from functools import partial as _partial

    from jax.experimental.shard_map import shard_map as _shard_map

    # the legacy replication checker has no rule for while/fori_loop
    # (every traversal kernel's core), so its own documented workaround
    # is applied once here; the modern vma checker stays ON via the
    # branch above, so new-jax runs keep full checking
    shard_map = _partial(_shard_map, check_rep=False)


def pvary(x, axis_names):
    """Mark `x` as device-varying over `axis_names` for shard_map's
    vma typing (loop carries must start varying when the loop body
    makes them varying). On jax versions without vma typing this is
    the identity — the old check_rep inference needs no cast."""
    pc = getattr(lax, "pcast", None)
    if pc is not None:
        return pc(x, axis_names, to="varying")
    pv = getattr(lax, "pvary", None)
    if pv is not None:
        return pv(x, axis_names)
    return x
