"""Device traversal kernels: dense-mask BFS frontier advance.

The TPU-native replacement for the reference's per-hop RPC loop
(graphd re-crossing the network every step, ref SURVEY.md §3.1): the
whole multi-hop expansion compiles to ONE XLA program —

    per hop:  gather   active = frontier[edge_src] & type_ok      (VPU)
              scatter  hits[dst_gidx] |= active                   (HBM)
    loop:     lax.fori_loop over hops (dynamic trip count, no retrace)

A dense bool frontier per partition gives within-step dst dedup for
free — exactly the reference's `getDstIdsFromResp` unordered_set
semantics (GO revisits previously-seen vertices across steps; BFS-style
visited masks are used only by shortest-path, which tracks first-hit
depth in `dist`).

All shapes are static: [P, cap_v] frontiers, [P, cap_e] edge arrays,
requested edge types padded to a fixed-width vector. Invalid/padded
edges scatter into a dump slot at index P*cap_v.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

MAX_EDGE_TYPES_PER_QUERY = 8  # fixed width so type sets don't retrace


def pad_edge_types(edge_types: List[int]) -> np.ndarray:
    """Pad the requested signed-type list to fixed width with 0
    (0 is never a valid edge type)."""
    if len(edge_types) > MAX_EDGE_TYPES_PER_QUERY:
        raise ValueError(f"too many edge types in one traversal "
                         f"({len(edge_types)} > {MAX_EDGE_TYPES_PER_QUERY})")
    out = np.zeros(MAX_EDGE_TYPES_PER_QUERY, np.int32)
    out[:len(edge_types)] = edge_types
    return out


def _edge_ok(edge_etype: jnp.ndarray, edge_valid: jnp.ndarray,
             req_types: jnp.ndarray) -> jnp.ndarray:
    """[P, cap_e] mask of edges matching the requested signed types."""
    m = (edge_etype[None, :, :] == req_types[:, None, None]).any(axis=0)
    return m & edge_valid


def _advance(frontier: jnp.ndarray, edge_src: jnp.ndarray,
             edge_gidx: jnp.ndarray, edge_ok: jnp.ndarray) -> jnp.ndarray:
    """One BFS hop on stacked partitions (single device).

    frontier: bool[P, cap_v] -> bool[P, cap_v]
    """
    P, cap_v = frontier.shape
    active = jnp.take_along_axis(frontier, edge_src, axis=1) & edge_ok
    flat = jnp.zeros((P * cap_v + 1,), dtype=jnp.bool_)
    flat = flat.at[edge_gidx.reshape(-1)].max(active.reshape(-1))
    return flat[:P * cap_v].reshape(P, cap_v)


@jax.jit
def multi_hop(frontier0: jnp.ndarray, steps: jnp.ndarray,
              edge_src: jnp.ndarray, edge_gidx: jnp.ndarray,
              edge_etype: jnp.ndarray, edge_valid: jnp.ndarray,
              req_types: jnp.ndarray
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run `steps-1` frontier advances, then emit the final-step active
    edge mask (GO semantics: result = edges leaving the step-(N-1)
    frontier). `steps` is a traced scalar — one compile serves any N.

    -> (final_frontier bool[P, cap_v], final_active bool[P, cap_e])
    """
    edge_ok = _edge_ok(edge_etype, edge_valid, req_types)

    def body(_, f):
        return _advance(f, edge_src, edge_gidx, edge_ok)

    frontier = lax.fori_loop(0, steps - 1, body, frontier0)
    final_active = jnp.take_along_axis(frontier, edge_src, axis=1) & edge_ok
    return frontier, final_active


@jax.jit
def multi_hop_upto(frontier0: jnp.ndarray, steps: jnp.ndarray,
                   edge_src: jnp.ndarray, edge_gidx: jnp.ndarray,
                   edge_etype: jnp.ndarray, edge_valid: jnp.ndarray,
                   req_types: jnp.ndarray) -> jnp.ndarray:
    """GO UPTO: union of active edge masks over steps 1..N.

    -> any_active bool[P, cap_e]
    """
    edge_ok = _edge_ok(edge_etype, edge_valid, req_types)

    def body(_, state):
        frontier, acc = state
        active = jnp.take_along_axis(frontier, edge_src, axis=1) & edge_ok
        return _advance(frontier, edge_src, edge_gidx, edge_ok), acc | active

    _, acc = lax.fori_loop(
        0, steps, body,
        (frontier0, jnp.zeros_like(edge_ok)))
    return acc


@jax.jit
def count_edges(final_active: jnp.ndarray) -> jnp.ndarray:
    return final_active.sum(dtype=jnp.int32)


@jax.jit
def bfs_dist(frontier0: jnp.ndarray, max_steps: jnp.ndarray,
             edge_src: jnp.ndarray, edge_gidx: jnp.ndarray,
             edge_etype: jnp.ndarray, edge_valid: jnp.ndarray,
             req_types: jnp.ndarray) -> jnp.ndarray:
    """Single-source-set BFS depth map for shortest path: dist[p, v] =
    first step at which v was reached (0 for sources, -1 unreached).

    -> dist int32[P, cap_v]
    """
    edge_ok = _edge_ok(edge_etype, edge_valid, req_types)
    P, cap_v = frontier0.shape
    dist0 = jnp.where(frontier0, 0, -1).astype(jnp.int32)

    def cond(state):
        frontier, dist, step = state
        return (step < max_steps) & frontier.any()

    def body(state):
        frontier, dist, step = state
        nxt = _advance(frontier, edge_src, edge_gidx, edge_ok)
        fresh = nxt & (dist < 0)
        dist = jnp.where(fresh, step + 1, dist)
        return fresh, dist, step + 1

    _, dist, _ = lax.while_loop(cond, body, (frontier0, dist0,
                                             jnp.int32(0)))
    return dist


# ---------------------------------------------------------------------------
# multi-hop traversal with edge counting per hop (bench instrumentation)
# ---------------------------------------------------------------------------

@jax.jit
def multi_hop_count(frontier0: jnp.ndarray, steps: jnp.ndarray,
                    edge_src: jnp.ndarray, edge_gidx: jnp.ndarray,
                    edge_etype: jnp.ndarray, edge_valid: jnp.ndarray,
                    req_types: jnp.ndarray) -> jnp.ndarray:
    """Total edges traversed across ALL hops (the bench metric:
    edges-traversed/sec counts every hop's expansions, not just the
    final emission)."""
    edge_ok = _edge_ok(edge_etype, edge_valid, req_types)

    def body(_, state):
        frontier, total = state
        active = jnp.take_along_axis(frontier, edge_src, axis=1) & edge_ok
        total = total + active.sum(dtype=jnp.int64)
        return _advance(frontier, edge_src, edge_gidx, edge_ok), total

    _, total = lax.fori_loop(0, steps, body,
                             (frontier0, jnp.zeros((), jnp.int64)))
    return total
