"""Device traversal kernels: scatter-free BFS frontier advance.

The TPU-native replacement for the reference's per-hop RPC loop
(graphd re-crossing the network every step, ref SURVEY.md §3.1): the
whole multi-hop expansion compiles to ONE XLA program.

Why no scatter: XLA lowers scatter on TPU to a mostly-serialized
update loop, which made the first dense-mask implementation ~1000x
slower than the data movement justifies. Instead a STATIC dst-sort
permutation over the edges is computed at build time (the graph is a
snapshot), which turns a hop into purely parallel, bandwidth-bound
primitives — edge arrays stay in canonical (src, etype, rank, dst)
order; only the 1-bit active values are permuted per hop:

    gather   sorted[e] = frontier[src_sorted[e]] & type_ok_sorted[e]
    scan     S = cumsum(sorted)                                (HBM)
    gather   reached[v] = S[seg_end[v]] - S[seg_start[v]] > 0
    loop     lax.fori_loop over hops (dynamic trip count, no retrace)

The edge arrays are kept in BOTH layouts (EdgeKernel): canonical
(src, etype, rank, dst) order for result materialization, and a
dst-sorted copy permuted ON THE HOST at snapshot-build time — random
[E] gathers are the hop's bottleneck on TPU (~90M indices/s measured
on v5e, far below HBM bandwidth), so baking the dst-sort into a second
static copy halves the per-hop gather count (~1.8x on the batched
path). seg boundaries are searchsorted per destination slot — O(E)
permutation plus O(P*cap_v) boundaries, linear in both, regardless of
partition count. Cross-block combination is all_to_all + OR
(distributed.py).

Dense bool frontiers give within-step dst dedup for free — exactly the
reference's `getDstIdsFromResp` unordered_set semantics (GO revisits
previously-seen vertices across steps; BFS-style visited masks are used
only by shortest-path, which tracks first-hit depth in `dist`).

All shapes are static: [P, cap_v] frontiers, [P, cap_e] edge arrays in
canonical order, [B, P*cap_v] segment boundaries, requested edge types
padded to a fixed-width vector.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

MAX_EDGE_TYPES_PER_QUERY = 8  # fixed width so type sets don't retrace


def _stable_sort_by(keys: np.ndarray, n_keys: int) -> np.ndarray:
    """Stable argsort of small-range non-negative keys: the native
    parallel counting sort when available (O(E), ~6x numpy at 50M and
    growing with size), else numpy's comparison sort."""
    try:
        from .. import native
        order = native.stable_counting_sort(keys, n_keys)
        if order is not None:
            return order
    except Exception:
        pass
    return np.argsort(keys, kind="stable")


def pad_edge_types(edge_types: List[int]) -> np.ndarray:
    """Pad the requested signed-type list to fixed width with 0
    (0 is never a valid edge type)."""
    if len(edge_types) > MAX_EDGE_TYPES_PER_QUERY:
        raise ValueError(f"too many edge types in one traversal "
                         f"({len(edge_types)} > {MAX_EDGE_TYPES_PER_QUERY})")
    out = np.zeros(MAX_EDGE_TYPES_PER_QUERY, np.int32)
    out[:len(edge_types)] = edge_types
    return out


class EdgeKernel(NamedTuple):
    """Device arrays one traversal block needs, both layouts.

    Canonical [bp, cap_e] arrays serve result materialization (the mask
    emitted to the executor is in canonical (src, etype, rank, dst)
    order). The dst-sorted flat copies are what the per-hop advance
    reads: sorting is STATIC (the graph is a snapshot), so paying the
    permute once on the host at build time removes one [E] random
    gather from every hop — measured ~1.8x on the batched path (the
    hop is gather-bound; cumsum and boundary reads are minor).
    """
    src: jnp.ndarray          # i16|i32[bp, cap_e] local src, canonical
    etype: jnp.ndarray        # i8|i32[bp, cap_e] signed type, canonical
    valid: jnp.ndarray        # bool [bp, cap_e] canonical
    src_sorted: jnp.ndarray   # int32[bp*cap_e] frontier slot, dst-sorted
    etype_sorted: jnp.ndarray  # i8|i32[bp*cap_e] dst-sorted
    valid_sorted: jnp.ndarray  # bool [bp*cap_e] dst-sorted
    seg_starts: jnp.ndarray   # int32[P*cap_v] cumsum boundary (incl.)
    seg_ends: jnp.ndarray     # int32[P*cap_v] cumsum boundary (excl.)


def build_kernel(edge_src: np.ndarray, edge_etype: np.ndarray,
                 edge_valid: np.ndarray, edge_gidx: np.ndarray,
                 num_parts: int, cap_v: int,
                 num_blocks: int = 1,
                 orders_out: Optional[List[np.ndarray]] = None
                 ) -> List[EdgeKernel]:
    """Build per-block EdgeKernels (host-side, numpy).

    edge_gidx: int32[P, cap_e] global dst index `dst_part*cap_v +
    dst_local` in CANONICAL edge order; invalid/padded edges must carry
    the dump value num_parts*cap_v so they sort to the tail and fall
    outside every segment.

    Shards are merged in `num_blocks` contiguous groups (1 = whole
    space, single chip; D = one block per device for the distributed
    path, since each device only reads its own edges). `src_sorted`
    holds block-local frontier slots `local_part*cap_v + src_local`.

    orders_out: when given, receives each block's canonical->sorted
    permutation (int64[bp*cap_e]) — the delta applier uses it to point-
    update `valid_sorted` when an edge is tombstoned in place.
    """
    P, cap_e = edge_gidx.shape
    assert P % num_blocks == 0
    bp = P // num_blocks
    n = num_parts * cap_v
    slots = np.arange(n)
    out = []
    for b in range(num_blocks):
        sl = slice(b * bp, (b + 1) * bp)
        flat_g = edge_gidx[sl].reshape(-1)
        order = _stable_sort_by(flat_g, n + 1)
        sorted_g = flat_g[order]
        if orders_out is not None:
            orders_out.append(order)
        src_flat = (np.arange(bp, dtype=np.int64)[:, None] * cap_v
                    + edge_src[sl]).reshape(-1)
        out.append(EdgeKernel(
            src=jnp.asarray(edge_src[sl]),
            etype=jnp.asarray(edge_etype[sl]),
            valid=jnp.asarray(edge_valid[sl]),
            src_sorted=jnp.asarray(src_flat[order].astype(np.int32)),
            etype_sorted=jnp.asarray(edge_etype[sl].reshape(-1)[order]),
            valid_sorted=jnp.asarray(edge_valid[sl].reshape(-1)[order]),
            seg_starts=jnp.asarray(
                np.searchsorted(sorted_g, slots, "left").astype(np.int32)),
            seg_ends=jnp.asarray(
                np.searchsorted(sorted_g, slots, "right").astype(np.int32)),
        ))
    return out


def stack_kernels(kerns: List[EdgeKernel]) -> EdgeKernel:
    """Stack per-block kernels into one [B, ...] pytree for shard_map."""
    return EdgeKernel(*(jnp.stack(a) for a in zip(*kerns)))


def _edge_ok(edge_etype: jnp.ndarray, edge_valid: jnp.ndarray,
             req_types: jnp.ndarray) -> jnp.ndarray:
    """Mask of edges matching the requested signed types (any layout —
    broadcasts over the leading dims of edge_etype)."""
    expand = (None,) * edge_etype.ndim
    m = (edge_etype[None] == req_types[(slice(None),) + expand]).any(axis=0)
    return m & edge_valid


def hop_hits(frontier: jnp.ndarray, src_sorted: jnp.ndarray,
             ok_sorted: jnp.ndarray, seg_starts: jnp.ndarray,
             seg_ends: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """THE hop primitive, shared by every traversal variant (single-chip
    advance, counting, and the distributed per-block contribution): one
    [E] gather (sorted src slots) + cumsum + two boundary gathers;
    scatter-free.

    frontier: bool[P_local, cap_v] -> (hits bool[n_slots],
    active_count i32) where n_slots = len(seg_starts) (the full space's
    destination slots — equal to frontier.size on a single block).
    """
    flat = frontier.reshape(-1)[src_sorted] & ok_sorted
    S0 = jnp.pad(jnp.cumsum(flat.astype(jnp.int32)), (1, 0))
    return (S0[seg_ends] - S0[seg_starts]) > 0, S0[-1]


def _advance(frontier: jnp.ndarray, k: EdgeKernel,
             ok_sorted: jnp.ndarray) -> jnp.ndarray:
    """One BFS hop on stacked partitions (single device = one block)."""
    P, cap_v = frontier.shape
    hits, _ = hop_hits(frontier, k.src_sorted, ok_sorted,
                       k.seg_starts, k.seg_ends)
    return hits.reshape(P, cap_v)


@jax.jit
def multi_hop(frontier0: jnp.ndarray, steps: jnp.ndarray,
              k: EdgeKernel, req_types: jnp.ndarray
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run `steps-1` frontier advances, then emit the final-step active
    edge mask (GO semantics: result = edges leaving the step-(N-1)
    frontier). `steps` is a traced scalar — one compile serves any N.

    -> (final_frontier bool[P, cap_v], final_active bool[P, cap_e]);
    the edge mask is in canonical edge order.
    """
    ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req_types)

    def body(_, f):
        return _advance(f, k, ok_sorted)

    frontier = lax.fori_loop(0, steps - 1, body, frontier0)
    edge_ok = _edge_ok(k.etype, k.valid, req_types)
    final_active = jnp.take_along_axis(frontier, k.src, axis=1) & edge_ok
    return frontier, final_active


@jax.jit
def multi_hop_upto(frontier0: jnp.ndarray, steps: jnp.ndarray,
                   k: EdgeKernel, req_types: jnp.ndarray) -> jnp.ndarray:
    """GO UPTO: union of active edge masks over steps 1..N.

    -> any_active bool[P, cap_e] in canonical edge order.
    """
    edge_ok = _edge_ok(k.etype, k.valid, req_types)
    ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req_types)

    def body(_, state):
        frontier, acc = state
        active = jnp.take_along_axis(frontier, k.src, axis=1) & edge_ok
        return _advance(frontier, k, ok_sorted), acc | active

    _, acc = lax.fori_loop(
        0, steps, body,
        (frontier0, jnp.zeros_like(edge_ok)))
    return acc


@jax.jit
def count_edges(final_active: jnp.ndarray) -> jnp.ndarray:
    return final_active.sum(dtype=jnp.int32)


# ---------------------------------------------------------------------------
# delta-aware traversal (CSR + ELL add-buffer union)
# ---------------------------------------------------------------------------

class DeltaKernel(NamedTuple):
    """Device form of the snapshot's ELL add-buffer: up to K delta
    edges per DESTINATION slot. Keying by dst makes the per-hop union a
    pure GATHER (reached[v] |= any_k frontier[src[v,k]]) — no scatter,
    which XLA would serialize on TPU (see module doc). Unused lanes
    have ok=False and src=0 (slot 0 is a real slot; the False mask
    gates it)."""
    src: jnp.ndarray     # int32[n_slots, K] global src slot
    etype: jnp.ndarray   # int32[n_slots, K] signed edge type
    ok: jnp.ndarray      # bool [n_slots, K] lane in use


def _delta_hits(frontier: jnp.ndarray, dk: DeltaKernel,
                d_ok: jnp.ndarray) -> jnp.ndarray:
    """Union contribution of the delta edges for one hop: bool[P, cap_v]."""
    hit = (frontier.reshape(-1)[dk.src] & d_ok).any(axis=1)
    return hit.reshape(frontier.shape)


@jax.jit
def multi_hop_delta(frontier0: jnp.ndarray, steps: jnp.ndarray,
                    k: EdgeKernel, dk: DeltaKernel, req_types: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """multi_hop over the union graph (base CSR ∪ delta adds; base
    tombstones are already cleared in k.valid/k.valid_sorted).

    -> (final_frontier [P, cap_v], final_active [P, cap_e] canonical,
        delta_active bool[n_slots, K])
    """
    ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req_types)
    d_ok = _edge_ok(dk.etype, dk.ok, req_types)

    def body(_, f):
        return _advance(f, k, ok_sorted) | _delta_hits(f, dk, d_ok)

    frontier = lax.fori_loop(0, steps - 1, body, frontier0)
    edge_ok = _edge_ok(k.etype, k.valid, req_types)
    final_active = jnp.take_along_axis(frontier, k.src, axis=1) & edge_ok
    delta_active = frontier.reshape(-1)[dk.src] & d_ok
    return frontier, final_active, delta_active


@jax.jit
def bfs_dist_delta(frontier0: jnp.ndarray, max_steps: jnp.ndarray,
                   k: EdgeKernel, dk: DeltaKernel,
                   req_types: jnp.ndarray) -> jnp.ndarray:
    """bfs_dist over the union graph (shortest-path depth maps)."""
    ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req_types)
    d_ok = _edge_ok(dk.etype, dk.ok, req_types)
    dist0 = jnp.where(frontier0, 0, -1).astype(jnp.int32)

    def cond(state):
        frontier, dist, step = state
        return (step < max_steps) & frontier.any()

    def body(state):
        frontier, dist, step = state
        nxt = _advance(frontier, k, ok_sorted) | _delta_hits(frontier, dk,
                                                             d_ok)
        fresh = nxt & (dist < 0)
        dist = jnp.where(fresh, step + 1, dist)
        return fresh, dist, step + 1

    _, dist, _ = lax.while_loop(cond, body, (frontier0, dist0,
                                             jnp.int32(0)))
    return dist


@jax.jit
def bfs_dist(frontier0: jnp.ndarray, max_steps: jnp.ndarray,
             k: EdgeKernel, req_types: jnp.ndarray) -> jnp.ndarray:
    """Single-source-set BFS depth map for shortest path: dist[p, v] =
    first step at which v was reached (0 for sources, -1 unreached).

    -> dist int32[P, cap_v]
    """
    ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req_types)
    dist0 = jnp.where(frontier0, 0, -1).astype(jnp.int32)

    def cond(state):
        frontier, dist, step = state
        return (step < max_steps) & frontier.any()

    def body(state):
        frontier, dist, step = state
        nxt = _advance(frontier, k, ok_sorted)
        fresh = nxt & (dist < 0)
        dist = jnp.where(fresh, step + 1, dist)
        return fresh, dist, step + 1

    _, dist, _ = lax.while_loop(cond, body, (frontier0, dist0,
                                             jnp.int32(0)))
    return dist


# ---------------------------------------------------------------------------
# multi-hop traversal with edge counting per hop (bench instrumentation)
# ---------------------------------------------------------------------------

@jax.jit
def multi_hop_count(frontier0: jnp.ndarray, steps: jnp.ndarray,
                    k: EdgeKernel, req_types: jnp.ndarray) -> jnp.ndarray:
    """Total edges traversed across ALL hops (the bench metric:
    edges-traversed/sec counts every hop's expansions, not just the
    final emission). Counts on the sorted layout — sums are
    order-invariant, so the canonical arrays are never touched."""
    ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req_types)

    def body(_, state):
        frontier, total = state
        hits, n = hop_hits(frontier, k.src_sorted, ok_sorted,
                           k.seg_starts, k.seg_ends)
        # int64 accumulator: >2^31 edges per query is reachable on large
        # graphs (canonicalizes to int32 only when x64 is disabled)
        total = total + n.astype(jnp.int64)
        return hits.reshape(frontier.shape), total

    _, total = lax.fori_loop(0, steps, body,
                             (frontier0, jnp.zeros((), jnp.int64)))
    return total


# ---------------------------------------------------------------------------
# UPTO (per-step masks) and input-ref (per-root) traversal
# ---------------------------------------------------------------------------

from functools import partial


@partial(jax.jit, static_argnames=("steps",))
def multi_hop_steps(frontier0: jnp.ndarray, k: EdgeKernel,
                    req_types: jnp.ndarray, steps: int) -> jnp.ndarray:
    """Per-step active edge masks for GO UPTO: the device analogue of
    emitting rows at EVERY step 1..N (ref: GoExecutor's upto emission).
    `steps` is static — the AST carries a literal N, and the stacked
    [steps, P, cap_e] output shape depends on it (one trace per N).
    """
    edge_ok = _edge_ok(k.etype, k.valid, req_types)
    ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req_types)
    masks = []
    f = frontier0
    for _ in range(steps):
        masks.append(jnp.take_along_axis(f, k.src, axis=1) & edge_ok)
        f = _advance(f, k, ok_sorted)
    return jnp.stack(masks)


@partial(jax.jit, static_argnames=("steps",))
def multi_hop_steps_delta(frontier0: jnp.ndarray, k: EdgeKernel,
                          dk: DeltaKernel, req_types: jnp.ndarray,
                          steps: int):
    """multi_hop_steps over the union graph.
    -> (masks [steps, P, cap_e], delta_masks [steps, n_slots, K])."""
    edge_ok = _edge_ok(k.etype, k.valid, req_types)
    ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req_types)
    d_ok = _edge_ok(dk.etype, dk.ok, req_types)
    masks, dmasks = [], []
    f = frontier0
    for _ in range(steps):
        masks.append(jnp.take_along_axis(f, k.src, axis=1) & edge_ok)
        dmasks.append(f.reshape(-1)[dk.src] & d_ok)
        f = _advance(f, k, ok_sorted) | _delta_hits(f, dk, d_ok)
    return jnp.stack(masks), jnp.stack(dmasks)


@jax.jit
def multi_hop_roots(frontiers0: jnp.ndarray, steps: jnp.ndarray,
                    k: EdgeKernel, req_types: jnp.ndarray) -> jnp.ndarray:
    """Final-step active edge masks per ROOT — input-ref GO runs one
    frontier per root so materialization can join result rows back to
    the input rows of the root that reached them (the device form of
    VertexBackTracker, ref GoExecutor.cpp:1067-1075).
    frontiers0: bool[R, P, cap_v] -> bool[R, P, cap_e]."""
    return jax.vmap(
        lambda f: multi_hop(f, steps, k, req_types)[1])(frontiers0)


@jax.jit
def multi_hop_roots_delta(frontiers0: jnp.ndarray, steps: jnp.ndarray,
                          k: EdgeKernel, dk: DeltaKernel,
                          req_types: jnp.ndarray):
    """multi_hop_roots over the union graph.
    -> (masks [R, P, cap_e], delta_masks [R, n_slots, K])."""
    def one(f):
        _, active, d_active = multi_hop_delta(f, steps, k, dk, req_types)
        return active, d_active
    return jax.vmap(one)(frontiers0)


# ---------------------------------------------------------------------------
# batched traversal: chunk-aligned layout + int8 lane matrix
# ---------------------------------------------------------------------------

C_ALIGN = 8     # edges per chunk (segment starts are chunk-aligned)
G_ALIGN = 16    # chunks per prefix group (two-level scan)
LANES = 128     # frontier lanes per row = one full TPU lane width


class AlignedKernel(NamedTuple):
    """Dst-aligned edge layout for the batched frontier-MATRIX path.

    Every destination slot's incoming edges are padded to a multiple of
    C_ALIGN and placed contiguously, so all segment boundaries are
    chunk-aligned: the per-hop reduction becomes (fused gather+chunk-sum)
    + a cheap two-level prefix over chunk sums + ONE boundary gather —
    no O(E)-length scan. Dead slots (padding, and per-dispatch type
    mismatches) point at frontier row n_slots, which is always zero.

    Measured on v5e vs the vmapped scalar formulation this replaces
    (round-2 verdict item: ~5% HBM util): ~2.5x per-dispatch at 64
    queries, ~5x at the full 128 lanes — the remaining cost is the [E]
    random row-gather, which runs at the TPU gather-engine rate
    (~300K rows/ms) independent of row width up to 128 bytes.

    deg_types/degs: out-degree of every source slot per signed edge
    type over the kernel's REAL edges — lets the packed-frontier
    variant count edges-per-lane as one [n_slots] dot against the
    frontier matrix instead of summing at the edge level.
    """
    src: jnp.ndarray     # int32[E_pad] global src slot; dead -> n_slots
    etype: jnp.ndarray   # i8|i32[E_pad] signed type; padding -> 0
    cbound: jnp.ndarray  # int32[n_slots+1] chunk index of each segment start
    deg_types: jnp.ndarray  # int32[T] signed types present in the graph
    degs: jnp.ndarray    # int32[T, n_slots] per-type out-degree per slot


def pick_chunk(n_edges: int) -> Tuple[int, int]:
    """(chunk, group) for an edge count: chunks of 8 measure fastest at
    <=10M-edge scale, but the per-chunk device arrays are O(E/chunk *
    512B) — at 10^8 edges chunk=8 alone would cost ~6.7GB, so larger
    graphs take bigger chunks (more segment padding, far less chunk-sum
    memory/traffic)."""
    if n_edges <= (1 << 25):
        return 8, 16
    if n_edges <= (1 << 27):
        return 16, 16
    return 32, 16


def build_aligned(gsrc: np.ndarray, etype: np.ndarray, gdst: np.ndarray,
                  n_slots: int,
                  chunk: Optional[int] = None,
                  group: int = G_ALIGN
                  ) -> Tuple[AlignedKernel, int, int]:
    """Host-side aligned-layout build from flat canonical edge arrays
    (gdst = dump >= n_slots for invalid/padded edges, which are
    dropped). -> (kernel, chunk, group) — chunk/group are static
    parameters of the matching multi_hop_count_batch call."""
    order = _stable_sort_by(gdst, n_slots + 1)
    sg = gdst[order]
    nreal = int(np.searchsorted(sg, n_slots))
    if chunk is None:
        chunk, group = pick_chunk(nreal)
    order, sg = order[:nreal], sg[:nreal]
    starts = np.searchsorted(sg, np.arange(n_slots)).astype(np.int64)
    ends = np.searchsorted(sg, np.arange(n_slots) + 1).astype(np.int64)
    pdeg = ((ends - starts + chunk - 1) // chunk) * chunk
    astart = np.zeros(n_slots + 1, np.int64)
    np.cumsum(pdeg, out=astart[1:])
    span = chunk * group
    # round up, then add one all-zero group so the prefix pieces cover
    # the final boundary
    e_pad = (int(astart[-1]) + span - 1) // span * span + span
    a_src = np.full(e_pad, n_slots, np.int32)
    # etype keeps the snapshot's packed width (int8 when it fits) —
    # the per-dispatch type-gate pass reads e_pad of these
    a_etype = np.zeros(e_pad, getattr(etype, "dtype", np.int32))
    if nreal:
        pos = astart[:-1][sg] + (np.arange(nreal) - starts[sg])
        a_src[pos] = gsrc[order]
        a_etype[pos] = etype[order]
    cbound = (astart // chunk).astype(np.int32)
    # per-signed-type out-degrees over the REAL edges (the packed
    # variant's count input) — ONE combined bincount over
    # type_index*n_slots + src, not a pass per type
    r_src, r_et = gsrc[order], etype[order]
    types = np.unique(r_et) if nreal else np.zeros(0, np.int32)
    nt = max(len(types), 1)
    if nreal:
        ti = np.searchsorted(types, r_et).astype(np.int64)
        degs = np.bincount(ti * n_slots + r_src,
                           minlength=nt * n_slots).reshape(
            nt, n_slots).astype(np.int32)
    else:
        degs = np.zeros((nt, n_slots), np.int32)
    deg_types = np.zeros(nt, np.int32)
    deg_types[:len(types)] = types
    return (AlignedKernel(jnp.asarray(a_src), jnp.asarray(a_etype),
                          jnp.asarray(cbound), jnp.asarray(deg_types),
                          jnp.asarray(degs)), chunk, group)


@partial(jax.jit, static_argnames=("chunk", "group"))
def multi_hop_count_batch(frontiers0: jnp.ndarray, steps: jnp.ndarray,
                          ak: AlignedKernel, req_types: jnp.ndarray,
                          chunk: int = C_ALIGN,
                          group: int = G_ALIGN) -> jnp.ndarray:
    """Batch of independent GO queries in ONE dispatch over a
    [n_slots+1, 128] int8 frontier matrix (row n_slots stays zero): per
    hop, ONE [E_pad] gather of 128-byte frontier rows fused into chunk
    sums, a two-level prefix over chunks, and one boundary gather. The
    random-gather count per hop is independent of B — batching
    amortizes the gather-engine bottleneck across all lanes.

    The edge axis is processed in ~8M-edge blocks (lax.map) so the
    [block, 128] gather intermediate stays bounded — at 10^8 edges an
    unblocked [E_pad, 128] int8 would be ~13GB and OOM the chip.
    chunk/group must be the values build_aligned returned for `ak`.

    frontiers0: bool[B, P, cap_v], B <= 128 (lanes beyond B ride along
    zero) -> int64[B] per-query edges traversed (every hop's expansions
    counted, same semantics as multi_hop_count).
    """
    B = frontiers0.shape[0]
    if B > LANES:
        raise ValueError(f"batch {B} > {LANES} lanes per dispatch")
    lay = _matrix_layout(ak, req_types, chunk, group)
    F = _init_lanes(frontiers0, lay[0])

    def body(_, state):
        f, total = state
        f, count = _matrix_hop(f, lay, chunk, group)
        return f, total + count

    _, total = lax.fori_loop(0, steps, body,
                             (F, jnp.zeros((LANES,), jnp.int64)))
    return total[:B]


def _matrix_layout(ak: AlignedKernel, req_types: jnp.ndarray,
                   chunk: int, group: int):
    """Shared per-dispatch prologue of the lane-matrix kernels: block
    sizing, type-gated effective sources, and boundary indices.
    -> (ns, blk, nc, ng, src_eff, g_idx, j_idx)."""
    ns = ak.cbound.shape[0] - 1
    e_pad = ak.src.shape[0]
    span = chunk * group
    nb = max(1, -(-e_pad // (1 << 23)))          # ~8M edges per block
    blk = -(-e_pad // nb // span) * span
    tot = nb * blk
    nc = tot // chunk
    ng = nc // group
    # dead edges (type mismatch this dispatch) -> the always-zero row
    ok = (ak.etype[None] == req_types[:, None]).any(axis=0)
    src_eff = jnp.pad(jnp.where(ok, ak.src, ns), (0, tot - e_pad),
                      constant_values=ns).reshape(nb, blk)
    g_idx = ak.cbound // group                   # [ns+1] group of boundary
    j_idx = ak.cbound % group                    # [ns+1] chunk within group
    return ns, blk, nc, ng, src_eff, g_idx, j_idx


def _init_lanes(frontiers0: jnp.ndarray, ns: int) -> jnp.ndarray:
    """[ns+1, LANES] int8 frontier matrix (row ns stays zero)."""
    B = frontiers0.shape[0]
    F = jnp.zeros((ns + 1, LANES), jnp.int8)
    return F.at[:ns, :B].set(frontiers0.reshape(B, -1).T.astype(jnp.int8))


def _matrix_hop(f: jnp.ndarray, lay, chunk: int, group: int):
    """One frontier-matrix hop over the aligned layout: fused gather +
    chunk sums, a two-level prefix over chunk sums, one boundary
    gather. -> (next int8 matrix, per-lane int64 expansion count)."""
    _ns, blk, nc, ng, src_eff, g_idx, j_idx = lay

    def block_cs(sb):                            # fused gather + chunk sum
        return f[sb].reshape(blk // chunk, chunk, LANES).sum(
            axis=1, dtype=jnp.int32)

    cs = lax.map(block_cs, src_eff).reshape(nc, LANES)
    local_inc = jnp.cumsum(cs.reshape(ng, group, LANES), axis=1)
    grp_tot = local_inc[:, -1]
    grp_exc = jnp.pad(jnp.cumsum(grp_tot, axis=0),
                      ((1, 0), (0, 0)))[:-1]
    # int64 accumulator: >2^31 edges per query is reachable on large
    # graphs (canonicalizes to int32 only when x64 is disabled)
    count = (grp_exc[-1] + grp_tot[-1]).astype(jnp.int64)
    # exclusive prefix AT the boundaries only (never materializing
    # the full [nc, LANES] scan): grp_exc[g] + within-group prefix
    local_prev = jnp.where(
        (j_idx > 0)[:, None],
        local_inc[g_idx, jnp.maximum(j_idx - 1, 0)], 0)
    Sv = grp_exc[g_idx] + local_prev             # [ns+1, LANES]
    hits = (Sv[1:] - Sv[:-1]) > 0
    return jnp.pad(hits.astype(jnp.int8), ((0, 1), (0, 0))), count


def _masks_batch_core(frontiers0: jnp.ndarray, steps: jnp.ndarray,
                      ak: AlignedKernel, k: EdgeKernel,
                      req_types: jnp.ndarray, chunk: int,
                      group: int) -> jnp.ndarray:
    """Unjitted body of multi_hop_masks_batch — shared with the fused
    window programs (fused.py), which append the compiled-WHERE lane
    filters inside the SAME compiled program."""
    B, P, cap_v = frontiers0.shape
    if B > LANES:
        raise ValueError(f"batch {B} > {LANES} lanes per dispatch")
    lay = _matrix_layout(ak, req_types, chunk, group)
    F = _init_lanes(frontiers0, lay[0])

    def body(_, f):
        return _matrix_hop(f, lay, chunk, group)[0]

    F = lax.fori_loop(0, jnp.maximum(steps - 1, 0), body, F)
    # one canonical gather closes the hop: [E, B] frontier bits at each
    # edge's global src slot, masked by validity + requested types
    cap_e = k.src.shape[-1]
    gsrc = (jnp.arange(P, dtype=jnp.int32)[:, None] * cap_v
            + k.src.reshape(P, cap_e))
    rows = F[:, :B][gsrc.reshape(-1)]            # [P*cap_e, B] int8
    ok_c = _edge_ok(k.etype.reshape(P, cap_e),
                    k.valid.reshape(P, cap_e), req_types)
    masks = (rows.reshape(P, cap_e, B) > 0) & ok_c[..., None]
    return jnp.moveaxis(masks, 2, 0)


@partial(jax.jit, static_argnames=("chunk", "group"))
def multi_hop_masks_batch(frontiers0: jnp.ndarray, steps: jnp.ndarray,
                          ak: AlignedKernel, k: EdgeKernel,
                          req_types: jnp.ndarray,
                          chunk: int = C_ALIGN,
                          group: int = G_ALIGN) -> jnp.ndarray:
    """Final-hop ACTIVE EDGE MASKS for a batch of GO queries in ONE
    dispatch — the cross-session dispatcher's shared kernel. The packed
    [n_slots+1, LANES] int8 frontier matrix advances steps-1 hops over
    the aligned layout (identical machinery to multi_hop_count_batch —
    the edge/index streams are read ONCE per hop for the whole window,
    where a vmapped multi_hop re-reads them per query on backends that
    lower vmap to loops), then one gather over the CANONICAL layout
    turns the matrix into per-lane canonical masks:

        active[b, p, e] = valid & etype_ok & F[global_src(p, e), b]

    Identical semantics to `[multi_hop(f, steps, k, req)[1] for f in
    batch]` (the frontier of hop N-1 selects hop N's edges; revisits
    allowed, dedup by saturation). frontiers0: bool[B, P, cap_v] ->
    bool[B, P, cap_e]; B is bounded by the caller's mask-memory budget
    (the output is the same size the vmapped form materializes)."""
    return _masks_batch_core(frontiers0, steps, ak, k, req_types,
                             chunk, group)


def build_aligned_blocks(gsrc: np.ndarray, etype: np.ndarray,
                         gdst: np.ndarray, n_slots: int, num_blocks: int,
                         block_of: np.ndarray,
                         chunk: Optional[int] = None,
                         group: int = G_ALIGN
                         ) -> Tuple[AlignedKernel, int, int]:
    """Per-device-block aligned layouts, stacked with a leading block
    dim (shard_map form of build_aligned): block b gets the aligned
    layout of ITS edges (block_of[e] == b) over the GLOBAL slot space,
    padded to a common E_pad; degs/deg_types use one global type list
    so every block's arrays shape-match."""
    types = np.unique(etype[gdst < n_slots]) if len(etype) else \
        np.zeros(0, np.int32)
    nt = max(len(types), 1)
    deg_types = np.zeros(nt, np.int32)
    deg_types[:len(types)] = types
    builds = []
    for b in range(num_blocks):
        sel = np.nonzero(block_of == b)[0]
        ak_b, chunk, group = build_aligned(gsrc[sel], etype[sel],
                                           gdst[sel], n_slots,
                                           chunk=chunk, group=group)
        builds.append(ak_b)
    e_pad = max(int(a.src.shape[0]) for a in builds)
    span = chunk * group
    e_pad = -(-e_pad // span) * span
    srcs, etypes, cbounds, degss = [], [], [], []
    for ak_b in builds:
        pad = e_pad - int(ak_b.src.shape[0])
        srcs.append(jnp.pad(ak_b.src, (0, pad), constant_values=n_slots))
        etypes.append(jnp.pad(ak_b.etype, (0, pad)))
        cbounds.append(ak_b.cbound)
        # re-key this block's degs onto the global type list
        d = np.zeros((nt, n_slots), np.int32)
        bt = np.asarray(ak_b.deg_types)
        bd = np.asarray(ak_b.degs)
        for i, t in enumerate(bt):
            j = np.searchsorted(types, t) if len(types) else 0
            if len(types) and j < len(types) and types[j] == t:
                d[j] += bd[i]
        degss.append(jnp.asarray(d))
    return (AlignedKernel(jnp.stack(srcs), jnp.stack(etypes),
                          jnp.stack(cbounds),
                          jnp.asarray(np.tile(deg_types, (num_blocks, 1))),
                          jnp.stack(degss)), chunk, group)


@partial(jax.jit, static_argnames=("chunk", "group"))
def multi_hop_count_batch_packed(frontiers0: jnp.ndarray,
                                 steps: jnp.ndarray, ak: AlignedKernel,
                                 req_types: jnp.ndarray,
                                 chunk: int = C_ALIGN,
                                 group: int = G_ALIGN) -> jnp.ndarray:
    """multi_hop_count_batch with BITPACKED frontier rows: the per-hop
    [E_pad] gather reads 16-byte uint32x4 rows (128 lanes as bits)
    instead of 128-byte int8 rows — 8x less gather traffic on the
    random-access bottleneck. Per-chunk lane hits come from a bitwise
    OR over the chunk (a chunk crossing a frontier lane >= once is all
    the advance needs), unpacked to {0,1} per lane only at CHUNK
    granularity (nc rows, not E_pad) for the same two-level prefix +
    boundary-diff as the int8 variant.

    Edges-traversed counts drop out of the edge level entirely: per
    hop, count[lane] = sum_v deg_req[v] * frontier[v, lane] — one dot
    against the per-slot requested-type out-degrees carried by the
    kernel (ak.degs), identical by construction to summing gathered
    actives.

    Semantics and signature match multi_hop_count_batch exactly.
    """
    B = frontiers0.shape[0]
    if B > LANES:
        raise ValueError(f"batch {B} > {LANES} lanes per dispatch")
    ns = ak.cbound.shape[0] - 1
    F = jnp.zeros((ns + 1, LANES), jnp.int8)
    F = F.at[:ns, :B].set(frontiers0.reshape(B, -1).T.astype(jnp.int8))
    src_eff = _packed_src_eff(ak, req_types, ns, chunk, group)
    deg_req = _deg_req(ak, req_types)
    g_idx = ak.cbound // group
    j_idx = ak.cbound % group

    def body(_, state):
        f, total = state
        # edges leaving the CURRENT frontier, per lane (int32 is safe:
        # one hop's count is bounded by E_pad < 2^31)
        cnt = (f[:ns].astype(jnp.int32) * deg_req[:, None]).sum(
            axis=0, dtype=jnp.int32)
        total = total + cnt.astype(jnp.int64)
        hits = _packed_hits(f, src_eff, g_idx, j_idx, ns, chunk, group)
        return jnp.pad(hits.astype(jnp.int8), ((0, 1), (0, 0))), total

    _, total = lax.fori_loop(0, steps, body,
                             (F, jnp.zeros((LANES,), jnp.int64)))
    return total[:B]


def _deg_req(ak: AlignedKernel, req_types: jnp.ndarray) -> jnp.ndarray:
    """int32[n_slots] out-degree per slot over the requested types."""
    tmask = (ak.deg_types[:, None] == req_types[None, :]).any(axis=1)
    return (ak.degs * tmask[:, None].astype(ak.degs.dtype)).sum(axis=0)


def _packed_src_eff(ak: AlignedKernel, req_types: jnp.ndarray, ns: int,
                    chunk: int, group: int) -> jnp.ndarray:
    """[nb, blk] gather indices with type-dead edges pointed at the
    always-zero row, padded to whole ~8M-edge map blocks."""
    e_pad = ak.src.shape[0]
    span = chunk * group
    nb = max(1, -(-e_pad // (1 << 23)))          # ~8M edges per block
    blk = -(-e_pad // nb // span) * span
    tot = nb * blk
    ok = (ak.etype[None] == req_types[:, None]).any(axis=0)
    return jnp.pad(jnp.where(ok, ak.src, ns), (0, tot - e_pad),
                   constant_values=ns).reshape(nb, blk)


def _packed_hits(f: jnp.ndarray, src_eff: jnp.ndarray,
                 g_idx: jnp.ndarray, j_idx: jnp.ndarray, ns: int,
                 chunk: int, group: int) -> jnp.ndarray:
    """One packed-frontier hop: -> hits bool[ns, LANES]. `f` is the
    [ns+1, LANES] int8 frontier matrix (row ns always zero)."""
    nb, blk = src_eff.shape
    nc = (nb * blk) // chunk
    ng = nc // group
    shifts = jnp.arange(32, dtype=jnp.uint32)
    # lanes -> bits: word w holds lanes [32w, 32w+32)
    packed = (jnp.left_shift(
        f.astype(jnp.uint32).reshape(ns + 1, 4, 32),
        shifts[None, None, :])).sum(axis=2, dtype=jnp.uint32)

    def block_or(sb):                            # fused gather + chunk OR
        rows = packed[sb].reshape(blk // chunk, chunk, 4)
        return lax.reduce(rows, jnp.uint32(0), lax.bitwise_or, (1,))

    cs = lax.map(block_or, src_eff).reshape(nc, 4)
    u = ((cs[:, :, None] >> shifts[None, None, :])
         & jnp.uint32(1)).reshape(nc, LANES).astype(jnp.int8)
    local_inc = jnp.cumsum(u.reshape(ng, group, LANES), axis=1,
                           dtype=jnp.int32)
    grp_tot = local_inc[:, -1]
    grp_exc = jnp.pad(jnp.cumsum(grp_tot, axis=0),
                      ((1, 0), (0, 0)))[:-1]
    local_prev = jnp.where(
        (j_idx > 0)[:, None],
        local_inc[g_idx, jnp.maximum(j_idx - 1, 0)], 0)
    Sv = grp_exc[g_idx] + local_prev             # [ns+1, LANES]
    return (Sv[1:] - Sv[:-1]) > 0
