"""Device traversal kernels: scatter-free BFS frontier advance.

The TPU-native replacement for the reference's per-hop RPC loop
(graphd re-crossing the network every step, ref SURVEY.md §3.1): the
whole multi-hop expansion compiles to ONE XLA program.

Why no scatter: XLA lowers scatter on TPU to a mostly-serialized
update loop, which made the first dense-mask implementation ~1000x
slower than the data movement justifies. Instead a STATIC dst-sort
permutation over the edges is computed at build time (the graph is a
snapshot), which turns a hop into purely parallel, bandwidth-bound
primitives — edge arrays stay in canonical (src, etype, rank, dst)
order; only the 1-bit active values are permuted per hop:

    gather   active[e] = frontier[edge_src[e]] & type_ok[e]   (VPU)
    gather   sorted = active.flat[order]    (order: static dst-sort)
    scan     S = cumsum(sorted)                                (HBM)
    gather   reached[v] = S[seg_end[v]] - S[seg_start[v]] > 0
    loop     lax.fori_loop over hops (dynamic trip count, no retrace)

order/seg_start/seg_end come from build_segments: the edges of a BLOCK
of shards (the whole space on one chip; one device's shards in the
distributed path) are merge-sorted by destination global index, and
seg boundaries are searchsorted per destination slot — O(E) permutation
plus O(P*cap_v) boundaries, linear in both, regardless of partition
count. Cross-block combination is all_to_all + OR (distributed.py).

Dense bool frontiers give within-step dst dedup for free — exactly the
reference's `getDstIdsFromResp` unordered_set semantics (GO revisits
previously-seen vertices across steps; BFS-style visited masks are used
only by shortest-path, which tracks first-hit depth in `dist`).

All shapes are static: [P, cap_v] frontiers, [P, cap_e] edge arrays in
canonical order, [B, P*cap_v] segment boundaries, requested edge types
padded to a fixed-width vector.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

MAX_EDGE_TYPES_PER_QUERY = 8  # fixed width so type sets don't retrace


def pad_edge_types(edge_types: List[int]) -> np.ndarray:
    """Pad the requested signed-type list to fixed width with 0
    (0 is never a valid edge type)."""
    if len(edge_types) > MAX_EDGE_TYPES_PER_QUERY:
        raise ValueError(f"too many edge types in one traversal "
                         f"({len(edge_types)} > {MAX_EDGE_TYPES_PER_QUERY})")
    out = np.zeros(MAX_EDGE_TYPES_PER_QUERY, np.int32)
    out[:len(edge_types)] = edge_types
    return out


def build_segments(edge_gidx: np.ndarray, num_parts: int, cap_v: int,
                   num_blocks: int = 1
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Static dst-sort order + per-destination segment boundaries.

    edge_gidx: int32[P, cap_e] global dst index `dst_part*cap_v +
    dst_local` in CANONICAL edge order; invalid/padded edges must carry
    the dump value num_parts*cap_v so they sort to the tail and fall
    outside every segment.

    Shards are merged in `num_blocks` contiguous groups (1 = whole
    space, single chip; D = one block per device for the distributed
    path, since each device can only permute its own edges).

    Returns (order, seg_starts, seg_ends):
      order      int32[B, (P/B)*cap_e]  sorted position -> flat
                                        canonical index within block
      seg_starts int32[B, P*cap_v]      cumsum-boundary (incl. start)
      seg_ends   int32[B, P*cap_v]      cumsum-boundary (excl. end)
    """
    P, cap_e = edge_gidx.shape
    assert P % num_blocks == 0
    bp = P // num_blocks
    n = num_parts * cap_v
    order = np.empty((num_blocks, bp * cap_e), np.int32)
    seg_starts = np.empty((num_blocks, n), np.int32)
    seg_ends = np.empty((num_blocks, n), np.int32)
    slots = np.arange(n)
    for b in range(num_blocks):
        flat = edge_gidx[b * bp:(b + 1) * bp].reshape(-1)
        order[b] = np.argsort(flat, kind="stable").astype(np.int32)
        sorted_g = flat[order[b]]
        seg_starts[b] = np.searchsorted(sorted_g, slots, side="left")
        seg_ends[b] = np.searchsorted(sorted_g, slots, side="right")
    return order, seg_starts, seg_ends


def _edge_ok(edge_etype: jnp.ndarray, edge_valid: jnp.ndarray,
             req_types: jnp.ndarray) -> jnp.ndarray:
    """[P, cap_e] mask of edges matching the requested signed types."""
    m = (edge_etype[None, :, :] == req_types[:, None, None]).any(axis=0)
    return m & edge_valid


def _advance(frontier: jnp.ndarray, edge_src: jnp.ndarray,
             edge_ok: jnp.ndarray, order: jnp.ndarray,
             seg_starts: jnp.ndarray, seg_ends: jnp.ndarray) -> jnp.ndarray:
    """One BFS hop on stacked partitions (single device = one block).

    frontier: bool[P, cap_v] -> bool[P, cap_v]
    order/seg_starts/seg_ends: block 0 of build_segments(num_blocks=1),
    i.e. int32[P*cap_e] / int32[P*cap_v] / int32[P*cap_v].
    """
    P, cap_v = frontier.shape
    active = jnp.take_along_axis(frontier, edge_src, axis=1) & edge_ok
    # dst-sorted segmented count: static permute + cumsum + boundaries
    flat = active.reshape(-1)[order]
    S0 = jnp.pad(jnp.cumsum(flat.astype(jnp.int32)), (1, 0))
    counts = S0[seg_ends] - S0[seg_starts]
    return (counts > 0).reshape(P, cap_v)


@jax.jit
def multi_hop(frontier0: jnp.ndarray, steps: jnp.ndarray,
              edge_src: jnp.ndarray, edge_etype: jnp.ndarray,
              edge_valid: jnp.ndarray, order: jnp.ndarray,
              seg_starts: jnp.ndarray, seg_ends: jnp.ndarray,
              req_types: jnp.ndarray
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run `steps-1` frontier advances, then emit the final-step active
    edge mask (GO semantics: result = edges leaving the step-(N-1)
    frontier). `steps` is a traced scalar — one compile serves any N.

    -> (final_frontier bool[P, cap_v], final_active bool[P, cap_e]);
    the edge mask is in canonical edge order.
    """
    edge_ok = _edge_ok(edge_etype, edge_valid, req_types)

    def body(_, f):
        return _advance(f, edge_src, edge_ok, order,
                        seg_starts, seg_ends)

    frontier = lax.fori_loop(0, steps - 1, body, frontier0)
    final_active = jnp.take_along_axis(frontier, edge_src, axis=1) & edge_ok
    return frontier, final_active


@jax.jit
def multi_hop_upto(frontier0: jnp.ndarray, steps: jnp.ndarray,
                   edge_src: jnp.ndarray, edge_etype: jnp.ndarray,
                   edge_valid: jnp.ndarray, order: jnp.ndarray,
                   seg_starts: jnp.ndarray, seg_ends: jnp.ndarray,
                   req_types: jnp.ndarray) -> jnp.ndarray:
    """GO UPTO: union of active edge masks over steps 1..N.

    -> any_active bool[P, cap_e] in canonical edge order.
    """
    edge_ok = _edge_ok(edge_etype, edge_valid, req_types)

    def body(_, state):
        frontier, acc = state
        active = jnp.take_along_axis(frontier, edge_src, axis=1) & edge_ok
        return (_advance(frontier, edge_src, edge_ok, order, seg_starts,
                         seg_ends),
                acc | active)

    _, acc = lax.fori_loop(
        0, steps, body,
        (frontier0, jnp.zeros_like(edge_ok)))
    return acc


@jax.jit
def count_edges(final_active: jnp.ndarray) -> jnp.ndarray:
    return final_active.sum(dtype=jnp.int32)


@jax.jit
def bfs_dist(frontier0: jnp.ndarray, max_steps: jnp.ndarray,
             edge_src: jnp.ndarray, edge_etype: jnp.ndarray,
             edge_valid: jnp.ndarray, order: jnp.ndarray,
             seg_starts: jnp.ndarray, seg_ends: jnp.ndarray,
             req_types: jnp.ndarray) -> jnp.ndarray:
    """Single-source-set BFS depth map for shortest path: dist[p, v] =
    first step at which v was reached (0 for sources, -1 unreached).

    -> dist int32[P, cap_v]
    """
    edge_ok = _edge_ok(edge_etype, edge_valid, req_types)
    dist0 = jnp.where(frontier0, 0, -1).astype(jnp.int32)

    def cond(state):
        frontier, dist, step = state
        return (step < max_steps) & frontier.any()

    def body(state):
        frontier, dist, step = state
        nxt = _advance(frontier, edge_src, edge_ok, order, seg_starts,
                       seg_ends)
        fresh = nxt & (dist < 0)
        dist = jnp.where(fresh, step + 1, dist)
        return fresh, dist, step + 1

    _, dist, _ = lax.while_loop(cond, body, (frontier0, dist0,
                                             jnp.int32(0)))
    return dist


# ---------------------------------------------------------------------------
# multi-hop traversal with edge counting per hop (bench instrumentation)
# ---------------------------------------------------------------------------

@jax.jit
def multi_hop_count(frontier0: jnp.ndarray, steps: jnp.ndarray,
                    edge_src: jnp.ndarray, edge_etype: jnp.ndarray,
                    edge_valid: jnp.ndarray, order: jnp.ndarray,
                    seg_starts: jnp.ndarray, seg_ends: jnp.ndarray,
                    req_types: jnp.ndarray) -> jnp.ndarray:
    """Total edges traversed across ALL hops (the bench metric:
    edges-traversed/sec counts every hop's expansions, not just the
    final emission)."""
    edge_ok = _edge_ok(edge_etype, edge_valid, req_types)

    def body(_, state):
        frontier, total = state
        active = jnp.take_along_axis(frontier, edge_src, axis=1) & edge_ok
        # int64 accumulator: >2^31 edges per query is reachable on large
        # graphs (canonicalizes to int32 only when x64 is disabled)
        total = total + active.sum(dtype=jnp.int64)
        return (_advance(frontier, edge_src, edge_ok, order, seg_starts,
                         seg_ends),
                total)

    _, total = lax.fori_loop(0, steps, body,
                             (frontier0, jnp.zeros((), jnp.int64)))
    return total


@jax.jit
def multi_hop_count_batch(frontiers0: jnp.ndarray, steps: jnp.ndarray,
                          edge_src: jnp.ndarray, edge_etype: jnp.ndarray,
                          edge_valid: jnp.ndarray, order: jnp.ndarray,
                          seg_starts: jnp.ndarray, seg_ends: jnp.ndarray,
                          req_types: jnp.ndarray) -> jnp.ndarray:
    """Batch of independent GO queries in one dispatch: frontiers0 is
    bool[B, P, cap_v]; returns int32[B] per-query edges traversed.
    Amortizes per-dispatch overhead — the throughput path for QPS-style
    workloads (many concurrent sessions issuing GO)."""
    def one(f0):
        return multi_hop_count(f0, steps, edge_src, edge_etype, edge_valid,
                               order, seg_starts, seg_ends, req_types)
    return jax.vmap(one)(frontiers0)
