from .expressions import (  # noqa: F401
    Expression, Literal, FunctionCall, UnaryExpr, TypeCastExpr,
    ArithmeticExpr, RelationalExpr, LogicalExpr, SourcePropExpr,
    DestPropExpr, EdgePropExpr, EdgeSrcIdExpr, EdgeDstIdExpr,
    EdgeRankExpr, EdgeTypeExpr, InputPropExpr, VariablePropExpr,
    ExpressionContext, encode_expression, decode_expression, EvalError,
)
from .functions import FunctionManager  # noqa: F401
