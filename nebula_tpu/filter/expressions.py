"""Expression trees: parse-time AST, evaluation, and wire serialization.

Role parity with the reference's `common/filter/Expressions.{h,cpp}`:
16 expression kinds (ref: Expressions.h:329-344) covering literals,
function calls, unary/arithmetic/relational/logical ops, type casts,
and the nGQL property references:

    $^.tag.prop     source-vertex property        (SourcePropExpr)
    $$.tag.prop     destination-vertex property   (DestPropExpr)
    edge.prop       edge property / alias prop    (EdgePropExpr)
    _src _dst _rank _type   edge key fields       (EdgeSrcId/... exprs)
    $-.col          pipe-input column             (InputPropExpr)
    $var.col        stored-variable column        (VariablePropExpr)

Two capabilities matter architecturally and are kept from the reference:

1. **Serializability** (`encode_expression`/`decode_expression`): WHERE
   filters cross the graphd→storaged RPC boundary in encoded form so
   they can be evaluated storage-side ("filter pushdown", ref:
   storage.thrift:159 + storage/QueryBaseProcessor.inl:146-167).

2. **Pluggable getter context** (`ExpressionContext`): evaluation binds
   property references to whatever the host has — RPC row readers in
   the query engine, KV iterators in storage, columnar device arrays in
   the TPU engine (which *compiles* the tree to vectorized masks
   instead of evaluating per row; see engine_tpu/filter_compile.py).
   (ref: graph/GoExecutor.cpp:849-945, storage/QueryBaseProcessor
   .inl:415-443.)
"""
from __future__ import annotations

import struct
from typing import Any, Callable, List, Optional, Sequence

from ..common.status import ErrorCode, Status

Value = Any  # None | bool | int | float | str


class EvalError(Exception):
    def __init__(self, msg: str):
        super().__init__(msg)
        self.status = Status.error(ErrorCode.E_EXECUTION_ERROR, msg)


class ExpressionContext:
    """Getter closure bundle. Hosts override the getters they support."""

    def get_input_prop(self, prop: str) -> Value:
        raise EvalError(f"input prop $-.{prop} not available here")

    def get_variable_prop(self, var: str, prop: str) -> Value:
        raise EvalError(f"variable prop ${var}.{prop} not available here")

    def get_src_prop(self, tag: str, prop: str) -> Value:
        raise EvalError(f"source prop $^.{tag}.{prop} not available here")

    def get_dst_prop(self, tag: str, prop: str) -> Value:
        raise EvalError(f"dest prop $$.{tag}.{prop} not available here")

    def get_edge_prop(self, edge: Optional[str], prop: str) -> Value:
        raise EvalError(f"edge prop {edge}.{prop} not available here")

    def get_edge_src(self, edge: Optional[str]) -> Value:
        raise EvalError("_src not available here")

    def get_edge_dst(self, edge: Optional[str]) -> Value:
        raise EvalError("_dst not available here")

    def get_edge_rank(self, edge: Optional[str]) -> Value:
        raise EvalError("_rank not available here")

    def get_edge_type_name(self, edge: Optional[str]) -> Value:
        raise EvalError("_type not available here")


# ---------------------------------------------------------------------------
# AST nodes
# ---------------------------------------------------------------------------

class Expression:
    KIND = 0

    def eval(self, ctx: ExpressionContext) -> Value:
        raise NotImplementedError

    def to_string(self) -> str:
        raise NotImplementedError

    def children(self) -> Sequence["Expression"]:
        return ()

    def walk(self):
        yield self
        for c in self.children():
            yield from c.walk()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.to_string()}>"


class Literal(Expression):
    KIND = 1

    def __init__(self, value: Value):
        self.value = value

    def eval(self, ctx: ExpressionContext) -> Value:
        return self.value

    def to_string(self) -> str:
        v = self.value
        if v is None:
            return "NULL"
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, str):
            escaped = v.replace("\\", "\\\\").replace('"', '\\"')
            return f'"{escaped}"'
        return repr(v)


class FunctionCall(Expression):
    KIND = 2

    def __init__(self, name: str, args: List[Expression]):
        self.name = name.lower()
        self.args = args

    def eval(self, ctx: ExpressionContext) -> Value:
        from .functions import FunctionManager
        vals = [a.eval(ctx) for a in self.args]
        return FunctionManager.invoke(self.name, vals)

    def to_string(self) -> str:
        return f"{self.name}({', '.join(a.to_string() for a in self.args)})"

    def children(self):
        return self.args


class UnaryExpr(Expression):
    KIND = 3
    OPS = ("+", "-", "!")

    def __init__(self, op: str, operand: Expression):
        assert op in self.OPS
        self.op = op
        self.operand = operand

    def eval(self, ctx: ExpressionContext) -> Value:
        v = self.operand.eval(ctx)
        if self.op == "+":
            _require_num(v, "unary +")
            return v
        if self.op == "-":
            _require_num(v, "unary -")
            return -v
        return not _truthy(v)

    def to_string(self) -> str:
        return f"{self.op}({self.operand.to_string()})"

    def children(self):
        return (self.operand,)


class TypeCastExpr(Expression):
    KIND = 4
    TYPES = ("int", "double", "string", "bool")

    def __init__(self, type_name: str, operand: Expression):
        self.type_name = type_name.lower()
        self.operand = operand

    def eval(self, ctx: ExpressionContext) -> Value:
        v = self.operand.eval(ctx)
        try:
            if self.type_name == "int":
                return int(v)
            if self.type_name == "double":
                return float(v)
            if self.type_name == "string":
                if isinstance(v, bool):
                    return "true" if v else "false"
                return str(v)
            if self.type_name == "bool":
                return _truthy(v)
        except (TypeError, ValueError) as e:
            raise EvalError(f"bad cast to {self.type_name}: {e}")
        raise EvalError(f"unknown cast type {self.type_name}")

    def to_string(self) -> str:
        return f"({self.type_name}){self.operand.to_string()}"

    def children(self):
        return (self.operand,)


class ArithmeticExpr(Expression):
    KIND = 5
    OPS = ("+", "-", "*", "/", "%", "^")

    def __init__(self, op: str, left: Expression, right: Expression):
        assert op in self.OPS
        self.op = op
        self.left = left
        self.right = right

    def eval(self, ctx: ExpressionContext) -> Value:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        op = self.op
        if op == "+":
            if isinstance(l, str) or isinstance(r, str):
                # string concat coerces the other side, like the reference
                return _to_str(l) + _to_str(r)
            _require_num(l, "+"); _require_num(r, "+")
            return l + r
        _require_num(l, op); _require_num(r, op)
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            if r == 0:
                raise EvalError("division by zero")
            if isinstance(l, int) and isinstance(r, int):
                return int(l / r)  # C-style truncation, not floor
            return l / r
        if op == "%":
            if r == 0:
                raise EvalError("modulo by zero")
            if isinstance(l, int) and isinstance(r, int):
                return l - int(l / r) * r  # C-style remainder
            raise EvalError("% requires integers")
        if op == "^":
            return l ** r
        raise AssertionError(op)

    def to_string(self) -> str:
        return f"({self.left.to_string()}{self.op}{self.right.to_string()})"

    def children(self):
        return (self.left, self.right)


class RelationalExpr(Expression):
    KIND = 6
    OPS = ("==", "!=", "<", "<=", ">", ">=", "CONTAINS")

    def __init__(self, op: str, left: Expression, right: Expression):
        assert op in self.OPS
        self.op = op
        self.left = left
        self.right = right

    def eval(self, ctx: ExpressionContext) -> Value:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        op = self.op
        if op == "CONTAINS":
            if not isinstance(l, str) or not isinstance(r, str):
                raise EvalError("CONTAINS requires strings")
            return r in l
        if l is None or r is None:
            # NULL comparisons: only == and != are defined
            if op == "==":
                return l is None and r is None
            if op == "!=":
                return (l is None) != (r is None)
            return False
        num_l = isinstance(l, (int, float)) and not isinstance(l, bool)
        num_r = isinstance(r, (int, float)) and not isinstance(r, bool)
        if num_l != num_r or (isinstance(l, str) != isinstance(r, str)):
            if op == "==":
                return False
            if op == "!=":
                return True
            raise EvalError(f"incomparable operands for {op}: {l!r} vs {r!r}")
        if op == "==":
            return l == r
        if op == "!=":
            return l != r
        if op == "<":
            return l < r
        if op == "<=":
            return l <= r
        if op == ">":
            return l > r
        if op == ">=":
            return l >= r
        raise AssertionError(op)

    def to_string(self) -> str:
        return f"({self.left.to_string()}{self.op}{self.right.to_string()})"

    def children(self):
        return (self.left, self.right)


class LogicalExpr(Expression):
    KIND = 7
    OPS = ("&&", "||", "XOR")

    def __init__(self, op: str, left: Expression, right: Expression):
        assert op in self.OPS
        self.op = op
        self.left = left
        self.right = right

    def eval(self, ctx: ExpressionContext) -> Value:
        l = _truthy(self.left.eval(ctx))
        if self.op == "&&":
            return l and _truthy(self.right.eval(ctx))
        if self.op == "||":
            return l or _truthy(self.right.eval(ctx))
        return l != _truthy(self.right.eval(ctx))

    def to_string(self) -> str:
        return f"({self.left.to_string()}{self.op}{self.right.to_string()})"

    def children(self):
        return (self.left, self.right)


class SourcePropExpr(Expression):
    """$^.tag.prop"""
    KIND = 8

    def __init__(self, tag: str, prop: str):
        self.tag = tag
        self.prop = prop

    def eval(self, ctx: ExpressionContext) -> Value:
        return ctx.get_src_prop(self.tag, self.prop)

    def to_string(self) -> str:
        return f"$^.{self.tag}.{self.prop}"


class DestPropExpr(Expression):
    """$$.tag.prop"""
    KIND = 9

    def __init__(self, tag: str, prop: str):
        self.tag = tag
        self.prop = prop

    def eval(self, ctx: ExpressionContext) -> Value:
        return ctx.get_dst_prop(self.tag, self.prop)

    def to_string(self) -> str:
        return f"$$.{self.tag}.{self.prop}"


class EdgePropExpr(Expression):
    """edge.prop (edge may be None when only one edge type is in scope)."""
    KIND = 10

    def __init__(self, edge: Optional[str], prop: str):
        self.edge = edge
        self.prop = prop

    def eval(self, ctx: ExpressionContext) -> Value:
        return ctx.get_edge_prop(self.edge, self.prop)

    def to_string(self) -> str:
        return f"{self.edge}.{self.prop}" if self.edge else self.prop


class EdgeSrcIdExpr(Expression):
    KIND = 11

    def __init__(self, edge: Optional[str] = None):
        self.edge = edge

    def eval(self, ctx: ExpressionContext) -> Value:
        return ctx.get_edge_src(self.edge)

    def to_string(self) -> str:
        return f"{self.edge}._src" if self.edge else "_src"


class EdgeDstIdExpr(Expression):
    KIND = 12

    def __init__(self, edge: Optional[str] = None):
        self.edge = edge

    def eval(self, ctx: ExpressionContext) -> Value:
        return ctx.get_edge_dst(self.edge)

    def to_string(self) -> str:
        return f"{self.edge}._dst" if self.edge else "_dst"


class EdgeRankExpr(Expression):
    KIND = 13

    def __init__(self, edge: Optional[str] = None):
        self.edge = edge

    def eval(self, ctx: ExpressionContext) -> Value:
        return ctx.get_edge_rank(self.edge)

    def to_string(self) -> str:
        return f"{self.edge}._rank" if self.edge else "_rank"


class EdgeTypeExpr(Expression):
    KIND = 14

    def __init__(self, edge: Optional[str] = None):
        self.edge = edge

    def eval(self, ctx: ExpressionContext) -> Value:
        return ctx.get_edge_type_name(self.edge)

    def to_string(self) -> str:
        return f"{self.edge}._type" if self.edge else "_type"


class InputPropExpr(Expression):
    """$-.col"""
    KIND = 15

    def __init__(self, prop: str):
        self.prop = prop

    def eval(self, ctx: ExpressionContext) -> Value:
        return ctx.get_input_prop(self.prop)

    def to_string(self) -> str:
        return f"$-.{self.prop}"


class VariablePropExpr(Expression):
    """$var.col"""
    KIND = 16

    def __init__(self, var: str, prop: str):
        self.var = var
        self.prop = prop

    def eval(self, ctx: ExpressionContext) -> Value:
        return ctx.get_variable_prop(self.var, self.prop)

    def to_string(self) -> str:
        return f"${self.var}.{self.prop}"


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _truthy(v: Value) -> bool:
    if isinstance(v, bool):
        return v
    if v is None:
        return False
    if isinstance(v, (int, float)):
        return v != 0
    raise EvalError(f"value {v!r} is not a boolean")


def _require_num(v: Value, op: str) -> None:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise EvalError(f"operator {op} requires a numeric operand, got {v!r}")


def _to_str(v: Value) -> str:
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "NULL"
    return str(v)


# ---------------------------------------------------------------------------
# wire serialization (filter pushdown across the storage RPC boundary)
# ---------------------------------------------------------------------------

_VT_NULL, _VT_BOOL, _VT_INT, _VT_DOUBLE, _VT_STR = 0, 1, 2, 3, 4


def _enc_value(buf: bytearray, v: Value) -> None:
    if v is None:
        buf.append(_VT_NULL)
    elif isinstance(v, bool):
        buf.append(_VT_BOOL)
        buf.append(1 if v else 0)
    elif isinstance(v, int):
        buf.append(_VT_INT)
        buf += struct.pack("<q", v)
    elif isinstance(v, float):
        buf.append(_VT_DOUBLE)
        buf += struct.pack("<d", v)
    elif isinstance(v, str):
        b = v.encode("utf-8")
        buf.append(_VT_STR)
        buf += struct.pack("<I", len(b)) + b
    else:
        raise ValueError(f"cannot encode value {v!r}")


def _dec_value(data: bytes, off: int):
    t = data[off]
    off += 1
    if t == _VT_NULL:
        return None, off
    if t == _VT_BOOL:
        return data[off] != 0, off + 1
    if t == _VT_INT:
        return struct.unpack_from("<q", data, off)[0], off + 8
    if t == _VT_DOUBLE:
        return struct.unpack_from("<d", data, off)[0], off + 8
    if t == _VT_STR:
        n = struct.unpack_from("<I", data, off)[0]
        off += 4
        return data[off:off + n].decode("utf-8"), off + n
    raise ValueError(f"bad value tag {t}")


def _enc_str(buf: bytearray, s: Optional[str]) -> None:
    if s is None:
        buf += struct.pack("<I", 0xFFFFFFFF)
    else:
        b = s.encode("utf-8")
        buf += struct.pack("<I", len(b)) + b


def _dec_str(data: bytes, off: int):
    n = struct.unpack_from("<I", data, off)[0]
    off += 4
    if n == 0xFFFFFFFF:
        return None, off
    return data[off:off + n].decode("utf-8"), off + n


def _encode_into(buf: bytearray, e: Expression) -> None:
    buf.append(e.KIND)
    if isinstance(e, Literal):
        _enc_value(buf, e.value)
    elif isinstance(e, FunctionCall):
        _enc_str(buf, e.name)
        buf.append(len(e.args))
        for a in e.args:
            _encode_into(buf, a)
    elif isinstance(e, UnaryExpr):
        _enc_str(buf, e.op)
        _encode_into(buf, e.operand)
    elif isinstance(e, TypeCastExpr):
        _enc_str(buf, e.type_name)
        _encode_into(buf, e.operand)
    elif isinstance(e, (ArithmeticExpr, RelationalExpr, LogicalExpr)):
        _enc_str(buf, e.op)
        _encode_into(buf, e.left)
        _encode_into(buf, e.right)
    elif isinstance(e, (SourcePropExpr, DestPropExpr)):
        _enc_str(buf, e.tag)
        _enc_str(buf, e.prop)
    elif isinstance(e, EdgePropExpr):
        _enc_str(buf, e.edge)
        _enc_str(buf, e.prop)
    elif isinstance(e, (EdgeSrcIdExpr, EdgeDstIdExpr, EdgeRankExpr, EdgeTypeExpr)):
        _enc_str(buf, e.edge)
    elif isinstance(e, InputPropExpr):
        _enc_str(buf, e.prop)
    elif isinstance(e, VariablePropExpr):
        _enc_str(buf, e.var)
        _enc_str(buf, e.prop)
    else:
        raise ValueError(f"cannot encode {type(e).__name__}")


def encode_expression(e: Expression) -> bytes:
    buf = bytearray()
    _encode_into(buf, e)
    return bytes(buf)


def _decode_from(data: bytes, off: int):
    kind = data[off]
    off += 1
    if kind == Literal.KIND:
        v, off = _dec_value(data, off)
        return Literal(v), off
    if kind == FunctionCall.KIND:
        name, off = _dec_str(data, off)
        n = data[off]
        off += 1
        args = []
        for _ in range(n):
            a, off = _decode_from(data, off)
            args.append(a)
        return FunctionCall(name, args), off
    if kind == UnaryExpr.KIND:
        op, off = _dec_str(data, off)
        o, off = _decode_from(data, off)
        return UnaryExpr(op, o), off
    if kind == TypeCastExpr.KIND:
        t, off = _dec_str(data, off)
        o, off = _decode_from(data, off)
        return TypeCastExpr(t, o), off
    if kind in (ArithmeticExpr.KIND, RelationalExpr.KIND, LogicalExpr.KIND):
        op, off = _dec_str(data, off)
        l, off = _decode_from(data, off)
        r, off = _decode_from(data, off)
        cls = {ArithmeticExpr.KIND: ArithmeticExpr,
               RelationalExpr.KIND: RelationalExpr,
               LogicalExpr.KIND: LogicalExpr}[kind]
        return cls(op, l, r), off
    if kind in (SourcePropExpr.KIND, DestPropExpr.KIND):
        tag, off = _dec_str(data, off)
        prop, off = _dec_str(data, off)
        cls = SourcePropExpr if kind == SourcePropExpr.KIND else DestPropExpr
        return cls(tag, prop), off
    if kind == EdgePropExpr.KIND:
        edge, off = _dec_str(data, off)
        prop, off = _dec_str(data, off)
        return EdgePropExpr(edge, prop), off
    if kind in (EdgeSrcIdExpr.KIND, EdgeDstIdExpr.KIND, EdgeRankExpr.KIND, EdgeTypeExpr.KIND):
        edge, off = _dec_str(data, off)
        cls = {EdgeSrcIdExpr.KIND: EdgeSrcIdExpr, EdgeDstIdExpr.KIND: EdgeDstIdExpr,
               EdgeRankExpr.KIND: EdgeRankExpr, EdgeTypeExpr.KIND: EdgeTypeExpr}[kind]
        return cls(edge), off
    if kind == InputPropExpr.KIND:
        prop, off = _dec_str(data, off)
        return InputPropExpr(prop), off
    if kind == VariablePropExpr.KIND:
        var, off = _dec_str(data, off)
        prop, off = _dec_str(data, off)
        return VariablePropExpr(var, prop), off
    raise ValueError(f"bad expression kind {kind}")


def decode_expression(data: bytes) -> Expression:
    e, off = _decode_from(data, 0)
    if off != len(data):
        raise ValueError("trailing bytes after expression")
    return e
