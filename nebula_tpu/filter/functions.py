"""Built-in function registry for nGQL expressions.

Role parity with the reference's `common/filter/FunctionManager.cpp:23-440`
(~35 built-ins: math, rand, now, string functions, hash, udf_is_in).
Arity is validated at lookup time like the reference's minArity/maxArity.
"""
from __future__ import annotations

import math
import random
import time
from typing import Any, Callable, Dict, List, Tuple

from .expressions import EvalError


class _Fn:
    __slots__ = ("fn", "min_arity", "max_arity")

    def __init__(self, fn: Callable, min_arity: int, max_arity: int):
        self.fn = fn
        self.min_arity = min_arity
        self.max_arity = max_arity


def _num(v, name):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise EvalError(f"{name}() requires numeric argument, got {v!r}")
    return v


def _s(v, name):
    if not isinstance(v, str):
        raise EvalError(f"{name}() requires string argument, got {v!r}")
    return v


def _fnv1a64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    # present as signed int64, like the reference's int64 hash
    return h - (1 << 64) if h >= (1 << 63) else h


class FunctionManager:
    _registry: Dict[str, _Fn] = {}

    @classmethod
    def register(cls, name: str, min_arity: int, max_arity: int = None):
        if max_arity is None:
            max_arity = min_arity

        def deco(fn):
            cls._registry[name] = _Fn(fn, min_arity, max_arity)
            return fn
        return deco

    @classmethod
    def exists(cls, name: str) -> bool:
        return name.lower() in cls._registry

    @classmethod
    def invoke(cls, name: str, args: List[Any]) -> Any:
        f = cls._registry.get(name.lower())
        if f is None:
            raise EvalError(f"unknown function {name}()")
        if not (f.min_arity <= len(args) <= f.max_arity):
            raise EvalError(
                f"{name}() takes {f.min_arity}"
                + (f"..{f.max_arity}" if f.max_arity != f.min_arity else "")
                + f" args, got {len(args)}")
        return f.fn(*args)

    @classmethod
    def names(cls) -> List[str]:
        return sorted(cls._registry)


_reg = FunctionManager.register

# --- math ------------------------------------------------------------------
_reg("abs", 1)(lambda x: abs(_num(x, "abs")))
_reg("floor", 1)(lambda x: float(math.floor(_num(x, "floor"))))
_reg("ceil", 1)(lambda x: float(math.ceil(_num(x, "ceil"))))
_reg("round", 1)(lambda x: float(round(_num(x, "round"))))
_reg("sqrt", 1)(lambda x: math.sqrt(_num(x, "sqrt")))
_reg("cbrt", 1)(lambda x: math.copysign(abs(_num(x, "cbrt")) ** (1 / 3), x))
_reg("hypot", 2)(lambda x, y: math.hypot(_num(x, "hypot"), _num(y, "hypot")))
_reg("pow", 2)(lambda x, y: _num(x, "pow") ** _num(y, "pow"))
_reg("exp", 1)(lambda x: math.exp(_num(x, "exp")))
_reg("exp2", 1)(lambda x: 2.0 ** _num(x, "exp2"))
_reg("log", 1)(lambda x: math.log(_num(x, "log")))
_reg("log2", 1)(lambda x: math.log2(_num(x, "log2")))
_reg("log10", 1)(lambda x: math.log10(_num(x, "log10")))
_reg("sin", 1)(lambda x: math.sin(_num(x, "sin")))
_reg("asin", 1)(lambda x: math.asin(_num(x, "asin")))
_reg("cos", 1)(lambda x: math.cos(_num(x, "cos")))
_reg("acos", 1)(lambda x: math.acos(_num(x, "acos")))
_reg("tan", 1)(lambda x: math.tan(_num(x, "tan")))
_reg("atan", 1)(lambda x: math.atan(_num(x, "atan")))

# --- rand / time -----------------------------------------------------------
_reg("rand32", 0, 2)(lambda *a: (
    random.randrange(0, 1 << 32) if len(a) == 0 else
    random.randrange(0, int(a[0])) if len(a) == 1 else
    random.randrange(int(a[0]), int(a[1]))))
_reg("rand64", 0, 2)(lambda *a: (
    random.randrange(0, 1 << 63) if len(a) == 0 else
    random.randrange(0, int(a[0])) if len(a) == 1 else
    random.randrange(int(a[0]), int(a[1]))))
_reg("now", 0)(lambda: int(time.time()))

# --- strings ---------------------------------------------------------------
_reg("strcasecmp", 2)(lambda a, b: (
    (lambda x, y: (x > y) - (x < y))(_s(a, "strcasecmp").lower(), _s(b, "strcasecmp").lower())))
_reg("lower", 1)(lambda v: _s(v, "lower").lower())
_reg("upper", 1)(lambda v: _s(v, "upper").upper())
_reg("length", 1)(lambda v: len(_s(v, "length")))
_reg("trim", 1)(lambda v: _s(v, "trim").strip())
_reg("ltrim", 1)(lambda v: _s(v, "ltrim").lstrip())
_reg("rtrim", 1)(lambda v: _s(v, "rtrim").rstrip())
_reg("left", 2)(lambda v, n: _s(v, "left")[:max(0, int(n))])
_reg("right", 2)(lambda v, n: _s(v, "right")[len(_s(v, "right")) - max(0, int(n)):] if int(n) > 0 else "")
_reg("substr", 3)(lambda v, p, n: _s(v, "substr")[max(0, int(p)):max(0, int(p)) + max(0, int(n))])


def _pad(v, size, pad, left, name):
    v, pad, size = _s(v, name), _s(pad, name), max(0, int(size))
    if size <= len(v):
        return v[:size]
    if not pad:
        return v
    fill = (pad * ((size - len(v)) // len(pad) + 1))[: size - len(v)]
    return fill + v if left else v + fill


_reg("lpad", 3)(lambda v, n, p: _pad(v, n, p, True, "lpad"))
_reg("rpad", 3)(lambda v, n, p: _pad(v, n, p, False, "rpad"))

# --- misc ------------------------------------------------------------------
_reg("hash", 1)(lambda v: _fnv1a64(
    v.encode("utf-8") if isinstance(v, str)
    else str(v).encode("utf-8")))
_reg("udf_is_in", 2, 255)(lambda v, *candidates: v in candidates)
