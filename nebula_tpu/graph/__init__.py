from .engine import ExecutionEngine, ExecutionResponse  # noqa: F401
from .session import SessionManager, ClientSession  # noqa: F401
from .interim import InterimResult  # noqa: F401
