"""Admin / DDL / RBAC executors.

Role parity with the reference's DDL+admin executor family
(CreateSpace/DropSpace/DescribeSpace, Create/Alter/Drop/Describe
Tag/Edge, ShowExecutor, ConfigExecutor, BalanceExecutor, UseExecutor,
user management executors) — thin translations from AST to MetaService
calls plus table formatting (ref: SURVEY.md §2.1 DDL/admin row).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..common.status import ErrorCode, Status, StatusOr
from ..parser import ast
from .context import ExecContext
from .executors import Result, _err, _ok
from .interim import InterimResult

_TYPE_NAMES = {1: "bool", 2: "int", 3: "vid", 5: "double", 6: "string",
               7: "timestamp"}


def execute_use(ctx: ExecContext, s: ast.UseSentence) -> Result:
    r = ctx.meta.get_space(s.space)
    if not r.ok():
        return StatusOr.from_status(r.status)
    ctx.session.space_name = s.space
    ctx.session.space_id = r.value().space_id
    # USE is the earliest signal a space is about to be queried: build
    # its device snapshot + compile the traversal kernels in the
    # background so the first big GO doesn't pay the XLA compile
    tpu = getattr(ctx.engine, "tpu_engine", None)
    if tpu is not None:
        tpu.prewarm(r.value().space_id)
    return _ok()


def execute_create_space(ctx: ExecContext, s: ast.CreateSpaceSentence) -> Result:
    r = ctx.meta.create_space(s.name, s.partition_num, s.replica_factor,
                              s.if_not_exists)
    if not r.ok():
        return StatusOr.from_status(r.status)
    return _ok()


def execute_drop_space(ctx: ExecContext, s: ast.DropSpaceSentence) -> Result:
    st = ctx.meta.drop_space(s.name, s.if_exists)
    if not st.ok():
        return StatusOr.from_status(st)
    if ctx.session.space_name == s.name:
        ctx.session.space_name = None
        ctx.session.space_id = -1
    return _ok()


def execute_describe_space(ctx: ExecContext, s: ast.DescribeSpaceSentence) -> Result:
    r = ctx.meta.get_space(s.name)
    if not r.ok():
        return StatusOr.from_status(r.status)
    d = r.value()
    return _ok(InterimResult(
        ["ID", "Name", "Partition number", "Replica Factor"],
        [(d.space_id, d.name, d.partition_num, d.replica_factor)]))


def _columns_from_ast(cols: List[ast.ColumnDef]) -> List[dict]:
    return [{"name": c.name, "type": c.type_name, "default": c.default}
            for c in cols]


def execute_create_schema(ctx: ExecContext, s: ast.CreateSchemaSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    fn = ctx.meta.create_edge if s.is_edge else ctx.meta.create_tag
    r = fn(ctx.space_id(), s.name, _columns_from_ast(s.columns),
           ttl_col=s.opts.ttl_col, ttl_duration=s.opts.ttl_duration or 0,
           if_not_exists=s.if_not_exists)
    if not r.ok():
        return StatusOr.from_status(r.status)
    return _ok()


def execute_alter_schema(ctx: ExecContext, s: ast.AlterSchemaSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    fn = ctx.meta.alter_edge if s.is_edge else ctx.meta.alter_tag
    st = fn(ctx.space_id(), s.name,
            adds=_columns_from_ast(s.adds),
            changes=_columns_from_ast(s.changes),
            drops=list(s.drops),
            ttl_col=s.opts.ttl_col, ttl_duration=s.opts.ttl_duration)
    if not st.ok():
        return StatusOr.from_status(st)
    return _ok()


def execute_drop_schema(ctx: ExecContext, s: ast.DropSchemaSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    fn = ctx.meta.drop_edge if s.is_edge else ctx.meta.drop_tag
    st = fn(ctx.space_id(), s.name, s.if_exists)
    if not st.ok():
        return StatusOr.from_status(st)
    return _ok()


def execute_create_index(ctx: ExecContext,
                         s: ast.CreateIndexSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    r = ctx.meta.create_index(ctx.space_id(), s.name, s.is_edge,
                              s.schema_name, s.fields, s.if_not_exists)
    if not r.ok():
        return StatusOr.from_status(r.status)
    return _ok()


def execute_drop_index(ctx: ExecContext, s: ast.DropIndexSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    st = ctx.meta.drop_index(ctx.space_id(), s.name, s.if_exists)
    if not st.ok():
        return StatusOr.from_status(st)
    return _ok()


def execute_describe_schema(ctx: ExecContext, s: ast.DescribeSchemaSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    sid = (ctx.sm.edge_type if s.is_edge else ctx.sm.tag_id)(space, s.name)
    if sid is None:
        return _err(ErrorCode.E_EDGE_NOT_FOUND if s.is_edge
                    else ErrorCode.E_TAG_NOT_FOUND, s.name)
    sr = (ctx.sm.edge_schema if s.is_edge else ctx.sm.tag_schema)(space, sid)
    if not sr.ok():
        return StatusOr.from_status(sr.status)
    schema = sr.value()
    rows = [(f.name, _TYPE_NAMES.get(int(f.type), str(int(f.type))),
             "YES" if f.nullable else "NO",
             f.default if f.default is not None else "")
            for f in schema.fields]
    return _ok(InterimResult(["Field", "Type", "Null", "Default"], rows))


def execute_show_create(ctx: ExecContext,
                        s: ast.ShowCreateSentence) -> Result:
    """SHOW CREATE SPACE|TAG|EDGE — render the DDL that would recreate
    the object (ref SchemaTest.cpp:101-110, :238-250 output shapes)."""
    if s.what == "SPACE":
        r = ctx.meta.get_space(s.name)
        if not r.ok():
            return StatusOr.from_status(r.status)
        d = r.value()
        ddl = (f"CREATE SPACE {d.name} (partition_num = "
               f"{d.partition_num}, replica_factor = {d.replica_factor})")
        return _ok(InterimResult(["Space", "Create Space"],
                                 [(d.name, ddl)]))
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    is_edge = s.what == "EDGE"
    sid = (ctx.sm.edge_type if is_edge else ctx.sm.tag_id)(space, s.name)
    if sid is None:
        return _err(ErrorCode.E_EDGE_NOT_FOUND if is_edge
                    else ErrorCode.E_TAG_NOT_FOUND, s.name)
    sch = (ctx.sm.edge_schema if is_edge else ctx.sm.tag_schema)(
        space, sid).value()
    cols = []
    for f in sch.fields:
        col = f"  {f.name} {f.type.name.lower()}"
        if f.default is not None:
            col += f" default {f.default!r}" if isinstance(f.default, str) \
                else f" default {f.default}"
        cols.append(col)
    ddl = (f"CREATE {s.what} {s.name} (\n" + ",\n".join(cols) + "\n) "
           f"ttl_duration = {sch.ttl_duration or 0}, "
           f"ttl_col = \"{sch.ttl_col or ''}\"")
    return _ok(InterimResult([s.what.title(), f"Create {s.what.title()}"],
                             [(s.name, ddl)]))


def execute_show(ctx: ExecContext, s: ast.ShowSentence) -> Result:
    k = s.what
    if k == ast.ShowKind.SPACES:
        return _ok(InterimResult(["Name"],
                                 [(d.name,) for d in ctx.meta.list_spaces()]))
    if k in (ast.ShowKind.TAGS, ast.ShowKind.EDGES):
        st = ctx.require_space()
        if not st.ok():
            return StatusOr.from_status(st)
        items = (ctx.meta.list_edges if k == ast.ShowKind.EDGES
                 else ctx.meta.list_tags)(ctx.space_id())
        return _ok(InterimResult(["ID", "Name"],
                                 [(i, n) for n, i in sorted(items)]))
    if k == ast.ShowKind.HOSTS:
        # leader/partition distribution columns from the heartbeat-fed
        # leader view (ref ListHostsProcessor output shape); falls back
        # to the two-column form against a meta without the overview
        def _dist(d):
            return ", ".join(f"{n}: {c}" for n, c in sorted(d.items())) \
                or "No valid partition"
        try:
            overview = ctx.meta.hosts_overview()
        except Exception:
            overview = None
        if overview is None:
            rows = [(info.host, "online" if alive else "offline")
                    for info, alive in ctx.meta.all_hosts()]
            return _ok(InterimResult(["Ip:Port", "Status"], rows))
        rows = [(h["host"], h["status"], h["leader_count"],
                 _dist(h["leader_dist"]), _dist(h["part_dist"]),
                 h.get("leader_heat", 0.0))
                for h in overview]
        return _ok(InterimResult(
            ["Ip:Port", "Status", "Leader count", "Leader distribution",
             "Partition distribution", "Leader heat"], rows))
    if k == ast.ShowKind.PARTS:
        st = ctx.require_space()
        if not st.ok():
            return StatusOr.from_status(st)
        try:
            parts = ctx.meta.parts_overview(ctx.space_id())
            rows = []
            for row in parts:
                # [part, leader, hosts, losts] pre-ISSUE-14 metas;
                # [+ heat, staleness_ms] since the heat view landed
                pid, leader, hosts, losts = row[:4]
                heat_score = row[4] if len(row) > 4 else 0.0
                stale_ms = row[5] if len(row) > 5 else 0.0
                rows.append((pid, leader, ", ".join(hosts),
                             ", ".join(losts), heat_score, stale_ms))
            return _ok(InterimResult(
                ["Partition ID", "Leader", "Peers", "Losts", "Heat",
                 "Staleness ms"], rows))
        except Exception:
            alloc = ctx.meta.get_parts_alloc(ctx.space_id())
            rows = [(pid, ", ".join(hosts))
                    for pid, hosts in sorted(alloc.items())]
            return _ok(InterimResult(["Partition ID", "Peers"], rows))
    if k in (ast.ShowKind.TAG_INDEXES, ast.ShowKind.EDGE_INDEXES):
        st = ctx.require_space()
        if not st.ok():
            return StatusOr.from_status(st)
        want_edge = k == ast.ShowKind.EDGE_INDEXES
        rows = [(d["index_id"], d["name"], d["schema_name"],
                 ", ".join(d["fields"]))
                for d in sorted(ctx.meta.list_indexes(ctx.space_id()),
                                key=lambda d: d["index_id"])
                if bool(d.get("is_edge")) == want_edge]
        return _ok(InterimResult(
            ["Index ID", "Index Name", "Schema Name", "Fields"], rows))
    if k == ast.ShowKind.USERS:
        return _ok(InterimResult(["User"],
                                 [(u,) for u in ctx.meta.list_users()]))
    if k == ast.ShowKind.ROLES:
        r = ctx.meta.get_space(s.arg)
        if not r.ok():
            return StatusOr.from_status(r.status)
        return _ok(InterimResult(["User", "Role"],
                                 ctx.meta.list_roles(r.value().space_id)))
    if k == ast.ShowKind.SNAPSHOTS:
        return _ok(InterimResult(["Name", "Status"],
                                 ctx.meta.list_snapshots()))
    if k == ast.ShowKind.VARIABLES:
        rows = [(name, repr(res.columns)) for name, res in ctx.variables.items()]
        return _ok(InterimResult(["Variable", "Columns"], rows))
    if k == ast.ShowKind.CONSISTENCY:
        return _show_consistency(ctx)
    return _err(ErrorCode.E_UNSUPPORTED, f"SHOW {k.value}")


def _fetch_consistency_endpoints(endpoints, timeout: float = 2.0):
    """[(endpoint, /consistency JSON | None)] fetched CONCURRENTLY —
    shared by SHOW CONSISTENCY and graphd's /consistency federation
    (the /cluster_metrics fan-out idiom: one slow/dead target costs
    one timeout for the whole round, not one per target)."""
    import json as _json
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    def fetch(ep):
        try:
            with urllib.request.urlopen(
                    f"http://{ep['web']}/consistency",
                    timeout=timeout) as r:
                return _json.loads(r.read())
        except Exception:
            return None

    if not endpoints:
        return []
    with ThreadPoolExecutor(max_workers=min(len(endpoints), 16)) \
            as pool:
        docs = list(pool.map(fetch, endpoints))
    return list(zip(endpoints, docs))


def _show_consistency(ctx: ExecContext) -> Result:
    """SHOW CONSISTENCY (docs/manual/10-observability.md, "Consistency
    observatory"): cluster-wide per-part content-digest state. In a
    deployed cluster the rows federate from every registered
    storaged's /consistency endpoint (the /cluster_metrics target
    registry); in a single-process deployment they come from the local
    store's parts. Leaders expand one row per replica with the
    leader-side digest verdict."""
    from ..common import consistency as _cons
    columns = ["Host", "Space", "Part", "Role", "Anchor term",
               "Anchor id", "Digest", "Replica", "Match", "Applied",
               "Digest ok"]
    rows = []

    def part_rows(host, p):
        dig = p.get("digest") or {}
        if isinstance(dig, dict):
            aterm, aid, dhex = (dig.get("anchor_term"),
                                dig.get("anchor_id"), dig.get("digest"))
        else:
            aterm = p.get("anchor_term")
            aid = p.get("anchor_id")
            dhex = p.get("digest")
        reps = p.get("replicas") or []
        base = (host, p["space"], p["part"], p.get("role", "?"),
                aterm, aid, dhex)
        if not reps:
            rows.append(base + ("-", "-", "-", "-"))
            return
        for m in reps:
            ok = m.get("digest_ok")
            rows.append(base + (
                m.get("addr", "?"), m.get("match"), m.get("applied"),
                "?" if ok is None else ("ok" if ok else "DIVERGED")))

    endpoints = []
    try:
        endpoints = [ep for ep in ctx.meta.web_endpoints()
                     if ep.get("role") == "storage"]
    except Exception:
        endpoints = []
    if endpoints:
        # concurrent fan-out (the /cluster_metrics idiom): several
        # dead/slow storagds must cost ONE timeout for the whole
        # statement, not one each — this runs on a user session
        for ep, doc in _fetch_consistency_endpoints(endpoints):
            if doc is None:
                rows.append((ep["web"], "-", "-", "UNREACHABLE",
                             None, None, None, "-", "-", "-", "-"))
                continue
            for p in doc.get("parts") or []:
                part_rows(doc.get("addr") or ep["web"], p)
    else:
        # single-process deployment: walk the local store directly
        svc = getattr(ctx.client, "_hosts", {}).get("local")
        store = getattr(svc, "store", None)
        if store is not None:
            for p in _cons.store_rows(store):
                part_rows("local", p)
    return _ok(InterimResult(columns, rows))


def execute_config(ctx: ExecContext, s: ast.ConfigSentence) -> Result:
    if s.action == "SHOW":
        rows = [(mn.split(":")[0], mn.split(":")[1], str(v), mode)
                for mn, v, mode in ctx.meta.list_configs(s.module)]
        return _ok(InterimResult(["module", "name", "value", "mode"], rows))
    if s.action == "GET":
        r = ctx.meta.get_config(s.module or "GRAPH", s.name)
        if not r.ok():
            return StatusOr.from_status(r.status)
        return _ok(InterimResult(["name", "value"], [(s.name, str(r.value()))]))
    if s.action == "SET":
        from .expr_context import RowExprContext
        try:
            val = s.value.eval(RowExprContext())
        except Exception as e:
            return _err(ErrorCode.E_INVALID_ARGUMENT, str(e))
        st = ctx.meta.set_config(s.module or "GRAPH", s.name, val)
        if not st.ok():
            return StatusOr.from_status(st)
        return _ok()
    return _err(ErrorCode.E_UNSUPPORTED, s.action)


class _MetaBalancerProxy:
    """BALANCE in a deployed cluster: graphd holds no balancer — the
    statement forwards to the metad-hosted one over the meta RPC
    surface (ref: BalanceProcessor)."""

    def __init__(self, meta):
        self._meta = meta

    def leader_balance(self):
        return self._meta.balance_leader()

    def balance(self, remove_hosts=()):
        return self._meta.balance_data(list(remove_hosts))

    def show_plan(self, plan_id=None):
        return self._meta.balance_show(plan_id)

    def advise_heat(self):
        return self._meta.balance_advise_heat()

    def stop(self):
        return self._meta.balance_stop()


def execute_balance(ctx: ExecContext, s: ast.BalanceSentence) -> Result:
    balancer = getattr(ctx.engine, "balancer", None)
    if balancer is None:
        balancer = _MetaBalancerProxy(ctx.meta)
    if s.sub == "LEADER":
        st = balancer.leader_balance()
        if not st.ok():
            return StatusOr.from_status(st)
        return _ok()
    if s.sub == "DATA":
        r = balancer.balance(remove_hosts=s.remove_hosts)
        if not r.ok():
            return StatusOr.from_status(r.status)
        return _ok(InterimResult(["ID"], [(r.value(),)]))
    if s.sub == "SHOW":
        rows = balancer.show_plan(s.plan_id)
        return _ok(InterimResult(
            ["plan", "space", "part", "src", "dst", "status"], rows))
    if s.sub == "HEAT":
        # heat-aware ADVISORY plan (docs/manual/12-replication.md):
        # per-host current vs modeled heat, the proposed moves, and
        # the spread delta — nothing is executed
        if hasattr(balancer, "advise_heat"):
            r = balancer.advise_heat()
        else:
            r = _MetaBalancerProxy(ctx.meta).advise_heat()
        if hasattr(r, "ok"):
            if not r.ok():
                return StatusOr.from_status(r.status)
            plan = r.value()
        else:
            plan = r
        rows = [("host", h, plan["current"].get(h, 0.0),
                 plan["planned"].get(h, 0.0))
                for h in plan.get("hosts", [])]
        rows += [("move", f"s{m['space']} p{m['part']} "
                  f"{m['src']} -> {m['dst']} ({m['kind']})",
                  m["score"], None)
                 for m in plan.get("moves", [])]
        rows.append(("spread", "max-min per-host heat",
                     plan.get("spread_before", 0.0),
                     plan.get("spread_after", 0.0)))
        return _ok(InterimResult(
            ["Kind", "Detail", "Heat", "Planned"], rows))
    if s.sub == "STOP":
        st = balancer.stop()
        if not st.ok():
            return StatusOr.from_status(st)
        return _ok()
    return _err(ErrorCode.E_UNSUPPORTED, s.sub)


# --- users (ref: graph user executors + meta usersMan) ---------------------

def execute_create_user(ctx: ExecContext, s: ast.CreateUserSentence) -> Result:
    st = ctx.meta.create_user(s.user, s.password, s.if_not_exists)
    return _ok() if st.ok() else StatusOr.from_status(st)


def execute_drop_user(ctx: ExecContext, s: ast.DropUserSentence) -> Result:
    st = ctx.meta.drop_user(s.user, s.if_exists)
    return _ok() if st.ok() else StatusOr.from_status(st)


def execute_change_password(ctx: ExecContext, s: ast.ChangePasswordSentence) -> Result:
    caller = ctx.session.user
    if s.old_password is None and caller != "root":
        # ALTER USER (no old password) is a GOD-only account takeover path
        return _err(ErrorCode.E_BAD_PERMISSION,
                    "ALTER USER requires GOD; use CHANGE PASSWORD ... FROM ... TO ...")
    st = ctx.meta.change_password(s.user, s.new_password, s.old_password)
    return _ok() if st.ok() else StatusOr.from_status(st)


_ROLE_RANK = {"GOD": 4, "ADMIN": 3, "USER": 2, "GUEST": 1}


def execute_download(ctx: ExecContext, s: ast.DownloadSentence) -> Result:
    """DOWNLOAD HDFS "url" — stage bulk-load SSTs for the current space
    (ref: meta /download-dispatch → storaged /download per part)."""
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    st = ctx.client.download(ctx.space_id(), s.url)
    if not st.ok():
        return StatusOr.from_status(st)
    return _ok()


def execute_ingest(ctx: ExecContext, s: ast.IngestSentence) -> Result:
    """INGEST — load staged SSTs into the current space (ref:
    IngestExecutor → storaged /ingest → engine ingest)."""
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    st, n = ctx.client.ingest(ctx.space_id())
    if not st.ok():
        return StatusOr.from_status(st)
    if n == 0:
        return _err(ErrorCode.E_EXECUTION_ERROR,
                    "no staged part files found on any storage host "
                    "(run DOWNLOAD first)")
    return _ok(InterimResult(["Ingested"], [(n,)]))


def _snapshot_name(suffix: int = 0) -> str:
    import time
    base = time.strftime("SNAPSHOT_%Y_%m_%d_%H_%M_%S")
    return base if suffix == 0 else f"{base}_{suffix}"


def execute_create_snapshot(ctx: ExecContext,
                            s: ast.CreateSnapshotSentence) -> Result:
    """CREATE SNAPSHOT — meta records the snapshot, every storage host
    dumps a checkpoint, then the record flips INVALID→VALID (crash
    between the two leaves an INVALID record, like the reference)."""
    st = None
    name = ""
    for suffix in range(16):  # same-second snapshots get a suffix
        name = _snapshot_name(suffix)
        st = ctx.meta.create_snapshot(name)
        if st.ok() or st.code != ErrorCode.E_EXISTED:
            break
    if not st.ok():
        return StatusOr.from_status(st)
    st = ctx.client.create_checkpoint(name)
    if not st.ok():
        return StatusOr.from_status(st)
    st = ctx.meta.set_snapshot_status(name, "VALID")
    if not st.ok():
        return StatusOr.from_status(st)
    return _ok(InterimResult(["Name"], [(name,)]))


def execute_drop_snapshot(ctx: ExecContext,
                          s: ast.DropSnapshotSentence) -> Result:
    # storage dumps go first: if any host fails, the catalog record
    # survives so DROP SNAPSHOT can be retried
    if not ctx.meta.has_snapshot(s.name):
        return _err(ErrorCode.E_NOT_FOUND, f"snapshot {s.name} not found")
    st = ctx.client.drop_checkpoint(s.name)
    if not st.ok():
        return StatusOr.from_status(st)
    st = ctx.meta.drop_snapshot(s.name)
    if not st.ok():
        return StatusOr.from_status(st)
    return _ok()


def _caller_rank_in(ctx: ExecContext, space_id: int) -> int:
    if ctx.session.user == "root":
        return _ROLE_RANK["GOD"]
    role = ctx.meta.get_role(space_id, ctx.session.user)
    return _ROLE_RANK.get(role, 0)


def execute_grant(ctx: ExecContext, s: ast.GrantSentence) -> Result:
    r = ctx.meta.get_space(s.space)
    if not r.ok():
        return StatusOr.from_status(r.status)
    space_id = r.value().space_id
    # checked against the TARGET space; granted role must be strictly
    # below the granter's own rank there (only GOD can mint ADMIN/GOD)
    rank = _caller_rank_in(ctx, space_id)
    # GOD may grant any role (incl. GOD); others only roles strictly below
    allowed = (rank == _ROLE_RANK["GOD"]
               or (rank >= _ROLE_RANK["ADMIN"]
                   and _ROLE_RANK.get(s.role, 5) < rank))
    if not allowed:
        return _err(ErrorCode.E_BAD_PERMISSION,
                    f"granting {s.role} on {s.space} requires a higher role there")
    st = ctx.meta.grant_role(space_id, s.user, s.role)
    return _ok() if st.ok() else StatusOr.from_status(st)


def execute_revoke(ctx: ExecContext, s: ast.RevokeSentence) -> Result:
    r = ctx.meta.get_space(s.space)
    if not r.ok():
        return StatusOr.from_status(r.status)
    space_id = r.value().space_id
    rank = _caller_rank_in(ctx, space_id)
    current = ctx.meta.get_role(space_id, s.user)
    allowed = (rank == _ROLE_RANK["GOD"]
               or (rank >= _ROLE_RANK["ADMIN"]
                   and _ROLE_RANK.get(current, 0) < rank))
    if not allowed:
        return _err(ErrorCode.E_BAD_PERMISSION,
                    f"revoking {current} on {s.space} requires a higher role there")
    st = ctx.meta.revoke_role(space_id, s.user)
    return _ok() if st.ok() else StatusOr.from_status(st)
