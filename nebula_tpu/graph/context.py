"""Per-query execution context.

Role parity with the reference's `graph/ExecutionContext` +
`VariableHolder.cpp`: carries the session, the engine's service handles
(meta / schema / storage client), the `$var` table, and the pipe input
flowing between executors.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..common.status import ErrorCode, Status
from .interim import InterimResult
from .session import ClientSession


@dataclass
class ExecutionResponse:
    code: ErrorCode = ErrorCode.SUCCEEDED
    error_msg: str = ""
    columns: List[str] = field(default_factory=list)
    rows: List[Tuple] = field(default_factory=list)
    latency_us: int = 0
    space_name: str = ""
    warning: str = ""
    # device-path stage breakdown when the TPU engine served this query
    # (ref role: per-stage latency in ExecutionPlan.cpp:57 responses)
    profile: Optional[Dict[str, Any]] = None

    def ok(self) -> bool:
        return self.code == ErrorCode.SUCCEEDED


class ExecContext:
    def __init__(self, engine, session: ClientSession):
        self.engine = engine
        self.session = session
        self.variables: Dict[str, InterimResult] = {}
        self.input: Optional[InterimResult] = None

    @property
    def meta(self):
        return self.engine.meta

    @property
    def sm(self):
        return self.engine.sm

    @property
    def client(self):
        return self.engine.client

    def space_id(self) -> int:
        return self.session.space_id

    def require_space(self) -> Status:
        if self.session.space_id < 0:
            return Status.error(ErrorCode.E_EXECUTION_ERROR,
                                "please choose a graph space with `USE spaceName` first")
        return Status.OK()
