"""Per-query execution context.

Role parity with the reference's `graph/ExecutionContext` +
`VariableHolder.cpp`: carries the session, the engine's service handles
(meta / schema / storage client), the `$var` table, and the pipe input
flowing between executors.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..common.status import ErrorCode, Status
from .interim import InterimResult
from .session import ClientSession


@dataclass
class ExecutionResponse:
    code: ErrorCode = ErrorCode.SUCCEEDED
    error_msg: str = ""
    columns: List[str] = field(default_factory=list)
    rows: List[Tuple] = field(default_factory=list)
    latency_us: int = 0
    space_name: str = ""
    warning: str = ""
    # device-path stage breakdown when the TPU engine served this query
    # (ref role: per-stage latency in ExecutionPlan.cpp:57 responses).
    # A `PROFILE <stmt>` additionally carries the query's span tree in
    # here ("trace_id" + "trace_spans" keys) — the profile MAP is the
    # one extensible slot the FROZEN v1 wire spec gives us
    # (docs/manual/6-wire-protocol.md: ExecutionResponse has exactly 8
    # positional fields; old clients skip unknown map keys, adding a
    # dataclass field would break every conformance vector)
    profile: Optional[Dict[str, Any]] = None

    def ok(self) -> bool:
        return self.code == ErrorCode.SUCCEEDED

    # convenience accessors over the profile map (see field comment)
    @property
    def trace_id(self) -> str:
        return (self.profile or {}).get("trace_id", "")

    @property
    def trace_spans(self):
        """PROFILE span tree: list of (span_id, parent_id, name,
        t0_us, dur_us, tags) — common/tracing.render_tree renders it."""
        return (self.profile or {}).get("trace_spans")

    def attach_trace(self, trace_id: str, spans) -> None:
        # copy-on-write: profile may alias a shared dict (the engine's
        # last_profile) — writing trace keys into it in place would
        # leak this query's span tree into other sessions' responses
        self.profile = dict(self.profile) if self.profile else {}
        self.profile["trace_id"] = trace_id
        self.profile["trace_spans"] = spans


class ExecContext:
    def __init__(self, engine, session: ClientSession):
        self.engine = engine
        self.session = session
        self.variables: Dict[str, InterimResult] = {}
        self.input: Optional[InterimResult] = None
        # QoS dispatcher lane for this query (common/qos.py): set by
        # the graph engine from session override > space plan >
        # statement shape; None lets the dispatcher classify itself.
        # `qos_lane_pinned` marks an EXPLICIT override (session pin /
        # plan lane=): the dispatcher honors it verbatim, whereas a
        # shape-classified interactive lane may still be upgraded to
        # bulk once the RESOLVED start set turns out wide (a pipe
        # feeding thousands of start vids parses as 0 literal vids)
        self.qos_lane: Optional[str] = None
        self.qos_lane_pinned: bool = False

    @property
    def meta(self):
        return self.engine.meta

    @property
    def sm(self):
        return self.engine.sm

    @property
    def client(self):
        return self.engine.client

    def space_id(self) -> int:
        return self.session.space_id

    def require_space(self) -> Status:
        if self.session.space_id < 0:
            return Status.error(ErrorCode.E_EXECUTION_ERROR,
                                "please choose a graph space with `USE spaceName` first")
        return Status.OK()
