"""Execution engine + graph service facade.

Role parity with the reference's `graph/GraphService.cpp` (authenticate/
signout/execute), `graph/ExecutionEngine.cpp` (owns meta + schema +
storage clients), `graph/ExecutionPlan.cpp` (parse → execute → respond
with latency) and `graph/PermissionManager.h` (RBAC gate per sentence).
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from ..common import consistency, ledger, qos, writepath
from ..common.cache import CacheRung, plan_stage_enabled
from ..common.status import ErrorCode, Status, StatusOr
from ..common.tracing import (ActiveQueryRegistry, SlowQueryLog,
                              split_profile_prefix, tracer)
from ..meta.schema_manager import SchemaManager
from ..parser import GQLParser, ParseError, ast
from . import admin_executors as adm
from . import executors as ex
from .context import ExecContext, ExecutionResponse
from .interim import InterimResult
from .session import ClientSession, SessionManager

# role ranks (GOD > ADMIN > USER > GUEST, ref meta.thrift:56-70)
_ROLE_RANK = {"GOD": 4, "ADMIN": 3, "USER": 2, "GUEST": 1, None: 0}

# sentence kind -> minimum role required in the current space
_WRITE_KINDS = {ast.Kind.INSERT_VERTICES, ast.Kind.INSERT_EDGES,
                ast.Kind.DELETE_VERTICES, ast.Kind.DELETE_EDGES,
                ast.Kind.UPDATE_VERTEX, ast.Kind.UPDATE_EDGE, ast.Kind.INGEST,
                ast.Kind.DOWNLOAD}
_SCHEMA_KINDS = {ast.Kind.CREATE_TAG, ast.Kind.CREATE_EDGE, ast.Kind.ALTER_TAG,
                 ast.Kind.ALTER_EDGE, ast.Kind.DROP_TAG, ast.Kind.DROP_EDGE,
                 ast.Kind.CREATE_INDEX, ast.Kind.DROP_INDEX}
_GOD_KINDS = {ast.Kind.CREATE_SPACE, ast.Kind.DROP_SPACE, ast.Kind.BALANCE,
              ast.Kind.CREATE_USER, ast.Kind.DROP_USER, ast.Kind.CONFIG,
              ast.Kind.CREATE_SNAPSHOT, ast.Kind.DROP_SNAPSHOT}

# data-plane statement kinds gated by per-space admission (common/qos
# .py; docs/manual/14-qos.md). Admin/DDL/session statements are exempt
# — a throttled tenant must still be able to USE, SHOW and fix its own
# schema; it's the scan/write volume that overloads the serve path.
_QOS_GATED_KINDS = _WRITE_KINDS | {
    ast.Kind.GO, ast.Kind.FIND_PATH, ast.Kind.FETCH_VERTICES,
    ast.Kind.FETCH_EDGES, ast.Kind.YIELD, ast.Kind.PIPE,
    ast.Kind.SET_OP, ast.Kind.ASSIGNMENT, ast.Kind.ORDER_BY,
    ast.Kind.LIMIT, ast.Kind.GROUP_BY,
    ast.Kind.LOOKUP, ast.Kind.GET_SUBGRAPH, ast.Kind.MATCH}


def _lane_leaf(s: ast.Sentence) -> ast.Sentence:
    """The leftmost data-bearing leaf of a pipe/assignment tree — the
    statement whose shape decides the lane (GO ... | YIELD agg rides
    the GO's scan weight)."""
    while True:
        if s.kind == ast.Kind.PIPE or s.kind == ast.Kind.SET_OP:
            s = s.left
        elif s.kind == ast.Kind.ASSIGNMENT:
            s = s.sentence
        else:
            return s


def sentence_lane(s0: ast.Sentence) -> str:
    """Statement-shape lane classification for ONE sentence
    (docs/manual/14-qos.md): deep or wide GO traversals and bounded
    path searches are BULK (scan-weight work); point lookups and
    shallow hops are INTERACTIVE. Session and space-plan overrides
    win over this. The steps/starts thresholds live in ONE place —
    qos.bulk_shape — shared with the dispatcher's fallback."""
    s = _lane_leaf(s0)
    if s.kind == ast.Kind.GO:
        steps = int(getattr(s.step, "steps", 1) or 1)
        starts = getattr(s.from_, "vids", None) or ()
        if qos.bulk_shape(steps, len(starts)):
            return qos.LANE_BULK
    elif s.kind == ast.Kind.FIND_PATH:
        if qos.bulk_shape(int(getattr(s.step, "steps", 0) or 0), 0):
            return qos.LANE_BULK
    return qos.LANE_INTERACTIVE


def classify_lane(seq: ast.SequentialSentences) -> str:
    """Lane for a whole statement sequence: bulk if ANY sentence is."""
    for s0 in seq.sentences:
        if sentence_lane(s0) == qos.LANE_BULK:
            return qos.LANE_BULK
    return qos.LANE_INTERACTIVE


class PermissionManager:
    """ref: graph/PermissionManager.h — role gate ahead of execution."""

    @staticmethod
    def check(ctx: ExecContext, sentence: ast.Sentence) -> Status:
        user = ctx.session.user
        if user == "root":
            return Status.OK()
        kind = sentence.kind
        role = ctx.meta.get_role(ctx.space_id(), user) \
            if ctx.space_id() >= 0 else None
        rank = _ROLE_RANK.get(role, 0)
        if kind in _GOD_KINDS and rank < 4:
            return Status.error(ErrorCode.E_BAD_PERMISSION,
                                f"{kind.value} requires GOD role")
        if kind in _SCHEMA_KINDS and rank < 3:
            return Status.error(ErrorCode.E_BAD_PERMISSION,
                                f"{kind.value} requires ADMIN role")
        if kind in _WRITE_KINDS and rank < 2:
            return Status.error(ErrorCode.E_BAD_PERMISSION,
                                f"{kind.value} requires USER role")
        # GRANT/REVOKE and password changes are checked in their executors
        # against the TARGET space / target user, not the session space
        return Status.OK()


class ExecutionEngine:
    """Owns the service clients; executes parsed statements."""

    def __init__(self, meta, schema_manager: SchemaManager, storage_client,
                 tpu_engine=None, balancer=None):
        self.meta = meta
        self.sm = schema_manager
        self.client = storage_client
        self.tpu_engine = tpu_engine
        self.balancer = balancer
        # plan-cache rung (common/cache.py; docs/manual/11-caching.md):
        # statement text -> parsed AST. Parse is pure text->tree and
        # execution never mutates the AST (expressions assign only in
        # __init__), so one parsed tree serves every session; the
        # per-call GQLParser below is still constructed PER MISS (its
        # token cursor lives on the instance). No invalidation needed —
        # text->AST has no versioned inputs; the LRU bound governs.
        self.plan_cache = CacheRung("graph.plan_cache", 512,
                                    stats_prefix="graph.plan_cache")

    # statement kinds whose parse is never cached: mutations/DDL are
    # one-shot by construction (bulk loads would pin hundreds of
    # never-repeated literal-heavy INSERT trees and churn out the
    # read entries the cache exists for)
    _UNCACHED_KINDS = _WRITE_KINDS | _SCHEMA_KINDS | _GOD_KINDS
    # and so are huge statements, whatever their kind (bulk-load rows)
    PLAN_CACHE_MAX_TEXT = 4096

    # ------------------------------------------------------------------
    def _parse_cached(self, text: str) -> ast.SequentialSentences:
        """Parse through the plan cache. The key is the statement with
        any PROFILE prefix stripped (split_profile_prefix — the shared,
        comment-aware rule), so `PROFILE <stmt>` and `<stmt>` share one
        entry; the profile decision itself is made from the raw text by
        the trace head, never from the cached tree. Parse errors are
        not cached (they re-derive their exact message per call)."""
        from ..common.flags import graph_flags
        if not plan_stage_enabled(graph_flags):
            with tracer.span("parse"):
                return GQLParser().parse(text)
        _, key = split_profile_prefix(text)
        if len(key) > self.PLAN_CACHE_MAX_TEXT:
            with tracer.span("parse"):
                return GQLParser().parse(text)
        seq = self.plan_cache.get(key)
        if seq is not None:
            with tracer.span("parse", cached=True):
                return seq
        # parser PER MISS: GQLParser keeps its token cursor on the
        # instance, and graphd is thread-per-connection — a shared
        # parser under concurrent sessions interleaves cursors and
        # throws spurious syntax errors (found by the concurrent
        # soak; the reference constructs its parser per query too,
        # GQLParser.h). The ORIGINAL text is parsed (the parser stays
        # the authority that consumes the PROFILE prefix).
        with tracer.span("parse"):
            seq = GQLParser().parse(text)
        if any(s.kind in self._UNCACHED_KINDS for s in seq.sentences):
            return seq
        if not seq.profile:
            self.plan_cache.put(key, seq)
        else:
            # the key is the PROFILE-stripped text, so the cached tree
            # must represent the stripped statement: store a profile-
            # free twin over the same (immutable) sentences — a later
            # plain-text hit must not receive a tree claiming
            # profile=True (latent today, wrong tomorrow)
            self.plan_cache.put(key, ast.SequentialSentences(
                seq.sentences, profile=False))
        return seq

    # ------------------------------------------------------------------
    def execute(self, session: ClientSession, text: str) -> ExecutionResponse:
        t0 = time.monotonic()
        resp = ExecutionResponse(space_name=session.space_name or "")
        try:
            seq = self._parse_cached(text)
        except ParseError as e:
            resp.code = ErrorCode.E_SYNTAX_ERROR
            resp.error_msg = str(e)
            return resp
        if seq.sentences:
            tracer.tag_root("feature", seq.sentences[0].kind.value)
            led = ledger.current()
            if led is not None and not led.verb:
                # per-verb cost rollup dimension (graph.cost.verb.*)
                # + the profiler's per-thread verb mirror
                ledger.set_verb(led, seq.sentences[0].kind.value)
        ctx = ExecContext(self, session)
        result: Optional[InterimResult] = None
        tpu = self.tpu_engine
        profile_seq0 = tpu.profile_seq if tpu is not None else 0
        # shadow freshness token, pinned BEFORE any sentence computes
        # rows: a write committing between row computation and the
        # sampling seam below must make the shadow comparison SKIP,
        # never false-positive (one flag read when disarmed)
        shadow_ver = None
        if consistency.shadow.armed() and not consistency.is_shadow():
            try:
                shadow_ver = consistency.shadow.current_version(
                    session.space_name or "")
            except Exception:
                shadow_ver = None
        for sentence in seq.sentences:
            # multi-tenant QoS (common/qos.py; docs/manual/14-qos.md):
            # per-space token-bucket admission gates every data-plane
            # SENTENCE against the session's CURRENT space — per
            # sentence, not per request, so `USE abuser; GO ...`
            # cannot slip through on the pre-USE space and a 50-GO
            # compound cannot ride one token. Denials are typed +
            # retryable (E_OVERLOAD with a retry-after hint), tagged
            # on the trace root and counted per tenant — never a
            # hang, never a generic failure. The lane the sentence
            # rides (session override > space-plan override >
            # statement shape) travels on the ctx for the
            # dispatcher's weighted-fair scheduling.
            space = session.space_name or ""
            # shadow-read re-executions are off-path internal
            # verification (common/consistency.py): they must not
            # spend a tenant's admission tokens — being denied would
            # starve verification exactly when the system is busiest
            if space and sentence.kind in _QOS_GATED_KINDS \
                    and not consistency.is_shadow():
                admitted, retry_ms, lane_override = \
                    qos.admission.admit(space)
                if not admitted:
                    tracer.tag_root("admission_denied", space)
                    from ..common.stats import stats
                    stats.add_value("graph.query_overload",
                                    kind="counter")
                    resp.code = ErrorCode.E_OVERLOAD
                    resp.error_msg = (
                        f"space '{space}' over its admission budget "
                        f"(E_OVERLOAD, retryable); retry in "
                        f"~{retry_ms}ms")
                    resp.profile = {"retry_after_ms": retry_ms}
                    resp.latency_us = int((time.monotonic() - t0) * 1e6)
                    return resp
                pinned = getattr(session, "qos_lane", None) \
                    or lane_override
                ctx.qos_lane = pinned or sentence_lane(sentence)
                ctx.qos_lane_pinned = pinned is not None
                if ctx.qos_lane == qos.LANE_BULK:
                    tracer.tag_root("qos_lane", qos.LANE_BULK)
            try:
                with tracer.span("exec." + sentence.kind.value):
                    if sentence.kind in _WRITE_KINDS:
                        # write-path observatory: the mutation
                        # executor's full run is the `execute` stage of
                        # the write timeline (common/writepath.py); the
                        # StorageClient fan-out below it times itself
                        with writepath.timed_stage("execute",
                                                   "write_exec_us"):
                            r = self._run(ctx, sentence)
                    else:
                        r = self._run(ctx, sentence)
            except qos.OverloadShed as e:
                # a dispatcher shed surfaces with the SAME machine-
                # readable contract as an admission denial: typed
                # E_OVERLOAD + profile retry_after_ms (the trace root
                # was already tagged shed:<reason> at the shed site)
                resp.code = ErrorCode.E_OVERLOAD
                resp.error_msg = str(e)
                resp.profile = {"retry_after_ms": e.retry_after_ms}
                resp.latency_us = int((time.monotonic() - t0) * 1e6)
                return resp
            if not r.ok():
                resp.code = r.status.code
                resp.error_msg = r.status.msg or r.status.code.name
                resp.latency_us = int((time.monotonic() - t0) * 1e6)
                return resp
            result = r.value()
            ctx.input = None  # pipe input does not leak across ';'
            if sentence.kind in _WRITE_KINDS:
                # shadow freshness: a committed mutation moves the
                # space's write sequence so any in-flight shadow
                # sample skips its comparison (one flag read when
                # shadow sampling is disarmed)
                consistency.note_space_write(session.space_name or "")
        if result is not None:
            resp.columns = result.columns
            resp.rows = result.rows
        resp.space_name = session.space_name or ""
        self._maybe_shadow_sample(session, seq, text, resp, shadow_ver)
        if tpu is not None and tpu.profile_seq != profile_seq0:
            # device-served: attach the engine's per-stage breakdown
            # (under concurrent sessions the latest served wins — the
            # breakdown is diagnostics, not an accounting ledger).
            # COPY: the engine dict is shared across sessions, and the
            # response may later merge per-query trace keys into it
            lp = tpu.last_profile
            resp.profile = dict(lp) if lp else lp
        resp.latency_us = int((time.monotonic() - t0) * 1e6)
        return resp

    # ------------------------------------------------------------------
    # shadow-read sampling (consistency observatory, common/
    # consistency.py; docs/manual/10-observability.md)
    # ------------------------------------------------------------------
    # statements eligible for shadow re-execution: pure reads whose
    # leftmost data leaf is a GO/FETCH (the serve paths the device
    # engine owns), single-sentence so re-execution in a fresh shadow
    # session has identical semantics
    _SHADOW_LEAF_KINDS = {ast.Kind.GO, ast.Kind.FETCH_VERTICES,
                          ast.Kind.FETCH_EDGES, ast.Kind.LOOKUP,
                          ast.Kind.GET_SUBGRAPH}
    _SHADOW_KINDS = _SHADOW_LEAF_KINDS | {
        ast.Kind.PIPE, ast.Kind.SET_OP, ast.Kind.YIELD,
        ast.Kind.ORDER_BY, ast.Kind.LIMIT, ast.Kind.GROUP_BY}

    def _maybe_shadow_sample(self, session, seq, text: str,
                             resp: ExecutionResponse,
                             shadow_ver=None) -> None:
        """Sample this successful serve for CPU-pipe re-execution —
        one flag read disarmed; armed, a digest of the rows + a
        bounded enqueue (the verifier worker does the rest off the
        serve path). `shadow_ver` is the freshness token pinned at
        execute START (before row computation). Never raises."""
        try:
            if resp.code != ErrorCode.SUCCEEDED or \
                    shadow_ver is None or \
                    not consistency.shadow.armed() or \
                    consistency.is_shadow():
                return
            if seq.profile or len(seq.sentences) != 1:
                return
            s = seq.sentences[0]
            if s.kind not in self._SHADOW_KINDS or \
                    _lane_leaf(s).kind not in self._SHADOW_LEAF_KINDS:
                return
            # $var refs read another statement's result — they don't
            # survive re-execution in a fresh session; $- / $^ / $$
            # forms are self-contained within the one statement
            i = text.find("$")
            while i != -1:
                if text[i + 1:i + 2] not in ("-", "^", "$"):
                    return
                i = text.find("$", i + 2)
            from ..common.stats import current_trace_id
            consistency.shadow.maybe_sample(
                session.space_name or "", s.kind.value, text,
                resp.rows or [], current_trace_id(),
                version=shadow_ver)
        except Exception:
            pass    # verification must never fail a serve

    # ------------------------------------------------------------------
    def _run(self, ctx: ExecContext, s: ast.Sentence) -> ex.Result:
        st = PermissionManager.check(ctx, s)
        if not st.ok():
            return StatusOr.from_status(st)
        kind = s.kind
        if kind == ast.Kind.PIPE:
            # GO | YIELD <aggregates>: one masked device reduction
            # instead of materialize-then-aggregate (bound_stats role)
            ar = ex.try_device_aggregate(ctx, s)
            if ar is not None:
                return ar
            lr = self._run(ctx, s.left)
            if not lr.ok():
                return lr
            ctx.input = lr.value()
            rr = self._run(ctx, s.right)
            ctx.input = None
            return rr
        if kind == ast.Kind.ASSIGNMENT:
            rr = self._run(ctx, s.sentence)
            if not rr.ok():
                return rr
            if rr.value() is None:
                return ex._err(ErrorCode.E_EXECUTION_ERROR,
                               f"${s.var} = <statement> produced no table")
            ctx.variables[s.var] = rr.value()
            return ex._ok(None)
        if kind == ast.Kind.SET_OP:
            return ex.execute_set_op(ctx, s, self._run)
        fn = _DISPATCH.get(kind)
        if fn is None:
            return ex._err(ErrorCode.E_UNSUPPORTED,
                           f"statement {kind.value} not supported yet")
        return fn(ctx, s)


_DISPATCH: Dict[ast.Kind, Callable] = {
    ast.Kind.GO: ex.execute_go,
    ast.Kind.FIND_PATH: ex.execute_find_path,
    ast.Kind.FETCH_VERTICES: ex.execute_fetch_vertices,
    ast.Kind.FETCH_EDGES: ex.execute_fetch_edges,
    ast.Kind.INSERT_VERTICES: ex.execute_insert_vertices,
    ast.Kind.INSERT_EDGES: ex.execute_insert_edges,
    ast.Kind.DELETE_VERTICES: ex.execute_delete_vertices,
    ast.Kind.DELETE_EDGES: ex.execute_delete_edges,
    ast.Kind.UPDATE_VERTEX: ex.execute_update_vertex,
    ast.Kind.UPDATE_EDGE: ex.execute_update_edge,
    ast.Kind.LOOKUP: ex.execute_lookup,
    ast.Kind.GET_SUBGRAPH: ex.execute_subgraph,
    ast.Kind.MATCH: ex.execute_match,
    ast.Kind.YIELD: ex.execute_yield,
    ast.Kind.ORDER_BY: ex.execute_order_by,
    ast.Kind.LIMIT: ex.execute_limit,
    ast.Kind.GROUP_BY: ex.execute_group_by,
    ast.Kind.USE: adm.execute_use,
    ast.Kind.CREATE_SPACE: adm.execute_create_space,
    ast.Kind.DROP_SPACE: adm.execute_drop_space,
    ast.Kind.DESCRIBE_SPACE: adm.execute_describe_space,
    ast.Kind.CREATE_TAG: adm.execute_create_schema,
    ast.Kind.CREATE_EDGE: adm.execute_create_schema,
    ast.Kind.ALTER_TAG: adm.execute_alter_schema,
    ast.Kind.ALTER_EDGE: adm.execute_alter_schema,
    ast.Kind.DROP_TAG: adm.execute_drop_schema,
    ast.Kind.DROP_EDGE: adm.execute_drop_schema,
    ast.Kind.DESCRIBE_TAG: adm.execute_describe_schema,
    ast.Kind.DESCRIBE_EDGE: adm.execute_describe_schema,
    ast.Kind.CREATE_INDEX: adm.execute_create_index,
    ast.Kind.DROP_INDEX: adm.execute_drop_index,
    ast.Kind.SHOW: adm.execute_show,
    ast.Kind.SHOW_CREATE: adm.execute_show_create,
    ast.Kind.CONFIG: adm.execute_config,
    ast.Kind.BALANCE: adm.execute_balance,
    ast.Kind.CREATE_USER: adm.execute_create_user,
    ast.Kind.DROP_USER: adm.execute_drop_user,
    ast.Kind.ALTER_USER: adm.execute_change_password,
    ast.Kind.CHANGE_PASSWORD: adm.execute_change_password,
    ast.Kind.GRANT: adm.execute_grant,
    ast.Kind.REVOKE: adm.execute_revoke,
    ast.Kind.DOWNLOAD: adm.execute_download,
    ast.Kind.INGEST: adm.execute_ingest,
    ast.Kind.CREATE_SNAPSHOT: adm.execute_create_snapshot,
    ast.Kind.DROP_SNAPSHOT: adm.execute_drop_snapshot,
}


# ledger fields streamed into the graph.cost.* histogram families —
# the ISSUE-12 rollup surface (per space and per verb). rpc bytes are
# folded into one field to bound family cardinality.
_COST_ROLLUP_FIELDS = ("device_us", "queue_wait_us", "h2d_bytes",
                       "d2h_bytes", "rows_scanned", "bytes_returned",
                       "wal_bytes")


def _roll_cost(led, space_name: str, trace_id: str) -> None:
    """Stream one query's ledger into the PR 10 histogram machinery:
    `graph.cost.<space>.<field>` + `graph.cost.verb.<verb>.<field>`
    native histograms whose exemplars carry the query's trace id when
    sampled (the metric -> trace join rides cost too). Kind is pinned
    to "histogram" — nebula-lint NL004 enforces it for every
    graph.cost.* site."""
    from ..common.stats import stats
    space = space_name or "_"
    for f in _COST_ROLLUP_FIELDS:
        v = getattr(led, f)
        if not v:
            continue
        stats.add_value(f"graph.cost.{space}.{f}", v,
                        kind="histogram", trace_id=trace_id)
        if led.verb:
            stats.add_value(f"graph.cost.verb.{led.verb}.{f}", v,
                            kind="histogram", trace_id=trace_id)
    rpc_b = led.rpc_bytes_out + led.rpc_bytes_in
    if rpc_b:
        stats.add_value(f"graph.cost.{space}.rpc_bytes", rpc_b,
                        kind="histogram", trace_id=trace_id)
        if led.verb:
            stats.add_value(f"graph.cost.verb.{led.verb}.rpc_bytes",
                            rpc_b, kind="histogram", trace_id=trace_id)


def _wants_profile(text: str) -> bool:
    """Pre-parse sniff for the PROFILE prefix — the sampling decision
    must land BEFORE parsing so the parse span is in the trace; the
    parser is the authority on actually consuming the prefix."""
    from ..common.tracing import split_profile_prefix
    return split_profile_prefix(text)[0]


class GraphService:
    """Authentication + session-scoped execute (ref: graph/GraphService
    .cpp:17-77). Hosts the per-daemon observability registries: the
    active-query registry and slow-query log behind /queries, and the
    trace head (begin/finish) for every executed statement."""

    def __init__(self, engine: ExecutionEngine,
                 sessions: Optional[SessionManager] = None):
        self.engine = engine
        self.sessions = sessions or SessionManager()
        self.active_queries = ActiveQueryRegistry()
        self.slow_log = SlowQueryLog()
        # shadow-read verification (common/consistency.py): this
        # service owns the process's shadow runner — sampled serves
        # re-execute here through the CPU pipe (the shadow ContextVar
        # makes the device engine decline) and compare byte-for-byte.
        # install() replaces by design (the flight-collector idiom).
        consistency.shadow.install(self._shadow_run,
                                   self._shadow_version)

    def _shadow_run(self, space: str, text: str) -> list:
        """Re-execute one sampled statement in a fresh root session
        (the worker sets the shadow ContextVar around this call, so
        the device engine declines and admission is bypassed)."""
        session = self.sessions.create("root")
        try:
            if space:
                r = self.engine.execute(session, f"USE {space}")
                if not r.ok():
                    raise RuntimeError(f"shadow USE failed: "
                                       f"{r.error_msg}")
            resp = self.engine.execute(session, text)
            if not resp.ok():
                raise RuntimeError(f"shadow execute failed "
                                   f"[{resp.code.name}]: "
                                   f"{resp.error_msg}")
            return resp.rows or []
        finally:
            self.sessions.remove(session.session_id)

    def _shadow_version(self, space: str):
        """The freshness token a shadow comparison must hold across:
        the graph-level write sequence plus — when a device provider
        serves the space — its structural version token (any committed
        write moves it)."""
        seq = consistency.space_write_seq(space)
        tok = None
        tpu = self.engine.tpu_engine
        if tpu is not None and space and \
                getattr(tpu, "_provider", None) is not None:
            try:
                sid = self.engine.meta.get_space(space).value().space_id
                tok = tpu._provider.version(sid)
            except Exception:
                tok = None
        return (seq, tok)

    def authenticate(self, user: str, password: str) -> StatusOr[int]:
        if not self.engine.meta.check_password(user, password):
            return StatusOr.err(ErrorCode.E_BAD_USERNAME_PASSWORD,
                                "invalid username or password")
        return StatusOr.of(self.sessions.create(user).session_id)

    def signout(self, session_id: int) -> None:
        self.sessions.remove(session_id)

    def execute(self, session_id: int, text: str) -> ExecutionResponse:
        sr = self.sessions.find(session_id)
        if not sr.ok():
            resp = ExecutionResponse()
            resp.code = sr.status.code
            resp.error_msg = sr.status.msg
            return resp
        session = sr.value()
        # trace head: one sampled-flag check per query; PROFILE forces
        # the sample (and attaches the span tree to the response)
        profiled = _wants_profile(text)
        handle = tracer.begin("query", force=profiled,
                              session=session_id, user=session.user)
        # cost head (common/ledger.py): EVERY query carries a ledger
        # (sampling on or off) — the slow-query log and the per-tenant
        # cost rollups below must cover what head sampling misses
        led, led_tok = ledger.begin()
        qtok = self.active_queries.register(
            text, session=session_id, user=session.user,
            trace_id=handle.trace_id)
        # arm the per-query deadline context (common/qos.py): every
        # retry loop downstream — the StorageClient fan-out rounds in
        # particular — consults the remaining budget, so a stalled
        # election's retries can never outlive the query's own
        # tpu_query_deadline_ms (deadline balks beat open-ended
        # retrying; docs/manual/14-qos.md watermark ladder)
        from ..common.flags import graph_flags
        dl_ms = graph_flags.get("tpu_query_deadline_ms", 0) or 0
        dl_tok = qos.set_query_deadline(
            time.monotonic() + dl_ms / 1e3) if dl_ms > 0 else None
        try:
            resp = self.engine.execute(session, text)
        except BaseException:
            # the handle owns this thread's trace context: finish it
            # even on an engine bug, or the NEXT query on this
            # connection thread would record into a dead trace (the
            # ledger token likewise)
            if dl_tok is not None:
                qos.clear_query_deadline(dl_tok)
            ledger.end(led_tok)
            self.active_queries.unregister(qtok)
            handle.finish(ok=False, error=True)
            raise
        if dl_tok is not None:
            qos.clear_query_deadline(dl_tok)
        ledger.end(led_tok)
        self.active_queries.unregister(qtok)
        trace = handle.finish(ok=resp.ok(), latency_us=resp.latency_us)
        if trace is not None and profiled and resp.ok():
            resp.attach_trace(trace["trace_id"], [
                (s["span_id"], s["parent_id"], s["name"], s["t0_us"],
                 s["dur_us"], s["tags"]) for s in trace["spans"]])
        if led is not None and profiled and resp.ok():
            # the PROFILE cost block rides next to the span tree in
            # the profile map (the one extensible slot of the frozen
            # ExecutionResponse — see graph/context.py)
            resp.profile = dict(resp.profile) if resp.profile else {}
            cost = led.to_dict()
            resp.profile["cost"] = cost
            # PROFILE on a mutation renders the per-stage write
            # timeline the way reads already render their cost block:
            # the synchronous stages' ledger charges, in pipeline order
            ws = {st: cost[f]
                  for st, f in writepath.LEDGER_FIELDS.items()
                  if cost.get(f)}
            if ws:
                resp.profile["write_stages"] = ws
        # per-query QPS/latency metrics + slow-op log (ref: per-query
        # latency_in_us in every response, SlowOpTracker)
        from ..common.flags import graph_flags
        from ..common.stats import stats
        stats.add_value("graph.query", kind="counter")
        # native histogram (docs/manual/10-observability.md): real
        # _bucket/_sum/_count series on /metrics whose exemplars carry
        # this query's trace id when sampled — but the handle already
        # finished above, so pin the exemplar explicitly
        stats.add_value("graph.query_latency_us", resp.latency_us,
                        kind="histogram",
                        trace_id=handle.trace_id)   # "" = no exemplar
        if session.space_name:
            # per-tenant latency slice (the SLO engine's per-space
            # latency objectives ride these; cardinality = live spaces)
            stats.add_value(
                "graph.space." + session.space_name + ".latency_us",
                resp.latency_us, kind="histogram",
                trace_id=handle.trace_id)   # "" = no exemplar
        if led is not None:
            # per-tenant + per-verb COST rollups (graph.cost.*, native
            # histograms — SLOs and exemplars ride cost, not just
            # latency; docs/manual/10-observability.md). Zero fields
            # are skipped: a FETCH that never touched the device must
            # not pour zeros into the device_us distribution.
            _roll_cost(led, session.space_name, handle.trace_id)
        if not resp.ok():
            stats.add_value("graph.query_error", kind="counter")
        slow_ms = graph_flags.get("slow_op_threshold_ms", 50)
        if resp.latency_us > slow_ms * 1000:
            stats.add_value("graph.slow_query", kind="counter")
        slowlog_ms = graph_flags.get("slow_query_threshold_ms", 500)
        if slowlog_ms and resp.latency_us > slowlog_ms * 1000:
            self.slow_log.add(text, resp.latency_us, session=session_id,
                              user=session.user,
                              trace_id=handle.trace_id, ok=resp.ok(),
                              cost=led.to_dict() if led is not None
                              else None)
        return resp
