"""Query executors: traversal + data manipulation.

Role parity with the reference's `graph/*Executor.cpp` family —
GoExecutor (1084 L, ref graph/GoExecutor.cpp), FindPathExecutor (717 L),
Fetch*/Insert*/Delete*/Update* executors, Yield/OrderBy/Limit/GroupBy/
Set executors and the Pipe/Sequential/Assignment combinators
(dispatched like `Executor::makeExecutor`, ref graph/Executor.cpp:53-170).

Control flow divergence from the reference: the reference chains
executors through async onFinish callbacks across folly futures; here
execution is a synchronous recursion over the AST — the concurrency
story moved down into the storage client fan-out and (for traversals)
onto the TPU engine, where the whole multi-hop loop becomes one
compiled program instead of a callback chain per hop.
"""
from __future__ import annotations

import math
import statistics
from typing import Any, Dict, List, Optional, Set, Tuple

from ..codec.row import RowWriter
from ..codec.schema import PropType, Schema, default_for
from ..common.status import ErrorCode, Status, StatusOr
from ..filter.expressions import (DestPropExpr, EdgeDstIdExpr, EdgePropExpr,
                                  EdgeRankExpr, EdgeSrcIdExpr, EdgeTypeExpr,
                                  EvalError, Expression, FunctionCall,
                                  InputPropExpr, Literal, RelationalExpr,
                                  SourcePropExpr, VariablePropExpr,
                                  encode_expression)
from ..parser import ast
from ..storage.processors import is_pushable
from ..storage.types import EdgeKey, NewEdge, NewVertex, UpdateItemReq
from .context import ExecContext
from .expr_context import EdgeRowExprContext, RowExprContext, TagRowExprContext
from .interim import InterimResult

Result = StatusOr[Optional[InterimResult]]


def _ok(result: Optional[InterimResult] = None) -> Result:
    return StatusOr.of(result)


def _err(code: ErrorCode, msg: str = "") -> Result:
    return StatusOr.err(code, msg)


# part-level storage codes a client may retry verbatim: surfacing them
# (instead of flattening every write failure to E_EXECUTION_ERROR)
# lets clients distinguish "the cluster is failing over, try again"
# from "your statement is broken" — without it, every partition window
# turns transient write failures into permanent-looking client errors
_RETRYABLE_STORAGE = frozenset({
    ErrorCode.E_LEADER_CHANGED, ErrorCode.E_CONSENSUS_ERROR,
    ErrorCode.E_TIMEOUT, ErrorCode.E_OVERLOAD,
})


def _storage_err(resp, what: str) -> Result:
    """Graph-level error for a failed storage ExecResponse: keep the
    part's own code when it is retryable, E_EXECUTION_ERROR otherwise."""
    codes = sorted({r.code for r in resp.results.values()
                    if r.code is not ErrorCode.SUCCEEDED},
                   key=lambda c: c.value, reverse=True)
    code = next((c for c in codes if c in _RETRYABLE_STORAGE),
                ErrorCode.E_EXECUTION_ERROR)
    detail = ",".join(c.name for c in codes) or "unknown"
    return _err(code, f"{what} failed ({detail})")


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def resolve_starts(ctx: ExecContext, ref: ast.VertexRef) -> StatusOr[List[int]]:
    """Resolve FROM sources: literal vid exprs (incl. uuid()/hash()) or an
    input/variable column (ref: GoExecutor::setupStarts)."""
    if ref.ref is not None:
        e = ref.ref
        if isinstance(e, InputPropExpr):
            if ctx.input is None:
                return StatusOr.of([])
            try:
                return StatusOr.of(ctx.input.get_vids(e.prop))
            except (KeyError, ValueError) as ex:
                return StatusOr.err(ErrorCode.E_EXECUTION_ERROR, str(ex))
        if isinstance(e, VariablePropExpr):
            var = ctx.variables.get(e.var)
            if var is None:
                return StatusOr.err(ErrorCode.E_EXECUTION_ERROR,
                                    f"variable ${e.var} not defined")
            try:
                return StatusOr.of(var.get_vids(e.prop))
            except (KeyError, ValueError) as ex:
                return StatusOr.err(ErrorCode.E_EXECUTION_ERROR, str(ex))
        return StatusOr.err(ErrorCode.E_EXECUTION_ERROR,
                            f"bad FROM reference {e.to_string()}")
    vids: List[int] = []
    seen: Set[int] = set()
    for e in ref.vids or []:
        r = eval_vid(ctx, e)
        if not r.ok():
            return StatusOr.from_status(r.status)
        vid = r.value()
        if vid not in seen:
            seen.add(vid)
            vids.append(vid)
    return StatusOr.of(vids)


def eval_vid(ctx: ExecContext, e: Expression) -> StatusOr[int]:
    if isinstance(e, FunctionCall) and e.name == "uuid":
        if len(e.args) != 1 or not isinstance(e.args[0], Literal):
            return StatusOr.err(ErrorCode.E_EXECUTION_ERROR, "uuid(name) expects a string")
        _, vid = ctx.client.get_uuid(ctx.space_id(), str(e.args[0].value))
        return StatusOr.of(vid)
    try:
        v = e.eval(RowExprContext())
    except EvalError as ex:
        return StatusOr.err(ErrorCode.E_EXECUTION_ERROR, str(ex))
    if isinstance(v, bool) or not isinstance(v, int):
        return StatusOr.err(ErrorCode.E_EXECUTION_ERROR,
                            f"vertex id must be an integer, got {v!r}")
    return StatusOr.of(v)


def resolve_over(ctx: ExecContext, over: ast.OverClause
                 ) -> StatusOr[Tuple[List[int], Dict[str, str], Dict[int, str]]]:
    """-> (signed edge types, alias->name map, |etype|->name map)."""
    space = ctx.space_id()
    alias_map: Dict[str, str] = {}
    name_by_type: Dict[int, str] = {}
    if over.is_all:
        pairs = [(n, t) for n, t in ctx.meta.list_edges(space)] \
            if hasattr(ctx.meta, "list_edges") else []
        if not pairs:
            pairs = [(ctx.sm.edge_name(space, t) or str(t), t)
                     for t in ctx.sm.all_edge_types(space)]
        for name, et in pairs:
            alias_map[name] = name
            name_by_type[et] = name
        base_types = [et for _, et in pairs]
    else:
        base_types = []
        for e in over.edges:
            et = ctx.sm.edge_type(space, e.name)
            if et is None:
                return StatusOr.err(ErrorCode.E_EDGE_NOT_FOUND, e.name)
            base_types.append(et)
            alias_map[e.name] = e.name
            if e.alias:
                alias_map[e.alias] = e.name
            name_by_type[et] = e.name
    if over.direction == ast.Direction.OUT:
        types = base_types
    elif over.direction == ast.Direction.IN:
        types = [-t for t in base_types]
    else:
        types = base_types + [-t for t in base_types]
    return StatusOr.of((types, alias_map, name_by_type))


def _collect_prop_requirements(exprs: List[Expression], ctx: ExecContext
                               ) -> Tuple[Dict[int, List[str]], bool, bool]:
    """-> (src tag props needed, needs dst props, needs input rows)."""
    space = ctx.space_id()
    src_tags: Dict[int, Set[str]] = {}
    needs_dst = False
    needs_input = False
    for expr in exprs:
        for node in expr.walk():
            if isinstance(node, SourcePropExpr):
                tid = ctx.sm.tag_id(space, node.tag)
                if tid is not None:
                    src_tags.setdefault(tid, set()).add(node.prop)
            elif isinstance(node, DestPropExpr):
                needs_dst = True
            elif isinstance(node, (InputPropExpr, VariablePropExpr)):
                needs_input = True
    return {k: sorted(v) for k, v in src_tags.items()}, needs_dst, needs_input


def _check_tag_prop_refs(exprs: List[Expression],
                         ctx: ExecContext) -> Status:
    """Plan-time validation of every $^ / $$ reference: the TAG and the
    PROP must exist in the catalog (ref: checkAndBuildContexts returns
    E_TAG_PROP_NOT_FOUND, QueryBaseProcessor.inl:71-78; GoTest
    NotExistTagProp). A vertex merely not CARRYING a known tag is NOT
    an error — it reads as the schema default at eval time."""
    space = ctx.space_id()
    for expr in exprs:
        for node in expr.walk():
            if isinstance(node, (SourcePropExpr, DestPropExpr)):
                tid = ctx.sm.tag_id(space, node.tag)
                r = ctx.sm.tag_schema(space, tid) \
                    if tid is not None else None
                if r is None or not r.ok() or \
                        not r.value().has_field(node.prop):
                    ref = "$^" if isinstance(node, SourcePropExpr) \
                        else "$$"
                    return Status.error(
                        ErrorCode.E_EXECUTION_ERROR,
                        f"{ref}.{node.tag}.{node.prop} not found")
    return Status.OK()


def _fetch_dst_props(ctx: ExecContext, dsts: List[int]
                     ) -> Dict[int, Dict[str, Dict[str, Any]]]:
    """$$-prop support: batch-fetch dst vertex props keyed by tag name
    (ref: GoExecutor::fetchVertexProps — the second RPC)."""
    space = ctx.space_id()
    resp = ctx.client.get_vertex_props(space, dsts)
    out: Dict[int, Dict[str, Dict[str, Any]]] = {}
    for v in resp.vertices:
        named = {}
        for tid, props in v.tag_props.items():
            name = ctx.sm.tag_name(space, tid) or str(tid)
            named[name] = props
        out[v.vid] = named
    return out


# ---------------------------------------------------------------------------
# GO (ref: graph/GoExecutor.cpp — the north-star read path)
# ---------------------------------------------------------------------------

def build_input_index(ctx: ExecContext, s: ast.GoSentence
                      ) -> Dict[int, List[Dict[str, Any]]]:
    """Root vid -> input rows for $-/$var back-references (the
    VertexBackTracker join table, ref GoExecutor.cpp:1067-1075). Shared
    by the CPU loop and the device engine's per-root path."""
    input_index: Dict[int, List[Dict[str, Any]]] = {}
    src_table = None
    key_col = None
    if s.from_.ref is not None and isinstance(s.from_.ref, VariablePropExpr):
        src_table = ctx.variables.get(s.from_.ref.var)
        key_col = s.from_.ref.prop
    elif ctx.input is not None and s.from_.ref is not None:
        src_table = ctx.input
        key_col = s.from_.ref.prop
    if src_table is not None:
        for vid, rows in src_table.build_index(key_col).items():
            input_index[vid] = [src_table.row_dict(r) for r in rows]
    return input_index


def execute_go(ctx: ExecContext, s: ast.GoSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()

    starts_r = resolve_starts(ctx, s.from_)
    if not starts_r.ok():
        return StatusOr.from_status(starts_r.status)
    starts = starts_r.value()
    if not starts:
        return _ok(InterimResult(_default_go_columns(s, ctx)))

    over_r = resolve_over(ctx, s.over)
    if not over_r.ok():
        return StatusOr.from_status(over_r.status)
    edge_types, alias_map, name_by_type = over_r.value()
    if not edge_types:
        return _err(ErrorCode.E_EDGE_NOT_FOUND, "no edges in OVER clause")

    yield_cols = _go_yield_columns(s, ctx, name_by_type)
    all_exprs = [c.expr for c in yield_cols]
    if s.where:
        all_exprs.append(s.where.filter)
    # plan-time $^/$$ validation runs BEFORE the device dispatch so
    # both engines reject unknown tag props identically
    st = _check_tag_prop_refs(all_exprs, ctx)
    if not st.ok():
        return StatusOr.from_status(st)

    # TPU offload seam: multi-hop frontier advance runs on device when the
    # space has a CSR snapshot attached (Phase 2+); CPU scatter/gather here.
    tpu = getattr(ctx.engine, "tpu_engine", None)
    if tpu is not None and tpu.can_serve(space, s):
        r = tpu.execute_go(ctx, s, starts, edge_types, alias_map, name_by_type)
        if r is not None:
            return r  # None = engine declined, fall back to CPU path

    vertex_props, needs_dst, needs_input = _collect_prop_requirements(all_exprs, ctx)

    filter_bytes = None
    local_filter = None
    if s.where:
        if is_pushable(s.where.filter):
            filter_bytes = encode_expression(s.where.filter)
        else:
            local_filter = s.where.filter

    # input back-reference index: root vid -> input rows
    input_index: Dict[int, List[Dict[str, Any]]] = {}
    input_var = s.from_.ref.var \
        if isinstance(s.from_.ref, VariablePropExpr) else None
    if needs_input:
        input_index = build_input_index(ctx, s)

    # multi-hop frontier loop (ref: stepOut/onStepOutResponse); roots map
    # mirrors VertexBackTracker (ref GoExecutor.cpp:1067-1075). With UPTO,
    # rows are emitted at every step 1..N (union semantics) — the filter
    # then applies per emission, never to frontier advancement, so it is
    # evaluated locally instead of pushed down.
    upto = s.step.upto
    if upto:
        local_filter = s.where.filter if s.where else None
        filter_bytes = None
    columns = [c.name() for c in yield_cols]
    rows: List[Tuple] = []
    frontier = starts
    roots: Dict[int, Set[int]] = {v: {v} for v in starts}
    for step_no in range(1, s.step.steps + 1):
        final = step_no == s.step.steps
        emit = upto or final
        if emit:
            resp = ctx.client.get_neighbors(space, frontier, edge_types,
                                            vertex_props=vertex_props,
                                            filter_bytes=filter_bytes)
            bad = [r for r in resp.results.values()
                   if r.code != ErrorCode.SUCCEEDED]
            if bad:
                return _err(bad[0].code, "storage error during GO")
            st = _emit_go_rows(ctx, resp, rows, yield_cols, local_filter,
                               alias_map, name_by_type, roots, input_index,
                               needs_input, needs_dst, input_var=input_var)
            if not st.ok():
                return StatusOr.from_status(st)
        else:
            resp = ctx.client.get_neighbors(space, frontier, edge_types,
                                            edge_props=[])
            bad = [r for r in resp.results.values()
                   if r.code != ErrorCode.SUCCEEDED]
            if bad:
                return _err(bad[0].code,
                            f"storage error during GO step {step_no}")
        if final:
            break
        next_roots: Dict[int, Set[int]] = {}
        seen: Set[int] = set()
        nxt: List[int] = []
        for v in resp.vertices:
            for e in v.edges:
                if e.dst not in seen:
                    seen.add(e.dst)
                    nxt.append(e.dst)
                next_roots.setdefault(e.dst, set()).update(roots.get(v.vid, {v.vid}))
        frontier = nxt
        roots = next_roots
        if not frontier:
            break
    result = InterimResult(columns, rows)
    if s.yield_ and s.yield_.distinct:
        result = result.distinct()
    return _ok(result)


def make_tag_default_resolver(sm, space: int):
    """(tag, prop) -> schema default for vertices that don't carry the
    tag (ref: VertexHolder::get → RowReader::getDefaultProp,
    GoExecutor.cpp:1009-1018); raises EvalError when the tag or prop
    doesn't exist in the catalog (GoTest NotExistTagProp)."""
    def resolver(tag: str, prop: str):
        tid = sm.tag_id(space, tag)
        if tid is not None:
            r = sm.tag_schema(space, tid)
            if r.ok():
                v = r.value().default_value(prop)
                if v is not None or r.value().has_field(prop):
                    return v
        raise EvalError(f"{tag}.{prop} not found")
    return resolver


def _emit_go_rows(ctx: ExecContext, resp, rows: List[Tuple],
                  yield_cols: List[ast.YieldColumn],
                  local_filter: Optional[Expression],
                  alias_map: Dict[str, str], name_by_type: Dict[int, str],
                  roots: Dict[int, Set[int]],
                  input_index: Dict[int, List[Dict[str, Any]]],
                  needs_input: bool, needs_dst: bool,
                  input_var: Optional[str] = None) -> Status:
    space = ctx.space_id()
    tag_default = make_tag_default_resolver(ctx.sm, space)
    dst_props: Dict[int, Dict[str, Dict[str, Any]]] = {}
    if needs_dst:
        dsts = sorted({e.dst for v in resp.vertices for e in v.edges})
        dst_props = _fetch_dst_props(ctx, dsts)
    for v in resp.vertices:
        src_named = {(ctx.sm.tag_name(space, tid) or str(tid)): props
                     for tid, props in v.tag_props.items()}
        for e in v.edges:
            edge_name = name_by_type.get(abs(e.etype), str(abs(e.etype)))
            base = dict(src_props=src_named, edge_props=e.props,
                        edge_name=edge_name, alias_map=alias_map,
                        src=e.src, dst=e.dst, rank=e.rank,
                        dst_props=dst_props.get(e.dst, {}),
                        tag_default=tag_default)
            if needs_input:
                in_rows = []
                for root in sorted(roots.get(v.vid, {v.vid})):
                    in_rows.extend(input_index.get(root, []))
                if not in_rows:
                    in_rows = [{}]
            else:
                in_rows = [None]
            for in_row in in_rows:
                # a $var-sourced GO exposes the joined row as BOTH the
                # input row and the named variable ($var.prop yields)
                variables = {input_var: in_row} \
                    if input_var is not None and in_row else None
                ectx = EdgeRowExprContext(input_row=in_row,
                                          variables=variables, **base)
                if local_filter is not None:
                    try:
                        if not local_filter.eval(ectx):
                            continue
                    except EvalError:
                        continue
                try:
                    row = tuple(_eval_yield(c, ectx, edge_name, name_by_type)
                                for c in yield_cols)
                except EvalError as ex:
                    return Status.error(ErrorCode.E_EXECUTION_ERROR, str(ex))
                rows.append(row)
    return Status.OK()


def _default_go_columns(s: ast.GoSentence, ctx: ExecContext) -> List[str]:
    if s.yield_:
        return [c.name() for c in s.yield_.columns]
    if s.over.is_all:
        return ["_dst"]
    return [f"{e.name}._dst" for e in s.over.edges]


def _go_yield_columns(s: ast.GoSentence, ctx: ExecContext,
                      name_by_type: Dict[int, str]) -> List[ast.YieldColumn]:
    if s.yield_:
        return s.yield_.columns
    if s.over.is_all:
        return [ast.YieldColumn(EdgeDstIdExpr(None), "_dst")]
    return [ast.YieldColumn(EdgeDstIdExpr(e.name), f"{e.name}._dst")
            for e in s.over.edges]


def _eval_yield(col: ast.YieldColumn, ectx: EdgeRowExprContext,
                edge_name: str, name_by_type: Dict[int, str]):
    """Default GO columns are per-edge-type; rows of another type get None."""
    e = col.expr
    if isinstance(e, (EdgeDstIdExpr, EdgeSrcIdExpr, EdgeRankExpr)) \
            and e.edge is not None:
        if ectx.alias_map.get(e.edge, e.edge) != ectx.edge_name:
            return None
    return e.eval(ectx)


# ---------------------------------------------------------------------------
# FIND PATH (ref: graph/FindPathExecutor.cpp — bidirectional BFS)
# ---------------------------------------------------------------------------

def execute_find_path(ctx: ExecContext, s: ast.FindPathSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    from_r = resolve_starts(ctx, s.from_)
    to_r = resolve_starts(ctx, s.to)
    if not from_r.ok():
        return StatusOr.from_status(from_r.status)
    if not to_r.ok():
        return StatusOr.from_status(to_r.status)
    over_r = resolve_over(ctx, s.over)
    if not over_r.ok():
        return StatusOr.from_status(over_r.status)
    edge_types, _alias, name_by_type = over_r.value()

    tpu = getattr(ctx.engine, "tpu_engine", None)
    if tpu is not None and tpu.can_serve_path(space, s):
        r = tpu.execute_find_path(ctx, s, from_r.value(), to_r.value(),
                                  edge_types, name_by_type)
        if r is not None:
            return r

    try:
        if s.shortest:
            paths = _shortest_paths(ctx, space, from_r.value(), to_r.value(),
                                    edge_types, s.step.steps, name_by_type)
        else:
            paths = _all_paths(ctx, space, from_r.value(), to_r.value(),
                               edge_types, s.step.steps, name_by_type,
                               noloop=s.noloop)
    except _StorageError as ex:
        return _err(ex.code, "storage error during FIND PATH")
    rows = [(p,) for p in paths]
    return _ok(InterimResult(["_path_"], rows))


class _StorageError(Exception):
    def __init__(self, code: ErrorCode):
        super().__init__(code.name)
        self.code = code


def _expand(ctx: ExecContext, space: int, frontier: List[int],
            edge_types: List[int]) -> Dict[int, List[Tuple[int, int, int]]]:
    """-> dst -> [(src, etype, rank)] adjacency discovered this hop.
    Raises _StorageError on any partition failure (a silent partial
    frontier would mean wrong 'no path' answers)."""
    resp = ctx.client.get_neighbors(space, frontier, edge_types, edge_props=[])
    for r in resp.results.values():
        if r.code != ErrorCode.SUCCEEDED:
            raise _StorageError(r.code)
    out: Dict[int, List[Tuple[int, int, int]]] = {}
    for v in resp.vertices:
        for e in v.edges:
            out.setdefault(e.dst, []).append((v.vid, e.etype, e.rank))
    return out


def _format_path(vids: List[int], steps: List[Tuple[int, int]],
                 name_by_type: Dict[int, str]) -> str:
    """1<like,0>2<like,0>3 — vid (edge,rank) alternation."""
    out = [str(vids[0])]
    for (et, rank), vid in zip(steps, vids[1:]):
        name = name_by_type.get(abs(et), str(abs(et)))
        out.append(f"<{name},{rank}>{vid}")
    return "".join(out)


def _shortest_paths(ctx: ExecContext, space: int, sources: List[int],
                    targets: List[int], edge_types: List[int], upto: int,
                    name_by_type: Dict[int, str], expand_fn=None) -> List[str]:
    """Bidirectional BFS, halved depth per side (ref: FindPathExecutor
    :155 `steps = ceil(k/2)`, odd/even meets :233-279).

    expand_fn(frontier, types) -> {dst: [(src, etype, rank)]}: optional
    adjacency source — the TPU engine's pull mode passes a snapshot-
    mirror expansion so small path queries skip both the storage RPC
    fan-out AND the dense device sweep."""
    if expand_fn is None:
        expand_fn = lambda f, t: _expand(ctx, space, f, t)  # noqa: E731
    if not sources or not targets:
        return []
    # paths_f[v] = list of (vids, steps) shortest prefixes from a source
    paths_f: Dict[int, List[Tuple[tuple, tuple]]] = \
        {v: [((v,), ())] for v in sources}
    paths_t: Dict[int, List[Tuple[tuple, tuple]]] = \
        {v: [((v,), ())] for v in targets}
    found: List[str] = []
    meets = set(paths_f) & set(paths_t)
    if meets:
        return sorted({_format_path(list(pf[0]), list(pf[1]), name_by_type)
                       for m in meets for pf in paths_f[m]})
    frontier_f, frontier_t = list(sources), list(targets)
    visited_f, visited_t = set(sources), set(targets)
    # reversed edge types for the target-side expansion (ref :186-198)
    rev_types = [-t for t in edge_types]
    for depth in range(upto):
        expand_from_f = len(frontier_f) <= len(frontier_t)
        if expand_from_f:
            adj = expand_fn(frontier_f, edge_types)
            nxt: Dict[int, List[Tuple[tuple, tuple]]] = {}
            for dst, incomings in adj.items():
                if dst in visited_f:
                    continue
                acc = []
                for (src, et, rank) in incomings:
                    for vids, steps in paths_f.get(src, []):
                        acc.append((vids + (dst,), steps + ((et, rank),)))
                if acc:
                    nxt[dst] = acc
            for dst, acc in nxt.items():
                paths_f[dst] = acc
            visited_f |= set(nxt)
            frontier_f = list(nxt)
        else:
            adj = expand_fn(frontier_t, rev_types)
            nxt = {}
            for dst, incomings in adj.items():
                if dst in visited_t:
                    continue
                acc = []
                for (src, et, rank) in incomings:
                    # src here is on the target side; the real edge runs
                    # dst -> src with type -et
                    for vids, steps in paths_t.get(src, []):
                        acc.append(((dst,) + vids, ((-et, rank),) + steps))
                if acc:
                    nxt[dst] = acc
            for dst, acc in nxt.items():
                paths_t[dst] = acc
            visited_t |= set(nxt)
            frontier_t = list(nxt)
        meets = (set(frontier_f) if expand_from_f else visited_f) & \
                (set(frontier_t) if not expand_from_f else visited_t)
        if meets:
            for m in meets:
                for vids_f, steps_f in paths_f.get(m, []):
                    for vids_t, steps_t in paths_t.get(m, []):
                        vids = list(vids_f) + list(vids_t[1:])
                        steps = list(steps_f) + list(steps_t)
                        found.append(_format_path(vids, steps, name_by_type))
            return sorted(set(found))
        if not frontier_f and not frontier_t:
            break
    return []


def _all_paths(ctx: ExecContext, space: int, sources: List[int],
               targets: List[int], edge_types: List[int], upto: int,
               name_by_type: Dict[int, str], noloop: bool = False,
               max_paths: int = 10000, expand_fn=None) -> List[str]:
    """ALL/NOLOOP PATH: iterative-deepening DFS over batched expansions.

    expand_fn(frontier, depth) -> {src: [(dst, etype, rank)]}: optional
    adjacency source — the TPU engine passes per-level device masks so
    the SAME enumeration loop runs over on-chip expansions (superset
    adjacency is fine; only path-end lookups are consulted)."""
    targets_set = set(targets)
    found: List[str] = []
    # BFS by levels, keeping every path (exponential — capped)
    level: List[Tuple[tuple, tuple]] = [((v,), ()) for v in sources]
    for v in sources:
        if v in targets_set:
            found.append(_format_path([v], [], name_by_type))
    for depth in range(upto):
        frontier = sorted({p[0][-1] for p in level})
        if not frontier:
            break
        if expand_fn is not None:
            by_src = expand_fn(frontier, depth)
        else:
            adj = _expand(ctx, space, frontier, edge_types)
            # index by src so each path extends in O(out-degree)
            by_src = {}
            for dst, incomings in adj.items():
                for (s_, et, rank) in incomings:
                    by_src.setdefault(s_, []).append((dst, et, rank))
        nxt: List[Tuple[tuple, tuple]] = []
        for vids, steps in level:
            for (dst, et, rank) in by_src.get(vids[-1], ()):
                if noloop and dst in vids:
                    continue
                cand = (vids + (dst,), steps + ((et, rank),))
                if dst in targets_set:
                    found.append(_format_path(list(cand[0]),
                                              list(cand[1]), name_by_type))
                    if len(found) >= max_paths:
                        return sorted(set(found))
                nxt.append(cand)
        level = nxt[:max_paths]
    return sorted(set(found))


# ---------------------------------------------------------------------------
# LOOKUP (ref: graph/LookupExecutor.cpp — index-backed property search)
# ---------------------------------------------------------------------------

_FLIP_OP = {"==": "==", "!=": "!=", "<": ">", "<=": ">=",
            ">": "<", ">=": "<="}


def _lookup_simple_condition(s: ast.LookupSentence
                             ) -> Optional[Tuple[str, str, Any]]:
    """WHERE as a single `prop OP literal` comparison (either
    orientation) -> (prop, op, value); None = richer filter, the CPU
    scan evaluates the full expression tree per row."""
    if s.where is None:
        return None
    f = s.where.filter
    if not isinstance(f, RelationalExpr):
        return None
    left, right, op = f.left, f.right, f.op
    if isinstance(left, Literal) and isinstance(right, EdgePropExpr):
        left, right = right, left
        op = _FLIP_OP.get(op)
    if op is None or not isinstance(left, EdgePropExpr) or \
            not isinstance(right, Literal):
        return None
    if left.edge not in (None, s.on_name):
        return None
    v = right.value
    if v is None:
        return None
    return (left.prop, op, v)


def _plain_yield_props(yield_cols: List[ast.YieldColumn], on_name: str
                       ) -> Optional[List[Tuple[str, str]]]:
    """YIELD columns as plain (column name, prop name) refs of the
    scanned schema — the only shape the device materializer serves;
    anything richer returns None and the CPU twin evaluates."""
    out: List[Tuple[str, str]] = []
    for c in yield_cols:
        e = c.expr
        if c.agg_fun or not isinstance(e, EdgePropExpr) or \
                e.edge not in (None, on_name):
            return None
        out.append((c.name(), e.prop))
    return out


def _lookup_yield_eval(yield_cols: List[ast.YieldColumn], on_name: str,
                       props: Dict[str, Any], src: int = 0, dst: int = 0,
                       rank: int = 0) -> List[Any]:
    """Evaluate YIELD exprs against one matched row. Prop refs bind to
    the scanned schema's row (bare `prop` or `schema.prop`); a ref the
    row can't satisfy yields NULL — the filter already decided
    membership, a missing yield cell must not fail the query."""
    ectx = EdgeRowExprContext(src_props={}, edge_props=props,
                              edge_name=on_name,
                              alias_map={on_name: on_name},
                              src=src, dst=dst, rank=rank)
    out: List[Any] = []
    for c in yield_cols:
        try:
            out.append(c.expr.eval(ectx))
        except EvalError:
            out.append(None)
    return out


def execute_lookup(ctx: ExecContext, s: ast.LookupSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    tag_id = ctx.sm.tag_id(space, s.on_name)
    is_edge = tag_id is None
    schema_id = tag_id
    if is_edge:
        schema_id = ctx.sm.edge_type(space, s.on_name)
        if schema_id is None:
            return _err(ErrorCode.E_TAG_NOT_FOUND, s.on_name)
    # LOOKUP is the index-backed verb: the catalog must hold an index
    # on the schema (ref: LookupExecutor checks IndexManager first) —
    # which ENGINE serves the search is a routing decision below
    specs = [d for d in ctx.sm.list_indexes(space)
             if bool(d.get("is_edge")) == is_edge
             and d.get("schema_id") == schema_id]
    if not specs:
        return _err(ErrorCode.E_INDEX_NOT_FOUND,
                    f"no index on {'edge' if is_edge else 'tag'} "
                    f"{s.on_name}")
    yield_cols = list(s.yield_.columns) if s.yield_ else []

    # TPU offload seam (tag form): single prop-OP-literal WHERE over an
    # index whose leading field is that prop, plain prop-ref yields.
    # None = declined -> the storaged CPU scan twin serves.
    tpu = getattr(ctx.engine, "tpu_engine", None)
    cond = _lookup_simple_condition(s)
    if tpu is not None and not is_edge and cond is not None and \
            tpu.can_serve_lookup(space):
        prop, op, value = cond
        yp = _plain_yield_props(yield_cols, s.on_name)
        if yp is not None and \
                any((d.get("fields") or [None])[0] == prop for d in specs):
            r = tpu.execute_lookup(ctx, schema_id, prop, op, value, yp)
            if r is not None:
                return r

    filter_bytes = encode_expression(s.where.filter) if s.where else None
    resp = ctx.client.lookup_scan(space, is_edge, schema_id, filter_bytes)
    bad = [r for r in resp.results.values()
           if r.code != ErrorCode.SUCCEEDED]
    if bad:
        return _err(bad[0].code, "storage error during LOOKUP")
    if is_edge:
        columns = ["SrcVID", "Ranking", "DstVID"] + \
            [c.name() for c in yield_cols]
        rows = []
        for r in sorted(resp.rows, key=lambda r: (r.src, r.rank, r.dst)):
            rows.append([r.src, r.rank, r.dst] +
                        _lookup_yield_eval(yield_cols, s.on_name, r.props,
                                           r.src, r.dst, r.rank))
    else:
        columns = ["VertexID"] + [c.name() for c in yield_cols]
        rows = []
        for r in sorted(resp.rows, key=lambda r: r.vid):
            rows.append([r.vid] +
                        _lookup_yield_eval(yield_cols, s.on_name, r.props))
    result = InterimResult(columns, rows)
    if s.yield_ and s.yield_.distinct:
        result = result.distinct()
    return _ok(result)


# ---------------------------------------------------------------------------
# GET SUBGRAPH (ref: graph/GetSubgraphExecutor — bounded expansion with
# edge capture)
# ---------------------------------------------------------------------------

_SUBGRAPH_COLUMNS = ["Step", "SrcVID", "EdgeName", "Ranking", "DstVID"]


def execute_subgraph(ctx: ExecContext, s: ast.GetSubgraphSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    starts_r = resolve_starts(ctx, s.from_)
    if not starts_r.ok():
        return StatusOr.from_status(starts_r.status)
    starts = starts_r.value()
    if not starts:
        return _ok(InterimResult(list(_SUBGRAPH_COLUMNS)))
    over_r = resolve_over(ctx, s.over)
    if not over_r.ok():
        return StatusOr.from_status(over_r.status)
    edge_types, _, name_by_type = over_r.value()
    if not edge_types:
        return _err(ErrorCode.E_EDGE_NOT_FOUND, "no edges in OVER clause")
    steps = max(1, int(s.step.steps))
    # one SIGNED type->name map shared by both engines (in-edge slots
    # carry the negated type; the emitted EdgeName stays the plain name)
    signed_names = {et: name_by_type[abs(et)] for et in edge_types
                    if abs(et) in name_by_type}

    # TPU offload seam: per-step fused window masks over the resident
    # kernel (traverse.multi_hop_steps / the meshed twin)
    tpu = getattr(ctx.engine, "tpu_engine", None)
    if tpu is not None and tpu.can_serve_subgraph(space, steps):
        r = tpu.execute_subgraph(ctx, steps, starts, edge_types,
                                 signed_names)
        if r is not None:
            return r

    # CPU twin: plain frontier advance, NO cross-step visited set —
    # the device masks re-activate edges reachable again at a later
    # step, and the twin must capture the identical row set
    rows: List[Tuple[int, int, str, int, int]] = []
    frontier = starts
    for step_no in range(1, steps + 1):
        resp = ctx.client.get_neighbors(space, frontier, edge_types,
                                        edge_props=[])
        bad = [r for r in resp.results.values()
               if r.code != ErrorCode.SUCCEEDED]
        if bad:
            return _err(bad[0].code,
                        f"storage error during SUBGRAPH step {step_no}")
        nxt: Set[int] = set()
        for v in resp.vertices:
            for e in v.edges:
                name = signed_names.get(e.etype)
                if name is None:
                    continue
                rows.append((step_no, v.vid, name, e.rank, e.dst))
                nxt.add(e.dst)
        frontier = sorted(nxt)
        if not frontier:
            break
    rows.sort()
    return _ok(InterimResult(list(_SUBGRAPH_COLUMNS),
                             [list(t) for t in rows]))


# ---------------------------------------------------------------------------
# MATCH subset: (a:tag {prop: v})-[e*m..n]->(b) RETURN ... lowered onto
# a LOOKUP-seeded GO plan (ref: the reference stubs MatchExecutor
# entirely; this serves the pattern shape the parser recognizes and
# keeps the raw fallback on the reference's 'not supported' answer)
# ---------------------------------------------------------------------------

def _match_seed_rows(ctx: ExecContext, tag_name: str, tag_id: int,
                     prop: str, value, a_props: List[str]
                     ) -> StatusOr[List[List[Any]]]:
    """Equality-matched seeds for the pattern's source node, each row
    [vid, *a_props values], sorted by vid — the LOOKUP stage of the
    MATCH plan (device index search when it accepts, CPU scan twin
    otherwise)."""
    space = ctx.space_id()
    tpu = getattr(ctx.engine, "tpu_engine", None)
    if tpu is not None and tpu.can_serve_lookup(space):
        r = tpu.execute_lookup(ctx, tag_id, prop, "==", value,
                               [(p, p) for p in a_props])
        if r is not None:
            if not r.ok():
                return StatusOr.from_status(r.status)
            return StatusOr.of([list(row) for row in r.value().rows])
    flt = RelationalExpr("==", EdgePropExpr(None, prop), Literal(value))
    resp = ctx.client.lookup_scan(space, False, tag_id,
                                  encode_expression(flt))
    bad = [pr for pr in resp.results.values()
           if pr.code != ErrorCode.SUCCEEDED]
    if bad:
        return StatusOr.err(bad[0].code, "storage error during MATCH seed")
    rows = [[r.vid] + [r.props.get(p) for p in a_props]
            for r in sorted(resp.rows, key=lambda r: r.vid)]
    return StatusOr.of(rows)


def execute_match(ctx: ExecContext, s: ast.MatchSentence) -> Result:
    if s.pattern is None or s.return_ is None:
        return _err(ErrorCode.E_UNSUPPORTED,
                    "MATCH is supported only as (a:tag {prop: value})"
                    "-[e[:name][*m..n]]->(b) RETURN ...")
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    p = s.pattern
    tag_id = ctx.sm.tag_id(space, p.tag)
    if tag_id is None:
        return _err(ErrorCode.E_TAG_NOT_FOUND, p.tag)
    try:
        value = p.value.eval(RowExprContext())
    except EvalError as ex:
        return _err(ErrorCode.E_EXECUTION_ERROR, str(ex))

    # hop range -> GO step clause: *n..n = GO n STEPS, *1..n = GO UPTO n
    if p.min_hops == p.max_hops:
        step = ast.StepClause(p.max_hops)
    elif p.min_hops == 1:
        step = ast.StepClause(p.max_hops, upto=True)
    else:
        return _err(ErrorCode.E_UNSUPPORTED,
                    f"MATCH hop range *{p.min_hops}..{p.max_hops}: only "
                    "*1..n and *n..n lower onto GO plans")
    over = ast.OverClause(edges=[ast.OverEdge(n) for n in p.edge_names],
                          is_all=not p.edge_names)

    # RETURN analysis: bare aliases and a.prop refs lower; anything
    # else (b.prop needs a second fetch per row, e needs edge identity
    # reconstruction) stays unsupported
    ret: List[Tuple[str, str, Optional[str]]] = []  # (kind, colname, prop)
    a_props: List[str] = []
    for c in s.return_.columns:
        e = c.expr
        if not isinstance(e, EdgePropExpr) or c.agg_fun:
            return _err(ErrorCode.E_UNSUPPORTED,
                        f"MATCH RETURN {c.name()}: only the pattern "
                        "aliases and a.<prop> are supported")
        if e.edge is None and e.prop == p.src_alias:
            ret.append(("a", c.name(), None))
        elif e.edge is None and e.prop == p.dst_alias:
            ret.append(("b", c.name(), None))
        elif e.edge == p.src_alias:
            ret.append(("a_prop", c.name(), e.prop))
            if e.prop not in a_props:
                a_props.append(e.prop)
        else:
            return _err(ErrorCode.E_UNSUPPORTED,
                        f"MATCH RETURN {c.name()}: only the pattern "
                        "aliases and a.<prop> are supported")

    seeds_r = _match_seed_rows(ctx, p.tag, tag_id, p.prop, value, a_props)
    if not seeds_r.ok():
        return StatusOr.from_status(seeds_r.status)
    columns = [name for _, name, _ in ret]
    rows: List[List[Any]] = []
    for seed in seeds_r.value():
        vid = seed[0]
        # per-seed GO: the seed IS `a`, so a / a.prop become literal
        # columns riding the expansion rows (VertexBackTracker without
        # the join — one root per plan)
        go_cols = []
        for kind, name, pr in ret:
            if kind == "b":
                go_cols.append(ast.YieldColumn(EdgeDstIdExpr(None),
                                               alias=name))
            elif kind == "a":
                go_cols.append(ast.YieldColumn(Literal(vid), alias=name))
            else:
                go_cols.append(ast.YieldColumn(
                    Literal(seed[1 + a_props.index(pr)]), alias=name))
        go = ast.GoSentence(step, ast.VertexRef(vids=[Literal(vid)]),
                            over, None, ast.YieldClause(go_cols))
        r = execute_go(ctx, go)
        if not r.ok():
            return r
        if r.value() is not None:
            rows.extend([list(row) for row in r.value().rows])
    return _ok(InterimResult(columns, rows))


# ---------------------------------------------------------------------------
# FETCH (ref: graph/FetchVerticesExecutor.cpp, FetchEdgesExecutor.cpp)
# ---------------------------------------------------------------------------

def execute_fetch_vertices(ctx: ExecContext, s: ast.FetchVerticesSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    # the grammar can't always tell tag-fetch from edge-fetch on $- input;
    # re-dispatch if the name is actually an edge
    if s.tag != "*" and ctx.sm.tag_id(space, s.tag) is None \
            and ctx.sm.edge_type(space, s.tag) is not None:
        return _err(ErrorCode.E_EXECUTION_ERROR,
                    f"FETCH PROP ON edge {s.tag} requires src->dst keys")
    starts_r = resolve_starts(ctx, s.src)
    if not starts_r.ok():
        return StatusOr.from_status(starts_r.status)
    vids = starts_r.value()
    tag_ids = None
    if s.tag != "*":
        tid = ctx.sm.tag_id(space, s.tag)
        if tid is None:
            return _err(ErrorCode.E_TAG_NOT_FOUND, s.tag)
        tag_ids = [tid]
    resp = ctx.client.get_vertex_props(space, vids, tag_ids)

    if s.yield_:
        columns = ["VertexID"] + [c.name() for c in s.yield_.columns]
        rows = []
        for v in resp.vertices:
            named = {(ctx.sm.tag_name(space, tid) or str(tid)): props
                     for tid, props in v.tag_props.items()}
            tctx = TagRowExprContext(named, v.vid)
            try:
                rows.append((v.vid,) + tuple(c.expr.eval(tctx)
                                             for c in s.yield_.columns))
            except EvalError as ex:
                return _err(ErrorCode.E_EXECUTION_ERROR, str(ex))
        res = InterimResult(columns, rows)
        if s.yield_.distinct:
            res = res.distinct()
        return _ok(res)

    # default: all props of the fetched tag(s), schema order
    if tag_ids is not None:
        schema = ctx.sm.tag_schema(space, tag_ids[0]).value()
        columns = ["VertexID"] + [f"{s.tag}.{f.name}" for f in schema.fields]
        rows = []
        for v in resp.vertices:
            props = v.tag_props.get(tag_ids[0], {})
            rows.append((v.vid,) + tuple(props.get(f.name)
                                         for f in schema.fields))
        return _ok(InterimResult(columns, rows))
    # ON *: union of all tags, one column block per tag
    all_tags = ctx.sm.all_tag_ids(space)
    columns = ["VertexID"]
    per_tag_fields: List[Tuple[int, List[str]]] = []
    for tid in all_tags:
        schema = ctx.sm.tag_schema(space, tid).value()
        tname = ctx.sm.tag_name(space, tid) or str(tid)
        per_tag_fields.append((tid, [f.name for f in schema.fields]))
        columns += [f"{tname}.{f.name}" for f in schema.fields]
    rows = []
    for v in resp.vertices:
        row: List[Any] = [v.vid]
        for tid, fields in per_tag_fields:
            props = v.tag_props.get(tid, {})
            row += [props.get(f) for f in fields]
        rows.append(tuple(row))
    return _ok(InterimResult(columns, rows))


def execute_fetch_edges(ctx: ExecContext, s: ast.FetchEdgesSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    et = ctx.sm.edge_type(space, s.edge)
    if et is None:
        return _err(ErrorCode.E_EDGE_NOT_FOUND, s.edge)
    keys: List[EdgeKey] = []
    for k in s.keys or []:
        if any(isinstance(x, (InputPropExpr, VariablePropExpr))
               for x in (k.src, k.dst)):
            # FETCH PROP ON e $-.src->$-.dst / $var.src->$var.dst:
            # one edge key per row of the referenced table (ref
            # FetchEdgesTest.cpp input-ref forms)
            var = None
            for x in (k.src, k.dst):
                if isinstance(x, VariablePropExpr):
                    var = x.var
            res = ctx.variables.get(var) if var else ctx.input
            if res is None or not res.rows:
                continue
            for row in res.rows:
                rd = res.row_dict(row)
                rctx = RowExprContext(None if var else rd,
                                      {var: rd} if var else None)
                try:
                    sv, dv = k.src.eval(rctx), k.dst.eval(rctx)
                except EvalError as ex:
                    return _err(ErrorCode.E_EXECUTION_ERROR, str(ex))
                for v in (sv, dv):
                    if isinstance(v, bool) or not isinstance(v, int):
                        return _err(
                            ErrorCode.E_EXECUTION_ERROR,
                            f"vertex id must be an integer, got {v!r}")
                keys.append(EdgeKey(sv, et, k.rank, dv))
            continue
        sr = eval_vid(ctx, k.src)
        dr = eval_vid(ctx, k.dst)
        if not sr.ok():
            return StatusOr.from_status(sr.status)
        if not dr.ok():
            return StatusOr.from_status(dr.status)
        keys.append(EdgeKey(sr.value(), et, k.rank, dr.value()))
    resp = ctx.client.get_edge_props(space, keys)
    schema = ctx.sm.edge_schema(space, et).value()
    if s.yield_:
        columns = [c.name() for c in s.yield_.columns]
        rows = []
        for e in resp.edges:
            ectx = EdgeRowExprContext(
                src_props={}, edge_props=e.props, edge_name=s.edge,
                alias_map={s.edge: s.edge}, src=e.src, dst=e.dst, rank=e.rank)
            try:
                rows.append(tuple(c.expr.eval(ectx) for c in s.yield_.columns))
            except EvalError as ex:
                return _err(ErrorCode.E_EXECUTION_ERROR, str(ex))
        res = InterimResult(columns, rows)
        if s.yield_.distinct:
            res = res.distinct()
        return _ok(res)
    columns = ([f"{s.edge}._src", f"{s.edge}._dst", f"{s.edge}._rank"]
               + [f"{s.edge}.{f.name}" for f in schema.fields])
    rows = [(e.src, e.dst, e.rank) + tuple(e.props.get(f.name)
                                           for f in schema.fields)
            for e in resp.edges]
    return _ok(InterimResult(columns, rows))


# ---------------------------------------------------------------------------
# INSERT (ref: graph/InsertVertexExecutor.cpp, InsertEdgeExecutor.cpp)
# ---------------------------------------------------------------------------

def execute_insert_vertices(ctx: ExecContext, s: ast.InsertVerticesSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    tag_metas: List[Tuple[int, Schema, List[str]]] = []
    total_props = 0
    for tag_name, props in s.tag_items:
        tid = ctx.sm.tag_id(space, tag_name)
        if tid is None:
            return _err(ErrorCode.E_TAG_NOT_FOUND, tag_name)
        schema = ctx.sm.tag_schema(space, tid).value()
        for p in props:
            if not schema.has_field(p):
                return _err(ErrorCode.E_INVALID_ARGUMENT,
                            f"unknown prop {p!r} on tag {tag_name}")
        tag_metas.append((tid, schema, props))
        total_props += len(props)
    vertices: List[NewVertex] = []
    for vid_expr, values in s.rows:
        if len(values) != total_props:
            return _err(ErrorCode.E_INVALID_ARGUMENT,
                        f"value count {len(values)} != prop count {total_props}")
        vr = eval_vid(ctx, vid_expr)
        if not vr.ok():
            return StatusOr.from_status(vr.status)
        vid = vr.value()
        tags: List[Tuple[int, bytes]] = []
        off = 0
        for tid, schema, props in tag_metas:
            w = RowWriter(schema)
            for p in props:
                try:
                    v = values[off].eval(RowExprContext())
                    w.set(p, v)
                except (EvalError, TypeError) as ex:
                    return _err(ErrorCode.E_INVALID_ARGUMENT, str(ex))
                off += 1
            tags.append((tid, w.encode()))
        vertices.append(NewVertex(vid, tags))
    resp = ctx.client.add_vertices(space, vertices, s.overwritable)
    if not resp.ok():
        return _storage_err(resp, "insert vertices")
    return _ok()


def execute_insert_edges(ctx: ExecContext, s: ast.InsertEdgesSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    et = ctx.sm.edge_type(space, s.edge)
    if et is None:
        return _err(ErrorCode.E_EDGE_NOT_FOUND, s.edge)
    schema = ctx.sm.edge_schema(space, et).value()
    for p in s.props:
        if not schema.has_field(p):
            return _err(ErrorCode.E_INVALID_ARGUMENT,
                        f"unknown prop {p!r} on edge {s.edge}")
    edges: List[NewEdge] = []
    for src_e, dst_e, rank, values in s.rows:
        if len(values) != len(s.props):
            return _err(ErrorCode.E_INVALID_ARGUMENT,
                        f"value count {len(values)} != prop count {len(s.props)}")
        sr = eval_vid(ctx, src_e)
        dr = eval_vid(ctx, dst_e)
        if not sr.ok():
            return StatusOr.from_status(sr.status)
        if not dr.ok():
            return StatusOr.from_status(dr.status)
        w = RowWriter(schema)
        for p, val_e in zip(s.props, values):
            try:
                w.set(p, val_e.eval(RowExprContext()))
            except (EvalError, TypeError) as ex:
                return _err(ErrorCode.E_INVALID_ARGUMENT, str(ex))
        edges.append(NewEdge(sr.value(), et, rank, dr.value(), w.encode()))
    resp = ctx.client.add_edges(space, edges, s.overwritable)
    if not resp.ok():
        return _storage_err(resp, "insert edges")
    return _ok()


# ---------------------------------------------------------------------------
# DELETE / UPDATE
# ---------------------------------------------------------------------------

def execute_delete_vertices(ctx: ExecContext, s: ast.DeleteVerticesSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    starts_r = resolve_starts(ctx, s.src)
    if not starts_r.ok():
        return StatusOr.from_status(starts_r.status)
    resp = ctx.client.delete_vertices(ctx.space_id(), starts_r.value())
    if not resp.ok():
        return _storage_err(resp, "delete vertices")
    return _ok()


def execute_delete_edges(ctx: ExecContext, s: ast.DeleteEdgesSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    et = ctx.sm.edge_type(space, s.edge)
    if et is None:
        return _err(ErrorCode.E_EDGE_NOT_FOUND, s.edge)
    eks = []
    for k in s.keys:
        sr = eval_vid(ctx, k.src)
        dr = eval_vid(ctx, k.dst)
        if not sr.ok():
            return StatusOr.from_status(sr.status)
        if not dr.ok():
            return StatusOr.from_status(dr.status)
        eks.append(EdgeKey(sr.value(), et, k.rank, dr.value()))
    resp = ctx.client.delete_edges(space, eks)
    if not resp.ok():
        return _storage_err(resp, "delete edges")
    return _ok()


def _update_items(items: List[ast.UpdateItem]) -> List[UpdateItemReq]:
    return [UpdateItemReq(i.field_name, encode_expression(i.value))
            for i in items]


def _yield_prop_names(yld: Optional[ast.YieldClause]) -> Optional[List[str]]:
    if yld is None:
        return None
    out = []
    for c in yld.columns:
        e = c.expr
        if isinstance(e, EdgePropExpr):
            out.append(e.prop)
        elif isinstance(e, SourcePropExpr):
            out.append(e.prop)
        else:
            out.append(c.name())
    return out


def execute_update_vertex(ctx: ExecContext, s: ast.UpdateVertexSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    vr = eval_vid(ctx, s.vid)
    if not vr.ok():
        return StatusOr.from_status(vr.status)
    # resolve target tag: explicit, or the unique tag owning the first item
    tag_id = None
    if s.tag:
        tag_id = ctx.sm.tag_id(space, s.tag)
        if tag_id is None:
            return _err(ErrorCode.E_TAG_NOT_FOUND, s.tag)
    else:
        first = s.items[0].field_name.split(".")[-1]
        for tid in ctx.sm.all_tag_ids(space):
            schema = ctx.sm.tag_schema(space, tid).value()
            if schema.has_field(first):
                tag_id = tid
                break
        if tag_id is None:
            return _err(ErrorCode.E_TAG_NOT_FOUND,
                        f"no tag has field {first!r}")
    when = encode_expression(s.when.filter) if s.when else None
    yld = _yield_prop_names(s.yield_)
    resp = ctx.client.update_vertex(space, vr.value(), tag_id,
                                    _update_items(s.items), when,
                                    s.insertable, yld)
    if resp.code != ErrorCode.SUCCEEDED:
        return _err(resp.code, "update vertex failed")
    if yld:
        return _ok(InterimResult([c.name() for c in s.yield_.columns],
                                 [tuple(resp.props.get(p) for p in yld)]))
    return _ok()


def execute_update_edge(ctx: ExecContext, s: ast.UpdateEdgeSentence) -> Result:
    st = ctx.require_space()
    if not st.ok():
        return StatusOr.from_status(st)
    space = ctx.space_id()
    et = ctx.sm.edge_type(space, s.edge)
    if et is None:
        return _err(ErrorCode.E_EDGE_NOT_FOUND, s.edge)
    sr = eval_vid(ctx, s.src)
    dr = eval_vid(ctx, s.dst)
    if not sr.ok():
        return StatusOr.from_status(sr.status)
    if not dr.ok():
        return StatusOr.from_status(dr.status)
    when = encode_expression(s.when.filter) if s.when else None
    yld = _yield_prop_names(s.yield_)
    resp = ctx.client.update_edge(space, EdgeKey(sr.value(), et, s.rank,
                                                 dr.value()),
                                  _update_items(s.items), when,
                                  s.insertable, yld)
    if resp.code != ErrorCode.SUCCEEDED:
        return _err(resp.code, "update edge failed")
    if yld:
        return _ok(InterimResult([c.name() for c in s.yield_.columns],
                                 [tuple(resp.props.get(p) for p in yld)]))
    return _ok()


# ---------------------------------------------------------------------------
# result shaping: YIELD / ORDER BY / LIMIT / GROUP BY (ref: graph/
# YieldExecutor.cpp, OrderByExecutor.cpp, LimitExecutor.cpp, GroupByExecutor.cpp)
# ---------------------------------------------------------------------------

def _expand_star_cols(ctx: ExecContext,
                      cols: List[ast.YieldColumn]) -> List[ast.YieldColumn]:
    """YIELD $-.* / $var.* expands to every column of the referenced
    table (ref YieldTest: `YIELD $var.*`, `$var.* WHERE …`)."""
    out: List[ast.YieldColumn] = []
    for c in cols:
        e = c.expr
        if c.agg_fun is None and isinstance(e, InputPropExpr) \
                and e.prop == "*":
            src = ctx.input
            for name in (src.columns if src is not None else []):
                out.append(ast.YieldColumn(InputPropExpr(name), name))
            continue
        if c.agg_fun is None and isinstance(e, VariablePropExpr) \
                and e.prop == "*":
            src = ctx.variables.get(e.var)
            for name in (src.columns if src is not None else []):
                out.append(ast.YieldColumn(
                    VariablePropExpr(e.var, name), name))
            continue
        out.append(c)
    return out


def execute_yield(ctx: ExecContext, s: ast.YieldSentence) -> Result:
    cols = _expand_star_cols(ctx, s.yield_.columns)
    agg = [c for c in cols if c.agg_fun]
    if ctx.input is None:
        # a standalone YIELD referencing ONE variable iterates that
        # variable's rows (ref YieldTest yieldVar: `$var = GO …; YIELD
        # $var.team` emits one row per var row)
        exprs = [c.expr for c in cols]
        if s.where:
            exprs.append(s.where.filter)
        vars_used = {n.var for e in exprs for n in e.walk()
                     if isinstance(n, VariablePropExpr)}
        if len(vars_used) == 1:
            res = ctx.variables.get(next(iter(vars_used)))
            if res is not None:
                var = next(iter(vars_used))
                rows = []
                for r in res.rows:
                    rctx = RowExprContext(None, {var: res.row_dict(r)})
                    if s.where:
                        try:
                            if not s.where.filter.eval(rctx):
                                continue
                        except EvalError:
                            continue
                    try:
                        rows.append(tuple(c.expr.eval(rctx)
                                          for c in cols))
                    except EvalError as ex:
                        return _err(ErrorCode.E_EXECUTION_ERROR, str(ex))
                if agg:
                    return _aggregate_rows(list(cols), rows)
                out = InterimResult([c.name() for c in cols], rows)
                if s.yield_.distinct:
                    out = out.distinct()
                return _ok(out)
        elif len(vars_used) > 1:
            return _err(ErrorCode.E_EXECUTION_ERROR,
                        "a YIELD may reference only one variable table")
    if ctx.input is not None:
        rows = []
        for r in ctx.input.rows:
            rctx = RowExprContext(ctx.input.row_dict(r),
                                  {v: res.row_dict(res.rows[0])
                                   for v, res in ctx.variables.items() if res.rows})
            if s.where:
                try:
                    if not s.where.filter.eval(rctx):
                        continue
                except EvalError:
                    continue
            try:
                rows.append(tuple(c.expr.eval(rctx) for c in cols))
            except EvalError as ex:
                return _err(ErrorCode.E_EXECUTION_ERROR, str(ex))
        if agg:
            # aggregate over the whole input (GROUP BY () semantics)
            return _aggregate_rows([c for c in cols], rows)
        res = InterimResult([c.name() for c in cols], rows)
        if s.yield_.distinct:
            res = res.distinct()
        return _ok(res)
    # constant yield
    rctx = RowExprContext(None, {v: res.row_dict(res.rows[0])
                                 for v, res in ctx.variables.items() if res.rows})
    if s.where:
        try:
            if not s.where.filter.eval(rctx):
                return _ok(InterimResult([c.name() for c in cols]))
        except EvalError as ex:
            return _err(ErrorCode.E_EXECUTION_ERROR, str(ex))
    try:
        row = tuple(c.expr.eval(rctx) for c in cols)
    except EvalError as ex:
        return _err(ErrorCode.E_EXECUTION_ERROR, str(ex))
    return _ok(InterimResult([c.name() for c in cols], [row]))


def execute_order_by(ctx: ExecContext, s: ast.OrderBySentence) -> Result:
    if ctx.input is None:
        return _ok(None)
    factors = []
    for f in s.factors:
        e = f.expr
        if isinstance(e, InputPropExpr):
            name = e.prop
        else:
            name = e.to_string()
        if not ctx.input.has_col(name):
            return _err(ErrorCode.E_EXECUTION_ERROR,
                        f"ORDER BY column {name!r} not found")
        factors.append((name, f.ascending))
    return _ok(ctx.input.order_by(factors))


def execute_limit(ctx: ExecContext, s: ast.LimitSentence) -> Result:
    if ctx.input is None:
        return _ok(None)
    return _ok(ctx.input.limit(s.count, s.offset))


_AGG_INIT: Dict[str, Any] = {}


def _agg_apply(fun: str, values: List[Any]):
    vals = [v for v in values if v is not None]
    if fun == "COUNT":
        return len(values)
    if fun == "COUNT_DISTINCT":
        return len(set(vals))
    if not vals:
        return None
    if fun == "SUM":
        return sum(vals)
    if fun == "AVG":
        return sum(vals) / len(vals)
    if fun == "MAX":
        return max(vals)
    if fun == "MIN":
        return min(vals)
    if fun == "STD":
        return statistics.pstdev(vals)
    if fun == "BIT_AND":
        out = vals[0]
        for v in vals[1:]:
            out &= v
        return out
    if fun == "BIT_OR":
        out = vals[0]
        for v in vals[1:]:
            out |= v
        return out
    if fun == "BIT_XOR":
        out = vals[0]
        for v in vals[1:]:
            out ^= v
        return out
    if fun == "COLLECT":
        return list(vals)
    raise EvalError(f"unknown aggregate {fun}")


def _aggregate_rows(cols: List[ast.YieldColumn], rows: List[Tuple]) -> Result:
    out_row = []
    for i, c in enumerate(cols):
        vals = [r[i] for r in rows]
        if c.agg_fun:
            out_row.append(_agg_apply(c.agg_fun, vals))
        else:
            out_row.append(vals[0] if vals else None)
    return _ok(InterimResult([c.name() for c in cols], [tuple(out_row)]))


# aggregates the device reduction path serves exactly (aggregate.py's
# int-exact surface); the rest (STD, BIT_*, COLLECT, COUNT_DISTINCT)
# stay on the CPU pipe
_DEVICE_AGGS = {"COUNT", "SUM", "AVG", "MIN", "MAX"}


def try_device_aggregate(ctx: ExecContext, pipe) -> Optional[Result]:
    """`GO … | YIELD <aggregates only>` served as a masked device
    reduction (bound_stats role on TPU — ref storage.thrift StatType
    :65-69; math in engine_tpu/aggregate.py). Returns the one-row
    Result, or None to run the generic pipe. Every pattern gate keeps
    CPU≡TPU identity: anything outside the exact surface (mixed
    agg/non-agg yields, DISTINCT, WHERE on the yield, input-ref GOs,
    non-edge-prop aggregate args) falls through."""
    tpu = getattr(ctx.engine, "tpu_engine", None)
    if tpu is None or not isinstance(pipe.left, ast.GoSentence):
        return None
    s, y = pipe.left, pipe.right
    group_key = None
    if isinstance(y, ast.GroupBySentence):
        # GROUP BY $-.<one col> — segment reduction keyed by dst slot
        if len(y.group_cols) != 1 or y.yield_.distinct:
            return None
        gk = y.group_cols[0].expr
        if not isinstance(gk, InputPropExpr):
            return None
        group_key = gk.prop
        cols = y.yield_.columns
        if not cols:
            return None
        for c in cols:
            ok = (c.agg_fun in _DEVICE_AGGS) or (
                c.agg_fun is None and isinstance(c.expr, InputPropExpr)
                and c.expr.prop == group_key)
            if not ok:
                return None
    elif isinstance(y, ast.YieldSentence):
        if y.where is not None or y.yield_ is None or y.yield_.distinct:
            return None
        cols = y.yield_.columns
        if not cols or not all(c.agg_fun in _DEVICE_AGGS for c in cols):
            return None
    else:
        return None
    if s.step.upto or int(s.step.steps) < 1 or \
            (s.yield_ and s.yield_.distinct):
        return None
    if not ctx.require_space().ok():
        return None
    space = ctx.space_id()
    if not tpu.can_serve(space, s):
        return None
    starts_r = resolve_starts(ctx, s.from_)
    if not starts_r.ok() or not starts_r.value():
        return None
    over_r = resolve_over(ctx, s.over)
    if not over_r.ok() or not over_r.value()[0]:
        return None
    edge_types, alias_map, name_by_type = over_r.value()
    left_cols = _go_yield_columns(s, ctx, name_by_type)
    left_exprs = [c.expr for c in left_cols]
    if s.where:
        left_exprs.append(s.where.filter)
    _, _, needs_input = _collect_prop_requirements(left_exprs, ctx)
    if needs_input:
        return None    # per-root attribution: CPU loop
    by_name = {c.name(): c.expr for c in left_cols}
    if group_key is not None:
        # the key must be a left column carrying the edge's dst id —
        # that's the slot the device reduction segments by. A NAMED
        # qualifier (serve._dst) must cover every traversed type: the
        # CPU yields None for <edge>._dst on rows of OTHER types
        # (a None-keyed group) which the slot keying can't express
        kexpr = by_name.get(group_key)
        if not isinstance(kexpr, EdgeDstIdExpr):
            return None
        if kexpr.edge is not None:
            canon = alias_map.get(kexpr.edge, kexpr.edge)
            if any(name_by_type.get(abs(t)) != canon
                   for t in edge_types):
                return None
    specs = []
    layout = []    # grouped: per-output-cell "key" | spec index
    for c in cols:
        e = c.expr
        if c.agg_fun is None:     # grouped only: the key column
            layout.append("key")
            continue
        if c.agg_fun == "COUNT":
            # COUNT(*) parses as Literal(1); COUNT($-.x) counts every
            # row (nulls included) as long as the column exists
            if isinstance(e, Literal) or (
                    isinstance(e, InputPropExpr) and e.prop in by_name):
                layout.append(len(specs))
                specs.append(("COUNT", None))
                continue
            return None
        if not isinstance(e, InputPropExpr):
            return None
        src = by_name.get(e.prop)
        if not isinstance(src, EdgePropExpr) or src.prop.startswith("_"):
            return None
        layout.append(len(specs))
        specs.append((c.agg_fun, src))
    return tpu.execute_go_aggregate(
        ctx, s, specs, [c.name() for c in cols], starts_r.value(),
        edge_types, alias_map, name_by_type,
        group_layout=layout if group_key is not None else None)


def execute_group_by(ctx: ExecContext, s: ast.GroupBySentence) -> Result:
    if ctx.input is None:
        return _ok(None)
    groups: Dict[Tuple, List[Tuple]] = {}
    # evaluate group keys + yield inputs per row
    yield_cols = s.yield_.columns
    # a bare-name group key may reference one of the yield's OWN output
    # aliases (ref GroupByExecutor: `GROUP BY teamName YIELD $-.name AS
    # teamName, …` groups by the aliased expression,
    # GroupByLimitTest.cpp:308-318); unknown names stay errors
    alias_exprs = {c.name(): c.expr for c in yield_cols
                   if not c.agg_fun}
    key_exprs = []
    for c in s.group_cols:
        e = c.expr
        if isinstance(e, EdgePropExpr) and e.edge is None \
                and e.prop in alias_exprs:
            e = alias_exprs[e.prop]
        key_exprs.append(e)
    for r in ctx.input.rows:
        rctx = RowExprContext(ctx.input.row_dict(r))
        try:
            key = tuple(e.eval(rctx) for e in key_exprs)
            vals = tuple(c.expr.eval(rctx) for c in yield_cols)
        except EvalError as ex:
            return _err(ErrorCode.E_EXECUTION_ERROR, str(ex))
        groups.setdefault(key, []).append(vals)
    columns = [c.name() for c in yield_cols]
    rows = []
    for key, grp in groups.items():
        row = []
        for i, c in enumerate(yield_cols):
            vals = [g[i] for g in grp]
            if c.agg_fun:
                row.append(_agg_apply(c.agg_fun, vals))
            else:
                row.append(vals[0])
        rows.append(tuple(row))
    return _ok(InterimResult(columns, rows))


# ---------------------------------------------------------------------------
# set ops (ref: graph/SetExecutor.cpp)
# ---------------------------------------------------------------------------

def execute_set_op(ctx: ExecContext, s: ast.SetSentence, run) -> Result:
    lr = run(ctx, s.left)
    if not lr.ok():
        return lr
    rr = run(ctx, s.right)
    if not rr.ok():
        return rr
    left, right = lr.value(), rr.value()
    if left is None or right is None:
        return _err(ErrorCode.E_EXECUTION_ERROR, "set operand yields no table")
    if len(left.columns) != len(right.columns):
        return _err(ErrorCode.E_EXECUTION_ERROR,
                    "set operands have different column counts")
    if s.op == ast.SetOp.UNION:
        return _ok(left.union(right, distinct=False))
    if s.op == ast.SetOp.UNION_DISTINCT:
        return _ok(left.union(right, distinct=True))
    if s.op == ast.SetOp.INTERSECT:
        return _ok(left.intersect(right))
    return _ok(left.minus(right))
