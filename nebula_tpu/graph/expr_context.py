"""Expression contexts for query-engine-side evaluation.

Role parity with the reference's getter-closure binding in
`graph/GoExecutor.cpp:849-945` (expression getters bound to RPC row
readers) — here bound to the decoded BoundResponse structures.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..filter.expressions import EvalError, ExpressionContext


class RowExprContext(ExpressionContext):
    """Binds $- / $var to one row of an InterimResult."""

    def __init__(self, input_row: Optional[Dict[str, Any]] = None,
                 variables: Optional[Dict[str, Dict[str, Any]]] = None):
        self.input_row = input_row or {}
        self.variables = variables or {}

    def get_input_prop(self, prop: str):
        if prop not in self.input_row:
            raise EvalError(f"$-.{prop} not found")
        return self.input_row[prop]

    def get_variable_prop(self, var: str, prop: str):
        row = self.variables.get(var)
        if row is None or prop not in row:
            raise EvalError(f"${var}.{prop} not found")
        return row[prop]


class EdgeRowExprContext(RowExprContext):
    """Full GO-row context: one edge + its endpoints + back-refs."""

    def __init__(self, *, src_props: Dict[str, Dict[str, Any]],
                 edge_props: Dict[str, Any], edge_name: str,
                 alias_map: Dict[str, str],
                 src: int, dst: int, rank: int,
                 dst_props: Optional[Dict[str, Dict[str, Any]]] = None,
                 input_row: Optional[Dict[str, Any]] = None,
                 variables: Optional[Dict[str, Dict[str, Any]]] = None,
                 tag_default=None):
        super().__init__(input_row, variables)
        self.src_props = src_props          # tag name -> props
        self.edge_props = edge_props
        self.edge_name = edge_name          # canonical name of this row's edge
        self.alias_map = alias_map          # alias/name -> canonical name
        self.src = src
        self.dst = dst
        self.rank = rank
        self.dst_props = dst_props or {}    # tag name -> props (of dst vertex)
        # (tag, prop) -> schema default, or raise EvalError when the
        # tag/prop is unknown. A vertex that doesn't CARRY the tag
        # yields the default (ref: VertexHolder::get falls back to
        # RowReader::getDefaultProp, GoExecutor.cpp:1009-1018) —
        # while an unknown tag/prop is a query error (GoTest
        # NotExistTagProp) and a row whose version lacks the prop
        # stays an error (GoExecutor.cpp:1023). Contexts built without
        # a resolver keep the strict error behavior.
        self._tag_default = tag_default

    def _check_edge(self, edge: Optional[str]) -> bool:
        if edge is None:
            return True
        return self.alias_map.get(edge, edge) == self.edge_name

    def _default_or_raise(self, ref: str, tag: str, prop: str):
        if self._tag_default is None:
            raise EvalError(f"{ref}.{tag}.{prop} not found")
        return self._tag_default(tag, prop)

    def get_src_prop(self, tag: str, prop: str):
        props = self.src_props.get(tag)
        if props is None:
            return self._default_or_raise("$^", tag, prop)
        if prop not in props:
            raise EvalError(f"$^.{tag}.{prop} not found")
        return props[prop]

    def get_dst_prop(self, tag: str, prop: str):
        props = self.dst_props.get(tag)
        if props is None:
            return self._default_or_raise("$$", tag, prop)
        if prop not in props:
            raise EvalError(f"$$.{tag}.{prop} not found")
        return props[prop]

    def get_edge_prop(self, edge: Optional[str], prop: str):
        if not self._check_edge(edge):
            raise EvalError(f"edge {edge} does not match current row")
        if prop not in self.edge_props:
            raise EvalError(f"edge prop {prop} not found")
        return self.edge_props[prop]

    def get_edge_src(self, edge: Optional[str]):
        if not self._check_edge(edge):
            raise EvalError(f"edge {edge} does not match current row")
        return self.src

    def get_edge_dst(self, edge: Optional[str]):
        if not self._check_edge(edge):
            raise EvalError(f"edge {edge} does not match current row")
        return self.dst

    def get_edge_rank(self, edge: Optional[str]):
        if not self._check_edge(edge):
            raise EvalError(f"edge {edge} does not match current row")
        return self.rank

    def get_edge_type_name(self, edge: Optional[str]):
        return self.edge_name


class TagRowExprContext(RowExprContext):
    """FETCH PROP ON tag: props of one vertex, addressed as tag.prop."""

    def __init__(self, tag_props: Dict[str, Dict[str, Any]], vid: int,
                 input_row=None, variables=None):
        super().__init__(input_row, variables)
        self.tag_props = tag_props
        self.vid = vid

    def get_edge_prop(self, edge: Optional[str], prop: str):
        # tag.prop parses as an EdgePropExpr; resolve against tag props
        if edge is not None:
            props = self.tag_props.get(edge)
            if props is None or prop not in props:
                raise EvalError(f"{edge}.{prop} not found")
            return props[prop]
        for props in self.tag_props.values():
            if prop in props:
                return props[prop]
        raise EvalError(f"{prop} not found")

    def get_src_prop(self, tag: str, prop: str):
        return self.get_edge_prop(tag, prop)
