"""InterimResult: the in-memory table flowing between executors.

Role parity with the reference's `graph/InterimResult.{h,cpp}`: the
pipe/variable intermediate representation with column access, vid
extraction for the next traversal step, and a per-vid index for
back-references ($- / $var props). The reference stores encoded rows
(RowSetWriter); we store Python tuples — the RPC boundary uses the
codec, the executor-to-executor hop does not need to.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple


class InterimResult:
    def __init__(self, columns: List[str], rows: Optional[List[Tuple]] = None):
        self.columns = list(columns)
        self.rows: List[Tuple] = rows or []

    # ------------------------------------------------------------------
    def col_index(self, name: str) -> int:
        try:
            return self.columns.index(name)
        except ValueError:
            return -1

    def has_col(self, name: str) -> bool:
        return name in self.columns

    def get_col(self, name: str) -> List[Any]:
        i = self.col_index(name)
        if i < 0:
            raise KeyError(f"no column {name!r} (have {self.columns})")
        return [r[i] for r in self.rows]

    def get_vids(self, name: str) -> List[int]:
        """Distinct int vids of a column, preserving first-seen order
        (ref: InterimResult::getVIDs)."""
        seen = set()
        out = []
        for v in self.get_col(name):
            if isinstance(v, bool) or not isinstance(v, int):
                raise ValueError(f"column {name!r} is not a vid column ({v!r})")
            if v not in seen:
                seen.add(v)
                out.append(v)
        return out

    def row_dict(self, row: Tuple) -> Dict[str, Any]:
        return dict(zip(self.columns, row))

    def build_index(self, name: str) -> Dict[int, List[Tuple]]:
        """vid -> rows (for $- back-references across a traversal step)."""
        i = self.col_index(name)
        if i < 0:
            raise KeyError(name)
        idx: Dict[int, List[Tuple]] = {}
        for r in self.rows:
            idx.setdefault(r[i], []).append(r)
        return idx

    # ------------------------------------------------------------------
    def distinct(self) -> "InterimResult":
        seen = set()
        out = []
        for r in self.rows:
            if r not in seen:
                seen.add(r)
                out.append(r)
        return InterimResult(self.columns, out)

    def union(self, other: "InterimResult", distinct: bool = False) -> "InterimResult":
        res = InterimResult(self.columns, self.rows + other.rows)
        return res.distinct() if distinct else res

    def intersect(self, other: "InterimResult") -> "InterimResult":
        theirs = set(other.rows)
        return InterimResult(self.columns,
                             [r for r in self.rows if r in theirs])

    def minus(self, other: "InterimResult") -> "InterimResult":
        theirs = set(other.rows)
        return InterimResult(self.columns,
                             [r for r in self.rows if r not in theirs])

    def limit(self, count: int, offset: int = 0) -> "InterimResult":
        return InterimResult(self.columns, self.rows[offset:offset + count])

    def order_by(self, factors: Sequence[Tuple[str, bool]]) -> "InterimResult":
        """factors: [(column, ascending)] applied with stable sorts,
        least-significant last-first."""
        rows = list(self.rows)
        for name, asc in reversed(list(factors)):
            i = self.col_index(name)
            if i < 0:
                raise KeyError(name)
            rows.sort(key=lambda r: _sort_key(r[i]), reverse=not asc)
        return InterimResult(self.columns, rows)

    def __len__(self) -> int:
        return len(self.rows)

    def __repr__(self) -> str:
        return f"<InterimResult {self.columns} x {len(self.rows)} rows>"


def _sort_key(v: Any):
    """Total order across mixed types: None < bool < numbers < strings."""
    if v is None:
        return (0, 0)
    if isinstance(v, bool):
        return (1, v)
    if isinstance(v, (int, float)):
        return (2, v)
    return (3, str(v))
