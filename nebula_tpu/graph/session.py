"""Sessions.

Role parity with the reference's `graph/SessionManager.cpp` /
`ClientSession.h`: an authenticated session carries the current space
and user; idle sessions are reclaimed after
`session_idle_timeout_secs` (ref: graph/GraphFlags.cpp:13-15).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, Optional

from ..common.status import ErrorCode, StatusOr

DEFAULT_IDLE_TIMEOUT_SECS = 8 * 3600


class ClientSession:
    def __init__(self, session_id: int, user: str):
        self.session_id = session_id
        self.user = user
        self.space_name: Optional[str] = None
        self.space_id: int = -1
        # QoS lane override (common/qos.py; docs/manual/14-qos.md):
        # "interactive" / "bulk" pins every statement of this session
        # onto that dispatcher lane, beating statement-shape
        # classification; None = classify per statement. Settable
        # through the graphd /qos endpoint (session=<id>:<lane>).
        self.qos_lane: Optional[str] = None
        self._last_access = time.time()

    def charge(self) -> None:
        self._last_access = time.time()

    def idle_secs(self) -> float:
        return time.time() - self._last_access


class SessionManager:
    def __init__(self, idle_timeout_secs: Optional[float] = None):
        self._sessions: Dict[int, ClientSession] = {}
        self._next_id = itertools.count(1)
        self._lock = threading.Lock()
        # explicit override wins; otherwise the MUTABLE
        # `session_idle_timeout_secs` flag is consulted per check, so a
        # hot-set (through /flags or the meta config pull) takes effect
        # without a restart — gflags parity (found by nebula-lint NL003:
        # the flag was declared but this manager hardcoded the default)
        self._idle_timeout_override = idle_timeout_secs

    @property
    def _idle_timeout(self) -> float:
        if self._idle_timeout_override is not None:
            return self._idle_timeout_override
        from ..common.flags import graph_flags
        return graph_flags.get_or("session_idle_timeout_secs",
                                  DEFAULT_IDLE_TIMEOUT_SECS, float)

    def create(self, user: str) -> ClientSession:
        with self._lock:
            sid = next(self._next_id)
            s = ClientSession(sid, user)
            self._sessions[sid] = s
            return s

    def find(self, session_id: int) -> StatusOr[ClientSession]:
        with self._lock:
            s = self._sessions.get(session_id)
            if s is None:
                return StatusOr.err(ErrorCode.E_SESSION_INVALID,
                                    f"session {session_id} not found")
            if s.idle_secs() > self._idle_timeout:
                del self._sessions[session_id]
                return StatusOr.err(ErrorCode.E_SESSION_INVALID,
                                    f"session {session_id} expired")
            s.charge()
            return StatusOr.of(s)

    def remove(self, session_id: int) -> None:
        with self._lock:
            self._sessions.pop(session_id, None)

    def reclaim_expired(self) -> int:
        with self._lock:
            dead = [sid for sid, s in self._sessions.items()
                    if s.idle_secs() > self._idle_timeout]
            for sid in dead:
                del self._sessions[sid]
            return len(dead)

    def count(self) -> int:
        return len(self._sessions)
