from .iface import KVEngine, KVIterator  # noqa: F401
from .memengine import MemEngine  # noqa: F401
from .store import GraphStore, SpaceInfo  # noqa: F401
from .part import Part  # noqa: F401
