from .iface import KVEngine, KVIterator  # noqa: F401
from .memengine import MemEngine  # noqa: F401

def native_engine_factory(data_root=None):
    """Engine factory producing native C++ engines (RocksEngine role);
    falls back to MemEngine when the native toolchain is unavailable."""
    import os
    from .. import native as _native
    if not _native.available():
        return lambda space_id: MemEngine()
    from .nativeengine import NativeEngine
    def factory(space_id):
        path = None
        if data_root:
            os.makedirs(data_root, exist_ok=True)
            path = os.path.join(data_root, f"space_{space_id}.nkv")
        return NativeEngine(path)
    return factory

from .store import GraphStore, SpaceInfo  # noqa: F401
from .part import Part  # noqa: F401
