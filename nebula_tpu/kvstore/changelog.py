"""Engine change log: the committed-write feed for incremental device
snapshots.

Role parity with the reference's in-place apply (`Part::commitLogs`
replays every committed batch into the engine and readers see it
immediately, ref kvstore/Part.cpp:208-319): here the engine ALSO
records each committed batch in a bounded ring, and the TPU engine
pulls the tail to patch its CSR snapshot instead of rebuilding —
SURVEY.md §7 hard-part (a), §2.10 P6's delta-buffer half.

Two layers:

- `ChangeRing` — raw committed ops `(version, op, payload)` recorded at
  the engine choke point (every write path — direct, raft leader AND
  follower apply, snapshot ingest — funnels into the engine's write
  methods). Bounded; `since()` returns None once truncated, which the
  consumer treats as "rebuild".
- `resolve_changes` — turns raw ops into LOGICAL deltas by re-reading
  the engine's CURRENT visible state per touched group. This makes
  application idempotent and immune to op-ordering subtleties: a
  compaction's removal of a superseded version resolves to "edge still
  there, same row", a real DELETE resolves to "gone", racing writes
  resolve to whatever is newest. Runs on the storage side (local
  engine access), so remote consumers receive resolved entries over
  one RPC.

Logical entry shapes (wire-codec friendly tuples):
    ("e", part, src, etype, rank, dst, row_bytes | None)   None = gone
    ("v", part, vid, tag_id, row_bytes | None)
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Iterable, List, Optional, Tuple

from ..common import keys as ku

RawEntry = Tuple[int, str, object]   # (version, op, payload)

OP_PUT = "put"          # payload: List[(key, value)]
OP_RM = "rm"            # payload: List[key]
OP_BARRIER = "barrier"  # payload: None — unresolvable (range/prefix wipe)


class ChangeRing:
    """Bounded ring of committed raw ops, tagged with the engine
    write_version AFTER each op (versions are strictly increasing, one
    per engine call)."""

    def __init__(self, cap_ops: int = 4096, cap_kvs: int = 131072):
        # the write-path observatory's REBOOT-effective cap overrides
        # (change_ring_ops/change_ring_kvs; the write bench shrinks
        # them to force genuine overruns) apply at construction — the
        # ring is born with an engine and lives exactly as long
        from ..common import writepath as _writepath
        self._entries: deque = deque()
        self._lock = threading.Lock()
        self._cap_ops = _writepath.ring_cap_ops(cap_ops)
        self._cap_kvs = _writepath.ring_cap_kvs(cap_kvs)
        self._kvs = 0
        # highest version known to be dropped from the ring; a `since`
        # at or below this can't be served (0 = nothing dropped yet,
        # and version 0 predates every write)
        self._floor = 0
        self._dropped = 0

    def record(self, version: int, op: str, payload) -> None:
        n = len(payload) if isinstance(payload, list) else 1
        with self._lock:
            self._entries.append((version, op, payload))
            self._kvs += n
            while self._entries and (len(self._entries) > self._cap_ops
                                     or self._kvs > self._cap_kvs):
                v, _, p = self._entries.popleft()
                self._kvs -= len(p) if isinstance(p, list) else 1
                self._floor = v
                self._dropped += 1

    def occupancy(self) -> dict:
        """Ring telemetry (write-path observatory gauges/flight
        bundles): live op/kv counts, the truncation floor and how many
        ops have ever been dropped past it."""
        with self._lock:
            return {"ops": len(self._entries), "kvs": self._kvs,
                    "floor": self._floor, "dropped": self._dropped,
                    "cap_ops": self._cap_ops}

    def since(self, version: int) -> Optional[List[RawEntry]]:
        """Entries with version > `version`, oldest first; None when the
        ring no longer reaches back that far (consumer must rebuild)."""
        with self._lock:
            if version < self._floor:
                return None
            return [e for e in self._entries if e[0] > version]


def _group_of(key: bytes):
    """Data-key -> logical group id, or None for non-data kinds
    (system/commit markers, uuid, index)."""
    if ku.is_edge_key(key):
        part, src, etype, rank, dst, _ = ku.parse_edge_key(key)
        return ("e", part, src, etype, rank, dst)
    if ku.is_vertex_key(key):
        part, vid, tag, _ = ku.parse_vertex_key(key)
        return ("v", part, vid, tag)
    return None


def _visible_row(engine, prefix: bytes) -> Optional[bytes]:
    """Current visible row for a version group: versions are decreasing
    (newest sorts first, ref AddVerticesProcessor.cpp:32-35), so the
    first key under the group prefix wins; empty value = tombstone."""
    for _, v in engine.prefix(prefix):
        return v if v else None
    return None


def resolve_changes(engine, raw: Iterable[RawEntry]
                    ) -> Optional[List[tuple]]:
    """Raw ring entries -> logical deltas against CURRENT engine state.
    None = a barrier op was seen (range wipe / part cleanup): rebuild."""
    groups = {}
    for _, op, payload in raw:
        if op == OP_BARRIER:
            return None
        keys = [k for k, _ in payload] if op == OP_PUT else payload
        for k in keys:
            g = _group_of(k)
            if g is not None:
                groups[g] = None
    out: List[tuple] = []
    for g in groups:
        if g[0] == "e":
            _, part, src, etype, rank, dst = g
            row = _visible_row(engine, ku.edge_group_prefix(
                part, src, etype, rank, dst))
            out.append(("e", part, src, etype, rank, dst, row))
        else:
            _, part, vid, tag = g
            row = _visible_row(engine, ku.vertex_prefix(part, vid, tag))
            out.append(("v", part, vid, tag, row))
    return out
