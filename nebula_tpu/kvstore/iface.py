"""KV engine / store interfaces.

Role parity with the reference's `kvstore/KVEngine.h` and
`kvstore/KVStore.h:58-159`: an engine is a single ordered KV namespace
with prefix/range scans and batched writes; a store multiplexes
space→partition→engine and pushes writes through consensus while reads
stay leader-local. The engine seam is the pluggable boundary — the
reference ships RocksEngine + an HBase plugin; we ship a Python
in-memory engine (tests/small), a C++ native engine (`native/`), and
the TPU CSR snapshot consumer hangs off the same seam.
"""
from __future__ import annotations

import abc
from typing import Iterable, Iterator, List, Optional, Tuple

from ..common.status import ErrorCode, Status

KV = Tuple[bytes, bytes]


class KVIterator(abc.ABC):
    """Forward iterator over an ordered key range."""

    @abc.abstractmethod
    def valid(self) -> bool: ...

    @abc.abstractmethod
    def next(self) -> None: ...

    @abc.abstractmethod
    def key(self) -> bytes: ...

    @abc.abstractmethod
    def value(self) -> bytes: ...

    def __iter__(self) -> Iterator[KV]:
        while self.valid():
            yield self.key(), self.value()
            self.next()


class KVEngine(abc.ABC):
    """One ordered KV namespace (one per (space, data-path) like the
    reference's one-RocksDB-per-space-per-path).

    `write_version` is a monotonic mutation counter — the TPU engine
    uses it to detect stale CSR snapshots (the device-side analogue of
    the reference's compaction/version visibility). Engines that keep a
    `changes` ring (kvstore/changelog.py) feed incremental snapshot
    patches through `changes_snapshot`."""

    write_version: int = 0
    changes = None   # Optional[ChangeRing]

    def set_option(self, name: str, value: int) -> Status:
        """Hot-apply an engine tuning knob (ref role:
        RocksEngine::setOption / the nested rocksdb option maps the
        meta config registry pushes, RocksEngineConfig.cpp). Engines
        without tunables accept nothing."""
        return Status.error(f"engine option {name!r} not supported")

    def get_option(self, name: str) -> Optional[int]:
        return None

    def changes_snapshot(self, since: int):
        """(current write_version, raw ring entries since `since` |
        None). The version is read BEFORE the ring pull so the caller's
        cursor never claims coverage of an op it didn't see; writers
        must record their ring entry before publishing the version (or
        override this under their write lock)."""
        if self.changes is None:
            return self.write_version, None
        now_v = int(self.write_version)
        return now_v, self.changes.since(since)

    # --- reads --------------------------------------------------------
    @abc.abstractmethod
    def get(self, key: bytes) -> Optional[bytes]: ...

    def multi_get(self, keys: List[bytes]) -> List[Optional[bytes]]:
        return [self.get(k) for k in keys]

    @abc.abstractmethod
    def prefix(self, prefix: bytes) -> KVIterator: ...

    @abc.abstractmethod
    def range(self, start: bytes, end: bytes) -> KVIterator: ...

    # --- writes -------------------------------------------------------
    @abc.abstractmethod
    def put(self, key: bytes, value: bytes) -> Status: ...

    def multi_put(self, kvs: Iterable[KV]) -> Status:
        for k, v in kvs:
            st = self.put(k, v)
            if not st.ok():
                return st
        return Status.OK()

    @abc.abstractmethod
    def remove(self, key: bytes) -> Status: ...

    def multi_remove(self, keys: Iterable[bytes]) -> Status:
        for k in keys:
            st = self.remove(k)
            if not st.ok():
                return st
        return Status.OK()

    @abc.abstractmethod
    def remove_range(self, start: bytes, end: bytes) -> Status: ...

    def remove_prefix(self, prefix: bytes) -> Status:
        it = self.prefix(prefix)
        dead = [k for k, _ in it]
        return self.multi_remove(dead)

    # --- maintenance --------------------------------------------------
    def ingest(self, kvs: Iterable[KV]) -> Status:
        """Bulk load pre-sorted data (ref: RocksEngine::ingest of SSTs)."""
        return self.multi_put(kvs)

    def compact(self) -> Status:
        return Status.OK()

    def flush(self) -> Status:
        return Status.OK()

    def approximate_size(self) -> int:
        return 0

    def total_keys(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass
