"""Raft/WAL log entry encoding for KV mutations.

Role parity with the reference's `kvstore/LogEncoder.h:14-25`
(OP_PUT, OP_MULTI_PUT, OP_REMOVE, OP_MULTI_REMOVE, OP_REMOVE_RANGE,
OP_ADD_LEARNER, OP_TRANS_LEADER, OP_ADD_PEER, OP_REMOVE_PEER): every
mutation that goes through consensus is first serialized to one log
blob, replicated, then decoded and applied to the engine inside
`Part.commit_logs` as a single batch.
"""
from __future__ import annotations

import struct
from typing import Iterable, List, Tuple, Union

KV = Tuple[bytes, bytes]

OP_PUT = 1
OP_MULTI_PUT = 2
OP_REMOVE = 3
OP_MULTI_REMOVE = 4
OP_REMOVE_RANGE = 5
OP_REMOVE_PREFIX = 6
OP_ADD_LEARNER = 7
OP_TRANS_LEADER = 8
OP_ADD_PEER = 9
OP_REMOVE_PEER = 10

_U32 = struct.Struct("<I")


def _blob(b: bytes) -> bytes:
    return _U32.pack(len(b)) + b


def _read_blob(data: bytes, off: int) -> Tuple[bytes, int]:
    n = _U32.unpack_from(data, off)[0]
    off += 4
    return data[off:off + n], off + n


def encode_single(op: int, key: bytes, value: bytes = b"") -> bytes:
    return bytes([op]) + _blob(key) + (_blob(value) if op == OP_PUT else b"")


def encode_multi_put(kvs: Iterable[KV]) -> bytes:
    out = bytearray([OP_MULTI_PUT])
    cnt = 0
    body = bytearray()
    for k, v in kvs:
        body += _blob(k) + _blob(v)
        cnt += 1
    out += _U32.pack(cnt) + body
    return bytes(out)


def encode_multi_remove(ks: Iterable[bytes]) -> bytes:
    out = bytearray([OP_MULTI_REMOVE])
    cnt = 0
    body = bytearray()
    for k in ks:
        body += _blob(k)
        cnt += 1
    out += _U32.pack(cnt) + body
    return bytes(out)


def encode_remove_range(start: bytes, end: bytes) -> bytes:
    return bytes([OP_REMOVE_RANGE]) + _blob(start) + _blob(end)


def encode_remove_prefix(prefix: bytes) -> bytes:
    return bytes([OP_REMOVE_PREFIX]) + _blob(prefix)


def encode_host(op: int, host: str) -> bytes:
    """Membership-change ops carry a host address string."""
    return bytes([op]) + _blob(host.encode("utf-8"))


DecodedOp = Tuple[int, tuple]


def decode(data: bytes) -> DecodedOp:
    """-> (op, payload) where payload depends on op:
    OP_PUT -> (key, value); OP_REMOVE -> (key,);
    OP_MULTI_PUT -> (kv_list,); OP_MULTI_REMOVE -> (key_list,);
    OP_REMOVE_RANGE -> (start, end); OP_REMOVE_PREFIX -> (prefix,);
    membership ops -> (host_str,).
    """
    op = data[0]
    off = 1
    if op == OP_PUT:
        k, off = _read_blob(data, off)
        v, off = _read_blob(data, off)
        return op, (k, v)
    if op == OP_REMOVE:
        k, off = _read_blob(data, off)
        return op, (k,)
    if op == OP_MULTI_PUT:
        cnt = _U32.unpack_from(data, off)[0]
        off += 4
        kvs: List[KV] = []
        for _ in range(cnt):
            k, off = _read_blob(data, off)
            v, off = _read_blob(data, off)
            kvs.append((k, v))
        return op, (kvs,)
    if op == OP_MULTI_REMOVE:
        cnt = _U32.unpack_from(data, off)[0]
        off += 4
        ks: List[bytes] = []
        for _ in range(cnt):
            k, off = _read_blob(data, off)
            ks.append(k)
        return op, (ks,)
    if op == OP_REMOVE_RANGE:
        s, off = _read_blob(data, off)
        e, off = _read_blob(data, off)
        return op, (s, e)
    if op == OP_REMOVE_PREFIX:
        p, off = _read_blob(data, off)
        return op, (p,)
    if op in (OP_ADD_LEARNER, OP_TRANS_LEADER, OP_ADD_PEER, OP_REMOVE_PEER):
        h, off = _read_blob(data, off)
        return op, (h.decode("utf-8"),)
    raise ValueError(f"bad log op {op}")
