"""In-memory ordered KV engine.

Role parity with the reference's `RocksEngine` for the non-durable case
(tests, meta fixtures, small spaces): sorted key array + dict, bisect
lookups, snapshot-free iterators with prefix/range semantics identical
to a RocksDB prefix iterator. Durability comes from the WAL + raft
layers above (exactly where the reference puts it), or from the C++
native engine behind the same `KVEngine` seam.

Concurrency model (found by the concurrent soak, round 5): storaged
applies writes on RPC handler threads while snapshot builds and delta
pulls scan — RocksDB gives the reference consistent iterators for
free, so this engine must too. Writers SERIALIZE on `_wlock` and
publish a fresh immutable `(keys, data)` pair per committed batch
(copy-on-write); readers grab `self._state` once and operate on that
pair, so a scan can never see a half-applied batch, lose an index
entry to a racing sort, or KeyError on a just-deleted key. The copy
is O(keys) per write batch — the native C++ engine serves write-heavy
production loads; this engine's job is correctness at test/meta scale.
"""
from __future__ import annotations

import bisect
import threading
from typing import Iterable, List, Optional, Tuple

from ..common.status import Status
from .iface import KV, KVEngine, KVIterator


class _ListIterator(KVIterator):
    __slots__ = ("_keys", "_data", "_idx", "_end")

    def __init__(self, keys: List[bytes], data: dict, lo: int, hi: int):
        self._keys = keys
        self._data = data
        self._idx = lo
        self._end = hi

    def valid(self) -> bool:
        return self._idx < self._end

    def next(self) -> None:
        self._idx += 1

    def key(self) -> bytes:
        return self._keys[self._idx]

    def value(self) -> bytes:
        return self._data[self._keys[self._idx]]


def _prefix_upper_bound(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every key with this prefix."""
    b = bytearray(prefix)
    while b:
        if b[-1] != 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return None  # prefix was all 0xFF: no upper bound


class MemEngine(KVEngine):
    def __init__(self) -> None:
        from .changelog import ChangeRing
        # immutable published snapshot: (sorted keys, key -> value).
        # Writers replace the whole tuple under _wlock; readers load it
        # once and never observe intermediate states.
        self._state: Tuple[List[bytes], dict] = ([], {})
        self.write_version = 0
        self.changes = ChangeRing()  # committed-write feed (delta sync)
        self._wlock = threading.Lock()

    # --- reads --------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        return self._state[1].get(key)

    def prefix(self, prefix: bytes) -> KVIterator:
        keys, data = self._state
        lo = bisect.bisect_left(keys, prefix)
        ub = _prefix_upper_bound(prefix)
        hi = bisect.bisect_left(keys, ub) if ub is not None else len(keys)
        return _ListIterator(keys, data, lo, hi)

    def range(self, start: bytes, end: bytes) -> KVIterator:
        keys, data = self._state
        lo = bisect.bisect_left(keys, start)
        hi = bisect.bisect_left(keys, end)
        return _ListIterator(keys, data, lo, hi)

    def scan_batch(self, prefix: bytes) -> Tuple[List[bytes], List[bytes]]:
        """Whole prefix range in two lists (keys, values) — the batched
        form the CSR snapshot builder consumes (one call, no per-item
        iterator overhead)."""
        keys, data = self._state
        lo = bisect.bisect_left(keys, prefix)
        ub = _prefix_upper_bound(prefix)
        hi = bisect.bisect_left(keys, ub) if ub is not None \
            else len(keys)
        ks = keys[lo:hi]
        return ks, list(map(data.__getitem__, ks))

    # --- writes -------------------------------------------------------
    # ring entries are recorded BEFORE write_version advances so a
    # concurrent pull at version v never misses an op it claims to
    # cover (the delta feed's never-stale rule); the new state is
    # published before the record, so a resolver reading "current
    # visible state" for that version always finds the write.
    def put(self, key: bytes, value: bytes) -> Status:
        with self._wlock:
            v = self.write_version + 1
            keys, data = self._state
            nd = dict(data)
            if key not in nd:
                nk = keys.copy()
                bisect.insort(nk, key)
            else:
                nk = keys
            nd[key] = value
            self._state = (nk, nd)
            self.changes.record(v, "put", [(key, value)])
            self.write_version = v
        return Status.OK()

    def multi_put(self, kvs: Iterable[KV]) -> Status:
        kvs = list(kvs)
        with self._wlock:
            ver = self.write_version + 1
            keys, data = self._state
            nd = dict(data)
            new = False
            for k, v in kvs:
                if k not in nd:
                    new = True
                nd[k] = v
            nk = sorted(nd) if new else keys
            self._state = (nk, nd)
            self.changes.record(ver, "put", kvs)
            self.write_version = ver
        return Status.OK()

    def remove(self, key: bytes) -> Status:
        with self._wlock:
            v = self.write_version + 1
            keys, data = self._state
            if key in data:
                nd = dict(data)
                del nd[key]
                nk = keys.copy()
                i = bisect.bisect_left(nk, key)
                if i < len(nk) and nk[i] == key:
                    nk.pop(i)
                self._state = (nk, nd)
            self.changes.record(v, "rm", [key])
            self.write_version = v
        return Status.OK()

    def multi_remove(self, keys_in: Iterable[bytes]) -> Status:
        keys_in = list(keys_in)
        with self._wlock:
            v = self.write_version + 1
            keys, data = self._state
            nd = dict(data)
            hit = False
            for k in keys_in:
                if k in nd:
                    del nd[k]
                    hit = True
            if hit:
                self._state = (sorted(nd), nd)
            self.changes.record(v, "rm", keys_in)
            self.write_version = v
        return Status.OK()

    def remove_range(self, start: bytes, end: bytes) -> Status:
        with self._wlock:
            v = self.write_version + 1
            keys, data = self._state
            lo = bisect.bisect_left(keys, start)
            hi = bisect.bisect_left(keys, end)
            nd = dict(data)
            for k in keys[lo:hi]:
                del nd[k]
            self._state = (keys[:lo] + keys[hi:], nd)
            self.changes.record(v, "barrier", None)
            self.write_version = v
        return Status.OK()

    # --- maintenance --------------------------------------------------
    def total_keys(self) -> int:
        return len(self._state[0])

    def approximate_size(self) -> int:
        return sum(len(k) + len(v) for k, v in self._state[1].items())

    def snapshot_items(self) -> List[KV]:
        """Stable copy for snapshot transfer / CSR building."""
        keys, data = self._state
        return [(k, data[k]) for k in keys]
