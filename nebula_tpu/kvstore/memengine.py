"""In-memory ordered KV engine.

Role parity with the reference's `RocksEngine` for the non-durable case
(tests, meta fixtures, small spaces): sorted key array + dict, bisect
lookups, snapshot-free iterators with prefix/range semantics identical
to a RocksDB prefix iterator. Durability comes from the WAL + raft
layers above (exactly where the reference puts it), or from the C++
native engine behind the same `KVEngine` seam.
"""
from __future__ import annotations

import bisect
from typing import Iterable, List, Optional, Tuple

from ..common.status import Status
from .iface import KV, KVEngine, KVIterator


class _ListIterator(KVIterator):
    __slots__ = ("_keys", "_data", "_idx", "_end")

    def __init__(self, keys: List[bytes], data: dict, lo: int, hi: int):
        self._keys = keys
        self._data = data
        self._idx = lo
        self._end = hi

    def valid(self) -> bool:
        return self._idx < self._end

    def next(self) -> None:
        self._idx += 1

    def key(self) -> bytes:
        return self._keys[self._idx]

    def value(self) -> bytes:
        return self._data[self._keys[self._idx]]


def _prefix_upper_bound(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every key with this prefix."""
    b = bytearray(prefix)
    while b:
        if b[-1] != 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return None  # prefix was all 0xFF: no upper bound


class MemEngine(KVEngine):
    def __init__(self) -> None:
        from .changelog import ChangeRing
        self._keys: List[bytes] = []
        self._data: dict = {}
        self.write_version = 0
        self.changes = ChangeRing()  # committed-write feed (delta sync)

    # --- reads --------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        return self._data.get(key)

    def prefix(self, prefix: bytes) -> KVIterator:
        lo = bisect.bisect_left(self._keys, prefix)
        ub = _prefix_upper_bound(prefix)
        hi = bisect.bisect_left(self._keys, ub) if ub is not None else len(self._keys)
        return _ListIterator(self._keys, self._data, lo, hi)

    def range(self, start: bytes, end: bytes) -> KVIterator:
        lo = bisect.bisect_left(self._keys, start)
        hi = bisect.bisect_left(self._keys, end)
        return _ListIterator(self._keys, self._data, lo, hi)

    def scan_batch(self, prefix: bytes) -> Tuple[List[bytes], List[bytes]]:
        """Whole prefix range in two lists (keys, values) — the batched
        form the CSR snapshot builder consumes (one call, no per-item
        iterator overhead)."""
        lo = bisect.bisect_left(self._keys, prefix)
        ub = _prefix_upper_bound(prefix)
        hi = bisect.bisect_left(self._keys, ub) if ub is not None \
            else len(self._keys)
        ks = self._keys[lo:hi]
        return ks, list(map(self._data.__getitem__, ks))

    # --- writes -------------------------------------------------------
    def put(self, key: bytes, value: bytes) -> Status:
        # ring entry is recorded BEFORE write_version advances so a
        # concurrent changes_snapshot(v) never misses an op it claims
        # to cover (the delta feed's never-stale rule)
        v = self.write_version + 1
        if key not in self._data:
            bisect.insort(self._keys, key)
        self._data[key] = value
        self.changes.record(v, "put", [(key, value)])
        self.write_version = v
        return Status.OK()

    def multi_put(self, kvs: Iterable[KV]) -> Status:
        kvs = list(kvs)
        ver = self.write_version + 1
        new = False
        for k, v in kvs:
            if k not in self._data:
                new = True
            self._data[k] = v
        if new:
            self._keys = sorted(self._data)
        self.changes.record(ver, "put", kvs)
        self.write_version = ver
        return Status.OK()

    def remove(self, key: bytes) -> Status:
        v = self.write_version + 1
        if key in self._data:
            del self._data[key]
            i = bisect.bisect_left(self._keys, key)
            if i < len(self._keys) and self._keys[i] == key:
                self._keys.pop(i)
        self.changes.record(v, "rm", [key])
        self.write_version = v
        return Status.OK()

    def multi_remove(self, keys: Iterable[bytes]) -> Status:
        keys = list(keys)
        v = self.write_version + 1
        hit = False
        for k in keys:
            if k in self._data:
                del self._data[k]
                hit = True
        if hit:
            self._keys = sorted(self._data)
        self.changes.record(v, "rm", keys)
        self.write_version = v
        return Status.OK()

    def remove_range(self, start: bytes, end: bytes) -> Status:
        v = self.write_version + 1
        lo = bisect.bisect_left(self._keys, start)
        hi = bisect.bisect_left(self._keys, end)
        for k in self._keys[lo:hi]:
            del self._data[k]
        del self._keys[lo:hi]
        self.changes.record(v, "barrier", None)
        self.write_version = v
        return Status.OK()

    # --- maintenance --------------------------------------------------
    def total_keys(self) -> int:
        return len(self._keys)

    def approximate_size(self) -> int:
        return sum(len(k) + len(v) for k, v in self._data.items())

    def snapshot_items(self) -> List[KV]:
        """Stable copy for snapshot transfer / CSR building."""
        return [(k, self._data[k]) for k in self._keys]
