"""KVEngine backed by the native C++ ordered-map engine.

Role parity with the reference's default RocksEngine (ref
kvstore/RocksEngine.{h,cpp}): the engine below every Part, with batched
writes, materialized prefix/range scans, bulk ingest, a checkpoint for
durability (the raft WAL above replays the tail), and the
newest-version-dedup scan the storage processors use as their hot loop.
"""
from __future__ import annotations

import ctypes
import struct
from typing import Iterable, List, Optional, Tuple

from .. import native
from ..common.status import ErrorCode, Status
from .iface import KVEngine, KVIterator

KV = Tuple[bytes, bytes]
_U32 = struct.Struct("<I")
_I32 = struct.Struct("<i")   # multi_get value length (-1 = missing)


def _pack_kvs(kvs: List[KV]) -> bytes:
    parts = []
    for k, v in kvs:
        parts.append(_U32.pack(len(k)))
        parts.append(k)
        parts.append(_U32.pack(len(v)))
        parts.append(v)
    return b"".join(parts)


def _pack_keys(keys: List[bytes]) -> bytes:
    parts = []
    for k in keys:
        parts.append(_U32.pack(len(k)))
        parts.append(k)
    return b"".join(parts)


def _unpack_kvs(raw: bytes, n: int) -> List[KV]:
    out = []
    off = 0
    for _ in range(n):
        (klen,) = _U32.unpack_from(raw, off)
        off += 4
        k = raw[off:off + klen]
        off += klen
        (vlen,) = _U32.unpack_from(raw, off)
        off += 4
        v = raw[off:off + vlen]
        off += vlen
        out.append((k, v))
    return out


class _ListIterator(KVIterator):
    def __init__(self, items: List[KV]):
        self._items = items
        self._i = 0

    def valid(self) -> bool:
        return self._i < len(self._items)

    def next(self) -> None:
        self._i += 1

    def key(self) -> bytes:
        return self._items[self._i][0]

    def value(self) -> bytes:
        return self._items[self._i][1]


class NativeEngine(KVEngine):
    def __init__(self, checkpoint_path: Optional[str] = None):
        import threading
        from .changelog import ChangeRing
        self._lib = native.load()
        self._h = self._lib.nkv_open(
            checkpoint_path.encode() if checkpoint_path else None)
        if not self._h:
            raise OSError(f"cannot open native engine at {checkpoint_path}")
        self._ckpt = checkpoint_path
        self._closed = False
        self.changes = ChangeRing()  # committed-write feed (delta sync)
        # orders the (native write, python-side record) pair — the C++
        # engine has its own mutex but the ring tag must match
        self._wlock = threading.Lock()

    @property
    def native_handle(self):
        """Raw nkv* for native one-call operations (CSR extraction)."""
        return self._h

    @property
    def write_version(self) -> int:          # type: ignore[override]
        return self._lib.nkv_version(self._h)

    @write_version.setter
    def write_version(self, v: int) -> None:
        pass  # native counter is authoritative

    # --- reads --------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.nkv_get(self._h, key, len(key), ctypes.byref(out))
        if n < 0:
            return None
        return ctypes.string_at(out, n) if n else b""

    def multi_get(self, keys: List[bytes]) -> List[Optional[bytes]]:
        """Batched lookups in ONE native call (the KVStore::multiGet
        role): one shared-lock acquisition for the whole batch, and the
        GIL is released across every key instead of per key."""
        if not keys:
            return []
        buf = b"".join(_U32.pack(len(k)) + k for k in keys)
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_int64()
        rc = self._lib.nkv_multi_get(self._h, buf, len(buf), len(keys),
                                     ctypes.byref(out),
                                     ctypes.byref(out_len))
        if rc < 0:
            return [self.get(k) for k in keys]
        try:
            raw = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.nkv_buf_free(out)
        res: List[Optional[bytes]] = []
        off = 0
        for _ in range(len(keys)):
            (vlen,) = _I32.unpack_from(raw, off)
            off += 4
            if vlen < 0:
                res.append(None)
            else:
                res.append(raw[off:off + vlen])
                off += vlen
        return res

    def _scan(self, fn, *args) -> List[KV]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        total = fn(self._h, *args, ctypes.byref(out), ctypes.byref(n))
        if total <= 0:
            return []
        try:
            raw = ctypes.string_at(out, total)
        finally:
            self._lib.nkv_buf_free(out)
        return _unpack_kvs(raw, n.value)

    def prefix(self, prefix: bytes) -> KVIterator:
        return _ListIterator(
            self._scan(self._lib.nkv_scan_prefix, prefix, len(prefix)))

    def range(self, start: bytes, end: bytes) -> KVIterator:
        return _ListIterator(
            self._scan(self._lib.nkv_scan_range, start, len(start),
                       end, len(end)))

    def scan_batch(self, prefix: bytes) -> Tuple[List[bytes], List[bytes]]:
        """(keys, values) under prefix — batched scan for the CSR
        snapshot builder (one native call + one unpack pass)."""
        items = self._scan(self._lib.nkv_scan_prefix, prefix, len(prefix))
        return [k for k, _ in items], [v for _, v in items]

    def scan_cols(self, prefix: bytes):
        """Columnar scan (nkv_scan_prefix_cols): keys blob + values blob
        + length arrays in ONE native call, zero per-item Python — the
        CSR builder's hot scan path."""
        import numpy as np
        from .scan import ScanCols
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        kb, vb = u8p(), u8p()
        kl, vl = u32p(), u32p()
        kn, vn = ctypes.c_int64(), ctypes.c_int64()
        n = self._lib.nkv_scan_prefix_cols(
            self._h, prefix, len(prefix), ctypes.byref(kb),
            ctypes.byref(kn), ctypes.byref(vb), ctypes.byref(vn),
            ctypes.byref(kl), ctypes.byref(vl))
        if n < 0:
            raise MemoryError("nkv_scan_prefix_cols failed")
        if n == 0:
            return ScanCols.from_lists([], [])
        try:
            keys_blob = ctypes.string_at(kb, kn.value)
            vals_blob = ctypes.string_at(vb, vn.value) if vn.value else b""
            klens = np.ctypeslib.as_array(kl, shape=(n,)).astype(np.int64)
            vlens = np.ctypeslib.as_array(vl, shape=(n,)).astype(np.int64)
        finally:
            self._lib.nkv_buf_free(kb)
            self._lib.nkv_buf_free(vb)
            self._lib.nkv_buf_free(ctypes.cast(kl, u8p))
            self._lib.nkv_buf_free(ctypes.cast(vl, u8p))
        return ScanCols.from_blobs(n, keys_blob, vals_blob, vlens, klens)

    def prefix_dedup(self, prefix: bytes,
                     group_suffix: int = 8) -> List[KV]:
        """Newest row per version group — the getBound hot-loop scan."""
        return self._scan(self._lib.nkv_scan_prefix_dedup,
                          prefix, len(prefix), group_suffix)

    # --- writes -------------------------------------------------------
    def put(self, key: bytes, value: bytes) -> Status:
        with self._wlock:
            self._lib.nkv_put(self._h, key, len(key), value, len(value))
            self.changes.record(self.write_version, "put", [(key, value)])
        return Status.OK()

    def multi_put(self, kvs: Iterable[KV]) -> Status:
        kvs = list(kvs)
        buf = _pack_kvs(kvs)
        with self._wlock:
            rc = self._lib.nkv_multi_put(self._h, buf, len(buf), len(kvs))
            if rc == 0:
                self.changes.record(self.write_version, "put", kvs)
        return Status.OK() if rc == 0 else \
            Status.error(ErrorCode.E_INVALID_DATA, f"multi_put rc={rc}")

    def remove(self, key: bytes) -> Status:
        with self._wlock:
            self._lib.nkv_remove(self._h, key, len(key))
            self.changes.record(self.write_version, "rm", [key])
        return Status.OK()

    def multi_remove(self, keys: Iterable[bytes]) -> Status:
        ks = list(keys)
        buf = _pack_keys(ks)
        with self._wlock:
            rc = self._lib.nkv_multi_remove(self._h, buf, len(buf), len(ks))
            if rc == 0:
                self.changes.record(self.write_version, "rm", ks)
        return Status.OK() if rc == 0 else \
            Status.error(ErrorCode.E_INVALID_DATA, f"multi_remove rc={rc}")

    def remove_range(self, start: bytes, end: bytes) -> Status:
        with self._wlock:
            self._lib.nkv_remove_range(self._h, start, len(start),
                                       end, len(end))
            self.changes.record(self.write_version, "barrier", None)
        return Status.OK()

    def remove_prefix(self, prefix: bytes) -> Status:
        with self._wlock:
            self._lib.nkv_remove_prefix(self._h, prefix, len(prefix))
            self.changes.record(self.write_version, "barrier", None)
        return Status.OK()

    def ingest_packed(self, buf: bytes, n: int) -> Status:
        """Bulk load `n` pre-sorted [u32 klen][k][u32 vlen][v] rows in
        one native call (the SST-ingest fast path; ref:
        RocksEngine::ingest, RocksEngine.cpp:360). Records a barrier on
        the change ring — consumers rebuild rather than replaying an
        arbitrarily large load as deltas."""
        with self._wlock:
            rc = self._lib.nkv_ingest_sorted(self._h, buf, len(buf), n)
            self.changes.record(self.write_version, "barrier", None)
        return Status.OK() if rc == n else \
            Status.error(ErrorCode.E_INVALID_DATA, f"ingest rc={rc}")

    def changes_snapshot(self, since: int):
        # under _wlock: the native version advances inside the C++ call
        # BEFORE the python-side ring record, so an unlocked reader
        # could see a version whose op isn't in the ring yet
        with self._wlock:
            now_v = int(self.write_version)
            return now_v, self.changes.since(since)

    # --- maintenance --------------------------------------------------
    def ingest(self, kvs: Iterable[KV]) -> Status:
        return self.multi_put(kvs)

    # flush/checkpoint/close share _wlock: a background flusher (the
    # storaged WAL-compaction task) racing close() must find either a
    # live handle or the closed flag — a bare check-then-call would
    # let close() free the native handle mid-checkpoint (UAF)
    def flush(self) -> Status:
        with self._wlock:
            if self._closed:
                return Status.error(ErrorCode.E_CHECKPOINT_ERROR,
                                    "closed")
            if self._ckpt:
                rc = self._lib.nkv_checkpoint(self._h,
                                              self._ckpt.encode())
                if rc != 0:
                    return Status.error(ErrorCode.E_CHECKPOINT_ERROR,
                                        f"checkpoint rc={rc}")
            return Status.OK()

    def checkpoint(self, path: str) -> Status:
        with self._wlock:
            if self._closed:
                return Status.error(ErrorCode.E_CHECKPOINT_ERROR,
                                    "closed")
            rc = self._lib.nkv_checkpoint(self._h, path.encode())
        return Status.OK() if rc == 0 else \
            Status.error(ErrorCode.E_CHECKPOINT_ERROR, f"checkpoint rc={rc}")

    def approximate_size(self) -> int:
        return self._lib.nkv_approx_size(self._h)

    def total_keys(self) -> int:
        return self._lib.nkv_count(self._h)

    def run_count(self) -> int:
        """Frozen (immutable) runs currently held — compaction-state
        observability for the tuning tests and /get_stats."""
        return self._lib.nkv_run_count(self._h)

    def set_option(self, name: str, value: int) -> Status:
        rc = self._lib.nkv_set_option(self._h, name.encode(), int(value))
        if rc == 0:
            return Status.OK()
        return Status.error(
            f"engine option {name!r} " +
            ("not supported" if rc == -1 else f"invalid value {value}"))

    def get_option(self, name: str) -> Optional[int]:
        v = self._lib.nkv_get_option(self._h, name.encode())
        return None if v < 0 else int(v)

    def close(self) -> None:
        with self._wlock:
            if not self._closed:
                self._lib.nkv_close(self._h)
                self._closed = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
