"""Partition state machine.

Role parity with the reference's `kvstore/Part.cpp:34-417`: a Part is a
replicated state machine over a KV engine. Mutations are encoded as log
blobs (log_encoder), submitted through a consensus hook, and applied in
`commit_logs` as one engine batch together with the committed-log-id
marker (`system_commit_key`, ref Part.cpp:350-356) so restart recovery
knows where WAL replay must resume.

In Phase 1 the consensus hook is `DirectCommit` (single replica, commit
immediately). The Raft layer (kvstore/raft/) plugs into the same hook:
`RaftPart.append_async` replicates the identical log blobs, then calls
back into `Part.commit_logs` on quorum — mirroring how the reference
keeps consensus *below* the KVStore interface and out of the read path.
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable, List, Optional, Tuple

from ..common import keys as keyutils
from ..common import profiler as _profiler
from ..common.status import ErrorCode, Status
from . import log_encoder as le
from .iface import KVEngine

KV = Tuple[bytes, bytes]

# An atomic op runs at the serialization point and returns encoded log
# bytes to commit (or None to abort) — ref: KVStore.h:140-143 asyncAtomicOp.
AtomicOp = Callable[[], Optional[bytes]]


class Part:
    def __init__(self, space_id: int, part_id: int, engine: KVEngine,
                 consensus: Optional["ConsensusHook"] = None):
        self.space_id = space_id
        self.part_id = part_id
        self.engine = engine
        # contention-profiled: all kv parts share the "kv_part" site
        # (common/profiler.py; nebula_lock_wait_us_kv_part)
        self._lock = _profiler.profiled_lock("kv_part")
        self.last_committed_log_id = 0
        self.last_committed_term = 0
        self._snapshot_active = False   # mid-install chunk sequence
        self._load_commit_marker()
        self._consensus = consensus or DirectCommit(self)
        # consensus impls that need the Part (raft: commit/snapshot
        # callbacks + applied id) late-bind here
        if hasattr(self._consensus, "bind"):
            self._consensus.bind(self)

    # ------------------------------------------------------------------
    # public write API (async through consensus in the reference; our
    # Phase-1 hook commits synchronously, raft hook returns futures)
    # ------------------------------------------------------------------
    def async_put(self, key: bytes, value: bytes) -> Status:
        return self._consensus.submit(le.encode_single(le.OP_PUT, key, value))

    def async_multi_put(self, kvs: Iterable[KV]) -> Status:
        return self._consensus.submit(le.encode_multi_put(kvs))

    def async_remove(self, key: bytes) -> Status:
        return self._consensus.submit(le.encode_single(le.OP_REMOVE, key))

    def async_multi_remove(self, ks: Iterable[bytes]) -> Status:
        return self._consensus.submit(le.encode_multi_remove(ks))

    def async_remove_range(self, start: bytes, end: bytes) -> Status:
        return self._consensus.submit(le.encode_remove_range(start, end))

    def async_remove_prefix(self, prefix: bytes) -> Status:
        return self._consensus.submit(le.encode_remove_prefix(prefix))

    def async_atomic_op(self, op: AtomicOp) -> Status:
        return self._consensus.submit_atomic(op)

    # ------------------------------------------------------------------
    # state machine apply (called under the consensus serialization point)
    # ------------------------------------------------------------------
    def commit_logs(self, logs: List[Tuple[int, int, bytes]]) -> Status:
        """Apply a batch of (log_id, term, data) entries as one engine
        batch + commit marker (ref: Part::commitLogs Part.cpp:208-319)."""
        if not logs:
            return Status.OK()
        batch_puts: List[KV] = []
        with self._lock:
            # applying log batches means no snapshot install is in
            # flight — clear the flag a sender-side abort can leave
            # behind, so the NEXT install gets its prefix cleanup
            self._snapshot_active = False
            for log_id, term, data in logs:
                if not data:
                    continue  # heartbeat/noop entry
                op, payload = le.decode(data)
                if op == le.OP_PUT:
                    batch_puts.append(payload)
                elif op == le.OP_MULTI_PUT:
                    batch_puts.extend(payload[0])
                else:
                    # non-put ops flush accumulated puts first to keep order
                    if batch_puts:
                        self.engine.multi_put(batch_puts)
                        batch_puts = []
                    if op == le.OP_REMOVE:
                        self.engine.remove(payload[0])
                    elif op == le.OP_MULTI_REMOVE:
                        self.engine.multi_remove(payload[0])
                    elif op == le.OP_REMOVE_RANGE:
                        self.engine.remove_range(payload[0], payload[1])
                    elif op == le.OP_REMOVE_PREFIX:
                        self.engine.remove_prefix(payload[0])
                    elif op in (le.OP_ADD_LEARNER, le.OP_TRANS_LEADER,
                                le.OP_ADD_PEER, le.OP_REMOVE_PEER):
                        pass  # handled by raft pre-process, not the engine
                    else:
                        return Status.error(ErrorCode.E_INVALID_DATA,
                                            f"bad op {op}")
            last_id, last_term, _ = logs[-1][0], logs[-1][1], None
            batch_puts.append((keyutils.system_commit_key(self.part_id),
                               keyutils.encode_commit_value(last_id, logs[-1][1])))
            self.engine.multi_put(batch_puts)
            self.last_committed_log_id = last_id
            self.last_committed_term = logs[-1][1]
        return Status.OK()

    def commit_snapshot(self, kvs: List[KV], committed_log_id: int,
                        committed_term: int, finished: bool) -> int:
        """Ingest a snapshot chunk (ref: Part::commitSnapshot :321-348).
        The first chunk of an install clears the part's prefix first —
        a snapshot REPLACES history, so keys deleted at the leader must
        not survive as ghosts on a receiver that already held data
        (reachable since WAL compaction: a lagging replica whose gap
        was truncated re-syncs by snapshot onto a non-empty engine).
        The commit marker lands only with the FINAL chunk; a crash
        mid-install therefore restarts recovery from marker 0 and the
        receiver simply re-requests the snapshot."""
        with self._lock:
            if not self._snapshot_active:
                self.engine.remove_prefix(
                    keyutils.part_prefix(self.part_id))
                self._snapshot_active = True
            self.engine.multi_put(kvs)
            if finished:
                self.engine.put(keyutils.system_commit_key(self.part_id),
                                keyutils.encode_commit_value(committed_log_id,
                                                             committed_term))
                self.last_committed_log_id = committed_log_id
                self.last_committed_term = committed_term
                self._snapshot_active = False
        return len(kvs)

    def cleanup(self) -> Status:
        """Drop all data of this part (ref: Part::cleanup on removePart)."""
        with self._lock:
            return self.engine.remove_prefix(keyutils.part_prefix(self.part_id))

    # ------------------------------------------------------------------
    def _load_commit_marker(self) -> None:
        v = self.engine.get(keyutils.system_commit_key(self.part_id))
        if v is not None:
            self.last_committed_log_id, self.last_committed_term = \
                keyutils.decode_commit_value(v)

    def is_leader(self) -> bool:
        return self._consensus.is_leader()

    def leader(self) -> Optional[str]:
        return self._consensus.leader()


class ConsensusHook:
    """Seam between Part and the replication machinery."""

    def submit(self, log: bytes) -> Status:
        raise NotImplementedError

    def submit_atomic(self, op: AtomicOp) -> Status:
        raise NotImplementedError

    def is_leader(self) -> bool:
        return True

    def leader(self) -> Optional[str]:
        return None


class DirectCommit(ConsensusHook):
    """Single-replica commit path: serialize + apply immediately."""

    def __init__(self, part: Part):
        self._part = part
        self._lock = threading.Lock()
        self._next_log_id = 1

    def submit(self, log: bytes) -> Status:
        with self._lock:
            log_id = self._next_log_id
            self._next_log_id += 1
            return self._part.commit_logs([(log_id, 1, log)])

    def submit_atomic(self, op: AtomicOp) -> Status:
        with self._lock:
            log = op()
            if log is None:
                return Status.error(ErrorCode.E_FILTER_OUT, "atomic op aborted")
            log_id = self._next_log_id
            self._next_log_id += 1
            return self._part.commit_logs([(log_id, 1, log)])
