"""Partition state machine.

Role parity with the reference's `kvstore/Part.cpp:34-417`: a Part is a
replicated state machine over a KV engine. Mutations are encoded as log
blobs (log_encoder), submitted through a consensus hook, and applied in
`commit_logs` as one engine batch together with the committed-log-id
marker (`system_commit_key`, ref Part.cpp:350-356) so restart recovery
knows where WAL replay must resume.

In Phase 1 the consensus hook is `DirectCommit` (single replica, commit
immediately). The Raft layer (kvstore/raft/) plugs into the same hook:
`RaftPart.append_async` replicates the identical log blobs, then calls
back into `Part.commit_logs` on quorum — mirroring how the reference
keeps consensus *below* the KVStore interface and out of the read path.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, List, Optional, Tuple

from ..common import consistency as _consistency
from ..common import keys as keyutils
from ..common import ledger as _ledger
from ..common import profiler as _profiler
from ..common import writepath as _writepath
from ..common.faults import InjectedFault, faults
from ..common.status import ErrorCode, Status
from . import log_encoder as le
from .iface import KVEngine

KV = Tuple[bytes, bytes]

# consistency.corrupt (docs/manual/9-robustness.md): armed on ONE
# replica (n=1 in an in-proc cluster fires on exactly one apply), it
# silently flips one byte of a committed put's value as the state
# machine applies it — the replica's store AND its content digest
# drift from the committed log, and the leader's next digest exchange
# round must flag the divergence (the bench --consistency drill)
faults.register("consistency.corrupt",
                doc="flip one byte of a committed put value during a "
                    "replicated Part.commit_logs apply — the silent "
                    "single-replica corruption the consistency "
                    "observatory exists to detect")

# An atomic op runs at the serialization point and returns encoded log
# bytes to commit (or None to abort) — ref: KVStore.h:140-143 asyncAtomicOp.
AtomicOp = Callable[[], Optional[bytes]]


class Part:
    def __init__(self, space_id: int, part_id: int, engine: KVEngine,
                 consensus: Optional["ConsensusHook"] = None):
        self.space_id = space_id
        self.part_id = part_id
        self.engine = engine
        # contention-profiled: all kv parts share the "kv_part" site
        # (common/profiler.py; nebula_lock_wait_us_kv_part)
        self._lock = _profiler.profiled_lock("kv_part")
        self.last_committed_log_id = 0
        self.last_committed_term = 0
        self._snapshot_active = False   # mid-install chunk sequence
        self._load_commit_marker()
        # consistency observatory (common/consistency.py): rolling
        # content digest over this part's data keys, anchored to
        # (term, applied_log_id) at every commit batch. Built eagerly
        # at bind when armed (one prefix scan — the same cost class as
        # a CSR build); a disarm window invalidates it and the next
        # probe rebuilds lazily.
        self.digest = _consistency.PartDigest()
        if _consistency.enabled():
            self.digest.rebuild(self.engine,
                                keyutils.part_prefix(self.part_id))
            self.digest.anchor_to(self.last_committed_term,
                                  self.last_committed_log_id)
        self._consensus = consensus or DirectCommit(self)
        # replicated parts (raft consensus) are the consistency.corrupt
        # drill's targets — DirectCommit (meta store, single-replica
        # spaces) has no second replica to diverge from
        self._replicated = hasattr(self._consensus, "raft")
        # consensus impls that need the Part (raft: commit/snapshot
        # callbacks + applied id) late-bind here
        if hasattr(self._consensus, "bind"):
            self._consensus.bind(self)

    # ------------------------------------------------------------------
    # public write API (async through consensus in the reference; our
    # Phase-1 hook commits synchronously, raft hook returns futures)
    # ------------------------------------------------------------------
    def async_put(self, key: bytes, value: bytes) -> Status:
        return self._consensus.submit(le.encode_single(le.OP_PUT, key, value))

    def async_multi_put(self, kvs: Iterable[KV]) -> Status:
        return self._consensus.submit(le.encode_multi_put(kvs))

    def async_remove(self, key: bytes) -> Status:
        return self._consensus.submit(le.encode_single(le.OP_REMOVE, key))

    def async_multi_remove(self, ks: Iterable[bytes]) -> Status:
        return self._consensus.submit(le.encode_multi_remove(ks))

    def async_remove_range(self, start: bytes, end: bytes) -> Status:
        return self._consensus.submit(le.encode_remove_range(start, end))

    def async_remove_prefix(self, prefix: bytes) -> Status:
        return self._consensus.submit(le.encode_remove_prefix(prefix))

    def async_atomic_op(self, op: AtomicOp) -> Status:
        return self._consensus.submit_atomic(op)

    # ------------------------------------------------------------------
    # state machine apply (called under the consensus serialization point)
    # ------------------------------------------------------------------
    def commit_logs(self, logs: List[Tuple[int, int, bytes]]) -> Status:
        """Apply a batch of (log_id, term, data) entries as one engine
        batch + commit marker (ref: Part::commitLogs Part.cpp:208-319)."""
        if not logs:
            return Status.OK()
        batch_puts: List[KV] = []
        with self._lock:
            # applying log batches means no snapshot install is in
            # flight — clear the flag a sender-side abort can leave
            # behind, so the NEXT install gets its prefix cleanup
            self._snapshot_active = False
            # consistency digest (common/consistency.py): fold this
            # batch's effects incrementally — overwrites/removes need
            # the OLD value (one engine get per touched key, armed
            # only). `pending` tracks keys of the still-unflushed put
            # batch so a key written twice in one batch folds against
            # its in-batch predecessor, not the engine.
            dig = None
            if _consistency.enabled():
                dig = self.digest
                if not dig.valid or dig.mid_install:
                    # re-arm after a disarm window / a sender-aborted
                    # install left the digest unreliable: rebuild from
                    # the pre-batch engine state before folding
                    dig.rebuild(self.engine,
                                keyutils.part_prefix(self.part_id))
            elif self.digest.valid:
                self.digest.invalidate()   # disarmed mid-flight
            pending: dict = {}
            corrupted = False

            def _fold_put(k: bytes, v: bytes) -> None:
                if dig is None or not _consistency.is_digestable_key(k):
                    return
                old = pending[k] if k in pending else self.engine.get(k)
                if old is not None:
                    dig.remove(k, old)
                dig.add(k, v)
                pending[k] = v

            def _flush() -> None:
                nonlocal batch_puts
                if batch_puts:
                    self.engine.multi_put(batch_puts)
                    batch_puts = []
                    pending.clear()

            def _corrupt(v: bytes) -> bytes:
                # consistency.corrupt: flip one byte of THIS put's
                # value (replicated parts only; an armed n=1 plan
                # corrupts exactly one replica's apply in an in-proc
                # cluster). The flipped value flows through the digest
                # too — the drift is cross-replica, detected by the
                # leader's digest exchange, never self-reported.
                nonlocal corrupted
                if corrupted or not self._replicated or not v:
                    return v
                try:
                    faults.fire("consistency.corrupt")
                except InjectedFault:
                    corrupted = True
                    return v[:-1] + bytes([v[-1] ^ 0x01])
                return v

            for log_id, term, data in logs:
                if not data:
                    continue  # heartbeat/noop entry
                op, payload = le.decode(data)
                if op == le.OP_PUT:
                    k, v = payload
                    v = _corrupt(v)
                    _fold_put(k, v)
                    batch_puts.append((k, v))
                elif op == le.OP_MULTI_PUT:
                    for k, v in payload[0]:
                        v = _corrupt(v)
                        _fold_put(k, v)
                        batch_puts.append((k, v))
                else:
                    # non-put ops flush accumulated puts first to keep order
                    _flush()
                    if op == le.OP_REMOVE:
                        if dig is not None:
                            old = self.engine.get(payload[0])
                            if old is not None and \
                                    _consistency.is_digestable_key(
                                        payload[0]):
                                dig.remove(payload[0], old)
                        self.engine.remove(payload[0])
                    elif op == le.OP_MULTI_REMOVE:
                        if dig is not None:
                            for k in payload[0]:
                                old = self.engine.get(k)
                                if old is not None and \
                                        _consistency.is_digestable_key(k):
                                    dig.remove(k, old)
                        self.engine.multi_remove(payload[0])
                    elif op == le.OP_REMOVE_RANGE:
                        if dig is not None:
                            for k, v in self.engine.range(payload[0],
                                                          payload[1]):
                                if _consistency.is_digestable_key(k):
                                    dig.remove(k, v)
                        self.engine.remove_range(payload[0], payload[1])
                    elif op == le.OP_REMOVE_PREFIX:
                        if dig is not None:
                            for k, v in self.engine.prefix(payload[0]):
                                if _consistency.is_digestable_key(k):
                                    dig.remove(k, v)
                        self.engine.remove_prefix(payload[0])
                    elif op in (le.OP_ADD_LEARNER, le.OP_TRANS_LEADER,
                                le.OP_ADD_PEER, le.OP_REMOVE_PEER):
                        pass  # handled by raft pre-process, not the engine
                    else:
                        return Status.error(ErrorCode.E_INVALID_DATA,
                                            f"bad op {op}")
            last_id, last_term, _ = logs[-1][0], logs[-1][1], None
            batch_puts.append((keyutils.system_commit_key(self.part_id),
                               keyutils.encode_commit_value(last_id, logs[-1][1])))
            self.engine.multi_put(batch_puts)
            self.last_committed_log_id = last_id
            self.last_committed_term = logs[-1][1]
            if dig is not None:
                dig.anchor_to(logs[-1][1], last_id)
        return Status.OK()

    def commit_snapshot(self, kvs: List[KV], committed_log_id: int,
                        committed_term: int, finished: bool) -> int:
        """Ingest a snapshot chunk (ref: Part::commitSnapshot :321-348).
        The first chunk of an install clears the part's prefix first —
        a snapshot REPLACES history, so keys deleted at the leader must
        not survive as ghosts on a receiver that already held data
        (reachable since WAL compaction: a lagging replica whose gap
        was truncated re-syncs by snapshot onto a non-empty engine).
        The commit marker lands only with the FINAL chunk; a crash
        mid-install therefore restarts recovery from marker 0 and the
        receiver simply re-requests the snapshot."""
        with self._lock:
            track = _consistency.enabled()
            if not self._snapshot_active:
                self.engine.remove_prefix(
                    keyutils.part_prefix(self.part_id))
                self._snapshot_active = True
                # install START replaces history wholesale: the digest
                # restarts from the cleared prefix and folds chunks in
                # (mid-install it is unreportable; the final chunk
                # anchors it to the snapshot's commit point)
                if track:
                    self.digest.begin_install()
                else:
                    self.digest.invalidate()
            if track and self.digest.valid:
                for k, v in kvs:
                    if _consistency.is_digestable_key(k):
                        # snapshot rows are a sorted unique scan of the
                        # sender's prefix (its system keys ride along
                        # but are excluded here like everywhere else)
                        self.digest.add(k, v)
            elif self.digest.valid:
                # disarmed MID-install: chunks applied but not folded
                # — the digest must not survive to be anchored as
                # valid at `finished` (or after a re-arm) missing this
                # window's keys; invalidate so the next probe rebuilds
                self.digest.invalidate()
            self.engine.multi_put(kvs)
            if finished:
                self.engine.put(keyutils.system_commit_key(self.part_id),
                                keyutils.encode_commit_value(committed_log_id,
                                                             committed_term))
                self.last_committed_log_id = committed_log_id
                self.last_committed_term = committed_term
                self._snapshot_active = False
                if track and self.digest.valid:
                    self.digest.anchor_to(committed_term,
                                          committed_log_id)
        return len(kvs)

    def cleanup(self) -> Status:
        """Drop all data of this part (ref: Part::cleanup on removePart)."""
        with self._lock:
            self.digest.invalidate()
            return self.engine.remove_prefix(keyutils.part_prefix(self.part_id))

    def ingest(self, kvs: Iterable[KV]) -> Status:
        """Bulk load around the log path (SST ingest): the engine
        content changes without a commit batch, so the digest is
        invalidated and lazily rebuilt on the next probe."""
        with self._lock:
            self.digest.invalidate()
            return self.engine.ingest(kvs)

    # ------------------------------------------------------------------
    # consistency observatory surface (common/consistency.py)
    # ------------------------------------------------------------------
    def digest_anchor(self) -> Optional[Tuple[int, int, int]]:
        """(anchor_term, anchor_log_id, digest) of this part's live
        content — None when disarmed or mid-snapshot-install. A digest
        invalidated by a disarm window / ingest rebuilds here from one
        engine scan (under the part lock, once per re-arm)."""
        if not _consistency.enabled():
            return None
        anc = self.digest.anchor()
        if anc is not None:
            return anc
        with self._lock:
            if self.digest.mid_install or self._snapshot_active:
                return None
            if not self.digest.valid:
                self.digest.rebuild(self.engine,
                                    keyutils.part_prefix(self.part_id))
                self.digest.anchor_to(self.last_committed_term,
                                      self.last_committed_log_id)
        return self.digest.anchor()

    def digest_at(self, log_id: int) -> Optional[int]:
        """This part's digest when its applied index was `log_id` —
        the leader's comparison base for follower-reported anchors
        (None when unknown: rolled off the bounded history or batch
        boundaries didn't align — skipped, never a false positive)."""
        if not _consistency.enabled():
            return None
        return self.digest.at(log_id)

    def digest_scrub(self) -> dict:
        """Deep scrub: recompute the content digest from a full engine
        scan under the part lock and compare against the incremental
        one — catches silent store mutation that bypassed the apply
        path (the bit-rot class). /consistency?scrub=1."""
        with self._lock:
            if not _consistency.enabled() or not self.digest.valid:
                return {"space": self.space_id, "part": self.part_id,
                        "ok": None, "reason": "disarmed"}
            scanned = _consistency.digest_items(
                (k, v) for k, v in self.engine.prefix(
                    keyutils.part_prefix(self.part_id))
                if _consistency.is_digestable_key(k))
            ok = scanned == self.digest.value
            return {"space": self.space_id, "part": self.part_id,
                    "ok": ok,
                    "incremental": _consistency.hex_digest(
                        self.digest.value),
                    "scanned": _consistency.hex_digest(scanned)}

    # ------------------------------------------------------------------
    def _load_commit_marker(self) -> None:
        v = self.engine.get(keyutils.system_commit_key(self.part_id))
        if v is not None:
            self.last_committed_log_id, self.last_committed_term = \
                keyutils.decode_commit_value(v)

    def is_leader(self) -> bool:
        return self._consensus.is_leader()

    def leader(self) -> Optional[str]:
        return self._consensus.leader()


class ConsensusHook:
    """Seam between Part and the replication machinery."""

    def submit(self, log: bytes) -> Status:
        raise NotImplementedError

    def submit_atomic(self, op: AtomicOp) -> Status:
        raise NotImplementedError

    def is_leader(self) -> bool:
        return True

    def leader(self) -> Optional[str]:
        return None


class DirectCommit(ConsensusHook):
    """Single-replica commit path: serialize + apply immediately.
    The commit_apply write stage (write-path observatory) is timed
    here — the raft path backdates the same stage from the part's
    commit accounting instead (kvstore/raft_store.py)."""

    def __init__(self, part: Part):
        self._part = part
        self._lock = threading.Lock()
        self._next_log_id = 1

    def _commit(self, log_id: int, log: bytes) -> Status:
        t0 = time.perf_counter()
        st = self._part.commit_logs([(log_id, 1, log)])
        us = (time.perf_counter() - t0) * 1e6
        led = _ledger.current()
        if led is not None:
            led.charge(commit_apply_us=us)
        _writepath.stage("commit_apply", us)
        return st

    def submit(self, log: bytes) -> Status:
        with self._lock:
            log_id = self._next_log_id
            self._next_log_id += 1
            return self._commit(log_id, log)

    def submit_atomic(self, op: AtomicOp) -> Status:
        with self._lock:
            log = op()
            if log is None:
                return Status.error(ErrorCode.E_FILTER_OUT, "atomic op aborted")
            log_id = self._next_log_id
            self._next_log_id += 1
            return self._commit(log_id, log)
