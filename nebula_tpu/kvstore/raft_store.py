"""Raft-replicated storage: the glue between Part and RaftPart.

Role parity with the reference's NebulaStore-over-raftex layering
(ref kvstore/NebulaStore.cpp + kvstore/Part.cpp): every storage Part is
a raft group member; writes are encoded log blobs submitted through
`RaftConsensusHook`, replicated by RaftPart, and applied on quorum via
`Part.commit_logs` — consensus stays below the KVStore interface and
out of the read path. Reads remain leader-local (`GraphStore.part`
rejects non-leaders with E_LEADER_CHANGED + leader hint, which the
StorageClient uses for redirect retries).

`ReplicatedStores` is the deployment/test helper that builds N
GraphStores whose parts form raft groups over a shared network — the
reference's in-process multi-server fixture idiom.
"""
from __future__ import annotations

import os
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

from ..common import keys as keyutils
from ..common import ledger
from ..common import writepath as _writepath
from ..common.stats import stats
from ..common.status import ErrorCode, Status
from ..common.tracing import tracer
from .iface import KVEngine
from .part import AtomicOp, ConsensusHook, Part
from .raftex import InProcNetwork, RaftCode, RaftPart, RaftexService
from .store import GraphStore

_CODE_MAP = {
    RaftCode.SUCCEEDED: ErrorCode.SUCCEEDED,
    RaftCode.E_NOT_A_LEADER: ErrorCode.E_LEADER_CHANGED,
    RaftCode.E_BAD_STATE: ErrorCode.E_FILTER_OUT,   # aborted atomic op
    # a stopping node redirects clients to another replica (hintless:
    # an election is typically in flight)
    RaftCode.E_HOST_STOPPED: ErrorCode.E_LEADER_CHANGED,
}


class RaftConsensusHook(ConsensusHook):
    """Submits Part log blobs through a RaftPart (created at bind time
    so the raft callbacks can reach the Part's state machine)."""

    def __init__(self, space_id: int, part_id: int, engine: KVEngine,
                 addr: str, peers: List[str], wal_root: str,
                 service: RaftexService, is_learner: bool = False,
                 leader_hint=None, on_leader_change=None, **raft_kw):
        self._space_id = space_id
        self._part_id = part_id
        self._engine = engine
        self._addr = addr
        self._peers = peers
        self._wal_root = wal_root
        self._service = service
        self._is_learner = is_learner
        # maps the leader's RAFT address to the address clients should
        # redirect to (the storage RPC addr; identity for in-proc tests
        # whose raft addrs ARE the client addrs)
        self._leader_hint = leader_hint or (lambda a: a)
        # on_leader_change(space, part, new_leader_raft_addr|None) —
        # called off the raft lock path; storaged counts the event and
        # reconciles membership when this replica takes over
        self._on_leader_change = on_leader_change
        self._raft_kw = raft_kw
        self.raft: Optional[RaftPart] = None

    def bind(self, part: Part) -> None:
        prefix = keyutils.part_prefix(self._part_id)

        def snapshot_rows():
            it = self._engine.prefix(prefix)
            return [(k, v) for k, v in it]

        wal_dir = os.path.join(
            self._wal_root, f"s{self._space_id}_p{self._part_id}")
        self.wal_dir = wal_dir
        on_lc = None
        if self._on_leader_change is not None:
            cb, sid, pid = self._on_leader_change, self._space_id, \
                self._part_id

            def on_lc(leader, _cb=cb, _sid=sid, _pid=pid):
                # RaftPart fires this under its lock — hand off to a
                # thread so the callback may call back into raft
                # (membership reconcile) without deadlocking
                import threading as _t
                _t.Thread(target=_cb, args=(_sid, _pid, leader),
                          daemon=True,
                          name=f"raft-lc-{_sid}-{_pid}").start()
        self.part = part
        self.raft = RaftPart(
            space_id=self._space_id, part_id=self._part_id,
            addr=self._addr, peers=self._peers, wal_dir=wal_dir,
            service=self._service,
            on_commit=lambda logs: part.commit_logs(logs),
            on_snapshot=lambda rows, cid, cterm, done:
                part.commit_snapshot(rows, cid, cterm, done),
            snapshot_rows=snapshot_rows,
            applied_id=part.last_committed_log_id,
            is_learner=self._is_learner,
            on_leader_change=on_lc,
            # consistency observatory: the state machine's content-
            # digest seams — responders report their anchor on every
            # append round, leaders compare against their own history
            digest_probe=part.digest_anchor,
            digest_at=part.digest_at,
            **self._raft_kw)
        self.raft.start()

    # ------------------------------------------------------------- submit
    def _wait(self, fut: Future) -> Status:
        try:
            code = fut.result(timeout=10)
        except Exception as e:
            return Status.error(ErrorCode.E_CONSENSUS_ERROR, str(e))
        mapped = _CODE_MAP.get(code)
        if mapped is ErrorCode.SUCCEEDED:
            return Status.OK()
        if mapped is ErrorCode.E_LEADER_CHANGED:
            # a stopped host's cached leader may be itself — never hint it
            hint = "" if code is RaftCode.E_HOST_STOPPED else \
                (self.leader() or "")
            return Status.error(ErrorCode.E_LEADER_CHANGED, hint)
        if mapped is ErrorCode.E_FILTER_OUT:
            return Status.error(ErrorCode.E_FILTER_OUT, "atomic op aborted")
        return Status.error(ErrorCode.E_CONSENSUS_ERROR, str(code))

    def submit(self, log: bytes) -> Status:
        # Raft write-path tracing + cost (ISSUE 12 satellite): spans
        # record on the WAITER's thread under its own trace — the
        # append span CLOSES after append_async returns (part lock
        # released), the replicate span covers the quorum wait, and
        # the commit_logs apply (replicator thread, under the part
        # lock — off-limits for recording, PR 10 rule) is backdated
        # from the part's last-commit accounting after the wait.
        t0 = time.perf_counter()
        with tracer.span("raft.append_wal", bytes=len(log)):
            fut = self.raft.append_async(log)
        t1 = time.perf_counter()
        led = ledger.current()
        if led is not None:
            led.wal_bytes += len(log)
            led.charge(wal_append_us=(t1 - t0) * 1e6)
        stats.add_value("raftex.append_bytes", len(log), kind="counter")
        _writepath.stage("wal_append", (t1 - t0) * 1e6)
        with tracer.span("raft.replicate"):
            st = self._wait(fut)
        t2 = time.perf_counter()
        if led is not None:
            led.charge(replicate_us=(t2 - t1) * 1e6)
        _writepath.stage("replicate", (t2 - t1) * 1e6)
        if st.ok() and self.raft.last_commit_us:
            # the engine apply ran on the replicator thread under the
            # part lock (off-limits for recording, PR 10 rule) — the
            # waiter backdates it from the part's commit accounting
            if tracer.active():
                tracer.add_span("raft.commit_logs",
                                self.raft.last_commit_us,
                                entries=self.raft.last_commit_n)
            if led is not None:
                led.charge(commit_apply_us=self.raft.last_commit_us)
            _writepath.stage("commit_apply", self.raft.last_commit_us)
        return st

    def submit_atomic(self, op: AtomicOp) -> Status:
        t0 = time.perf_counter()
        with tracer.span("raft.append_wal", atomic=True):
            fut = self.raft.atomic_op_async(op)
        t1 = time.perf_counter()
        led = ledger.current()
        if led is not None:
            led.charge(wal_append_us=(t1 - t0) * 1e6)
        _writepath.stage("wal_append", (t1 - t0) * 1e6)
        with tracer.span("raft.replicate"):
            st = self._wait(fut)
        t2 = time.perf_counter()
        if led is not None:
            led.charge(replicate_us=(t2 - t1) * 1e6)
        _writepath.stage("replicate", (t2 - t1) * 1e6)
        if st.ok() and self.raft.last_commit_us:
            if tracer.active():
                tracer.add_span("raft.commit_logs",
                                self.raft.last_commit_us,
                                entries=self.raft.last_commit_n)
            if led is not None:
                led.charge(commit_apply_us=self.raft.last_commit_us)
            _writepath.stage("commit_apply", self.raft.last_commit_us)
        return st

    def is_leader(self) -> bool:
        return self.raft is not None and self.raft.is_leader()

    def leader(self) -> Optional[str]:
        raw = self.raft.leader() if self.raft else None
        return self._leader_hint(raw) if raw else raw

    def stop(self, purge: bool = False) -> None:
        if self.raft is not None:
            self.raft.stop()
        if purge:
            # the part is being REMOVED from this host (balance
            # evacuation / space drop) — delete its WAL + raft_state
            # alongside the engine data Part.cleanup() drops, so a
            # later re-add of the same part here starts from a clean
            # dir. Without this, stale history would masquerade as a
            # same-dir member restart (RaftPart's learner override).
            import shutil
            wal_dir = getattr(self, "wal_dir", None)
            if wal_dir:
                shutil.rmtree(wal_dir, ignore_errors=True)


class StorageNode:
    """One storage host: a GraphStore whose parts join raft groups with
    per-part peer sets — the unit the balancer moves partitions between
    (ref storage/StorageServer.cpp boot + AdminProcessor surface)."""

    def __init__(self, addr: str, data_root: str, net: InProcNetwork,
                 engine_factory=None, leader_hint=None,
                 on_leader_change=None, **raft_kw):
        self.addr = addr
        self.data_root = data_root
        self.service = RaftexService(addr, net)
        self.hooks: Dict[tuple, RaftConsensusHook] = {}
        self._part_cfg: Dict[tuple, tuple] = {}
        self._raft_kw = raft_kw

        def consensus_factory(space_id: int, part_id: int, engine: KVEngine):
            peers, learner = self._part_cfg.pop(
                (space_id, part_id), ([addr], False))
            hook = RaftConsensusHook(
                space_id, part_id, engine, addr, peers,
                os.path.join(data_root, addr.replace(":", "_")),
                self.service, is_learner=learner,
                leader_hint=leader_hint,
                on_leader_change=on_leader_change, **raft_kw)
            self.hooks[(space_id, part_id)] = hook
            return hook

        self.store = GraphStore(engine_factory=engine_factory,
                                consensus_factory=consensus_factory)

    def add_part(self, space_id: int, part_id: int, peers: List[str],
                 as_learner: bool = False) -> None:
        self._part_cfg[(space_id, part_id)] = (list(peers), as_learner)
        self.store.add_part(space_id, part_id)

    def remove_part(self, space_id: int, part_id: int) -> None:
        hook = self.hooks.pop((space_id, part_id), None)
        if hook is not None:
            hook.stop(purge=True)   # evacuation: WAL goes with the data
        self.store.remove_part(space_id, part_id)

    def remove_space(self, space_id: int) -> None:
        """Stop every part's raft BEFORE the engine closes — committing
        into a freed native engine is a use-after-free."""
        for key in [k for k in self.hooks if k[0] == space_id]:
            self.hooks.pop(key).stop(purge=True)
        self.store.remove_space(space_id)

    def raft(self, space_id: int, part_id: int) -> Optional[RaftPart]:
        h = self.hooks.get((space_id, part_id))
        return h.raft if h else None

    def raft_status(self) -> List[dict]:
        """Every local part's raft state (role/term/commit-lag/peers) —
        the storaged /raft endpoint + Prometheus source."""
        out = []
        for key in sorted(self.hooks):
            h = self.hooks.get(key)
            if h is not None and h.raft is not None:
                out.append(h.raft.status_with_replicas())
        return out

    def compact_wals(self, lag: int) -> Dict[tuple, dict]:
        """Snapshot-anchored WAL compaction across every local part
        (the storaged background task's body; docs/manual/
        12-replication.md). Ordering is the durability argument:
        (1) capture each part's applied id as its anchor, (2) flush
        every space engine so everything at/below the anchors is on
        disk, (3) truncate each WAL behind anchor - lag. A crash at
        any point leaves the WAL covering everything the engine might
        be missing."""
        anchors: Dict[tuple, int] = {}
        for key, h in list(self.hooks.items()):
            if h.raft is not None:
                anchors[key] = h.raft.committed_id
        for sid in self.store.spaces():
            eng = self.store.space_engine(sid)
            flush = getattr(eng, "flush", None)
            if flush is not None:
                try:
                    flush()
                except Exception:
                    # an unflushed engine just means this round's
                    # anchors are too optimistic — skip truncation
                    anchors = {k: 0 for k in anchors}
                    break
        out: Dict[tuple, dict] = {}
        for key, anchor in anchors.items():
            h = self.hooks.get(key)
            if h is not None and h.raft is not None:
                out[key] = h.raft.compact_wal(lag, anchor=anchor)
        return out

    def consistency_status(self) -> List[dict]:
        """Per-part consistency view (the storaged /consistency body):
        this replica's digest anchor plus — on leaders — every
        replica's match/applied/digest_ok. A deep scrub is the Part's
        own digest_scrub (the ?scrub=1 path walks hooks too)."""
        out = []
        for key in sorted(self.hooks):
            h = self.hooks.get(key)
            if h is None or h.raft is None:
                continue
            st = h.raft.status_with_replicas()
            out.append({
                "space": st["space"], "part": st["part"],
                "role": st["role"], "term": st["term"],
                "committed": st["committed"],
                "digest": st.get("digest"),
                "digest_divergent": st.get("digest_divergent", []),
                "replicas": [
                    {k: m.get(k) for k in
                     ("addr", "learner", "match", "applied",
                      "digest_ok", "digest_anchor", "staleness_ms")}
                    for m in st.get("replicas", [])],
            })
        return out

    def digest_scrub(self) -> List[dict]:
        """Deep-scrub every local part's digest against a full engine
        scan (the /consistency?scrub=1 body)."""
        out = []
        for key in sorted(self.hooks):
            h = self.hooks.get(key)
            part = getattr(h, "part", None) if h is not None else None
            if part is not None:
                out.append(part.digest_scrub())
        return out

    def leader_parts(self) -> Dict[int, List[int]]:
        """{space_id: [parts this node currently leads]} — the
        heartbeat-carried leader view metad aggregates."""
        out: Dict[int, List[int]] = {}
        for (sid, pid), h in list(self.hooks.items()):
            if h.is_leader():
                out.setdefault(sid, []).append(pid)
        return {s: sorted(ps) for s, ps in out.items()}

    def stop(self) -> None:
        for h in list(self.hooks.values()):
            h.stop()
        self.hooks.clear()
        self.service.stop()


class AdminClient:
    """Part-admin operations the balancer drives, fanned out to storage
    nodes (ref meta/processors/admin/AdminClient + storaged's
    AdminProcessor: transLeader/addPart/addLearner/waitingForCatchUpData/
    memberChange/removePart)."""

    def __init__(self, nodes: Dict[str, StorageNode]):
        self.nodes = nodes

    def _leader_raft(self, space_id: int, part_id: int,
                     timeout: float = 5.0) -> RaftPart:
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for node in self.nodes.values():
                r = node.raft(space_id, part_id)
                if r is not None and r.is_leader():
                    return r
            time.sleep(0.02)
        raise TimeoutError(f"no leader for ({space_id},{part_id})")

    def leader_of(self, space_id: int, part_id: int,
                  timeout: float = 5.0) -> str:
        return self._leader_raft(space_id, part_id, timeout).addr

    def add_part(self, addr: str, space_id: int, part_id: int,
                 peers: List[str], as_learner: bool) -> None:
        self.nodes[addr].add_part(space_id, part_id, peers, as_learner)

    def add_learner(self, space_id: int, part_id: int, learner: str) -> bool:
        fut = self._leader_raft(space_id, part_id).add_learner_async(learner)
        return fut.result(timeout=5) is RaftCode.SUCCEEDED

    def wait_catchup(self, space_id: int, part_id: int, target: str,
                     timeout: float = 10.0) -> bool:
        import time
        leader = self._leader_raft(space_id, part_id)
        goal = leader.committed_id
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            r = self.nodes[target].raft(space_id, part_id)
            if r is not None and r.committed_id >= goal:
                return True
            time.sleep(0.02)
        return False

    def member_add(self, space_id: int, part_id: int, addr: str) -> bool:
        fut = self._leader_raft(space_id, part_id).add_peer_async(addr)
        return fut.result(timeout=5) is RaftCode.SUCCEEDED

    def member_remove(self, space_id: int, part_id: int, addr: str) -> bool:
        fut = self._leader_raft(space_id, part_id).remove_peer_async(addr)
        return fut.result(timeout=5) is RaftCode.SUCCEEDED

    def trans_leader(self, space_id: int, part_id: int, target: str,
                     timeout: float = 5.0) -> bool:
        import time
        leader = self._leader_raft(space_id, part_id)
        if leader.addr == target:
            return True
        leader.transfer_leader_async(target)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            r = self.nodes[target].raft(space_id, part_id)
            if r is not None and r.is_leader():
                return True
            time.sleep(0.02)
        return False

    def remove_part(self, addr: str, space_id: int, part_id: int) -> None:
        node = self.nodes.get(addr)
        if node is not None:
            node.remove_part(space_id, part_id)

    def leader_map(self, space_id: int,
                   parts: List[int]) -> Dict[int, Optional[str]]:
        out = {}
        for p in parts:
            try:
                out[p] = self.leader_of(space_id, p, timeout=2.0)
            except TimeoutError:
                out[p] = None
        return out


class ReplicatedStores:
    """N replica GraphStores over one raft network (test/deploy helper)."""

    def __init__(self, n: int, data_root: str,
                 engine_factory_for=None, **raft_kw):
        self.net = InProcNetwork()
        self.addrs = [f"storage-{i}" for i in range(n)]
        self.data_root = data_root
        self.raft_kw = raft_kw
        self.services: Dict[str, RaftexService] = {
            a: RaftexService(a, self.net) for a in self.addrs}
        self.hooks: Dict[str, Dict[tuple, RaftConsensusHook]] = {
            a: {} for a in self.addrs}
        self.stores: Dict[str, GraphStore] = {}
        for addr in self.addrs:
            self.stores[addr] = self._make_store(addr, engine_factory_for)

    def _make_store(self, addr: str, engine_factory_for) -> GraphStore:
        def consensus_factory(space_id: int, part_id: int, engine: KVEngine):
            hook = RaftConsensusHook(
                space_id, part_id, engine, addr, list(self.addrs),
                os.path.join(self.data_root, addr), self.services[addr],
                **self.raft_kw)
            self.hooks[addr][(space_id, part_id)] = hook
            return hook
        ef = engine_factory_for(addr) if engine_factory_for else None
        return GraphStore(engine_factory=ef,
                          consensus_factory=consensus_factory)

    def add_part(self, space_id: int, part_id: int) -> None:
        for addr in self.addrs:
            self.stores[addr].add_part(space_id, part_id)

    def leader_of(self, space_id: int, part_id: int,
                  timeout: float = 5.0) -> str:
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = [a for a in self.addrs
                       if self.hooks[a].get((space_id, part_id)) and
                       self.hooks[a][(space_id, part_id)].is_leader()]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.02)
        raise AssertionError(f"no leader for ({space_id},{part_id})")

    def stop(self) -> None:
        for hooks in self.hooks.values():
            for h in hooks.values():
                h.stop()
        for svc in self.services.values():
            svc.stop()
        self.net.shutdown()
