"""Raft consensus (ref kvstore/raftex/): RaftPart + Host + RaftexService
over a pluggable transport, with WAL-backed logs and snapshot transfer."""
from .types import (AppendLogRequest, AppendLogResponse, AskForVoteRequest,
                    AskForVoteResponse, LogRecord, LogType, RaftCode, Role,
                    SendSnapshotRequest, SendSnapshotResponse)
from .service import InProcNetwork, RaftexService, Transport
from .host import Host
from .raft_part import RaftPart

__all__ = [
    "AppendLogRequest", "AppendLogResponse", "AskForVoteRequest",
    "AskForVoteResponse", "LogRecord", "LogType", "RaftCode", "Role",
    "SendSnapshotRequest", "SendSnapshotResponse",
    "InProcNetwork", "RaftexService", "Transport", "Host", "RaftPart",
]
