"""Per-peer replication progress.

Role parity with the reference's `kvstore/raftex/Host.cpp`: tracks how
far each follower has acknowledged, resolves log gaps by backing the
send cursor up to the follower's actual last log id, and flags when the
follower is so far behind that the leader's WAL no longer holds the
needed logs — the trigger for snapshot transfer (ref Host.cpp:409).
"""
from __future__ import annotations

import threading
import time


class Host:
    def __init__(self, addr: str, is_learner: bool = False):
        self.addr = addr
        self.is_learner = is_learner
        # next log id to send; match = highest id known replicated
        self.next_id = 1
        self.match_id = 0
        self.sending_snapshot = False
        self.paused = False
        # replica staleness watermarks (docs/manual/12-replication.md,
        # "Workload & data observatory"): when this follower last
        # acked an append, and when it was last observed fully caught
        # up to the leader's commit index — staleness_ms derives from
        # these on the leader (RaftPart.replica_watermarks)
        self.last_ack_ts = 0.0
        self.caught_up_ts = time.monotonic()
        # consistency observatory (common/consistency.py): outcome of
        # the leader's last digest comparison against this replica —
        # None until a comparable anchor was seen, then True/False;
        # digest_anchor is the applied log id the verdict anchors to
        self.digest_ok: "bool|None" = None
        self.digest_anchor = 0
        self.digest_ts = 0.0
        self._lock = threading.Lock()

    def reset_for_leader(self, last_log_id: int) -> None:
        with self._lock:
            self.next_id = last_log_id + 1
            self.match_id = 0
            self.sending_snapshot = False
            self.caught_up_ts = time.monotonic()

    def on_success(self, last_sent: int) -> None:
        with self._lock:
            self.match_id = max(self.match_id, last_sent)
            self.next_id = self.match_id + 1
            self.last_ack_ts = time.monotonic()

    def on_gap(self, follower_last: int) -> None:
        """Follower is behind/conflicting: back up to just past its
        actual tail (ref Host.cpp:181-330 gap resolution)."""
        with self._lock:
            self.next_id = max(1, follower_last + 1)
            self.match_id = min(self.match_id, follower_last)

    def __repr__(self):
        return (f"Host({self.addr}, next={self.next_id}, "
                f"match={self.match_id}, learner={self.is_learner})")
