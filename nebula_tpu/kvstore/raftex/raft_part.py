"""Raft consensus state machine driver.

Role parity with the reference's `kvstore/raftex/RaftPart.{h,cpp}`:
 - roles LEADER/FOLLOWER/CANDIDATE/LEARNER (RaftPart.h:272-278)
 - log types NORMAL/ATOMIC_OP/COMMAND (RaftPart.h:48-60)
 - batched async appends: callers append to the leader's WAL under the
   serialization lock and get a future; a single replicator round ships
   everything new to every peer at once, so concurrent writers coalesce
   into one round exactly like the reference's PromiseSet buffering
   (RaftPart.h:381-455)
 - election with randomized timeout (RaftPart.cpp:1040,1148-1182)
 - follower append path with gap/stale/term-conflict handling and WAL
   rollback (RaftPart.cpp:1327, verifyLeader :1513)
 - membership COMMAND logs (add/remove peer, add learner, transfer
   leader) applied at append time, mirroring preProcessLog
   (kvstore/Part.cpp:358-417)
 - snapshot transfer when a follower is behind the leader's WAL head
   (SnapshotManager.cpp:20-120, receive at RaftPart.cpp:1601)

Commit rule: advance to the median match index, but only once a log of
the current term is committed (the term-start noop guarantees progress),
per the Raft safety argument.

The state machine seam is three callbacks (on_commit / on_snapshot /
snapshot_rows), matching the reference's commitLogs / commitSnapshot /
accessAllRowsInSnapshot virtuals (RaftPart.h:241-252).

Crash recovery (docs/manual/12-replication.md): at bind the part
measures the WAL tail above the engine's persisted commit marker
(`applied_id`) — the entries a hard kill left durable in the log but
not yet applied. The tail is NOT applied eagerly: raft forbids a
restarted replica from deciding commitment on its own, so the tail
replays through the normal `_commit_range_locked` -> `on_commit`
batch path (idempotent re-apply) once commitment is re-established —
either by the new leader's committed_log_id (follower) or by this
replica's own term-start no-op committing in its new term
(leader-elect). Membership COMMAND entries found in the tail are
re-applied to the in-memory peer/learner sets at bind (their append-
time effects died with the process); TRANS_LEADER is skipped — a
pre-crash transfer must not trigger an election from a constructor.
When the tail is fully covered the part emits a `wal_replay` flight
event and counts `raftex.wal_replayed`; a tail discarded by a term-
conflict rollback or replaced wholesale by a snapshot shrinks or
cancels the accounting instead.
"""
from __future__ import annotations

import binascii
import os
import random
import threading
import time
from concurrent.futures import FIRST_COMPLETED, Future
from concurrent.futures import wait as futures_wait
from typing import Callable, Dict, List, Optional, Tuple

from ...common import heat as _heat
from ...common import profiler as _profiler
from ...common import writepath as _writepath
from ...common.faults import faults
from ...common.flight import recorder as flight
from ...common.stats import stats
from ..wal import Wal
from .host import Host
from .service import RaftexService, Transport
from .types import (AppendLogRequest, AppendLogResponse, AskForVoteRequest,
                    AskForVoteResponse, LogRecord, LogType, RaftCode, Role,
                    SendSnapshotRequest, SendSnapshotResponse)

# WAL payload = 1-byte log-type marker + payload. COMMAND payloads are
# raft-owned (membership/leader-transfer), NORMAL payloads belong to the
# state machine.
_M_NORMAL = b"\x00"
_M_COMMAND = b"\x02"

# COMMAND opcodes (raft-internal encoding)
CMD_ADD_LEARNER = 1
CMD_ADD_PEER = 2
CMD_REMOVE_PEER = 3
CMD_TRANS_LEADER = 4

SNAPSHOT_CHUNK_ROWS = 1024


def _encode_cmd(op: int, addr: str) -> bytes:
    return bytes([op]) + addr.encode()


def _decode_cmd(data: bytes) -> Tuple[int, str]:
    return data[0], data[1:].decode()


class RaftPart:
    def __init__(self, space_id: int, part_id: int, addr: str,
                 peers: List[str], wal_dir: str,
                 service: RaftexService,
                 on_commit: Callable[[List[Tuple[int, int, bytes]]], None],
                 on_snapshot: Callable[[List[Tuple[bytes, bytes]], int, int, bool], None] = None,
                 snapshot_rows: Callable[[], List[Tuple[bytes, bytes]]] = None,
                 applied_id: int = 0,
                 is_learner: bool = False,
                 heartbeat_interval: float = 0.15,
                 election_timeout: float = 0.45,
                 rpc_timeout: float = 1.0,
                 wal_ttl_secs: int = 86400,
                 wal_file_size: int = 16 * 1024 * 1024,
                 on_leader_change: Callable[[Optional[str]], None] = None,
                 digest_probe: Callable[[], Optional[Tuple[int, int, int]]] = None,
                 digest_at: Callable[[int], Optional[int]] = None):
        self.space_id = space_id
        self.part_id = part_id
        self.addr = addr
        self.peers = list(peers)            # voting members, includes self
        self.learners: List[str] = []
        self.service = service
        self.network: Transport = service.network

        self._on_commit = on_commit
        self._on_snapshot = on_snapshot
        self._snapshot_rows = snapshot_rows
        self._on_leader_change = on_leader_change
        # consistency observatory (common/consistency.py): the state
        # machine's content-digest seams — the responder reports its
        # anchor on every append/heartbeat response, the leader
        # compares each follower's anchor against its own history
        self._digest_probe = digest_probe
        self._digest_at = digest_at

        self._hb = heartbeat_interval
        self._election_timeout = election_timeout
        self._rpc_timeout = rpc_timeout

        # contention-profiled (common/profiler.py): every raft part's
        # lock shares ONE site ("raft_part"), so the
        # nebula_lock_wait_us_raft_part histogram is the tier-wide
        # consensus-lock convoy signal
        self._lock = _profiler.profiled_rlock("raft_part")
        self.role = Role.LEARNER if is_learner else Role.FOLLOWER
        self.term = 0
        self.voted_for: Optional[str] = None
        self.leader_addr: Optional[str] = None
        self.committed_id = applied_id
        self._last_msg_recv = time.monotonic()
        self._next_election_due = self._rand_timeout()
        self._last_quorum_contact = time.monotonic()
        # replica-staleness bookkeeping throttle (_note_staleness)
        self._stale_noted_ts = 0.0
        # bounded-staleness follower-read fence (docs/manual/
        # 12-replication.md "Follower reads"): highest leader commit
        # index this replica has SEEN (not necessarily applied) and
        # the last instant it was provably caught up to it. Both
        # advance on the append/heartbeat path under the part lock;
        # read_fence() turns them into a grant/reject decision.
        self._fence_leader_commit = 0
        self._fence_caught_up_ts = 0.0
        self.follower_read_stats = {"granted": 0, "rejected_stale": 0,
                                    "rejected_commit": 0, "fault_lies": 0}

        os.makedirs(wal_dir, exist_ok=True)
        # wal_sync_every_append (REBOOT gflag, read at part bind like
        # the raft timing flags): per-append fsync for power-loss
        # durability — docs/manual/12-replication.md, durability
        # caveats
        from ...common.flags import storage_flags
        self.wal = Wal(os.path.join(wal_dir, "wal"), ttl_secs=wal_ttl_secs,
                       max_file_size=wal_file_size,
                       sync_every_append=bool(storage_flags.get(
                           "wal_sync_every_append", False)))
        self._state_path = os.path.join(wal_dir, "raft_state")
        self._persisted_learner: Optional[bool] = None
        self._load_state()

        # Same-dir restart fencing: the storaged topology-join
        # heuristic flags any part whose group already runs elsewhere
        # as a LEARNER (an EMPTY-log voter campaigning would depose
        # the incumbent). A replica restarting on its own data dir
        # trips that heuristic too — but its raft_state records the
        # role it actually held, and a persisted VOTER staying a
        # learner would silently shrink the voting set. Only a
        # provably-persisted voter is promoted: a genuine mid-catchup
        # learner (or a pre-upgrade state file) keeps the learner
        # fencing. Evacuations purge the WAL dir (raft_store
        # hook.stop(purge=True)), so surviving state is this part's
        # own history, not a predecessor's.
        if is_learner and self.role is Role.LEARNER and \
                self._persisted_learner is False:
            self.role = Role.FOLLOWER

        # ---- boot recovery bookkeeping (module doc: crash recovery).
        # The tail [committed_id+1 .. wal.last] survived the previous
        # process in the WAL but not (necessarily) in the engine; it
        # replays through _commit_range_locked once commitment is
        # re-established under a current term.
        boot_last = self.wal.last_log_id
        self._boot_replay_base = min(self.committed_id, boot_last)
        self._boot_replay_to = boot_last
        self._boot_replay_done = boot_last <= self.committed_id
        self.wal_replayed = 0        # tail entries re-applied at boot
        self.wal_cleaned = 0         # segment files compacted away
        # last commit_logs batch (duration us, entry count): read by
        # the WAITER after its append future resolves to backdate a
        # raft.commit_logs span into its OWN trace — the commit itself
        # runs on the replicator thread under the part lock, where the
        # PR 10 rule forbids recording spans (kvstore/raft_store.py)
        self.last_commit_us = 0
        self.last_commit_n = 0
        # hosts/pending must exist BEFORE the tail re-apply below — a
        # REMOVE_PEER command in the tail touches self.hosts
        self._pending: Dict[int, Future] = {}   # log_id -> caller future
        self.hosts: Dict[str, Host] = {}
        # bounded per-peer in-flight (ISSUE 18): append sends that
        # outlived their round's gather, keyed by follower addr.
        # Value: (future, request, host, committed-at-round-start).
        # Replicator-thread-private — see _replicate_once.
        self._repl_inflight: Dict[
            str, Tuple[Future, AppendLogRequest, Host, int]] = {}
        if not self._boot_replay_done:
            # membership COMMANDs in the tail mutated the in-memory
            # peer/learner sets at append time pre-crash; restore that
            # (TRANS_LEADER excluded — see module doc)
            for e in self.wal.iterate(self.committed_id + 1, boot_last):
                if e.data[:1] == _M_COMMAND:
                    op, _target = _decode_cmd(e.data[1:])
                    if op != CMD_TRANS_LEADER:
                        self._apply_command_locked(e.data[1:])

        self._running = True
        self._repl_cv = threading.Condition()
        self._repl_needed = False
        self._last_round = 0.0
        # nlint: disable=NL002 -- part-lifetime consensus loops; they
        # serve every client and must not adopt the booter's trace
        self._repl_thread = threading.Thread(
            target=self._replicator_loop, daemon=True,
            name=f"raft-repl-{space_id}-{part_id}-{addr}")
        # nlint: disable=NL002 -- part-lifetime consensus loop (above)
        self._tick_thread = threading.Thread(
            target=self._ticker_loop, daemon=True,
            name=f"raft-tick-{space_id}-{part_id}-{addr}")

        # snapshot receive state
        self._recv_snapshot_rows = 0

        service.add_part(self)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        self._repl_thread.start()
        self._tick_thread.start()

    def stop(self) -> None:
        with self._lock:
            self._running = False
            pending = list(self._pending.values())
            self._pending.clear()
        with self._repl_cv:
            self._repl_cv.notify_all()
        for f in pending:
            if not f.done():
                f.set_result(RaftCode.E_HOST_STOPPED)
        self.service.remove_part(self.space_id, self.part_id)
        self.wal.close()

    # ------------------------------------------------------------------
    # client API
    # ------------------------------------------------------------------
    def is_leader(self) -> bool:
        with self._lock:
            return self.role is Role.LEADER

    def leader(self) -> Optional[str]:
        with self._lock:
            return self.leader_addr

    def append_async(self, data: bytes) -> Future:
        return self._append(LogType.NORMAL, data)

    def atomic_op_async(self, op: Callable[[], Optional[bytes]]) -> Future:
        """Evaluate `op` at the serialization point; commit its output
        (ref atomicOpAsync, RaftPart.h:166-176)."""
        fut: Future = Future()
        with self._lock:
            if self.role is not Role.LEADER:
                fut.set_result(RaftCode.E_NOT_A_LEADER)
                return fut
            data = op()
            if data is None:
                fut.set_result(RaftCode.E_BAD_STATE)
                return fut
            return self._append_locked(LogType.NORMAL, data, fut)

    def add_learner_async(self, addr: str) -> Future:
        return self._append(LogType.COMMAND, _encode_cmd(CMD_ADD_LEARNER, addr))

    def add_peer_async(self, addr: str) -> Future:
        return self._append(LogType.COMMAND, _encode_cmd(CMD_ADD_PEER, addr))

    def remove_peer_async(self, addr: str) -> Future:
        return self._append(LogType.COMMAND, _encode_cmd(CMD_REMOVE_PEER, addr))

    def transfer_leader_async(self, target: str) -> Future:
        return self._append(LogType.COMMAND, _encode_cmd(CMD_TRANS_LEADER, target))

    def _append(self, log_type: LogType, data: bytes) -> Future:
        fut: Future = Future()
        with self._lock:
            if not self._running:
                fut.set_result(RaftCode.E_HOST_STOPPED)
                return fut
            if self.role is not Role.LEADER:
                fut.set_result(RaftCode.E_NOT_A_LEADER)
                return fut
            return self._append_locked(log_type, data, fut)

    def _append_locked(self, log_type: LogType, data: bytes,
                       fut: Future) -> Future:
        marker = _M_COMMAND if log_type is LogType.COMMAND else _M_NORMAL
        log_id = self.wal.last_log_id + 1
        if not self.wal.append(log_id, self.term, 0, marker + data):
            fut.set_result(RaftCode.E_WAL_FAIL)
            return fut
        # write heat, charged on the accepting leader (workload
        # observatory, common/heat.py — counter bump, leaf lock)
        _heat.accountant.charge(self.space_id, self.part_id,
                                raft_appends=1)
        if log_type is LogType.COMMAND:
            self._apply_command_locked(data)
        self._pending[log_id] = fut
        self._wake_replicator()
        return fut

    # ------------------------------------------------------------------
    # membership commands (applied at append time on every replica,
    # mirroring preProcessLog)
    # ------------------------------------------------------------------
    def _apply_command_locked(self, data: bytes) -> None:
        op, target = _decode_cmd(data)
        if op == CMD_ADD_LEARNER:
            if target not in self.learners and target not in self.peers:
                self.learners.append(target)
            if self.role is Role.LEADER and target != self.addr and \
                    target not in self.hosts:
                h = Host(target, is_learner=True)
                h.reset_for_leader(0)   # start from scratch; gap resolves
                self.hosts[target] = h
        elif op == CMD_ADD_PEER:
            if target in self.learners:
                self.learners.remove(target)
            if target not in self.peers:
                self.peers.append(target)
            if self.role is Role.LEADER and target != self.addr:
                h = self.hosts.get(target)
                if h is None:
                    h = Host(target)
                    h.reset_for_leader(0)
                    self.hosts[target] = h
                h.is_learner = False
            # a promoted learner becomes a follower on its own replica
            # — persisted, so a same-dir restart re-binds as a VOTER
            if target == self.addr and self.role is Role.LEARNER:
                self.role = Role.FOLLOWER
                self._last_msg_recv = time.monotonic()
                self._persist_state()
        elif op == CMD_REMOVE_PEER:
            if target in self.peers:
                self.peers.remove(target)
            self.hosts.pop(target, None)
            if target == self.addr and self.role is Role.LEADER:
                self._step_down_locked(self.term, None)
        elif op == CMD_TRANS_LEADER:
            # The designated successor campaigns immediately with a
            # higher term; the old leader steps down when it sees the
            # vote request (the command must replicate first, so the
            # leader does NOT step down at append time).
            if target == self.addr and self.role is not Role.LEADER:
                # nlint: disable=NL002 -- election is cluster state
                # machinery, not work owed to the triggering request
                threading.Thread(
                    target=self._leader_election, daemon=True,
                    name=f"raft-elect-{self.space_id}-{self.part_id}"
                ).start()

    # ------------------------------------------------------------------
    # replicator: one round ships wal[next..last] to every host, then
    # advances commit on quorum — serving appends, heartbeats and
    # follower catch-up with a single mechanism
    # ------------------------------------------------------------------
    def _wake_replicator(self) -> None:
        with self._repl_cv:
            self._repl_needed = True
            self._repl_cv.notify()

    def _replicator_loop(self) -> None:
        while True:
            with self._repl_cv:
                if not self._repl_needed:
                    self._repl_cv.wait(timeout=self._hb / 2)
                self._repl_needed = False
            if not self._running:
                return
            with self._lock:
                is_leader = self.role is Role.LEADER
                behind = is_leader and (
                    self.committed_id < self.wal.last_log_id or
                    any(h.match_id < self.wal.last_log_id
                        for h in self.hosts.values()))
            if is_leader and (behind or
                              time.monotonic() - self._last_round >= self._hb):
                try:
                    self._replicate_once()
                except Exception:
                    pass
                self._last_round = time.monotonic()

    def _absorb_append_resp(self, host: Host, req: AppendLogRequest,
                            resp: AppendLogResponse,
                            committed: int) -> bool:
        """Apply one append_log response to the host's replication
        state (shared by the fresh-send gather and the parked-send
        harvest). Returns False when the response deposed this leader
        — the caller must abandon its round."""
        if resp.code is RaftCode.SUCCEEDED:
            host.on_success(req.prev_log_id + len(req.entries))
            # staleness watermark: the follower is "caught up" when
            # its durable match covers everything the leader had
            # committed at the request's round start — the timestamp
            # staleness_ms is estimated from while it lags
            if host.match_id >= committed:
                host.caught_up_ts = time.monotonic()
            # consistency: compare the replica's reported content-
            # digest anchor against this leader's own history at the
            # same applied index (common/consistency.py) — outside
            # the part lock, monitoring-grade
            if getattr(resp, "digest", None) is not None:
                self._note_replica_digest(host, resp.digest)
        elif resp.code in (RaftCode.E_LOG_GAP, RaftCode.E_LOG_STALE):
            host.on_gap(resp.last_log_id)
        elif resp.code is RaftCode.E_TERM_OUT_OF_DATE:
            with self._lock:
                if resp.term > self.term:
                    self._step_down_locked(resp.term, None)
            return False
        return True

    def _replicate_once(self) -> None:
        """One replication round with bounded per-peer in-flight
        (ISSUE 18): a follower whose previous append is still in
        flight is SKIPPED this round instead of re-waited — a
        blackholed (accept-then-hang) follower costs the pipeline at
        most one bounded gather once, then zero, while healthy
        followers keep replicating at full cadence. Parked sends are
        harvested when their transport future finally resolves (late
        acks still advance match/commit), after which the follower
        re-enters the rotation and catches up batch by batch.
        `_repl_inflight` is touched only by the replicator thread."""
        t_round0 = time.monotonic()
        with self._lock:
            if self.role is not Role.LEADER:
                return
            term = self.term
            last_id = self.wal.last_log_id
            committed = self.committed_id
            # group-commit readiness (write-path observatory): appends
            # awaiting commit when this round starts — the occupancy a
            # pipelined group-commit design would batch
            n_pending = len(self._pending)
            targets = [(h, self._build_append_locked(h, committed))
                       for h in list(self.hosts.values())
                       if h.addr not in self._repl_inflight]

        # harvest parked sends whose reply finally arrived
        reached = 1   # self
        for addr, (f, req, host, req_committed) in \
                list(self._repl_inflight.items()):
            if not f.done():
                continue
            del self._repl_inflight[addr]
            try:
                resp: AppendLogResponse = f.result()
            except Exception:
                continue
            if req.term != term:
                continue      # parked under a previous leadership
            stats.add_value("raftex.replicate.late_ack", kind="counter")
            if resp.code is not RaftCode.E_UNREACHABLE \
                    and not host.is_learner:
                reached += 1
            if not self._absorb_append_resp(host, req, resp,
                                            req_committed):
                return
        if self._repl_inflight:
            stats.add_value("raftex.replicate.skipped_inflight",
                            kind="counter")

        sends = []
        for host, req in targets:
            if req is None:           # host needs a snapshot
                self._maybe_send_snapshot(host)
                continue
            f = self.network.call(self.addr, host.addr, "append_log", req)
            sends.append((host, req, f))
        n_shipped = sum(len(req.entries) for _, req, _f in sends)
        t_sends = time.monotonic()
        t_quorum: Optional[float] = None

        # gather under ONE shared deadline (not rpc_timeout PER host),
        # with a short post-quorum grace: once a quorum has acked, the
        # round closes and stragglers are parked instead of awaited
        quorum = len(self.peers) // 2 + 1
        pending = {f: (host, req) for host, req, f in sends}
        deadline = time.monotonic() + self._rpc_timeout
        grace_until: Optional[float] = None
        while pending:
            now = time.monotonic()
            limit = deadline if grace_until is None \
                else min(deadline, grace_until)
            if now >= limit:
                break
            done, _ = futures_wait(set(pending),
                                   timeout=min(0.05, limit - now),
                                   return_when=FIRST_COMPLETED)
            for f in done:
                host, req = pending.pop(f)
                try:
                    resp = f.result()
                except Exception:
                    continue
                if resp.code is not RaftCode.E_UNREACHABLE \
                        and not host.is_learner:
                    reached += 1
                if not self._absorb_append_resp(host, req, resp,
                                                committed):
                    return
            if grace_until is None and pending and reached >= quorum:
                t_quorum = time.monotonic()
                grace_until = t_quorum + 0.025
        if t_quorum is None and reached >= quorum:
            t_quorum = time.monotonic()   # quorum on the last response
        for f, (host, req) in pending.items():
            self._repl_inflight[host.addr] = (f, req, host, committed)
            stats.add_value("raftex.replicate.parked", kind="counter")

        # check-quorum: a leader partitioned away from a majority steps
        # down so its pending appends fail fast instead of hanging
        with self._lock:
            if reached >= quorum:
                self._last_quorum_contact = time.monotonic()
            elif (self.role is Role.LEADER and
                  time.monotonic() - self._last_quorum_contact >
                  2 * self._election_timeout):
                self._step_down_locked(self.term, None)
                return

        self._advance_commit(term, last_id)
        # group-commit readiness metrics (write-path observatory,
        # ROADMAP item 2's before-numbers): rounds that shipped entries
        # record batch size, round wall time, the quorum wait and the
        # pending-append occupancy; heartbeat-only rounds stay silent
        if n_shipped and _writepath.enabled():
            now = time.monotonic()
            stats.add_value("write.raft.round_us",
                            (now - t_round0) * 1e6, kind="histogram")
            stats.add_value("write.raft.round_entries", n_shipped,
                            kind="histogram")
            stats.add_value("write.raft.pending_appends", n_pending,
                            kind="histogram")
            if t_quorum is not None:
                stats.add_value("write.raft.quorum_wait_us",
                                (t_quorum - t_sends) * 1e6,
                                kind="histogram")
        self._note_staleness()

    def _note_staleness(self) -> None:
        """Per-round replica-staleness bookkeeping on the leader:
        feed the raftex.staleness_ms histogram and record a
        flight-recorder `staleness_breach` event past the
        `staleness_breach_ms` flag (0 = disarmed). Time-throttled to
        once per second — the replicator runs every hb/2. Gated on
        the observatory master switch like every other heat family
        (heat_enabled=false must leave /metrics byte-identical to a
        heat-free build; the /raft watermarks themselves are status,
        not telemetry, and stay)."""
        now = time.monotonic()
        if now - self._stale_noted_ts < 1.0:
            return
        self._stale_noted_ts = now
        if not _heat.enabled():
            return
        marks = self.replica_watermarks()
        if not marks:
            return
        breach_ms = float(_heat._flag("staleness_breach_ms", 0) or 0)
        for m in marks:
            stats.add_value("raftex.staleness_ms", m["staleness_ms"],
                            kind="histogram")
            if breach_ms > 0 and m["staleness_ms"] > breach_ms:
                flight.record("staleness_breach", space=self.space_id,
                              part=self.part_id, replica=m["addr"],
                              staleness_ms=m["staleness_ms"],
                              applied=m["applied"],
                              commit=m["commit"])

    def _note_replica_digest(self, host: Host,
                             dig: Tuple[int, int, int]) -> None:
        """Leader-side digest comparison for one replica (consistency
        observatory, common/consistency.py). The replica reports
        (anchor_term, applied_log_id, digest); two replicas at the
        same applied index MUST agree, so a known anchor with a
        different digest is a divergence — counted, flagged on the
        Host, and flight-recorded ON THE TRANSITION (a persistent
        divergence records one event per episode, not one per round).
        Unknown anchors (rolled off the bounded history / batch
        boundaries unaligned) are skipped — never a false positive."""
        from ...common import consistency as _consistency
        if self._digest_at is None or not _consistency.enabled():
            return
        try:
            term, log_id, value = dig
            mine = self._digest_at(int(log_id))
        except Exception:
            return
        stats.add_value("consistency.digest_checks", kind="counter")
        if mine is None:
            stats.add_value("consistency.anchor_miss", kind="counter")
            return
        if mine == value:
            host.digest_ok = True
            host.digest_anchor = int(log_id)
            host.digest_ts = time.monotonic()
            return
        first = host.digest_ok is not False
        host.digest_ok = False
        host.digest_anchor = int(log_id)
        host.digest_ts = time.monotonic()
        if first:
            _consistency.record_divergence(
                self.space_id, self.part_id, host.addr,
                int(log_id), int(term), mine, value)
        else:
            stats.add_value("consistency.divergence", kind="counter")

    def replica_watermarks(self) -> List[dict]:
        """Per-replica applied/commit watermarks + a staleness_ms
        estimate, leader-side (empty on followers/learners — only the
        leader sees the whole group). `applied` is the follower's
        durable match clamped to the leader's commit index (followers
        apply exactly what the leader tells them is committed, so this
        is the tightest bound the protocol itself provides);
        `staleness_ms` is time since the replica was last observed
        fully caught up — bounded by one heartbeat round in the steady
        state, growing while the follower lags. The measurement
        bounded-staleness follower reads will be gated on
        (ROADMAP item 1; docs/manual/12-replication.md)."""
        now = time.monotonic()
        with self._lock:
            if self.role is not Role.LEADER:
                return []
            committed = self.committed_id
            out = []
            for h in self.hosts.values():
                applied = min(h.match_id, committed)
                if h.match_id >= committed:
                    # caught up: staleness is at most the time since
                    # its last ack (one replication round)
                    ref = h.last_ack_ts or h.caught_up_ts
                else:
                    ref = h.caught_up_ts
                out.append({
                    "addr": h.addr, "learner": h.is_learner,
                    "match": h.match_id, "applied": applied,
                    "commit": committed,
                    "lag": max(0, committed - h.match_id),
                    "staleness_ms": round(
                        max(0.0, (now - ref) * 1000.0), 1),
                    # consistency observatory: the leader's latest
                    # digest verdict for this replica (None = no
                    # comparable anchor seen yet / disarmed)
                    "digest_ok": h.digest_ok,
                    "digest_anchor": h.digest_anchor,
                })
            return out

    def read_fence(self, max_ms: float) -> Tuple[bool, float, str]:
        """Bounded-staleness follower-read gate (ROADMAP item 1;
        docs/manual/12-replication.md "Follower reads").

        Returns (ok, staleness_ms, reason). The leader always grants
        at staleness 0 (linearizable by definition). A follower grants
        only when BOTH independent checks pass:

        - commit-index fence: everything the leader reported committed
          on the last append round is applied here (`committed_id >=
          _fence_leader_commit`) — a pure index comparison that a
          clock lie cannot forge;
        - time lease: the replica was provably caught up within
          `min(max_ms, election_timeout)`. The cap means the lease can
          NEVER outlive the window in which a new leader could have
          been elected and committed writes this replica hasn't heard
          about (the classic read-lease safety argument), no matter
          how loose the operator sets `follower_read_max_ms`.

        The `followerread.stale` fault point forges the time watermark
        (staleness -> 0) to prove the commit-index fence independently
        rejects a lying replica (docs/manual/9-robustness.md)."""
        now = time.monotonic()
        with self._lock:
            if self.role is Role.LEADER:
                return True, 0.0, "leader"
            bound = min(float(max_ms), self._election_timeout * 1000.0)
            ts = self._fence_caught_up_ts
            staleness = (now - ts) * 1000.0 if ts > 0 else float("inf")
            try:
                faults.fire("followerread.stale")
            except Exception:
                # injected lie: report a perfectly fresh time
                # watermark — only the commit-index fence stands
                staleness = 0.0
                self.follower_read_stats["fault_lies"] += 1
            if self.committed_id < self._fence_leader_commit:
                self.follower_read_stats["rejected_commit"] += 1
                stats.add_value("raftex.follower_read.rejected_commit",
                                kind="counter")
                return False, staleness, "commit_fence"
            if not (staleness <= bound):
                self.follower_read_stats["rejected_stale"] += 1
                stats.add_value("raftex.follower_read.rejected_stale",
                                kind="counter")
                return False, staleness, "stale"
            self.follower_read_stats["granted"] += 1
            stats.add_value("raftex.follower_read.granted",
                            kind="counter")
            return True, staleness, "follower"

    def _build_append_locked(self, host: Host,
                             committed: int) -> Optional[AppendLogRequest]:
        """Build the batch wal[host.next_id .. last], clamped to one term
        (the per-request log_term covers every entry). None → snapshot."""
        first = self.wal.first_log_id
        if first > 0 and host.next_id < first:
            return None
        prev_id = host.next_id - 1
        prev_term = 0
        if prev_id > 0:
            t = self.wal.log_term(prev_id)
            if t is None:
                return None          # prev evicted: snapshot
            prev_term = t
        entries: List[LogRecord] = []
        log_term = 0
        # bounded range: iterate() materializes under the WAL lock, so
        # the scan must not cover a lagging follower's whole tail
        for e in self.wal.iterate(host.next_id, host.next_id + 255):
            if not entries:
                log_term = e.term
            elif e.term != log_term:
                break                # keep the batch single-term
            entries.append(LogRecord(e.cluster, e.data))
            if len(entries) >= 256:  # ref max_batch_size
                break
        return AppendLogRequest(
            space=self.space_id, part=self.part_id, term=self.term,
            leader=self.addr, committed_log_id=committed,
            prev_log_id=prev_id, prev_log_term=prev_term,
            entries=entries, log_term=log_term or self.term)

    def _advance_commit(self, term: int, last_id: int) -> None:
        with self._lock:
            if self.role is not Role.LEADER or self.term != term:
                return
            # median match across voting members (self counts at last_id)
            matches = [last_id]
            for h in self.hosts.values():
                if not h.is_learner:
                    matches.append(h.match_id)
            matches.sort(reverse=True)
            quorum = len(matches) // 2 + 1
            candidate = matches[quorum - 1]
            if candidate <= self.committed_id:
                return
            # Raft safety: only commit once a current-term log is covered
            t = self.wal.log_term(candidate)
            if t is not None and t != self.term:
                return
            self._commit_range_locked(self.committed_id + 1, candidate)

    def _commit_range_locked(self, from_id: int, to_id: int) -> None:
        batch: List[Tuple[int, int, bytes]] = []
        for e in self.wal.iterate(from_id, to_id):
            marker, payload = e.data[:1], e.data[1:]
            if marker == _M_COMMAND:
                batch.append((e.log_id, e.term, b""))   # id advances only
            else:
                batch.append((e.log_id, e.term, payload))
        if batch:
            # crashpoint: the batch is durable in the WAL; the engine
            # has not applied it. A crash here is exactly the window
            # restart recovery must close (bench --crash forces it).
            faults.fire("crashpoint.wal_applied")
            t0 = time.monotonic()
            self._on_commit(batch)
            self.last_commit_us = int((time.monotonic() - t0) * 1e6)
            self.last_commit_n = len(batch)
            # raft append batch occupancy (write-path observatory):
            # entries applied as ONE engine batch — the group-commit
            # granularity item 2 will widen. Counter-class recording
            # under the raft lock follows the read_fence precedent.
            if _writepath.enabled():
                stats.add_value("write.raft.commit_batch_entries",
                                len(batch), kind="histogram")
        self.committed_id = to_id
        self._note_replay_locked(from_id, to_id)
        done = [f for i, f in self._pending.items() if i <= to_id]
        for i in [i for i in self._pending if i <= to_id]:
            del self._pending[i]
        for f in done:
            if not f.done():
                f.set_result(RaftCode.SUCCEEDED)

    # ------------------------------------------------------------------
    # boot-recovery accounting (module doc: crash recovery)
    # ------------------------------------------------------------------
    def _note_replay_locked(self, from_id: int, to_id: int) -> None:
        """Track how much of the boot tail a commit advance covered;
        emit the `wal_replay` flight event once the tail is fully
        re-applied (the bench --crash recovery proof reads it)."""
        if self._boot_replay_done or from_id > self._boot_replay_to:
            return
        replayed = min(to_id, self._boot_replay_to) - from_id + 1
        if replayed > 0:
            self.wal_replayed += replayed
            stats.add_value("raftex.wal_replayed", replayed,
                            kind="counter")
        if to_id >= self._boot_replay_to:
            self._boot_replay_done = True
            flight.record("wal_replay", space=self.space_id,
                          part=self.part_id, addr=self.addr,
                          from_id=self._boot_replay_base + 1,
                          to_id=self._boot_replay_to,
                          n=self.wal_replayed)

    def _note_tail_rollback_locked(self, keep_to: int) -> None:
        """A term-conflict rollback discarded WAL entries above
        `keep_to`: any part of the boot tail up there was never
        committed and will not replay — shrink the accounting so the
        wal_replay event still fires for what remains."""
        if self._boot_replay_done or keep_to >= self._boot_replay_to:
            return
        self._boot_replay_to = keep_to
        if self._boot_replay_to <= self.committed_id:
            self._boot_replay_done = True
            if self.wal_replayed:
                flight.record("wal_replay", space=self.space_id,
                              part=self.part_id, addr=self.addr,
                              from_id=self._boot_replay_base + 1,
                              to_id=self._boot_replay_to,
                              n=self.wal_replayed)

    # ------------------------------------------------------------------
    # elections
    # ------------------------------------------------------------------
    def _rand_timeout(self) -> float:
        return self._election_timeout * (1.0 + random.random())

    def _ticker_loop(self) -> None:
        tick = self._hb / 4
        while True:
            time.sleep(tick)
            if not self._running:
                return
            with self._lock:
                role = self.role
                idle = time.monotonic() - self._last_msg_recv
                due = self._next_election_due
            if role is Role.LEADER:
                self._wake_replicator()
            elif role in (Role.FOLLOWER, Role.CANDIDATE) and idle > due:
                self._leader_election()

    def _leader_election(self) -> None:
        with self._lock:
            if not self._running or self.role in (Role.LEADER, Role.LEARNER):
                return
            self.role = Role.CANDIDATE
            self.term += 1
            self.voted_for = self.addr
            self.leader_addr = None
            self._persist_state()
            term = self.term
            req = AskForVoteRequest(
                space=self.space_id, part=self.part_id, candidate=self.addr,
                term=term, last_log_id=self.wal.last_log_id,
                last_log_term=self.wal.last_log_term)
            voters = [p for p in self.peers if p != self.addr]
            quorum = len(self.peers) // 2 + 1
            self._last_msg_recv = time.monotonic()
            self._next_election_due = self._rand_timeout()

        votes = 1   # self
        futs = [self.network.call(self.addr, p, "ask_for_vote", req)
                for p in voters]
        max_term_seen = term
        for f in futs:
            try:
                resp: AskForVoteResponse = f.result(timeout=self._rpc_timeout)
            except Exception:
                continue
            if resp.code is RaftCode.SUCCEEDED:
                votes += 1
            max_term_seen = max(max_term_seen, resp.term)

        with self._lock:
            if self.term != term or self.role is not Role.CANDIDATE:
                return
            if max_term_seen > term:
                self._step_down_locked(max_term_seen, None)
                return
            if votes >= quorum:
                self._become_leader_locked()

    def _become_leader_locked(self) -> None:
        self.role = Role.LEADER
        self.leader_addr = self.addr
        self._last_quorum_contact = time.monotonic()
        last = self.wal.last_log_id
        self.hosts = {}
        for p in self.peers:
            if p != self.addr:
                self.hosts[p] = Host(p)
                self.hosts[p].reset_for_leader(last)
        for l in self.learners:
            self.hosts[l] = Host(l, is_learner=True)
            self.hosts[l].reset_for_leader(last)
        # term-start noop commits everything from prior terms
        self.wal.append(last + 1, self.term, 0, _M_NORMAL)
        if self._on_leader_change:
            try:
                self._on_leader_change(self.addr)
            except Exception:
                pass
        self._wake_replicator()

    def _step_down_locked(self, new_term: int, leader: Optional[str]) -> None:
        was_leader = self.role is Role.LEADER
        if self.role is not Role.LEARNER:
            self.role = Role.FOLLOWER
        if new_term > self.term:
            self.term = new_term
            self.voted_for = None
        self.leader_addr = leader
        self._persist_state()
        self._last_msg_recv = time.monotonic()
        self._next_election_due = self._rand_timeout()
        if was_leader:
            pending = list(self._pending.values())
            self._pending.clear()
            for f in pending:
                if not f.done():
                    f.set_result(RaftCode.E_NOT_A_LEADER)
            if self._on_leader_change:
                try:
                    self._on_leader_change(leader)
                except Exception:
                    pass

    # ------------------------------------------------------------------
    # message handlers (called by RaftexService on transport threads)
    # ------------------------------------------------------------------
    def process_ask_for_vote(self, req: AskForVoteRequest) -> AskForVoteResponse:
        with self._lock:
            if req.term < self.term:
                return AskForVoteResponse(RaftCode.E_TERM_OUT_OF_DATE, self.term)
            if req.term > self.term:
                self._step_down_locked(req.term, None)
            if self.voted_for is not None and self.voted_for != req.candidate:
                return AskForVoteResponse(RaftCode.E_TERM_OUT_OF_DATE, self.term)
            # candidate's log must be at least as up-to-date as ours
            my_last_term = self.wal.last_log_term
            my_last_id = self.wal.last_log_id
            if (req.last_log_term, req.last_log_id) < (my_last_term, my_last_id):
                return AskForVoteResponse(RaftCode.E_LOG_STALE, self.term)
            self.voted_for = req.candidate
            self._persist_state()
            self._last_msg_recv = time.monotonic()
            self._next_election_due = self._rand_timeout()
            return AskForVoteResponse(RaftCode.SUCCEEDED, self.term)

    def process_append_log(self, req: AppendLogRequest) -> AppendLogResponse:
        with self._lock:
            if req.term < self.term:
                return self._append_resp_locked(RaftCode.E_TERM_OUT_OF_DATE)
            if req.term > self.term or self.role is Role.CANDIDATE or \
                    (self.role is Role.LEADER and req.leader != self.addr):
                self._step_down_locked(req.term, req.leader)
            self.leader_addr = req.leader
            self._last_msg_recv = time.monotonic()
            self._next_election_due = self._rand_timeout()

            wal_last = self.wal.last_log_id
            # gap: we don't yet have the log preceding this batch
            if req.prev_log_id > wal_last:
                return self._append_resp_locked(RaftCode.E_LOG_GAP)
            # consistency check on the attach point
            if req.prev_log_id > 0:
                t = self.wal.log_term(req.prev_log_id)
                if t is None:
                    # evicted by snapshot: fine iff at/before our commit
                    if req.prev_log_id > self.committed_id:
                        return self._append_resp_locked(RaftCode.E_LOG_GAP)
                elif t != req.prev_log_term:
                    # conflicting history: drop our tail, ask for resend
                    keep = max(self.committed_id, req.prev_log_id - 1)
                    self.wal.rollback(keep)
                    self._note_tail_rollback_locked(keep)
                    return self._append_resp_locked(RaftCode.E_LOG_GAP)

            # append entries, skipping overlap and truncating conflicts
            next_id = req.prev_log_id + 1
            for i, rec in enumerate(req.entries):
                lid = next_id + i
                if lid <= self.wal.last_log_id:
                    if self.wal.log_term(lid) == req.log_term:
                        continue     # already have it
                    keep = max(self.committed_id, lid - 1)
                    self.wal.rollback(keep)
                    self._note_tail_rollback_locked(keep)
                if not self.wal.append(lid, req.log_term, rec.cluster,
                                       rec.data):
                    return self._append_resp_locked(RaftCode.E_WAL_FAIL)
                if rec.data[:1] == _M_COMMAND:
                    self._apply_command_locked(rec.data[1:])

            # advance commit to what the leader has committed
            new_commit = min(req.committed_log_id, self.wal.last_log_id)
            if new_commit > self.committed_id:
                self._commit_range_locked(self.committed_id + 1, new_commit)
            # follower-read fence bookkeeping: remember the freshest
            # leader commit index seen, and stamp the instant this
            # replica was provably caught up to it — the two inputs
            # read_fence() gates bounded-staleness reads on
            if req.committed_log_id > self._fence_leader_commit:
                self._fence_leader_commit = req.committed_log_id
            if self.committed_id >= req.committed_log_id:
                self._fence_caught_up_ts = time.monotonic()
            return self._append_resp_locked(RaftCode.SUCCEEDED)

    def _append_resp_locked(self, code: RaftCode) -> AppendLogResponse:
        # additive consistency element (v1.3): report this replica's
        # content-digest anchor so the leader can verify it on the
        # same round — one probe (disarmed: a single flag read)
        dig = None
        if self._digest_probe is not None:
            try:
                dig = self._digest_probe()
            except Exception:
                dig = None
        return AppendLogResponse(
            code=code, term=self.term, leader=self.leader_addr,
            committed_log_id=self.committed_id,
            last_log_id=self.wal.last_log_id,
            last_log_term=self.wal.last_log_term,
            digest=dig)

    # ------------------------------------------------------------------
    # snapshot transfer
    # ------------------------------------------------------------------
    def _maybe_send_snapshot(self, host: Host) -> None:
        with self._lock:
            if host.sending_snapshot or self._snapshot_rows is None:
                return
            host.sending_snapshot = True
        # nlint: disable=NL002 -- catch-up transfer to a lagging peer;
        # spans belong to no client trace
        threading.Thread(target=self._send_snapshot, args=(host,),
                         daemon=True,
                         name=f"raft-snapsend-{self.space_id}-"
                              f"{self.part_id}").start()

    def _send_snapshot(self, host: Host) -> None:
        try:
            with self._lock:
                if self.role is not Role.LEADER:
                    return
                term = self.term
                cid = self.committed_id
                cterm = self.wal.log_term(cid) or 0
            rows = list(self._snapshot_rows())
            total = len(rows)
            total_size = sum(len(k) + len(v) for k, v in rows)
            sent_ok = True
            for off in range(0, max(total, 1), SNAPSHOT_CHUNK_ROWS):
                chunk = rows[off:off + SNAPSHOT_CHUNK_ROWS]
                done = off + SNAPSHOT_CHUNK_ROWS >= total
                req = SendSnapshotRequest(
                    space=self.space_id, part=self.part_id, term=term,
                    leader=self.addr, committed_log_id=cid,
                    committed_log_term=cterm, rows=chunk,
                    total_size=total_size, total_count=total, done=done)
                f = self.network.call(self.addr, host.addr,
                                      "send_snapshot", req)
                try:
                    resp: SendSnapshotResponse = f.result(
                        timeout=self._rpc_timeout * 5)
                except Exception:
                    sent_ok = False
                    break
                if resp.code is not RaftCode.SUCCEEDED:
                    sent_ok = False
                    break
                if done:
                    break
            if sent_ok:
                host.on_success(cid)
        finally:
            host.sending_snapshot = False
            self._wake_replicator()

    def process_send_snapshot(self, req: SendSnapshotRequest) -> SendSnapshotResponse:
        with self._lock:
            if req.term < self.term:
                return SendSnapshotResponse(RaftCode.E_TERM_OUT_OF_DATE,
                                            self.term)
            if req.term > self.term:
                self._step_down_locked(req.term, req.leader)
            self.leader_addr = req.leader
            self._last_msg_recv = time.monotonic()
            if self._recv_snapshot_rows == 0:
                # install START: history is being replaced wholesale,
                # and the state-machine side clears the part prefix on
                # its first chunk — so this replica must become an
                # EMPTY replica now, not at done: WAL reset and commit
                # index back to 0. If the sender aborts mid-install,
                # recovery is then structurally sound either way — a
                # leader still holding log 1 replays the full history
                # into the wiped engine (commit restarts from 1), any
                # compacted leader sees the gap and re-sends a full
                # snapshot. Keeping the old committed_id would block
                # re-apply below it over an engine that no longer has
                # that data.
                self.wal.reset()
                self.committed_id = 0
                self._boot_replay_done = True
                self._boot_replay_to = 0
            if self._on_snapshot is not None:
                self._on_snapshot(req.rows, req.committed_log_id,
                                  req.committed_log_term, req.done)
            self._recv_snapshot_rows += len(req.rows)
            # crashpoint: chunk applied, install NOT finished — a crash
            # here leaves a partial snapshot with no commit marker; the
            # restarted receiver must be able to re-request the whole
            # snapshot and converge (bench --crash forces it)
            if not req.done:
                faults.fire("crashpoint.snapshot_recv")
            if req.done:
                # history replaced wholesale: WAL restarts after the
                # snapshot point (ref RaftPart.cpp:1601)
                self.wal.reset()
                self.committed_id = req.committed_log_id
                self._recv_snapshot_rows = 0
                # any boot tail is gone with the old history — the
                # recovery that actually happened is a snapshot install
                self._boot_replay_done = True
                flight.record("snapshot_install", space=self.space_id,
                              part=self.part_id, addr=self.addr,
                              committed=req.committed_log_id,
                              rows=req.total_count)
            return SendSnapshotResponse(RaftCode.SUCCEEDED, self.term)

    # ------------------------------------------------------------------
    # persistence of (term, voted_for)
    # ------------------------------------------------------------------
    # Layout: "term\nvoted_for\nrole(L|V)\ncrc32-of-first-3-lines\n".
    # The temp file is fsync'd BEFORE the rename and the directory
    # fsync'd after — without both, a power cut can publish a
    # zero-length or torn file under the final name, and without the
    # checksum a torn file parses as garbage (term regression =>
    # double vote). The role line lets a same-dir restart distinguish
    # a returning VOTER from a mid-catchup learner. A file that fails
    # the checksum is treated as absent: the replica restarts at the
    # in-memory defaults, counted (`raftex.state_recovered`) and
    # flight-recorded so operators see it happened.
    def _persist_state(self) -> None:
        role = "L" if self.role is Role.LEARNER else "V"
        payload = f"{self.term}\n{self.voted_for or ''}\n{role}\n"
        crc = binascii.crc32(payload.encode())
        tmp = self._state_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{payload}{crc:08x}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._state_path)
        dfd = os.open(os.path.dirname(self._state_path) or ".",
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def _load_state(self) -> None:
        try:
            with open(self._state_path) as f:
                lines = f.read().splitlines()
        except OSError:
            return                    # first boot: nothing persisted
        try:
            if len(lines) >= 4:
                payload = f"{lines[0]}\n{lines[1]}\n{lines[2]}\n"
                if int(lines[3], 16) != binascii.crc32(payload.encode()):
                    raise ValueError("raft_state checksum mismatch")
                if lines[2] not in ("L", "V"):
                    raise ValueError("raft_state bad role")
                self._persisted_learner = lines[2] == "L"
            elif len(lines) != 2:
                raise ValueError("raft_state truncated")
            # len(lines) == 2: pre-checksum format, accepted once —
            # the next _persist_state upgrades it (role stays unknown)
            self.term = int(lines[0])
            self.voted_for = lines[1] or None
        except (IndexError, ValueError):
            # torn/corrupt: fall back to defaults instead of wedging
            # the election on a garbage term
            stats.add_value("raftex.state_recovered", kind="counter")
            flight.record("state_recovered", space=self.space_id,
                          part=self.part_id, addr=self.addr,
                          path=self._state_path)

    # ------------------------------------------------------------------
    # snapshot-anchored WAL compaction (docs/manual/12-replication.md)
    # ------------------------------------------------------------------
    def compact_wal(self, lag: int, anchor: Optional[int] = None) -> dict:
        """Truncate the WAL prefix behind the applied anchor, keeping
        `lag` entries of headroom, plus run the TTL sweep. `anchor` is
        a caller-supplied DURABLE bound — the storaged compaction task
        captures each part's applied id BEFORE flushing the engine, so
        everything at/below the anchor is on disk when truncation
        happens. It is clamped to committed_id, and `lag >= 0`, so no
        unapplied entry can ever be dropped (whole sealed segments
        only — the native clean keeps every record >= keep_from).
        Bounds both WAL disk and restart replay length."""
        with self._lock:
            committed = self.committed_id
            running = self._running
        if not running:
            return {"removed": 0}
        a = committed if anchor is None else min(int(anchor), committed)
        keep_from = a - max(int(lag), 0)
        removed = 0
        if keep_from > 1:
            removed = self.wal.clean_before(keep_from)
        # satellite: the TTL sweep finally has a caller — aged sealed
        # segments go, but only BELOW the applied anchor: age must
        # never truncate an entry the engine hasn't durably applied
        removed += self.wal.clean_ttl(before_id=a + 1)
        if removed:
            self.wal_cleaned += removed
            stats.add_value("raftex.wal_cleaned", removed,
                            kind="counter")
        stats.add_value("raftex.wal_compactions", kind="counter")
        return {"removed": removed, "anchor": a, "keep_from": keep_from,
                "wal_first": self.wal.first_log_id,
                "wal_last": self.wal.last_log_id}

    # ------------------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "space": self.space_id, "part": self.part_id,
                "addr": self.addr, "role": self.role.name,
                "term": self.term, "leader": self.leader_addr,
                "committed": self.committed_id,
                "last_log_id": self.wal.last_log_id,
                # appended-but-uncommitted depth: >0 sustained on a
                # leader means replication is stuck below quorum
                "commit_lag": max(0, self.wal.last_log_id
                                  - self.committed_id),
                # compaction + boot-recovery state (/raft surfacing):
                # wal_first..last bounds restart replay; wal_replayed
                # is what THIS boot actually re-applied
                "wal_first_log_id": self.wal.first_log_id,
                "wal_replayed": self.wal_replayed,
                "wal_replay_done": self._boot_replay_done,
                "wal_cleaned": self.wal_cleaned,
                "peers": list(self.peers), "learners": list(self.learners),
            }

    def status_with_replicas(self) -> dict:
        """status() + the per-replica staleness watermarks (leader
        only) — the /raft endpoint row (docs/manual/12-replication.md,
        "Replica staleness watermarks")."""
        st = self.status()
        st["replicas"] = self.replica_watermarks()
        st["staleness_ms"] = max(
            (m["staleness_ms"] for m in st["replicas"]), default=0.0)
        # consistency observatory: this replica's own content-digest
        # anchor (status, not telemetry — like the /raft watermarks)
        dig = None
        if self._digest_probe is not None:
            try:
                dig = self._digest_probe()
            except Exception:
                dig = None
        if dig is not None:
            from ...common import consistency as _consistency
            st["digest"] = {"anchor_term": dig[0], "anchor_id": dig[1],
                            "digest": _consistency.hex_digest(dig[2])}
        else:
            st["digest"] = None
        st["digest_divergent"] = sorted(
            m["addr"] for m in st["replicas"]
            if m.get("digest_ok") is False)
        return st
