"""Raft transport + service routing.

Role parity with the reference's `RaftexService` (ref
kvstore/raftex/RaftexService.cpp): one service per process hosts many
raft parts and routes incoming messages by (space, part). The transport
seam is abstract so tests run the reference's idiom — N real services in
one process (ref kvstore/raftex/test/RaftexTestBase) — over an in-proc
network that can also inject partitions/isolation, while production can
bind the same service to TCP.
"""
from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional, Tuple

from .types import (AppendLogResponse, AskForVoteResponse, RaftCode,
                    SendSnapshotResponse)


class Transport:
    """Sends raft messages to a remote service address."""

    def call(self, from_addr: str, to_addr: str, method: str, req) -> Future:
        raise NotImplementedError


def _unreachable_response(method: str):
    if method == "ask_for_vote":
        return AskForVoteResponse(RaftCode.E_UNREACHABLE, 0)
    if method == "append_log":
        return AppendLogResponse(RaftCode.E_UNREACHABLE, 0, None, 0, 0, 0)
    return SendSnapshotResponse(RaftCode.E_UNREACHABLE, 0)


class RpcTransport(Transport):
    """Raft messages over the framed-TCP rpc/ layer — the cross-process
    production transport (role parity: the reference's RaftexService
    thrift server on the raft port, kvstore/NebulaStore.h:55-60
    getRaftAddr). Peer addresses are `host:port` of the peer's raft
    RpcServer hosting its RaftexService under the "raftex" name.

    Socket timeout is on the order of election timeouts, NOT the
    default 30s RPC timeout: a black-holed peer must not pin worker
    threads long enough to starve heartbeats to healthy peers."""

    def __init__(self, max_workers: int = 16, timeout: float = 1.5):
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="raft-rpc")
        self._timeout = timeout

    def call(self, from_addr: str, to_addr: str, method: str, req) -> Future:
        def run():
            from ...rpc import proxy
            try:
                # max_attempts=2: one stale-socket drain + one fresh
                # connect — a black-holed peer costs ~1 timeout, not a
                # whole pool drain. src=from_addr: raft traffic carries
                # its sender identity so DIRECTIONAL nemesis link rules
                # (peer=src>dst) apply — the asymmetric-partition shape
                resp = proxy(to_addr, "raftex", timeout=self._timeout,
                             max_attempts=2,
                             src=from_addr).call(method, req)
            except Exception:
                return _unreachable_response(method)
            if isinstance(resp, (AskForVoteResponse, AppendLogResponse,
                                 SendSnapshotResponse)):
                return resp
            # a peer mid-shutdown can answer with an rpc-layer error
            # payload (plain string) instead of a raft response —
            # treating it as typed crashed the caller's ticker thread
            return _unreachable_response(method)
        return self._pool.submit(run)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class InProcNetwork(Transport):
    """In-process message fabric with fault injection: services register
    under string addresses; `isolate(addr)` simulates a network
    partition (messages to AND from the address are dropped), `stop`
    unregisters — the reference's kill/restart-in-process test idiom."""

    def __init__(self, max_workers: int = 16):
        self._services: Dict[str, "RaftexService"] = {}
        self._isolated: set = set()
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="raft-net")

    def register(self, addr: str, service: "RaftexService") -> None:
        with self._lock:
            self._services[addr] = service

    def unregister(self, addr: str) -> None:
        with self._lock:
            self._services.pop(addr, None)

    def isolate(self, addr: str) -> None:
        with self._lock:
            self._isolated.add(addr)

    def heal(self, addr: str) -> None:
        with self._lock:
            self._isolated.discard(addr)

    def call(self, from_addr: str, to_addr: str, method: str, req) -> Future:
        def run():
            with self._lock:
                svc = self._services.get(to_addr)
                dropped = (from_addr in self._isolated or
                           to_addr in self._isolated or svc is None)
            if dropped:
                return _unreachable_response(method)
            return getattr(svc, method)(req)
        return self._pool.submit(run)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class RaftexService:
    """Routes incoming raft messages to registered parts by (space, part)."""

    def __init__(self, addr: str, network: Transport):
        self.addr = addr
        self.network = network
        self._parts: Dict[Tuple[int, int], object] = {}
        self._lock = threading.Lock()
        if isinstance(network, InProcNetwork):
            network.register(addr, self)

    def add_part(self, part) -> None:
        with self._lock:
            self._parts[(part.space_id, part.part_id)] = part

    def remove_part(self, space_id: int, part_id: int) -> None:
        with self._lock:
            self._parts.pop((space_id, part_id), None)

    def find_part(self, space_id: int, part_id: int):
        with self._lock:
            return self._parts.get((space_id, part_id))

    def stop(self) -> None:
        with self._lock:
            parts = list(self._parts.values())
        for p in parts:
            p.stop()
        if isinstance(self.network, InProcNetwork):
            self.network.unregister(self.addr)

    # ----------------------------------------------------------- handlers
    def ask_for_vote(self, req) -> AskForVoteResponse:
        part = self.find_part(req.space, req.part)
        if part is None:
            return AskForVoteResponse(RaftCode.E_UNKNOWN_PART, 0)
        return part.process_ask_for_vote(req)

    def append_log(self, req) -> AppendLogResponse:
        part = self.find_part(req.space, req.part)
        if part is None:
            return AppendLogResponse(RaftCode.E_UNKNOWN_PART, 0, None, 0, 0, 0)
        return part.process_append_log(req)

    def send_snapshot(self, req) -> SendSnapshotResponse:
        part = self.find_part(req.space, req.part)
        if part is None:
            return SendSnapshotResponse(RaftCode.E_UNKNOWN_PART, 0)
        return part.process_send_snapshot(req)
