"""Raft message/state types.

Mirrors the reference's raftex thrift IDL (ref interface/raftex.thrift:
AskForVote/AppendLog/SendSnapshot requests+responses) and RaftPart's
role/log-type enums (ref kvstore/raftex/RaftPart.h:48-60, 272-278).
Messages are plain dataclasses because the transport seam (in-proc or
TCP) owns serialization.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


class Role(enum.Enum):
    FOLLOWER = 1
    CANDIDATE = 2
    LEADER = 3
    LEARNER = 4


class LogType(enum.IntEnum):
    NORMAL = 0
    ATOMIC_OP = 1
    COMMAND = 2


class RaftCode(enum.IntEnum):
    SUCCEEDED = 0
    E_LOG_GAP = 1            # follower missing logs before the sent batch
    E_LOG_STALE = 2          # follower already has newer (conflicting) logs
    E_TERM_OUT_OF_DATE = 3
    E_WAL_FAIL = 4
    E_NOT_A_LEADER = 5
    E_BAD_STATE = 6
    E_HOST_STOPPED = 7
    E_NOT_READY = 8
    E_UNKNOWN_PART = 9
    E_UNREACHABLE = 10       # transport-level failure


@dataclass
class AskForVoteRequest:
    space: int
    part: int
    candidate: str           # transport address of the candidate
    term: int
    last_log_id: int
    last_log_term: int


@dataclass
class AskForVoteResponse:
    code: RaftCode
    term: int                # voter's current term


@dataclass
class LogRecord:
    cluster: int
    data: bytes


@dataclass
class AppendLogRequest:
    space: int
    part: int
    term: int
    leader: str
    committed_log_id: int
    # consistency check point: the log immediately before the batch
    prev_log_id: int
    prev_log_term: int
    entries: List[LogRecord] = field(default_factory=list)
    # term stamped on every entry in this batch
    log_term: int = 0


@dataclass
class AppendLogResponse:
    code: RaftCode
    term: int
    leader: Optional[str]
    committed_log_id: int
    last_log_id: int
    last_log_term: int
    # consistency observatory (v1.3 additive, docs/manual/
    # 6-wire-protocol.md §2): the responder's content-digest anchor
    # (anchor_term, applied_log_id, digest) for this part, or None
    # when disarmed/mid-install — the leader compares it against its
    # own anchor history on every replication round
    digest: Optional[Tuple[int, int, int]] = None


@dataclass
class SendSnapshotRequest:
    space: int
    part: int
    term: int
    leader: str
    committed_log_id: int
    committed_log_term: int
    rows: List[Tuple[bytes, bytes]]
    total_size: int
    total_count: int
    done: bool


@dataclass
class SendSnapshotResponse:
    code: RaftCode
    term: int
