"""Columnar scan containers — jax-free, importable by storaged.

The CSR snapshot builder (engine_tpu/csr.py) and the snapshot-sync RPC
(storage/processors.py scan_part_cols) share these forms; keeping them
out of engine_tpu means a storage daemon serving scans never imports
jax (graphd is the only device-touching process, matching the
reference's separation where storaged knows nothing about the query
engine's execution backend).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class ScanCols:
    """One partition-kind scan in columnar form: all keys in one blob,
    per-item length arrays, and values either as one blob + offsets
    (native engines, the snapshot-sync wire format) or as a list
    (engines that store Python bytes). Everything downstream is numpy.
    """
    __slots__ = ("n", "keys_blob", "klens", "vlens", "vals_blob", "voffs",
                 "vals_list")

    def __init__(self, n, keys_blob, klens, vlens, vals_blob=None,
                 voffs=None, vals_list=None):
        self.n = n
        self.keys_blob = keys_blob
        self.klens = klens
        self.vlens = vlens
        self.vals_blob = vals_blob
        self.voffs = voffs
        self.vals_list = vals_list

    @classmethod
    def from_lists(cls, keys: List[bytes], vals: List[bytes]) -> "ScanCols":
        n = len(keys)
        klens = np.fromiter(map(len, keys), np.int64, n)
        vlens = np.fromiter(map(len, vals), np.int64, n)
        return cls(n, b"".join(keys), klens, vlens, vals_list=vals)

    @classmethod
    def from_blobs(cls, n: int, keys_blob: bytes, vals_blob: bytes,
                   vlens: np.ndarray, klens: np.ndarray) -> "ScanCols":
        vlens = np.asarray(vlens, np.int64)
        voffs = np.zeros(n, np.int64)
        if n > 1:
            np.cumsum(vlens[:-1], out=voffs[1:])
        return cls(n, keys_blob, np.asarray(klens, np.int64), vlens,
                   vals_blob, voffs)


class RowsBlock:
    """Encoded rows selected from a scan, addressed for batch decode:
    blob + per-row (offset, length) + destination column index."""
    __slots__ = ("blob", "offs", "lens", "idxs")

    def __init__(self, blob: bytes, offs: np.ndarray, lens: np.ndarray,
                 idxs: np.ndarray):
        self.blob = blob
        self.offs = np.asarray(offs, np.int64)
        self.lens = np.asarray(lens, np.int32)
        self.idxs = np.asarray(idxs, np.int32)

    @classmethod
    def from_pairs(cls, pairs: List[Tuple[int, bytes]]) -> "RowsBlock":
        n = len(pairs)
        lens = np.fromiter((len(r) for _, r in pairs), np.int32, n)
        offs = np.zeros(n, np.int64)
        if n > 1:
            np.cumsum(lens[:-1], out=offs[1:])
        idxs = np.fromiter((i for i, _ in pairs), np.int32, n)
        return cls(b"".join(r for _, r in pairs), offs, lens, idxs)

    @classmethod
    def from_scan(cls, scan: ScanCols, scan_idx: np.ndarray,
                  dest_idx: np.ndarray) -> "RowsBlock":
        if scan.vals_blob is not None:
            return cls(scan.vals_blob, scan.voffs[scan_idx],
                       scan.vlens[scan_idx], dest_idx)
        vals = list(map(scan.vals_list.__getitem__, scan_idx.tolist()))
        lens = scan.vlens[scan_idx]
        offs = np.zeros(len(vals), np.int64)
        if len(vals) > 1:
            np.cumsum(lens[:-1], out=offs[1:])
        return cls(b"".join(vals), offs, lens, dest_idx)

    def __len__(self) -> int:
        return len(self.idxs)

    def items(self):
        """(dest index, row bytes) pairs — the Python-codec fallback."""
        for j in range(len(self.idxs)):
            o = int(self.offs[j])
            yield int(self.idxs[j]), self.blob[o:o + int(self.lens[j])]


def scan_cols(engine, prefix: bytes) -> ScanCols:
    """Batched columnar scan of an engine prefix range (key order)."""
    fn = getattr(engine, "scan_cols", None)
    if fn is not None:
        return fn(prefix)
    fn = getattr(engine, "scan_batch", None)
    if fn is not None:
        return ScanCols.from_lists(*fn(prefix))
    keys: List[bytes] = []
    vals: List[bytes] = []
    for k, v in engine.prefix(prefix):
        keys.append(k)
        vals.append(v)
    return ScanCols.from_lists(keys, vals)
