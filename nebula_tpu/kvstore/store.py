"""GraphStore: space → partition → engine multiplexing.

Role parity with the reference's `kvstore/NebulaStore.{h,cpp}`:
spaces hold a set of local Parts sharing a per-space engine; reads are
leader-local; writes route to the owning Part and go through its
consensus hook. Implements the PartManager handler surface
(add/remove space/part, ref NebulaStore.h:172-178) so meta-driven
topology changes create/destroy local parts at runtime — the balancer
drives exactly these entry points.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..common.status import ErrorCode, Status, StatusOr
from ..common import writepath as _writepath
from .iface import KVEngine, KVIterator
from .memengine import MemEngine
from .part import AtomicOp, Part

KV = Tuple[bytes, bytes]

EngineFactory = Callable[[int], KVEngine]  # space_id -> engine


class SpaceInfo:
    def __init__(self, space_id: int, engine: KVEngine):
        self.space_id = space_id
        self.engine = engine
        self.parts: Dict[int, Part] = {}


class GraphStore:
    def __init__(self, engine_factory: Optional[EngineFactory] = None,
                 consensus_factory=None):
        self._engine_factory = engine_factory or (lambda space_id: MemEngine())
        self._consensus_factory = consensus_factory  # (space,part,engine)->hook
        self._spaces: Dict[int, SpaceInfo] = {}
        self._engine_options: Dict[str, int] = {}
        self._lock = threading.Lock()
        # write-path observatory: change-ring occupancy gauges walk the
        # registered stores (weakly; common/writepath.py ring_status)
        _writepath.register_store(self)

    # ------------------------------------------------------------------
    # topology management (PartManager::Handler surface)
    # ------------------------------------------------------------------
    def add_space(self, space_id: int) -> None:
        with self._lock:
            if space_id not in self._spaces:
                info = SpaceInfo(space_id, self._engine_factory(space_id))
                self._spaces[space_id] = info
                for k, v in self._engine_options.items():
                    info.engine.set_option(k, int(v))

    def remove_space(self, space_id: int) -> None:
        with self._lock:
            info = self._spaces.pop(space_id, None)
        if info is not None:
            info.engine.close()

    def add_part(self, space_id: int, part_id: int) -> Part:
        self.add_space(space_id)
        with self._lock:
            info = self._spaces[space_id]
            if part_id not in info.parts:
                hook = None
                if self._consensus_factory is not None:
                    hook = self._consensus_factory(space_id, part_id, info.engine)
                info.parts[part_id] = Part(space_id, part_id, info.engine, hook)
            return info.parts[part_id]

    def remove_part(self, space_id: int, part_id: int) -> None:
        with self._lock:
            info = self._spaces.get(space_id)
            part = info.parts.pop(part_id, None) if info else None
        if part is not None:
            part.cleanup()

    def spaces(self) -> List[int]:
        return sorted(self._spaces)

    def parts(self, space_id: int) -> List[int]:
        info = self._spaces.get(space_id)
        return sorted(info.parts) if info else []

    def space_parts(self, space_id: int) -> List[Part]:
        """The live Part objects of one space (point-in-time list) —
        the consistency observatory's digest walk."""
        info = self._spaces.get(space_id)
        if info is None:
            return []
        return [p for _, p in sorted(info.parts.items())]

    def space_digest(self, space_id: int):
        """(folded content digest, engine write_version) of one
        space's parts, or None when disarmed / unavailable / a write
        raced the walk (version re-checked after folding — the pair is
        only returned when it names a consistent point). The store
        digest CSR snapshot lineage records (engine_tpu/engine.py
        snapshot audit)."""
        from ..common import consistency as _consistency
        if not _consistency.enabled():
            return None
        info = self._spaces.get(space_id)
        if info is None:
            return None
        v0 = info.engine.write_version
        total = 0
        for part in self.space_parts(space_id):
            anc = part.digest_anchor()
            if anc is None:
                return None
            total = _consistency.fold_add(total, anc[2])
        if info.engine.write_version != v0:
            return None          # a write landed mid-walk: no claim
        return total, v0

    def leader_parts(self, space_id: int) -> List[int]:
        """Parts of the space this node currently LEADS (every part for
        unreplicated DirectCommit nodes). Folded into the freshness
        token so a deposed replica's version channel stops vouching for
        parts it no longer serves authoritatively."""
        info = self._spaces.get(space_id)
        if info is None:
            return []
        return sorted(pid for pid, p in list(info.parts.items())
                      if p.is_leader())

    def close(self) -> None:
        """Close every space engine (flushing what they buffer) — the
        daemon's orderly-shutdown path."""
        with self._lock:
            infos = list(self._spaces.values())
            self._spaces.clear()
        for info in infos:
            try:
                info.engine.close()
            except Exception:
                pass

    def apply_engine_options(self, opts: Dict[str, int]) -> int:
        """Hot-apply engine tuning knobs to every space engine, and to
        engines of spaces added later (the config-registry path; ref
        role: MetaClient applying nested rocksdb option maps at
        runtime, MetaClient.cpp:1294-1429). Returns how many
        (engine, option) applications the engines accepted."""
        with self._lock:
            self._engine_options = {k: int(v) for k, v in opts.items()}
            engines = [i.engine for i in self._spaces.values()]
        n = 0
        for e in engines:
            for k, v in opts.items():
                if e.set_option(k, int(v)).ok():
                    n += 1
        return n

    def space_engine(self, space_id: int) -> Optional[KVEngine]:
        info = self._spaces.get(space_id)
        return info.engine if info else None

    # ------------------------------------------------------------------
    # part lookup / guards
    # ------------------------------------------------------------------
    def part(self, space_id: int, part_id: int) -> StatusOr[Part]:
        info = self._spaces.get(space_id)
        if info is None:
            return StatusOr.err(ErrorCode.E_SPACE_NOT_FOUND, f"space {space_id}")
        p = info.parts.get(part_id)
        if p is None:
            return StatusOr.err(ErrorCode.E_PART_NOT_FOUND,
                                f"part {part_id} of space {space_id}")
        if not p.is_leader():
            return StatusOr.err(ErrorCode.E_LEADER_CHANGED, p.leader() or "")
        return StatusOr.of(p)

    # ------------------------------------------------------------------
    # reads (leader-local, ref KVStore.h "reads are local-only")
    # ------------------------------------------------------------------
    def get(self, space_id: int, part_id: int, key: bytes) -> StatusOr[bytes]:
        pr = self.part(space_id, part_id)
        if not pr.ok():
            return StatusOr.from_status(pr.status)
        v = pr.value().engine.get(key)
        if v is None:
            return StatusOr.err(ErrorCode.E_KEY_NOT_FOUND)
        return StatusOr.of(v)

    def multi_get(self, space_id: int, part_id: int,
                  ks: List[bytes]) -> StatusOr[List[Optional[bytes]]]:
        pr = self.part(space_id, part_id)
        if not pr.ok():
            return StatusOr.from_status(pr.status)
        return StatusOr.of(pr.value().engine.multi_get(ks))

    def prefix(self, space_id: int, part_id: int,
               prefix: bytes) -> StatusOr[KVIterator]:
        pr = self.part(space_id, part_id)
        if not pr.ok():
            return StatusOr.from_status(pr.status)
        return StatusOr.of(pr.value().engine.prefix(prefix))

    def range(self, space_id: int, part_id: int, start: bytes,
              end: bytes) -> StatusOr[KVIterator]:
        pr = self.part(space_id, part_id)
        if not pr.ok():
            return StatusOr.from_status(pr.status)
        return StatusOr.of(pr.value().engine.range(start, end))

    # ------------------------------------------------------------------
    # writes (through consensus)
    # ------------------------------------------------------------------
    def async_multi_put(self, space_id: int, part_id: int,
                        kvs: Iterable[KV]) -> Status:
        pr = self.part(space_id, part_id)
        if not pr.ok():
            return pr.status
        return pr.value().async_multi_put(kvs)

    def async_multi_remove(self, space_id: int, part_id: int,
                           ks: Iterable[bytes]) -> Status:
        pr = self.part(space_id, part_id)
        if not pr.ok():
            return pr.status
        return pr.value().async_multi_remove(ks)

    def async_remove_range(self, space_id: int, part_id: int, start: bytes,
                           end: bytes) -> Status:
        pr = self.part(space_id, part_id)
        if not pr.ok():
            return pr.status
        return pr.value().async_remove_range(start, end)

    def async_atomic_op(self, space_id: int, part_id: int,
                        op: AtomicOp) -> Status:
        pr = self.part(space_id, part_id)
        if not pr.ok():
            return pr.status
        return pr.value().async_atomic_op(op)

    def ingest(self, space_id: int, part_id: int, kvs: Iterable[KV]) -> Status:
        pr = self.part(space_id, part_id)
        if not pr.ok():
            return pr.status
        # through the Part so its content digest invalidates (bulk
        # load bypasses the commit-batch digest fold)
        return pr.value().ingest(kvs)
