"""Write-ahead log over the native C++ segmented WAL.

Role parity with the reference's `kvstore/wal/FileBasedWal.{h,cpp}`:
raft appends here before replication, followers replay from here after
restart, and term conflicts roll the tail back. The heavy lifting
(segment files, CRC validation, torn-tail truncation, the in-memory
record index) is the native library (`native/src/wal.cc`); this wrapper
owns lifetime and exposes a Pythonic iterator.

Every native call is serialized with close() under one lock, so raft
background threads racing a part shutdown see benign defaults instead
of touching a freed native handle (use-after-free -> heap corruption).
"""
from __future__ import annotations

import ctypes
import threading
from dataclasses import dataclass
from typing import Iterator, List, Optional

import time

from .. import native
from ..common import writepath as _writepath
from ..common.faults import InjectedFault, faults


@dataclass(frozen=True)
class LogEntry:
    log_id: int
    term: int
    cluster: int
    data: bytes


class Wal:
    """One WAL instance per raft part (dir is per space/part)."""

    def __init__(self, dir_path: str, ttl_secs: int = 86400,
                 max_file_size: int = 16 * 1024 * 1024,
                 sync_every_append: bool = False):
        self._lib = native.load()
        self._dir = dir_path
        self.sync_every_append = bool(sync_every_append)
        self._h = self._lib.nwal_open(
            dir_path.encode(), ttl_secs, max_file_size,
            1 if sync_every_append else 0)
        if not self._h:
            raise OSError(f"cannot open WAL at {dir_path}")
        self._lock = threading.Lock()
        self._closed = False

    # ------------------------------------------------------------------
    @property
    def first_log_id(self) -> int:
        with self._lock:
            return 0 if self._closed else self._lib.nwal_first_log_id(self._h)

    @property
    def last_log_id(self) -> int:
        with self._lock:
            return 0 if self._closed else self._lib.nwal_last_log_id(self._h)

    @property
    def last_log_term(self) -> int:
        with self._lock:
            return 0 if self._closed else self._lib.nwal_last_log_term(self._h)

    def log_term(self, log_id: int) -> Optional[int]:
        with self._lock:
            if self._closed:
                return None
            t = self._lib.nwal_log_term(self._h, log_id)
        return None if t < 0 else t

    def append(self, log_id: int, term: int, cluster: int,
               data: bytes) -> bool:
        # fault point `wal.append` (common/faults.py): an injected
        # failure takes the REAL failure shape — a False return, the
        # same thing a full disk produces — so the raft quorum/retry
        # machinery above is what gets exercised, not exception
        # plumbing. Latency mode simply sleeps (a slow disk).
        try:
            faults.fire("wal.append")
        except InjectedFault:
            return False
        with self._lock:
            if self._closed:
                return False
            if self.sync_every_append:
                # durable append: the native call fsyncs inline, so
                # its latency IS the fsync-bearing write latency the
                # group-commit design needs measured (wal.fsync_us
                # histogram; docs/manual/10-observability.md)
                t0 = time.perf_counter()
                rc = self._lib.nwal_append(self._h, log_id, term,
                                           cluster, data, len(data))
                _writepath.note_fsync(
                    (time.perf_counter() - t0) * 1e6)
            else:
                rc = self._lib.nwal_append(self._h, log_id, term,
                                           cluster, data, len(data))
        return rc == 0

    def rollback(self, keep_to: int) -> bool:
        """Drop every log with id > keep_to (term conflict)."""
        with self._lock:
            if self._closed:
                return False
            return self._lib.nwal_rollback(self._h, keep_to) == 0

    def reset(self) -> None:
        with self._lock:
            if not self._closed:
                self._lib.nwal_reset(self._h)

    def clean_ttl(self, before_id: Optional[int] = None) -> int:
        """TTL sweep of aged sealed segments. `before_id` bounds it:
        an aged segment goes only when its every record id is below
        the bound — compaction passes the applied anchor so age alone
        can never truncate an unapplied entry. None = unbounded (the
        legacy shape, safe only when the caller knows the whole log
        is applied)."""
        with self._lock:
            if self._closed:
                return 0
            if before_id is None:
                return self._lib.nwal_clean_ttl(self._h)
            return self._lib.nwal_clean_ttl_before(self._h, before_id)

    def clean_before(self, before_id: int) -> int:
        """Drop sealed prefix segments whose every record id is below
        `before_id` (whole segments only, never the active one) —
        snapshot-anchored compaction. Callers pass an APPLIED anchor
        minus a replay-lag allowance, so no unapplied entry can ever
        be truncated. Returns segment files removed."""
        with self._lock:
            if self._closed:
                return 0
            return self._lib.nwal_clean_before(self._h, before_id)

    def sync(self) -> None:
        # fault point `wal.sync`: raises — a failed fsync means the
        # durability promise is broken and callers must see it (its
        # latency mode sleeps here, INSIDE the measured extent, so the
        # fsync_stall drill measures what a slow disk would)
        t0 = time.perf_counter()
        faults.fire("wal.sync")
        with self._lock:
            if not self._closed:
                self._lib.nwal_sync(self._h)
        _writepath.note_fsync((time.perf_counter() - t0) * 1e6)

    def iterate(self, from_id: int, to_id: int = -1) -> Iterator[LogEntry]:
        """Yield entries in [from_id, to_id] (to_id<0 → through last).
        The scan materializes under the lock so it cannot race close()."""
        entries: List[LogEntry] = []
        with self._lock:
            if self._closed:
                return iter(())
            it = self._lib.nwal_iter_new(self._h, from_id, to_id)
            try:
                while self._lib.nwal_iter_valid(it):
                    out = ctypes.POINTER(ctypes.c_uint8)()
                    n = self._lib.nwal_iter_data(it, ctypes.byref(out))
                    data = ctypes.string_at(out, n) if n else b""
                    entries.append(LogEntry(self._lib.nwal_iter_log_id(it),
                                            self._lib.nwal_iter_term(it),
                                            self._lib.nwal_iter_cluster(it),
                                            data))
                    self._lib.nwal_iter_next(it)
            finally:
                self._lib.nwal_iter_free(it)
        return iter(entries)

    def close(self) -> None:
        # fault point `wal.torn_tail`: after the native handle closes,
        # chop trailing bytes off the newest segment file — the
        # on-disk shape a power cut mid-append leaves behind. The next
        # open must CRC-truncate the torn record and recover the
        # prefix (native/src/wal.cc load_segment), proving the
        # torn-tail path end-to-end from Python.
        torn = False
        try:
            faults.fire("wal.torn_tail")
        except InjectedFault:
            torn = True
        with self._lock:
            if not self._closed:
                self._lib.nwal_close(self._h)
                self._closed = True
                if torn:
                    self._tear_tail()

    def _tear_tail(self) -> None:
        """Truncate the newest segment by a few bytes (fault-injection
        only; called after the native handle is closed)."""
        import os
        try:
            segs = sorted(f for f in os.listdir(self._dir)
                          if f.endswith(".wal"))
            if not segs:
                return
            path = os.path.join(self._dir, segs[-1])
            size = os.path.getsize(path)
            if size > 23:            # keep at least the 16B header
                with open(path, "r+b") as f:
                    f.truncate(size - 7)
        except OSError:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
