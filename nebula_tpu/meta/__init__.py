from .service import MetaService, SpaceDesc  # noqa: F401
from .schema_manager import SchemaManager  # noqa: F401
