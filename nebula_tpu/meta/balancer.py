"""Data & leader balancer.

Role parity with the reference's `meta/processors/admin/Balancer.{h,cpp}`:
diff the current part allocation against the live host set (the
heartbeat-driven failure detector, ActiveHostsMan), build a BalancePlan
of per-part move tasks, persist every task in the meta KV so a crashed
balancer resumes (`Balancer::recovery`, Balancer.cpp:67-106), and run
each task's FSM:

    ADD_PART(dst, learner) → ADD_LEARNER → WAIT_CATCHUP →
    MEMBER_ADD(dst) → [TRANS_LEADER if src led] → MEMBER_REMOVE(src) →
    REMOVE_PART(src) → update meta part allocation

A separate leader-balance pass (`Balancer::leaderBalance`,
Balancer.cpp:615) evens leader counts without moving data.
"""
from __future__ import annotations

import json
import math
import threading
from typing import Dict, List, Optional, Tuple

from ..common.status import ErrorCode, Status, StatusOr
from . import keys as mk

# task FSM states (ref BalanceTask::Status)
ST_START = "START"
ST_ADD_LEARNER = "ADD_LEARNER"
ST_CATCHUP = "CATCHUP"
ST_MEMBER_CHANGE = "MEMBER_CHANGE"
ST_REMOVE_PART = "REMOVE_PART"
ST_SUCCEEDED = "SUCCEEDED"
ST_FAILED = "FAILED"
ST_INVALID = "INVALID"

_TERMINAL = (ST_SUCCEEDED, ST_FAILED, ST_INVALID)


class BalanceTask:
    def __init__(self, plan_id: int, space_id: int, part_id: int,
                 src: str, dst: str, status: str = ST_START):
        self.plan_id = plan_id
        self.space_id = space_id
        self.part_id = part_id
        self.src = src
        self.dst = dst
        self.status = status

    def key(self) -> bytes:
        return mk.balance_task_key(self.plan_id, self.space_id,
                                   self.part_id, self.src, self.dst)

    def value(self) -> bytes:
        return json.dumps({"status": self.status}).encode()

    def as_row(self) -> List:
        return [self.plan_id, self.space_id, self.part_id,
                self.src, self.dst, self.status]


class Balancer:
    def __init__(self, meta, admin, get_active_hosts=None):
        """meta: MetaService; admin: AdminClient;
        get_active_hosts: override liveness source (defaults to the
        heartbeat-based ActiveHostsMan view)."""
        self.meta = meta
        self.admin = admin
        self._get_active = get_active_hosts or (
            lambda: [h.host for h in meta.active_hosts()])
        self._lock = threading.Lock()
        self._running_plan: Optional[int] = None
        self._stop_flag = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # plan construction (ref Balancer::balanceParts, Balancer.cpp:220-287)
    # ------------------------------------------------------------------
    def _build_tasks(self, plan_id: int,
                     remove_hosts: Tuple[str, ...]) -> List[BalanceTask]:
        active = [h for h in self._get_active() if h not in remove_hosts]
        if not active:
            return []
        tasks: List[BalanceTask] = []
        for desc in self.meta.list_spaces():
            alloc = self.meta.get_parts_alloc(desc.space_id)
            if not alloc:
                continue
            # load = #parts hosted per active host
            load: Dict[str, List[int]] = {h: [] for h in active}
            must_move: List[Tuple[int, str]] = []   # (part, bad_host)
            for part, hosts in alloc.items():
                for h in hosts:
                    if h in load:
                        load[h].append(part)
                    else:
                        must_move.append((part, h))
            # first, evacuate dead/removed hosts
            for part, bad in must_move:
                cur = set(alloc[part])
                candidates = [h for h in sorted(load, key=lambda x: len(load[x]))
                              if h not in cur]
                if not candidates:
                    continue
                dst = candidates[0]
                load[dst].append(part)
                alloc[part] = [dst if h == bad else h for h in alloc[part]]
                tasks.append(BalanceTask(plan_id, desc.space_id, part,
                                         bad, dst))
            # then, even out the load: move from max to min while the
            # spread exceeds 1 (ref balanceParts while-loop)
            while True:
                hmax = max(load, key=lambda h: len(load[h]))
                hmin = min(load, key=lambda h: len(load[h]))
                if len(load[hmax]) - len(load[hmin]) <= 1:
                    break
                moved = None
                for part in load[hmax]:
                    if part not in load[hmin] and hmin not in alloc[part]:
                        moved = part
                        break
                if moved is None:
                    break
                load[hmax].remove(moved)
                load[hmin].append(moved)
                alloc[moved] = [hmin if h == hmax else h
                                for h in alloc[moved]]
                tasks.append(BalanceTask(plan_id, desc.space_id, moved,
                                         hmax, hmin))
        return tasks

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def balance(self, remove_hosts: Tuple[str, ...] = ()) -> StatusOr[int]:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return StatusOr.err(ErrorCode.E_BALANCER_RUNNING,
                                    f"plan {self._running_plan} in flight")
            # resume an unfinished plan first (ref Balancer::recovery)
            unfinished = self._load_unfinished()
            if unfinished:
                plan_id, tasks = unfinished
            else:
                plan_id = self.meta._next_id("balance_plan")
                tasks = self._build_tasks(plan_id, tuple(remove_hosts))
                if not tasks:
                    return StatusOr.err(ErrorCode.E_NO_VALID_HOST,
                                        "already balanced / no tasks")
                for t in tasks:
                    self.meta._put((t.key(), t.value()))
            self._running_plan = plan_id
            self._stop_flag = False
            # nlint: disable=NL002 -- the plan runs for minutes, far
            # beyond the BALANCE DATA statement that submitted it
            self._thread = threading.Thread(
                target=self._run_plan, args=(plan_id, tasks), daemon=True,
                name=f"balance-plan-{plan_id}")
            self._thread.start()
            return StatusOr.of(plan_id)

    # ------------------------------------------------------------------
    # heat-aware BALANCE advisor (ISSUE 14; docs/manual/
    # 12-replication.md, "Heat-aware BALANCE advisor")
    # ------------------------------------------------------------------
    def advise_heat(self) -> Dict:
        """Fold the heartbeat-carried heat view into a placement
        scorer and produce an ADVISORY plan: the per-host heat today,
        the modeled per-host heat after the proposed moves, and the
        moves themselves — `BALANCE DATA heat` / `/balance?heat=1`
        report it; nothing is executed (moving is a later PR).

        Model: a host's heat is the summed 600s score of the parts it
        LEADS (the leader serves the reads/writes that heat measures).
        Greedy descent on the spread (hottest-host max - coldest-host
        min): repeatedly take the hottest host's hottest part and move
        its leadership to the host that would stay coolest —
        preferring an existing replica (kind="leader", a
        TRANS_LEADER-shaped move) over a data move to a non-replica
        host (kind="move", an add+remove-replica-shaped move) — until
        no single move lowers the spread. Bounded at 2x total part
        count. Scores come from each leader's own heartbeat; a part
        whose leader carries no heat scores 0 and never moves."""
        view = self.meta.heat_overview()
        active = sorted(self._get_active())
        if not active:
            return {"hosts": {}, "moves": [],
                    "spread_before": 0.0, "spread_after": 0.0,
                    "advisory": True}
        # part -> (leader, score): leadership can transiently be
        # claimed by TWO heartbeat views right after a leader change
        # (the deposed host's view survives until its next beat) — a
        # part counts ONCE, under the claimant with the NEWER view,
        # or the modeled totals and the move's src would both be wrong
        # for a whole heartbeat period
        part_leader: Dict[Tuple[int, int], str] = {}
        part_score: Dict[Tuple[int, int], float] = {}
        claim_ts: Dict[Tuple[int, int], float] = {}
        for host, hv in view.get("hosts", {}).items():
            if host not in active:
                continue
            ts = float(hv.get("ts") or 0.0)
            for key, score in hv.get("parts", {}).items():
                sid_s, _, pid_s = key.partition(":")
                k = (int(sid_s), int(pid_s))
                if k in part_leader and claim_ts[k] >= ts:
                    continue
                part_leader[k] = host
                part_score[k] = float(score)
                claim_ts[k] = ts
        modeled: Dict[str, float] = {h: 0.0 for h in active}
        for k, host in part_leader.items():
            modeled[host] += part_score[k]
        current = {h: round(v, 1) for h, v in modeled.items()}
        # replica sets, for preferring leader-transfer moves
        replicas: Dict[Tuple[int, int], List[str]] = {}
        for desc in self.meta.list_spaces():
            for part, hosts in self.meta.get_parts_alloc(
                    desc.space_id).items():
                replicas[(desc.space_id, part)] = [
                    h for h in hosts if h in modeled]

        def spread(m: Dict[str, float]) -> float:
            return (max(m.values()) - min(m.values())) if m else 0.0

        spread_before = spread(modeled)
        moves: List[Dict] = []
        max_moves = 2 * max(len(part_score), 1)
        while len(moves) < max_moves and len(modeled) > 1:
            hot = max(modeled, key=lambda h: modeled[h])
            led = sorted(
                (k for k, h in part_leader.items() if h == hot),
                key=lambda k: part_score.get(k, 0.0), reverse=True)
            best = None
            cur_spread = spread(modeled)
            for k in led:
                s = part_score.get(k, 0.0)
                if s <= 0:
                    break

                def after(dst):
                    return spread({
                        h: (modeled[h] - s if h == hot else
                            modeled[h] + s if h == dst
                            else modeled[h])
                        for h in modeled})
                # every destination whose move lowers the spread,
                # coolest-after first; among those, a replica holder
                # wins outright — a TRANS_LEADER-shaped move is far
                # cheaper than a data move, and any spread improvement
                # it offers beats a (possibly larger) one that has to
                # copy the part
                improving = sorted(
                    (h for h in modeled
                     if h != hot and after(h) < cur_spread - 1e-9),
                    key=lambda h: modeled[h] + s)
                if not improving:
                    continue
                dst = next((h for h in improving
                            if h in replicas.get(k, ())),
                           improving[0])
                best = (k, s, dst)
                break
            if best is None:
                break
            k, s, dst = best
            modeled[hot] -= s
            modeled[dst] += s
            part_leader[k] = dst
            moves.append({
                "space": k[0], "part": k[1], "src": hot, "dst": dst,
                "score": round(s, 1),
                "kind": "leader" if dst in replicas.get(k, ())
                else "move"})
        return {
            "hosts": sorted(modeled),
            "current": current,
            "planned": {h: round(v, 1) for h, v in modeled.items()},
            "moves": moves,
            "spread_before": round(spread_before, 1),
            "spread_after": round(spread(modeled), 1),
            "staleness": view.get("staleness", []),
            "advisory": True,
        }

    def leader_balance(self) -> Status:
        """Even out leaders per host without moving data (ref
        Balancer::leaderBalance)."""
        for desc in self.meta.list_spaces():
            alloc = self.meta.get_parts_alloc(desc.space_id)
            if not alloc:
                continue
            leaders = self.admin.leader_map(desc.space_id, sorted(alloc))
            hosts = sorted({h for hs in alloc.values() for h in hs})
            if not hosts:
                continue
            count = {h: 0 for h in hosts}
            for p, l in leaders.items():
                if l in count:
                    count[l] += 1
            target = math.ceil(len(alloc) / len(hosts))
            for part, leader in sorted(leaders.items()):
                if leader is None or count.get(leader, 0) <= target:
                    continue
                members = [h for h in alloc[part] if h != leader]
                members.sort(key=lambda h: count.get(h, 0))
                if not members or count[members[0]] + 1 > target:
                    continue
                if self.admin.trans_leader(desc.space_id, part, members[0]):
                    count[leader] -= 1
                    count[members[0]] += 1
        return Status.OK()

    def show_plan(self, plan_id: Optional[int] = None) -> List[List]:
        rows = []
        for k, v in self.meta._scan(mk.balance_prefix(plan_id)):
            t = _task_from_kv(k, v)
            rows.append(t.as_row())
        return rows

    def progress(self) -> Dict:
        """Latest plan's task counts by FSM status + liveness — the
        observability shape surfaced in graphd /tpu_stats and metad
        /metrics (docs/manual/12-replication.md)."""
        by_plan: Dict[int, Dict[str, int]] = {}
        for k, v in self.meta._scan(mk.balance_prefix()):
            t = _task_from_kv(k, v)
            by_plan.setdefault(t.plan_id, {})
            by_plan[t.plan_id][t.status] = \
                by_plan[t.plan_id].get(t.status, 0) + 1
        if not by_plan:
            return {"plan": 0, "running": False, "tasks": {}}
        latest = max(by_plan)
        with self._lock:
            running = self._thread is not None and self._thread.is_alive()
        return {"plan": latest, "running": running,
                "tasks": by_plan[latest]}

    def stop(self) -> Status:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                return Status.error(ErrorCode.E_NOT_FOUND,
                                    "no balance plan running")
            self._stop_flag = True
        return Status.OK()

    def wait(self, timeout: float = 30.0) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    # ------------------------------------------------------------------
    # plan execution
    # ------------------------------------------------------------------
    def _run_plan(self, plan_id: int, tasks: List[BalanceTask]) -> None:
        from ..common.flight import recorder as _flight
        for task in tasks:
            if self._stop_flag:
                break
            if task.status in _TERMINAL:
                continue
            try:
                self._run_task(task)
            except Exception:
                task.status = ST_FAILED
            if task.status == ST_FAILED:
                # a failed partition move is exactly the kind of
                # incident the flight ring should remember: the bundle
                # captured by whatever fires next (leader churn, an
                # SLO burn) shows the rebalance context alongside it
                _flight.record("balance_task_failed", plan=plan_id,
                               space=task.space_id, part=task.part_id,
                               src=str(task.src), dst=str(task.dst))
            self.meta._put((task.key(), task.value()))

    def _run_task(self, t: BalanceTask) -> None:
        space, part = t.space_id, t.part_id
        alloc = self.meta.get_parts_alloc(space)
        cur_hosts = alloc.get(part, [])
        if t.src not in cur_hosts and t.dst in cur_hosts:
            t.status = ST_SUCCEEDED   # already done (resume case)
            return
        peers = list(cur_hosts)

        # 1. create the destination replica as a learner
        self.admin.add_part(t.dst, space, part, peers + [t.dst],
                            as_learner=True)
        t.status = ST_ADD_LEARNER
        self.meta._put((t.key(), t.value()))
        if not self.admin.add_learner(space, part, t.dst):
            t.status = ST_FAILED
            return

        # 2. wait until the learner caught up
        t.status = ST_CATCHUP
        self.meta._put((t.key(), t.value()))
        if not self.admin.wait_catchup(space, part, t.dst):
            t.status = ST_FAILED
            return

        # 3. membership change: promote dst, demote src
        t.status = ST_MEMBER_CHANGE
        self.meta._put((t.key(), t.value()))
        if not self.admin.member_add(space, part, t.dst):
            t.status = ST_FAILED
            return
        # if src currently leads, hand leadership off first
        try:
            if self.admin.leader_of(space, part, timeout=2.0) == t.src:
                others = [h for h in peers + [t.dst] if h != t.src]
                if others:
                    self.admin.trans_leader(space, part, others[0])
        except TimeoutError:
            pass
        if not self.admin.member_remove(space, part, t.src):
            t.status = ST_FAILED
            return

        # 4. drop the source replica + record the new allocation
        t.status = ST_REMOVE_PART
        self.meta._put((t.key(), t.value()))
        self.admin.remove_part(t.src, space, part)
        new_hosts = [h for h in cur_hosts if h != t.src] + [t.dst]
        self.meta.update_part_alloc(space, part, new_hosts)
        t.status = ST_SUCCEEDED

    # ------------------------------------------------------------------
    def _load_unfinished(self) -> Optional[Tuple[int, List[BalanceTask]]]:
        by_plan: Dict[int, List[BalanceTask]] = {}
        for k, v in self.meta._scan(mk.balance_prefix()):
            t = _task_from_kv(k, v)
            by_plan.setdefault(t.plan_id, []).append(t)
        for plan_id in sorted(by_plan, reverse=True):
            tasks = by_plan[plan_id]
            if any(t.status not in _TERMINAL for t in tasks):
                return plan_id, tasks
        return None


def _task_from_kv(k: bytes, v: bytes) -> BalanceTask:
    import struct
    body = k[len(mk.P_BALANCE):]
    plan_id = struct.unpack(">Q", body[:8])[0]
    space_id = struct.unpack(">I", body[8:12])[0]
    part_id = struct.unpack(">I", body[12:16])[0]
    src, dst = body[16:].decode().split(">", 1)
    status = json.loads(v)["status"]
    return BalanceTask(plan_id, space_id, part_id, src, dst, status)
