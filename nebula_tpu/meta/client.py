"""Network MetaClient: RPC passthrough + background heartbeat/topology
loops.

Role parity with the reference's `meta/client/MetaClient` (ref
meta/client/MetaClient.{h,cpp}): daemons hold one MetaClient; it
forwards catalog RPCs to metad, sends heartbeats every
`heartbeat_interval_secs` (ref MetaClient.cpp:1132), and re-loads the
topology every `load_data_interval_secs`, diffing part allocation and
firing MetaChangedListener-style callbacks (ref MetaClient.cpp:120-193,
454-519) so storaged creates/drops local parts at runtime.

The passthrough design means SchemaManager and the executors run
unchanged over either a local MetaService or this client — the same
duck-typed surface, exactly how the reference's ServerBasedSchemaManager
sits on the MetaClient cache.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set

from ..common.flags import graph_flags, meta_flags, storage_flags


def _flag_registry_for_role(role: str):
    return {"storage": storage_flags, "graph": graph_flags,
            "meta": meta_flags}.get(role)
from ..common.status import ErrorCode
from ..rpc import proxy


class MetaClient:
    def __init__(self, meta_addr: str, local_addr: str = "",
                 role: str = "storage", cluster_id_file: str = ""):
        self._rpc = proxy(meta_addr, "meta")
        self.meta_addr = meta_addr
        self.local_addr = local_addr
        self.role = role
        self._cluster_id_file = cluster_id_file
        self.wrong_cluster = False
        # fired (once, from the heartbeat thread) when metad rejects our
        # cluster id — the daemon must stop serving (the reference
        # aborts the process, HBProcessor clusterId check)
        self.on_wrong_cluster: Optional[Callable[[], None]] = None
        # optional {space_id: [parts led]} provider: storaged wires its
        # raft leadership here so every heartbeat refreshes metad's
        # ActiveHostsMan leader view (SHOW HOSTS/PARTS leader columns)
        self.leader_source: Optional[Callable[[], Dict[int, List[int]]]] = None
        # optional heat-payload provider (common/heat.py
        # heartbeat_payload): per-(space, part) heat + staleness for
        # the parts this node leads, carried as an ADDITIVE heartbeat
        # field (the leader_parts idiom) into metad's heat view —
        # SHOW HOSTS/PARTS heat columns + the heat-aware BALANCE
        # advisor. None (or a None payload) = field not sent.
        self.heat_source: Optional[Callable[[], Optional[Dict]]] = None
        # this daemon's HTTP admin port, carried on every heartbeat so
        # metad can hand the /cluster_metrics federation its scrape
        # target (set by the daemon once its WebService is up; -1 =
        # no admin surface)
        self.ws_port = -1
        self._listeners: List[Callable] = []
        self._known_parts: Dict[int, Set[int]] = {}  # space -> my part ids
        self._known_spaces: Dict[int, object] = {}
        self._alloc: Dict[int, Dict[int, List[str]]] = {}  # space -> part -> hosts
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- passthrough ---------------------------------------------------
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._rpc, name)

    @property
    def catalog_version(self) -> int:
        """Fetched per access: SchemaManager keys its lookup cache on
        this, so correctness beats the extra round-trip (the reference
        instead pulls the whole catalog every second)."""
        try:
            return self._rpc.get_catalog_version()
        except Exception:
            return -1

    # -- listeners (MetaChangedListener) -------------------------------
    def add_listener(self, listener: Callable) -> None:
        """listener(event, **kw); events: space_added(space_id, desc,
        parts), space_removed(space_id), parts_added/parts_removed
        (space_id, parts)."""
        self._listeners.append(listener)

    def _notify(self, event: str, **kw) -> None:
        for l in self._listeners:
            try:
                l(event, **kw)
            except Exception:
                pass

    # -- background loops ----------------------------------------------
    def start(self, heartbeat: bool = True, watch_topology: bool = True,
              load_interval: float = 1.0) -> "MetaClient":
        if heartbeat and self.local_addr:
            # nlint: disable=NL002 -- process-lifetime heartbeat loop
            t = threading.Thread(target=self._hb_loop, daemon=True,
                                 name="meta-heartbeat")
            t.start()
            self._threads.append(t)
        if watch_topology:
            self._sync_once()  # synchronous first load (waitForMetadReady)
            # nlint: disable=NL002 -- process-lifetime topology watch
            t = threading.Thread(target=self._watch_loop,
                                 args=(load_interval,), daemon=True,
                                 name="meta-watch")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()

    def _load_cluster_id(self) -> int:
        """ClusterIdMan client side: a persisted id (cluster_id_file)
        pins this daemon to its original cluster like the reference's
        on-disk cluster.id; without one the id is learned from the metad
        we're pointed at (dev mode — the gate then only detects metad
        redeploys, not misconfiguration)."""
        if self._cluster_id_file:
            try:
                with open(self._cluster_id_file) as f:
                    return int(f.read().strip())
            except (OSError, ValueError):
                pass
        return 0

    def _store_cluster_id(self, cid: int) -> None:
        if self._cluster_id_file:
            try:
                with open(self._cluster_id_file, "w") as f:
                    f.write(str(cid))
            except OSError:
                pass

    def _hb_loop(self) -> None:
        cluster_id = self._load_cluster_id()
        while not self._stop.is_set():
            try:
                if not cluster_id:
                    cluster_id = self._rpc.get_cluster_id()
                    self._store_cluster_id(cluster_id)
                lp = None
                if self.leader_source is not None:
                    try:
                        lp = self.leader_source()
                    except Exception:
                        lp = None
                ph = None
                if self.heat_source is not None:
                    try:
                        ph = self.heat_source()
                    except Exception:
                        ph = None
                st = self._rpc.heartbeat(self.local_addr, self.role,
                                         cluster_id=cluster_id,
                                         leader_parts=lp,
                                         ws_port=self.ws_port,
                                         part_heat=ph)
                if st is not None and not st.ok() and \
                        st.code == ErrorCode.E_WRONG_CLUSTER:
                    # the reference daemon aborts on mismatch; as a
                    # library we de-register loudly and stop beating
                    self.wrong_cluster = True
                    import sys
                    print(f"FATAL: metad {self.meta_addr} belongs to a "
                          f"different cluster (our id {cluster_id}) — "
                          f"heartbeats stopped", file=sys.stderr)
                    if self.on_wrong_cluster is not None:
                        try:
                            self.on_wrong_cluster()
                        except Exception:
                            pass
                    return
            except Exception:
                pass
            # hot config pull rides the heartbeat (the reference pulls
            # gflags in MetaClient's bg thread, MetaClient.cpp:1294):
            # MUTABLE flags set cluster-wide via UPDATE CONFIGS reach
            # every daemon within one heartbeat period
            try:
                reg = _flag_registry_for_role(self.role)
                if reg is not None:
                    reg.pull_from_meta(self._rpc)
            except Exception:
                pass
            self._stop.wait(storage_flags.get("heartbeat_interval_secs", 10))

    def _watch_loop(self, interval: float) -> None:
        while not self._stop.is_set():
            self._stop.wait(interval)
            try:
                self._sync_once()
            except Exception:
                pass

    def _sync_once(self) -> None:
        """Pull the full topology snapshot and diff (the reference
        re-loads everything each tick and diffs, MetaClient.cpp:454)."""
        spaces = {d.space_id: d for d in self._rpc.list_spaces()}
        for sid, desc in spaces.items():
            alloc: Dict[int, List[str]] = self._rpc.get_parts_alloc(sid)
            prev = self._alloc.get(sid) or {}
            self._alloc[sid] = alloc
            mine = {p for p, hosts in alloc.items()
                    if not self.local_addr or self.local_addr in hosts
                    or hosts == ["local"]}
            known = self._known_parts.get(sid)
            if known is None:
                self._known_spaces[sid] = desc
                self._known_parts[sid] = mine
                self._notify("space_added", space_id=sid, desc=desc,
                             parts=sorted(mine))
            else:
                added, removed = mine - known, known - mine
                if added:
                    self._notify("parts_added", space_id=sid,
                                 parts=sorted(added))
                if removed:
                    self._notify("parts_removed", space_id=sid,
                                 parts=sorted(removed))
                # replica-set changes on parts we keep hosting: the
                # raft leader reconciles its membership against the
                # meta allocation (a reconcile/balance added a host)
                changed = {p: list(alloc[p]) for p in (mine & known)
                           if p in prev and prev.get(p) != alloc.get(p)}
                if changed:
                    self._notify("peers_changed", space_id=sid,
                                 parts=changed)
                self._known_parts[sid] = mine
        for sid in list(self._known_parts):
            if sid not in spaces:
                del self._known_parts[sid]
                self._known_spaces.pop(sid, None)
                self._alloc.pop(sid, None)
                self._notify("space_removed", space_id=sid)

    # -- routing helpers for graphd ------------------------------------
    def _alloc_for(self, space_id: int, part_id: int) -> Dict[int, List[str]]:
        """Topology-snapshot part allocation, refetched on cache miss —
        one metad round-trip per space, not one per routing lookup."""
        alloc = self._alloc.get(space_id)
        if alloc is None or part_id not in alloc:
            alloc = self._rpc.get_parts_alloc(space_id)
            self._alloc[space_id] = alloc
        return alloc

    def part_host(self, space_id: int, part_id: int) -> str:
        """First replica host of a part (leader by convention until the
        raft layer reports real leaders)."""
        hosts = self._alloc_for(space_id, part_id).get(part_id) or ["local"]
        return hosts[0]

    def part_peers(self, space_id: int, part_id: int) -> List[str]:
        """All replica hosts of a part (the raft peer set)."""
        return list(self._alloc_for(space_id, part_id).get(part_id) or [])

    def storage_hosts(self) -> List[str]:
        return [h.host for h in self._rpc.active_hosts("storage")]
