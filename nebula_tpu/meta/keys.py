"""Meta catalog KV schema.

Role parity with the reference's `meta/MetaServiceUtils.{h,cpp}:31-136`:
every catalog object lives in the meta store under a typed key prefix so
the whole catalog is one Raft-replicated KV space (space 0, part 0).
Values are JSON blobs (the reference uses serialized thrift structs).
"""
from __future__ import annotations

import struct

META_SPACE_ID = 0
META_PART_ID = 0

# key prefixes — kept as readable ascii tags since the meta store is tiny
P_SPACE = b"__spc:"           # + space_id(u32)        -> SpaceDesc json
P_SPACE_NAME = b"__spn:"      # + name                 -> space_id(u32)
P_TAG = b"__tag:"             # + space(u32)+tag(u32)+ver(u32) -> Schema json
P_TAG_NAME = b"__tgn:"        # + space(u32)+name      -> tag_id(u32)
P_EDGE = b"__edg:"            # + space(u32)+etype(u32)+ver(u32) -> Schema json
P_EDGE_NAME = b"__egn:"       # + space(u32)+name      -> edge_type(u32)
P_PART = b"__prt:"            # + space(u32)+part(u32) -> [host,...] json
P_HOST = b"__hst:"            # + host str             -> HostInfo json
P_USER = b"__usr:"            # + name                 -> user json
P_ROLE = b"__rol:"            # + space(u32)+user      -> role str
P_CONFIG = b"__cfg:"          # + module:name          -> config json
P_ID = b"__id:"               # + counter name         -> u32 (next id)
P_BALANCE = b"__bal:"         # + plan_id(u64)+task    -> task json
P_SEGMENT = b"__seg:"         # + segment:key          -> custom KV
P_SNAPSHOT = b"__snp:"        # + name                 -> status str
P_INDEX = b"__idx:"           # + space(u32)+name      -> IndexDesc json
K_CLUSTER_ID = b"__cluster_id__"  # -> u63 cluster id (ClusterIdMan)


_U32 = struct.Struct(">I")


def space_key(space_id: int) -> bytes:
    return P_SPACE + _U32.pack(space_id)


def space_name_key(name: str) -> bytes:
    return P_SPACE_NAME + name.encode("utf-8")


def tag_key(space_id: int, tag_id: int, version: int) -> bytes:
    return P_TAG + _U32.pack(space_id) + _U32.pack(tag_id) + _U32.pack(version)


def tag_prefix(space_id: int, tag_id: int = None) -> bytes:
    p = P_TAG + _U32.pack(space_id)
    return p if tag_id is None else p + _U32.pack(tag_id)


def tag_name_key(space_id: int, name: str) -> bytes:
    return P_TAG_NAME + _U32.pack(space_id) + name.encode("utf-8")


def edge_key(space_id: int, edge_type: int, version: int) -> bytes:
    return P_EDGE + _U32.pack(space_id) + _U32.pack(edge_type) + _U32.pack(version)


def edge_prefix(space_id: int, edge_type: int = None) -> bytes:
    p = P_EDGE + _U32.pack(space_id)
    return p if edge_type is None else p + _U32.pack(edge_type)


def edge_name_key(space_id: int, name: str) -> bytes:
    return P_EDGE_NAME + _U32.pack(space_id) + name.encode("utf-8")


def part_key(space_id: int, part_id: int) -> bytes:
    return P_PART + _U32.pack(space_id) + _U32.pack(part_id)


def part_prefix(space_id: int) -> bytes:
    return P_PART + _U32.pack(space_id)


def host_key(host: str) -> bytes:
    return P_HOST + host.encode("utf-8")


def user_key(name: str) -> bytes:
    return P_USER + name.encode("utf-8")


def role_key(space_id: int, user: str) -> bytes:
    return P_ROLE + _U32.pack(space_id) + user.encode("utf-8")


def config_key(module: str, name: str) -> bytes:
    return P_CONFIG + f"{module}:{name}".encode("utf-8")


def id_key(counter: str) -> bytes:
    return P_ID + counter.encode("utf-8")


def balance_task_key(plan_id: int, space_id: int, part_id: int,
                     src: str, dst: str) -> bytes:
    return (P_BALANCE + struct.pack(">Q", plan_id) + _U32.pack(space_id)
            + _U32.pack(part_id) + f"{src}>{dst}".encode("utf-8"))


def balance_prefix(plan_id: int = None) -> bytes:
    return P_BALANCE if plan_id is None else P_BALANCE + struct.pack(">Q", plan_id)


def segment_key(segment: str, key: str) -> bytes:
    return P_SEGMENT + f"{segment}:{key}".encode("utf-8")


def snapshot_key(name: str) -> bytes:
    return P_SNAPSHOT + name.encode("utf-8")


def index_key(space_id: int, name: str) -> bytes:
    return P_INDEX + _U32.pack(space_id) + name.encode("utf-8")


def index_prefix(space_id: int) -> bytes:
    return P_INDEX + _U32.pack(space_id)


def unpack_u32(b: bytes) -> int:
    return _U32.unpack(b)[0]


def pack_u32(v: int) -> bytes:
    return _U32.pack(v)
