"""Part-admin over the network: storaged-side AdminService + metad-side
NetAdminClient.

Role parity with the reference's storage AdminProcessor (transLeader/
addPart/addLearner/waitingForCatchUpData/memberChange/removePart,
storage/AdminProcessor.h) driven by the meta Balancer through
AdminClient RPC fan-out (meta/processors/admin/AdminClient). Addresses
crossing this boundary are STORAGE addrs; each side converts to raft
addrs with the port+1 convention locally.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..common.status import ErrorCode, Status
from ..rpc import proxy


def raft_addr_of(storage_addr: str) -> str:
    """Raft listens on storage port + 1 (the reference's getRaftAddr
    convention, kvstore/NebulaStore.h:55-60). THE single home of the
    conversion — the inverse lives right below."""
    h, p = storage_addr.rsplit(":", 1)
    return f"{h}:{int(p) + 1}"


def storage_addr_of(raft_addr: str) -> str:
    h, p = raft_addr.rsplit(":", 1)
    return f"{h}:{int(p) - 1}"


class AdminService:
    """Registered as the "admin" service on a replicated storaged's RPC
    server; operates on the local StorageNode."""

    def __init__(self, node):
        self._node = node

    def add_part(self, space_id: int, part_id: int,
                 peers_storage: List[str], as_learner: bool) -> bool:
        self._node.add_part(space_id, part_id,
                            [raft_addr_of(p) for p in peers_storage],
                            as_learner=as_learner)
        return True

    def remove_part(self, space_id: int, part_id: int) -> bool:
        self._node.remove_part(space_id, part_id)
        return True

    def raft_state(self, space_id: int, part_id: int) -> Optional[Dict]:
        r = self._node.raft(space_id, part_id)
        if r is None:
            return None
        return {"is_leader": r.is_leader(), "term": r.term,
                "committed": r.committed_id, "role": r.role.name}

    # leader-only raft membership ops (the balancer routes these to the
    # host it believes leads; a non-leader returns False and the caller
    # re-resolves)
    def add_learner(self, space_id: int, part_id: int,
                    learner_storage: str) -> bool:
        r = self._node.raft(space_id, part_id)
        if r is None or not r.is_leader():
            return False
        from ..kvstore.raftex import RaftCode
        return r.add_learner_async(
            raft_addr_of(learner_storage)).result(timeout=5) is RaftCode.SUCCEEDED

    def member_add(self, space_id: int, part_id: int,
                   target_storage: str) -> bool:
        r = self._node.raft(space_id, part_id)
        if r is None or not r.is_leader():
            return False
        from ..kvstore.raftex import RaftCode
        return r.add_peer_async(
            raft_addr_of(target_storage)).result(timeout=5) is RaftCode.SUCCEEDED

    def member_remove(self, space_id: int, part_id: int,
                      target_storage: str) -> bool:
        r = self._node.raft(space_id, part_id)
        if r is None or not r.is_leader():
            return False
        from ..kvstore.raftex import RaftCode
        return r.remove_peer_async(
            raft_addr_of(target_storage)).result(timeout=5) is RaftCode.SUCCEEDED

    def trans_leader(self, space_id: int, part_id: int,
                     target_storage: str) -> bool:
        r = self._node.raft(space_id, part_id)
        if r is None or not r.is_leader():
            return False
        r.transfer_leader_async(raft_addr_of(target_storage))
        return True


class NetAdminClient:
    """The Balancer's admin surface over storaged "admin" RPC services —
    same method contract as kvstore.raft_store.AdminClient, usable from
    inside metad."""

    def __init__(self, get_hosts: Callable[[], List[str]]):
        self._get_hosts = get_hosts

    def _svc(self, addr: str):
        return proxy(addr, "admin", timeout=5.0)

    def ready(self) -> Status:
        """Every active storaged must expose the admin service (i.e. run
        --replicated) before a balance plan can execute — otherwise the
        plan would return a success-looking id and fail asynchronously."""
        hosts = self._get_hosts()
        if not hosts:
            return Status.error(ErrorCode.E_NO_HOSTS, "no active storaged")
        for h in hosts:
            try:
                self._svc(h).raft_state(0, 0)
            except Exception:
                return Status.error(
                    ErrorCode.E_UNSUPPORTED,
                    f"storaged {h} has no admin service "
                    f"(balance requires --replicated storaged)")
        return Status.OK()

    def _state(self, addr: str, space_id: int, part_id: int) -> Optional[Dict]:
        try:
            return self._svc(addr).raft_state(space_id, part_id)
        except Exception:
            return None

    def _leader_host(self, space_id: int, part_id: int,
                     timeout: float = 5.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for h in self._get_hosts():
                st = self._state(h, space_id, part_id)
                if st and st["is_leader"]:
                    return h
            time.sleep(0.05)
        raise TimeoutError(f"no leader for ({space_id},{part_id})")

    # ----------------------------------------------------- AdminClient API
    def leader_of(self, space_id: int, part_id: int,
                  timeout: float = 5.0) -> str:
        return self._leader_host(space_id, part_id, timeout)

    def add_part(self, addr: str, space_id: int, part_id: int,
                 peers: List[str], as_learner: bool) -> None:
        self._svc(addr).add_part(space_id, part_id, peers, as_learner)

    def add_learner(self, space_id: int, part_id: int, learner: str) -> bool:
        try:
            leader = self._leader_host(space_id, part_id)
            return self._svc(leader).add_learner(space_id, part_id, learner)
        except (TimeoutError, Exception):
            return False

    def wait_catchup(self, space_id: int, part_id: int, target: str,
                     timeout: float = 10.0) -> bool:
        try:
            leader = self._leader_host(space_id, part_id)
            goal = (self._state(leader, space_id, part_id) or {}).get(
                "committed", 0)
        except TimeoutError:
            return False
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = self._state(target, space_id, part_id)
            if st is not None and st["committed"] >= goal:
                return True
            time.sleep(0.05)
        return False

    def member_add(self, space_id: int, part_id: int, addr: str) -> bool:
        try:
            leader = self._leader_host(space_id, part_id)
            return self._svc(leader).member_add(space_id, part_id, addr)
        except (TimeoutError, Exception):
            return False

    def member_remove(self, space_id: int, part_id: int, addr: str) -> bool:
        try:
            leader = self._leader_host(space_id, part_id)
            return self._svc(leader).member_remove(space_id, part_id, addr)
        except (TimeoutError, Exception):
            return False

    def trans_leader(self, space_id: int, part_id: int, target: str,
                     timeout: float = 5.0) -> bool:
        try:
            leader = self._leader_host(space_id, part_id)
            if leader == target:
                return True
            self._svc(leader).trans_leader(space_id, part_id, target)
        except (TimeoutError, Exception):
            return False
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = self._state(target, space_id, part_id)
            if st and st["is_leader"]:
                return True
            time.sleep(0.05)
        return False

    def remove_part(self, addr: str, space_id: int, part_id: int) -> None:
        try:
            self._svc(addr).remove_part(space_id, part_id)
        except Exception:
            pass  # host already gone: nothing to remove

    def leader_map(self, space_id: int,
                   parts: List[int]) -> Dict[int, Optional[str]]:
        out: Dict[int, Optional[str]] = {}
        for p in parts:
            try:
                out[p] = self.leader_of(space_id, p, timeout=2.0)
            except TimeoutError:
                out[p] = None
        return out
