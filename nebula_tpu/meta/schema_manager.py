"""SchemaManager: schema lookup facade.

Role parity with the reference's `meta/SchemaManager` /
`ServerBasedSchemaManager`: a thin resolve-by-name/id facade the storage
processors and query executors use, backed by the meta catalog (in-proc
or via MetaClient cache). Also covers the test-injection role of the
reference's `storage/test/AdHocSchemaManager` via `AdHocSchemaManager`.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..codec.schema import Schema
from ..common.status import ErrorCode, StatusOr


class SchemaManager:
    """Caches schema/name lookups keyed by the meta catalog version —
    the in-proc analogue of the reference MetaClient's local caches
    refreshed by `load_data_interval_secs` (ref: MetaClient.h:28-60).
    The cache keeps the traversal hot loop free of catalog scans."""

    def __init__(self, meta: "MetaService", cache_capacity: int = 4096):
        from ..common.lru import ConcurrentLRUCache
        self._meta = meta
        self._cache_ver = -1
        self._cache = ConcurrentLRUCache(cache_capacity)

    def _memo(self, key: Tuple, compute):
        ver = getattr(self._meta, "catalog_version", None)
        if ver is None:
            return compute()  # uncacheable meta (no version signal)
        if ver != self._cache_ver:
            self._cache.clear()
            self._cache_ver = ver
        return self._cache.get_or_compute(key, compute)

    def space_id(self, name: str) -> StatusOr[int]:
        r = self._meta.get_space(name)
        if not r.ok():
            return StatusOr.from_status(r.status)
        return StatusOr.of(r.value().space_id)

    def num_parts(self, space_id: int) -> int:
        def compute():
            r = self._meta.get_space_by_id(space_id)
            return r.value().partition_num if r.ok() else 0
        return self._memo(("nparts", space_id), compute)

    def tag_id(self, space_id: int, name: str) -> Optional[int]:
        return self._memo(("tid", space_id, name),
                          lambda: self._meta.get_tag_id(space_id, name))

    def edge_type(self, space_id: int, name: str) -> Optional[int]:
        return self._memo(("et", space_id, name),
                          lambda: self._meta.get_edge_type(space_id, name))

    def tag_name(self, space_id: int, tag_id: int) -> Optional[str]:
        def compute():
            return {tid: name for name, tid in self._meta.list_tags(space_id)}
        return self._memo(("tnames", space_id), compute).get(tag_id)

    def edge_name(self, space_id: int, edge_type: int) -> Optional[str]:
        def compute():
            return {et: name for name, et in self._meta.list_edges(space_id)}
        return self._memo(("enames", space_id), compute).get(abs(edge_type))

    def tag_schema(self, space_id: int, tag_id: int,
                   version: int = -1) -> StatusOr[Schema]:
        return self._memo(("tschema", space_id, tag_id, version),
                          lambda: self._meta.get_tag_schema(space_id, tag_id,
                                                            version))

    def edge_schema(self, space_id: int, edge_type: int,
                    version: int = -1) -> StatusOr[Schema]:
        return self._memo(("eschema", space_id, abs(edge_type), version),
                          lambda: self._meta.get_edge_schema(
                              space_id, abs(edge_type), version))

    def all_edge_types(self, space_id: int) -> List[int]:
        return self._memo(("ets", space_id),
                          lambda: [et for _, et in
                                   self._meta.list_edges(space_id)])

    def all_tag_ids(self, space_id: int) -> List[int]:
        return self._memo(("tids", space_id),
                          lambda: [tid for tid in
                                   [t for _, t in self._meta.list_tags(space_id)]])

    def list_indexes(self, space_id: int) -> List[dict]:
        return self._memo(("idxs", space_id),
                          lambda: self._meta.list_indexes(space_id))

    def indexes_for_tag(self, space_id: int, tag_id: int) -> List[dict]:
        return [d for d in self.list_indexes(space_id)
                if not d["is_edge"] and d["schema_id"] == tag_id]


class AdHocSchemaManager(SchemaManager):
    """Schema injection without a meta service, for storage-layer tests
    (ref: storage/test/AdHocSchemaManager.{h,cpp})."""

    def __init__(self):
        self._tags: Dict[Tuple[int, int], Schema] = {}
        self._edges: Dict[Tuple[int, int], Schema] = {}
        self._tag_names: Dict[Tuple[int, str], int] = {}
        self._edge_names: Dict[Tuple[int, str], int] = {}
        self._num_parts: Dict[int, int] = {}

    def add_tag(self, space_id: int, tag_id: int, name: str, schema: Schema):
        self._tags[(space_id, tag_id)] = schema
        self._tag_names[(space_id, name)] = tag_id

    def add_edge(self, space_id: int, edge_type: int, name: str, schema: Schema):
        self._edges[(space_id, edge_type)] = schema
        self._edge_names[(space_id, name)] = edge_type

    def set_num_parts(self, space_id: int, n: int):
        self._num_parts[space_id] = n

    def space_id(self, name: str) -> StatusOr[int]:
        return StatusOr.of(1)

    def num_parts(self, space_id: int) -> int:
        return self._num_parts.get(space_id, 1)

    def tag_id(self, space_id: int, name: str) -> Optional[int]:
        return self._tag_names.get((space_id, name))

    def edge_type(self, space_id: int, name: str) -> Optional[int]:
        return self._edge_names.get((space_id, name))

    def tag_name(self, space_id: int, tag_id: int) -> Optional[str]:
        for (sid, name), tid in self._tag_names.items():
            if sid == space_id and tid == tag_id:
                return name
        return None

    def edge_name(self, space_id: int, edge_type: int) -> Optional[str]:
        for (sid, name), et in self._edge_names.items():
            if sid == space_id and et == abs(edge_type):
                return name
        return None

    def tag_schema(self, space_id: int, tag_id: int,
                   version: int = -1) -> StatusOr[Schema]:
        s = self._tags.get((space_id, tag_id))
        if s is None:
            return StatusOr.err(ErrorCode.E_TAG_NOT_FOUND, str(tag_id))
        return StatusOr.of(s)

    def edge_schema(self, space_id: int, edge_type: int,
                    version: int = -1) -> StatusOr[Schema]:
        s = self._edges.get((space_id, abs(edge_type)))
        if s is None:
            return StatusOr.err(ErrorCode.E_EDGE_NOT_FOUND, str(edge_type))
        return StatusOr.of(s)

    def all_edge_types(self, space_id: int) -> List[int]:
        return sorted(self._edge_names[k] for k in self._edge_names
                      if k[0] == space_id)

    def all_tag_ids(self, space_id: int) -> List[int]:
        return sorted(self._tag_names[k] for k in self._tag_names
                      if k[0] == space_id)

    def list_indexes(self, space_id: int) -> List[dict]:
        return []

    def indexes_for_tag(self, space_id: int, tag_id: int) -> List[dict]:
        return []
