"""Meta service: the cluster catalog.

Role parity with the reference's `src/meta/` processors
(partsMan/schemaMan/usersMan/configMan/customKV + HBProcessor +
ActiveHostsMan): spaces with partition→host allocation, multi-version
tag/edge schemas, users/roles (RBAC data plane), cluster config
registry, custom segment KV, and host liveness via heartbeats. All
state lives in the meta KV store (space 0, part 0) through the same
Part/consensus seam as data partitions — so pointing the store factory
at a Raft-backed part makes the whole catalog replicated, exactly like
the reference's one-part meta NebulaStore (ref: daemons/MetaDaemon
.cpp:57-127).
"""
from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..codec.schema import PropType, Schema, SchemaField
from ..common.status import ErrorCode, Status, StatusOr
from ..kvstore.store import GraphStore
from . import keys as mk

DEFAULT_HEARTBEAT_INTERVAL_SECS = 10
DEFAULT_EXPIRED_THRESHOLD_SECS = 10 * DEFAULT_HEARTBEAT_INTERVAL_SECS


@dataclass
class SpaceDesc:
    space_id: int
    name: str
    partition_num: int
    replica_factor: int

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_json(b: bytes) -> "SpaceDesc":
        return SpaceDesc(**json.loads(b))


@dataclass
class HostInfo:
    host: str
    last_hb: float = 0.0
    role: str = "storage"

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_json(b: bytes) -> "HostInfo":
        return HostInfo(**json.loads(b))


class MetaService:
    """In-process meta handler; the RPC layer (rpc/) exposes the same
    methods over the wire for multi-process deployments."""

    def __init__(self, store: Optional[GraphStore] = None,
                 expired_threshold_secs: int = DEFAULT_EXPIRED_THRESHOLD_SECS,
                 root_password: str = ""):
        self._store = store or GraphStore()
        self._store.add_part(mk.META_SPACE_ID, mk.META_PART_ID)
        self._expired_threshold = expired_threshold_secs
        self._root_password = root_password
        self._listeners: List[Any] = []  # MetaChangedListener callbacks
        # bumped on every catalog mutation; lets SchemaManager cache safely
        self.catalog_version = 0
        # heartbeat-fed raft leadership: host -> {space_id: [parts led]}
        # (the ActiveHostsMan leader view; feeds SHOW HOSTS / SHOW PARTS
        # leader columns and the balancer's placement decisions)
        self._leader_view: Dict[str, Dict[int, List[int]]] = {}
        # heartbeat-carried workload heat (common/heat.py
        # heartbeat_payload): host -> {"parts": {sid: {pid: fields +
        # score}}, "staleness": {sid: {pid: {...}}}, "ts"}. In-memory
        # like the leader view — placement telemetry refreshes within
        # one heartbeat; feeds SHOW HOSTS/PARTS heat columns and the
        # heat-aware BALANCE advisor (meta/balancer.py)
        self._heat_view: Dict[str, Dict[str, Any]] = {}
        # heartbeat-carried HTTP admin ports: rpc host -> (ws_port,
        # role). The /cluster_metrics federation (daemons/graphd.py)
        # reads this to find every daemon's /metrics; in-memory like
        # the leader view (refreshes within one heartbeat)
        self._web_ports: Dict[str, Tuple[int, str]] = {}
        # replica-reconcile gating: the full catalog sweep runs only
        # for a host's FIRST heartbeat or while a space is known to be
        # under-replicated — not on every beat of every host (the
        # heartbeat handler is the liveness failure detector; it must
        # stay O(1) in the steady state)
        self._hosts_seen: set = set()
        self._needs_reconcile = True   # catalog may predate this boot
        # ClusterIdMan (ref: meta/ClusterIdMan.h + MetaDaemon.cpp:102-125):
        # generated once, persisted in the meta KV; clients echo it in
        # heartbeats so a daemon can't join the wrong cluster
        existing = self._get(mk.K_CLUSTER_ID)
        if existing is not None:
            self.cluster_id = int(existing)
        else:
            import os as _os
            self.cluster_id = int.from_bytes(_os.urandom(8), "big") >> 1
            self._put((mk.K_CLUSTER_ID, str(self.cluster_id).encode()))

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _get(self, key: bytes) -> Optional[bytes]:
        r = self._store.get(mk.META_SPACE_ID, mk.META_PART_ID, key)
        return r.value() if r.ok() else None

    def _put(self, *kvs: Tuple[bytes, bytes]) -> Status:
        return self._store.async_multi_put(mk.META_SPACE_ID, mk.META_PART_ID,
                                           list(kvs))

    def _remove(self, *ks: bytes) -> Status:
        return self._store.async_multi_remove(mk.META_SPACE_ID, mk.META_PART_ID,
                                              list(ks))

    def _scan(self, prefix: bytes) -> List[Tuple[bytes, bytes]]:
        r = self._store.prefix(mk.META_SPACE_ID, mk.META_PART_ID, prefix)
        return list(r.value()) if r.ok() else []

    def _next_id(self, counter: str) -> int:
        k = mk.id_key(counter)
        cur = self._get(k)
        nxt = (mk.unpack_u32(cur) if cur else 0) + 1
        self._put((k, mk.pack_u32(nxt)))
        return nxt

    def get_catalog_version(self) -> int:
        """RPC-friendly accessor (clients key their caches on this)."""
        return self.catalog_version

    def add_listener(self, listener) -> None:
        """listener: callable(event:str, **kw) — part add/remove pushes."""
        self._listeners.append(listener)

    def _notify(self, event: str, **kw) -> None:
        for l in self._listeners:
            l(event, **kw)

    # ------------------------------------------------------------------
    # spaces & parts (partsMan)
    # ------------------------------------------------------------------
    def create_space(self, name: str, partition_num: int = 100,
                     replica_factor: int = 1,
                     if_not_exists: bool = False) -> StatusOr[int]:
        if partition_num < 1 or replica_factor < 1:
            return StatusOr.err(ErrorCode.E_INVALID_ARGUMENT,
                                "partition_num and replica_factor must be >= 1")
        # fewer live hosts than replica_factor is fine (reconcile tops
        # up as hosts join) but an absurd factor is a typo, not a plan:
        # raft quorums beyond 7 voters only slow commits down
        if replica_factor > 7:
            return StatusOr.err(ErrorCode.E_INVALID_ARGUMENT,
                                f"replica_factor {replica_factor} > 7 "
                                f"(raft practicality cap)")
        existing = self._get(mk.space_name_key(name))
        if existing is not None:
            if if_not_exists:
                return StatusOr.of(mk.unpack_u32(existing))
            return StatusOr.err(ErrorCode.E_EXISTED, f"space {name!r} exists")
        hosts = [h.host for h in self.active_hosts()]
        space_id = self._next_id("space")
        desc = SpaceDesc(space_id, name, partition_num, replica_factor)
        kvs = [(mk.space_key(space_id), desc.to_json()),
               (mk.space_name_key(name), mk.pack_u32(space_id))]
        # round-robin part allocation over active hosts (ref: CreateSpace
        # processor allocating partition_num x replica_factor round-robin).
        # Fewer live hosts than replica_factor is NOT an error: the
        # allocation starts under-replicated and the heartbeat-driven
        # reconcile (_reconcile_replicas) tops each part up to
        # replica_factor as storageds join — CREATE SPACE ...
        # replica_factor=N works end-to-end regardless of boot order
        # (docs/manual/12-replication.md).
        for part in range(1, partition_num + 1):
            if hosts:
                assigned = [hosts[(part - 1 + r) % len(hosts)]
                            for r in range(min(replica_factor, len(hosts)))]
            else:
                assigned = ["local"]
            kvs.append((mk.part_key(space_id, part), json.dumps(assigned).encode()))
        st = self._put(*kvs)
        if not st.ok():
            return StatusOr.from_status(st)
        if len(hosts) < replica_factor:
            self._needs_reconcile = True   # top up as hosts join
        self.catalog_version += 1
        self._notify("space_added", space_id=space_id, desc=desc)
        return StatusOr.of(space_id)

    def drop_space(self, name: str, if_exists: bool = False) -> Status:
        sid = self._get(mk.space_name_key(name))
        if sid is None:
            if if_exists:
                return Status.OK()
            return Status.error(ErrorCode.E_SPACE_NOT_FOUND, name)
        space_id = mk.unpack_u32(sid)
        dead = [mk.space_key(space_id), mk.space_name_key(name)]
        for prefix in (mk.part_prefix(space_id), mk.tag_prefix(space_id),
                       mk.edge_prefix(space_id)):
            dead.extend(k for k, _ in self._scan(prefix))
        dead.extend(k for k, _ in self._scan(mk.P_TAG_NAME + mk.pack_u32(space_id)))
        dead.extend(k for k, _ in self._scan(mk.P_EDGE_NAME + mk.pack_u32(space_id)))
        dead.extend(k for k, _ in self._scan(mk.index_prefix(space_id)))
        st = self._remove(*dead)
        if st.ok():
            self.catalog_version += 1
            self._notify("space_removed", space_id=space_id)
        return st

    def get_space(self, name: str) -> StatusOr[SpaceDesc]:
        sid = self._get(mk.space_name_key(name))
        if sid is None:
            return StatusOr.err(ErrorCode.E_SPACE_NOT_FOUND, name)
        raw = self._get(mk.space_key(mk.unpack_u32(sid)))
        if raw is None:
            return StatusOr.err(ErrorCode.E_SPACE_NOT_FOUND, name)
        return StatusOr.of(SpaceDesc.from_json(raw))

    def get_space_by_id(self, space_id: int) -> StatusOr[SpaceDesc]:
        raw = self._get(mk.space_key(space_id))
        if raw is None:
            return StatusOr.err(ErrorCode.E_SPACE_NOT_FOUND, str(space_id))
        return StatusOr.of(SpaceDesc.from_json(raw))

    def list_spaces(self) -> List[SpaceDesc]:
        return [SpaceDesc.from_json(v) for _, v in self._scan(mk.P_SPACE)]

    def get_parts_alloc(self, space_id: int) -> Dict[int, List[str]]:
        out = {}
        for k, v in self._scan(mk.part_prefix(space_id)):
            part_id = mk.unpack_u32(k[-4:])
            out[part_id] = json.loads(v)
        return out

    def update_part_alloc(self, space_id: int, part_id: int,
                          hosts: List[str]) -> Status:
        return self._put((mk.part_key(space_id, part_id),
                          json.dumps(hosts).encode()))

    # ------------------------------------------------------------------
    # schemas (schemaMan) — multi-version, monotonic SchemaVer
    # ------------------------------------------------------------------
    @staticmethod
    def _columns_to_schema(columns, version, ttl_col=None, ttl_duration=0) -> Schema:
        fields = [SchemaField(c["name"], PropType.from_name(c["type"]),
                              nullable=c.get("nullable", False),
                              default=c.get("default"))
                  for c in columns]
        names = [f.name for f in fields]
        if len(set(names)) != len(names):
            raise ValueError("duplicate column name")
        if ttl_col:
            # the TTL column must exist and be int/timestamp (ref:
            # SchemaTest 'ttl_col on not integer and timestamp column'
            # must fail; meta/processors TTL validation)
            t = next((f.type for f in fields if f.name == ttl_col), None)
            if t is None:
                raise ValueError(f"ttl_col {ttl_col!r} not a column")
            if t not in (PropType.INT, PropType.TIMESTAMP):
                raise ValueError(
                    f"ttl_col {ttl_col!r} must be int or timestamp")
        return Schema(fields, version, ttl_col, ttl_duration)

    def _create_schema(self, is_edge: bool, space_id: int, name: str,
                       columns: List[dict], ttl_col=None, ttl_duration=0,
                       if_not_exists=False) -> StatusOr[int]:
        if self._get(mk.space_key(space_id)) is None:
            return StatusOr.err(ErrorCode.E_SPACE_NOT_FOUND, str(space_id))
        name_key = (mk.edge_name_key if is_edge else mk.tag_name_key)(space_id, name)
        # a tag and an edge may not share a name (reference behavior)
        other_key = (mk.tag_name_key if is_edge else mk.edge_name_key)(space_id, name)
        existing = self._get(name_key)
        if existing is not None:
            if if_not_exists:
                return StatusOr.of(mk.unpack_u32(existing))
            return StatusOr.err(ErrorCode.E_EXISTED, name)
        if self._get(other_key) is not None:
            return StatusOr.err(ErrorCode.E_CONFLICT,
                                f"{name!r} exists as a {'tag' if is_edge else 'edge'}")
        try:
            schema = self._columns_to_schema(columns, 0, ttl_col, ttl_duration)
        except ValueError as e:
            return StatusOr.err(ErrorCode.E_INVALID_ARGUMENT, str(e))
        sid = self._next_id("edge_type" if is_edge else "tag")
        skey = (mk.edge_key if is_edge else mk.tag_key)(space_id, sid, 0)
        st = self._put((name_key, mk.pack_u32(sid)),
                       (skey, json.dumps(schema.to_dict()).encode()))
        if not st.ok():
            return StatusOr.from_status(st)
        self.catalog_version += 1
        return StatusOr.of(sid)

    def create_tag(self, space_id: int, name: str, columns: List[dict],
                   ttl_col=None, ttl_duration=0,
                   if_not_exists=False) -> StatusOr[int]:
        return self._create_schema(False, space_id, name, columns, ttl_col,
                                   ttl_duration, if_not_exists)

    def create_edge(self, space_id: int, name: str, columns: List[dict],
                    ttl_col=None, ttl_duration=0,
                    if_not_exists=False) -> StatusOr[int]:
        return self._create_schema(True, space_id, name, columns, ttl_col,
                                   ttl_duration, if_not_exists)

    def _schema_id(self, is_edge: bool, space_id: int, name: str) -> Optional[int]:
        raw = self._get((mk.edge_name_key if is_edge else mk.tag_name_key)
                        (space_id, name))
        return mk.unpack_u32(raw) if raw is not None else None

    def get_tag_id(self, space_id: int, name: str) -> Optional[int]:
        return self._schema_id(False, space_id, name)

    def get_edge_type(self, space_id: int, name: str) -> Optional[int]:
        return self._schema_id(True, space_id, name)

    def _get_schema(self, is_edge: bool, space_id: int, sid: int,
                    version: int = -1) -> StatusOr[Schema]:
        prefix = (mk.edge_prefix if is_edge else mk.tag_prefix)(space_id, sid)
        rows = self._scan(prefix)
        if not rows:
            return StatusOr.err(
                ErrorCode.E_EDGE_NOT_FOUND if is_edge else ErrorCode.E_TAG_NOT_FOUND,
                f"id {sid}")
        if version < 0:
            k, v = rows[-1]  # versions ascending; last = latest
            return StatusOr.of(Schema.from_dict(json.loads(v)))
        for k, v in rows:
            if mk.unpack_u32(k[-4:]) == version:
                return StatusOr.of(Schema.from_dict(json.loads(v)))
        return StatusOr.err(ErrorCode.E_INVALID_SCHEMA_VER, str(version))

    def get_tag_schema(self, space_id: int, sid: int,
                       version: int = -1) -> StatusOr[Schema]:
        return self._get_schema(False, space_id, sid, version)

    def get_edge_schema(self, space_id: int, sid: int,
                        version: int = -1) -> StatusOr[Schema]:
        return self._get_schema(True, space_id, sid, version)

    def _alter_schema(self, is_edge: bool, space_id: int, name: str,
                      adds: List[dict], changes: List[dict], drops: List[str],
                      ttl_col=None, ttl_duration=None) -> Status:
        sid = self._schema_id(is_edge, space_id, name)
        if sid is None:
            return Status.error(
                ErrorCode.E_EDGE_NOT_FOUND if is_edge else ErrorCode.E_TAG_NOT_FOUND,
                name)
        cur = self._get_schema(is_edge, space_id, sid).value()
        try:
            new = cur
            if adds:
                new = new.with_added([SchemaField(c["name"],
                                                  PropType.from_name(c["type"]),
                                                  default=c.get("default"))
                                      for c in adds])
            if changes:
                new = new.with_changed([SchemaField(c["name"],
                                                    PropType.from_name(c["type"]),
                                                    default=c.get("default"))
                                        for c in changes])
            if drops:
                if new.ttl_col and new.ttl_col in drops and \
                        (ttl_col is None or ttl_col == new.ttl_col):
                    return Status.error(
                        ErrorCode.E_INVALID_ARGUMENT,
                        f"cannot drop active ttl_col {new.ttl_col!r}")
                new = new.with_dropped(drops)
            if not (adds or changes or drops):
                new = Schema(list(cur.fields), cur.version + 1,
                             cur.ttl_col, cur.ttl_duration)
        except ValueError as e:
            return Status.error(ErrorCode.E_INVALID_ARGUMENT, str(e))
        if ttl_col is not None:
            if ttl_col:
                t = new.field_type(ttl_col)
                if t is None:
                    return Status.error(ErrorCode.E_INVALID_ARGUMENT,
                                        f"ttl_col {ttl_col!r} not a column")
                if t not in (PropType.INT, PropType.TIMESTAMP):
                    return Status.error(
                        ErrorCode.E_INVALID_ARGUMENT,
                        f"ttl_col {ttl_col!r} must be int or timestamp")
            new.ttl_col = ttl_col
        if ttl_duration is not None:
            new.ttl_duration = ttl_duration
        skey = (mk.edge_key if is_edge else mk.tag_key)(space_id, sid, new.version)
        st = self._put((skey, json.dumps(new.to_dict()).encode()))
        if st.ok():
            self.catalog_version += 1
        return st

    def alter_tag(self, space_id: int, name: str, adds=(), changes=(),
                  drops=(), ttl_col=None, ttl_duration=None) -> Status:
        return self._alter_schema(False, space_id, name, list(adds),
                                  list(changes), list(drops), ttl_col, ttl_duration)

    def alter_edge(self, space_id: int, name: str, adds=(), changes=(),
                   drops=(), ttl_col=None, ttl_duration=None) -> Status:
        return self._alter_schema(True, space_id, name, list(adds),
                                  list(changes), list(drops), ttl_col, ttl_duration)

    def _drop_schema(self, is_edge: bool, space_id: int, name: str,
                     if_exists: bool) -> Status:
        sid = self._schema_id(is_edge, space_id, name)
        if sid is None:
            if if_exists:
                return Status.OK()
            return Status.error(
                ErrorCode.E_EDGE_NOT_FOUND if is_edge else ErrorCode.E_TAG_NOT_FOUND,
                name)
        name_key = (mk.edge_name_key if is_edge else mk.tag_name_key)(space_id, name)
        dead = [name_key]
        dead.extend(k for k, _ in self._scan(
            (mk.edge_prefix if is_edge else mk.tag_prefix)(space_id, sid)))
        # indexes on a dropped schema die with it (reference: DropTag
        # rejects while indexes exist; we cascade instead — simpler and
        # the graphd layer has no multi-statement transactions to stage
        # the two drops atomically)
        for k, v in self._scan(mk.index_prefix(space_id)):
            d = json.loads(v)
            if d.get("is_edge") == is_edge and d.get("schema_name") == name:
                dead.append(k)
        st = self._remove(*dead)
        if st.ok():
            self.catalog_version += 1
        return st

    def drop_tag(self, space_id: int, name: str, if_exists=False) -> Status:
        return self._drop_schema(False, space_id, name, if_exists)

    def drop_edge(self, space_id: int, name: str, if_exists=False) -> Status:
        return self._drop_schema(True, space_id, name, if_exists)

    def _list_schemas(self, is_edge: bool, space_id: int) -> List[Tuple[str, int]]:
        prefix = (mk.P_EDGE_NAME if is_edge else mk.P_TAG_NAME) + mk.pack_u32(space_id)
        out = []
        for k, v in self._scan(prefix):
            out.append((k[len(prefix):].decode(), mk.unpack_u32(v)))
        return out

    def list_tags(self, space_id: int) -> List[Tuple[str, int]]:
        return self._list_schemas(False, space_id)

    def list_edges(self, space_id: int) -> List[Tuple[str, int]]:
        return self._list_schemas(True, space_id)

    # ------------------------------------------------------------------
    # secondary indexes (indexMan; ref: meta/processors/indexMan
    # CreateTagIndexProcessor / CreateEdgeIndexProcessor). An index is a
    # named (schema, [fields]) pair; storaged serves it as a CPU prop
    # scan and engine_tpu/index.py builds the device-resident sorted
    # twin per snapshot. Descriptor is a JSON blob under P_INDEX.
    # ------------------------------------------------------------------
    def create_index(self, space_id: int, name: str, is_edge: bool,
                     schema_name: str, fields: List[str],
                     if_not_exists: bool = False) -> StatusOr[int]:
        if self._get(mk.space_key(space_id)) is None:
            return StatusOr.err(ErrorCode.E_SPACE_NOT_FOUND, str(space_id))
        if not fields:
            return StatusOr.err(ErrorCode.E_INVALID_ARGUMENT,
                                "index needs at least one field")
        if len(set(fields)) != len(fields):
            return StatusOr.err(ErrorCode.E_INVALID_ARGUMENT,
                                "duplicate index field")
        sid = self._schema_id(is_edge, space_id, schema_name)
        if sid is None:
            return StatusOr.err(
                ErrorCode.E_EDGE_NOT_FOUND if is_edge else ErrorCode.E_TAG_NOT_FOUND,
                schema_name)
        schema = self._get_schema(is_edge, space_id, sid).value()
        for f in fields:
            if schema.field_type(f) is None:
                return StatusOr.err(ErrorCode.E_INVALID_ARGUMENT,
                                    f"field {f!r} not in "
                                    f"{'edge' if is_edge else 'tag'} "
                                    f"{schema_name!r}")
        ikey = mk.index_key(space_id, name)
        existing = self._get(ikey)
        if existing is not None:
            if if_not_exists:
                return StatusOr.of(json.loads(existing)["index_id"])
            return StatusOr.err(ErrorCode.E_EXISTED, name)
        index_id = self._next_id("index")
        desc = {"index_id": index_id, "name": name, "is_edge": is_edge,
                "schema_name": schema_name, "schema_id": sid,
                "fields": list(fields)}
        st = self._put((ikey, json.dumps(desc).encode()))
        if not st.ok():
            return StatusOr.from_status(st)
        self.catalog_version += 1
        return StatusOr.of(index_id)

    def drop_index(self, space_id: int, name: str,
                   if_exists: bool = False) -> Status:
        ikey = mk.index_key(space_id, name)
        if self._get(ikey) is None:
            if if_exists:
                return Status.OK()
            return Status.error(ErrorCode.E_NOT_FOUND, name)
        st = self._remove(ikey)
        if st.ok():
            self.catalog_version += 1
        return st

    def get_index(self, space_id: int, name: str) -> StatusOr[dict]:
        raw = self._get(mk.index_key(space_id, name))
        if raw is None:
            return StatusOr.err(ErrorCode.E_NOT_FOUND, name)
        return StatusOr.of(json.loads(raw))

    def list_indexes(self, space_id: int) -> List[dict]:
        return [json.loads(v) for _, v in self._scan(mk.index_prefix(space_id))]

    # ------------------------------------------------------------------
    # users & roles (usersMan; roles GOD > ADMIN > USER > GUEST)
    # ------------------------------------------------------------------
    def create_user(self, name: str, password: str,
                    if_not_exists=False) -> Status:
        if self._get(mk.user_key(name)) is not None:
            return Status.OK() if if_not_exists else Status.error(
                ErrorCode.E_EXISTED, name)
        return self._put((mk.user_key(name),
                          json.dumps({"password": _pw_hash(password)}).encode()))

    def drop_user(self, name: str, if_exists=False) -> Status:
        if self._get(mk.user_key(name)) is None:
            return Status.OK() if if_exists else Status.error(
                ErrorCode.E_NOT_FOUND, name)
        dead = [mk.user_key(name)]
        # role key = P_ROLE + space(u32) + user; match the user part exactly
        for k, v in self._scan(mk.P_ROLE):
            if k[len(mk.P_ROLE) + 4:] == name.encode():
                dead.append(k)
        return self._remove(*dead)

    def check_password(self, name: str, password: str) -> bool:
        raw = self._get(mk.user_key(name))
        if raw is None:
            # root bootstrap account with a fixed initial password, like the
            # reference's SimpleAuthenticator (user=root/password=nebula);
            # ours defaults to "" and is changeable via CHANGE PASSWORD
            return name == "root" and password == self._root_password
        return json.loads(raw)["password"] == _pw_hash(password)

    def user_exists(self, name: str) -> bool:
        return self._get(mk.user_key(name)) is not None or name == "root"

    def change_password(self, name: str, new_password: str,
                        old_password: Optional[str] = None) -> Status:
        if old_password is not None and not self.check_password(name, old_password):
            return Status.error(ErrorCode.E_BAD_USERNAME_PASSWORD, name)
        if self._get(mk.user_key(name)) is None and name != "root":
            return Status.error(ErrorCode.E_NOT_FOUND, name)
        return self._put((mk.user_key(name),
                          json.dumps({"password": _pw_hash(new_password)}).encode()))

    def grant_role(self, space_id: int, user: str, role: str) -> Status:
        if not self.user_exists(user):
            return Status.error(ErrorCode.E_NOT_FOUND, user)
        return self._put((mk.role_key(space_id, user), role.encode()))

    def revoke_role(self, space_id: int, user: str) -> Status:
        return self._remove(mk.role_key(space_id, user))

    def get_role(self, space_id: int, user: str) -> Optional[str]:
        if user == "root":
            return "GOD"
        raw = self._get(mk.role_key(space_id, user))
        return raw.decode() if raw is not None else None

    def list_users(self) -> List[str]:
        names = [k[len(mk.P_USER):].decode() for k, _ in self._scan(mk.P_USER)]
        return sorted(set(names) | {"root"})

    def list_roles(self, space_id: int) -> List[Tuple[str, str]]:
        prefix = mk.P_ROLE + mk.pack_u32(space_id)
        return [(k[len(prefix):].decode(), v.decode())
                for k, v in self._scan(prefix)]

    # ------------------------------------------------------------------
    # snapshots (catalog records; the storage-side checkpoint dump is
    # driven by the graph executor through the storage client)
    # ------------------------------------------------------------------
    def create_snapshot(self, name: str) -> Status:
        if self._get(mk.snapshot_key(name)) is not None:
            return Status.error(ErrorCode.E_EXISTED,
                                f"snapshot {name} already exists")
        return self._put((mk.snapshot_key(name), b"INVALID"))

    def set_snapshot_status(self, name: str, status: str) -> Status:
        if self._get(mk.snapshot_key(name)) is None:
            return Status.error(ErrorCode.E_NOT_FOUND,
                                f"snapshot {name} not found")
        return self._put((mk.snapshot_key(name), status.encode()))

    def has_snapshot(self, name: str) -> bool:
        return self._get(mk.snapshot_key(name)) is not None

    def drop_snapshot(self, name: str) -> Status:
        if self._get(mk.snapshot_key(name)) is None:
            return Status.error(ErrorCode.E_NOT_FOUND,
                                f"snapshot {name} not found")
        return self._remove(mk.snapshot_key(name))

    def list_snapshots(self) -> List[Tuple[str, str]]:
        return [(k[len(mk.P_SNAPSHOT):].decode(), v.decode())
                for k, v in self._scan(mk.P_SNAPSHOT)]

    # ------------------------------------------------------------------
    # config registry (configMan; modes IMMUTABLE/REBOOT/MUTABLE)
    # ------------------------------------------------------------------
    def reg_config(self, module: str, name: str, value: Any,
                   mode: str = "MUTABLE") -> Status:
        k = mk.config_key(module, name)
        if self._get(k) is not None:
            return Status.OK()  # registration is idempotent
        return self._put((k, json.dumps({"value": value, "mode": mode}).encode()))

    def set_config(self, module: str, name: str, value: Any) -> Status:
        k = mk.config_key(module, name)
        raw = self._get(k)
        if raw is None:
            return Status.error(ErrorCode.E_NOT_FOUND, f"{module}:{name}")
        cfg = json.loads(raw)
        if cfg["mode"] == "IMMUTABLE":
            return Status.error(ErrorCode.E_UNSUPPORTED,
                                f"{module}:{name} is immutable")
        cfg["value"] = value
        return self._put((k, json.dumps(cfg).encode()))

    def get_config(self, module: str, name: str) -> StatusOr[Any]:
        raw = self._get(mk.config_key(module, name))
        if raw is None:
            return StatusOr.err(ErrorCode.E_NOT_FOUND, f"{module}:{name}")
        return StatusOr.of(json.loads(raw)["value"])

    def list_configs(self, module: Optional[str] = None) -> List[Tuple[str, Any, str]]:
        out = []
        for k, v in self._scan(mk.P_CONFIG):
            mod_name = k[len(mk.P_CONFIG):].decode()
            mod, name = mod_name.split(":", 1)
            if module and mod != module:
                continue
            cfg = json.loads(v)
            out.append((mod_name, cfg["value"], cfg["mode"]))
        return out

    # ------------------------------------------------------------------
    # custom segment KV (customKV)
    # ------------------------------------------------------------------
    def segment_put(self, segment: str, kvs: Dict[str, str]) -> Status:
        return self._put(*[(mk.segment_key(segment, k), v.encode())
                           for k, v in kvs.items()])

    def segment_get(self, segment: str, key: str) -> Optional[str]:
        raw = self._get(mk.segment_key(segment, key))
        return raw.decode() if raw is not None else None

    def segment_scan(self, segment: str) -> Dict[str, str]:
        prefix = mk.P_SEGMENT + f"{segment}:".encode()
        return {k[len(prefix):].decode(): v.decode()
                for k, v in self._scan(prefix)}

    def segment_remove(self, segment: str, key: str) -> Status:
        return self._remove(mk.segment_key(segment, key))

    # ------------------------------------------------------------------
    # heartbeats / liveness (HBProcessor + ActiveHostsMan — this IS the
    # failure detector, ref meta/ActiveHostsMan.h:20-60)
    # ------------------------------------------------------------------
    def get_cluster_id(self) -> int:
        return self.cluster_id

    def heartbeat(self, host: str, role: str = "storage",
                  cluster_id: int = 0, leader_parts=None,
                  ws_port: int = -1, part_heat=None) -> Status:
        # cluster_id 0 = first contact (client hasn't learned it yet);
        # a non-zero mismatch is a daemon from another cluster (ref:
        # HBProcessor clusterId check)
        if cluster_id and cluster_id != self.cluster_id:
            return Status.error(ErrorCode.E_WRONG_CLUSTER,
                                f"wrong cluster id {cluster_id}")
        info = HostInfo(host, time.time(), role)
        st = self._put((mk.host_key(host), info.to_json()))
        if ws_port is not None and int(ws_port) >= 0:
            # heartbeat-carried HTTP admin port: the /cluster_metrics
            # federation's scrape-target registry (in-memory like the
            # leader view — it refreshes within one heartbeat after a
            # metad restart; HostInfo itself is wire-frozen)
            self.note_web_port(host, int(ws_port), role)
        if leader_parts is not None:
            # heartbeat-carried raft leadership ({space_id: [part...]}),
            # the ActiveHostsMan leader view (ref meta/ActiveHostsMan.h
            # leader_parts_): in-memory — it refreshes within one
            # heartbeat after a metad restart
            self._leader_view[host] = {
                int(s): sorted(int(p) for p in ps)
                for s, ps in dict(leader_parts).items()}
        if part_heat is not None:
            # heartbeat-carried per-part heat + staleness (additive
            # field, the leader_parts idiom): normalized to int keys,
            # stamped so stale views age out with the host's liveness
            try:
                self._heat_view[host] = {
                    "ts": time.time(),
                    "parts": {int(s): {int(p): dict(f)
                                       for p, f in ps.items()}
                              for s, ps in dict(
                                  part_heat.get("parts") or {}).items()},
                    "staleness": {int(s): {int(p): dict(f)
                                           for p, f in ps.items()}
                                  for s, ps in dict(
                                      part_heat.get("staleness")
                                      or {}).items()},
                }
            except (TypeError, ValueError, AttributeError):
                pass   # malformed telemetry must never fail a beat
        elif role == "storage":
            # a storage beat WITHOUT heat means the node's observatory
            # is disarmed (heat_source returns None) — drop its view
            # so SHOW HOSTS/PARTS and the advisor don't serve frozen
            # telemetry forever (the disarm kill-switch contract)
            self._heat_view.pop(host, None)
        if st.ok() and role == "storage":
            new_host = host not in self._hosts_seen
            self._hosts_seen.add(host)
            if new_host or self._needs_reconcile:
                self._reconcile_replicas(host)
        return st

    def _reconcile_replicas(self, host: str) -> None:
        """Validate part allocation against the live host set when a
        storage host is first seen (or while a space is known
        under-replicated): a part allocated below its space's
        replica_factor (hosts were missing at CREATE SPACE, or the
        placeholder 'local' allocation predates any registration) is
        topped up with the heartbeating host. The raft side follows
        through the topology watch: the new host materializes the part
        (as a learner when it joins an existing group) and the current
        leader adds it as a peer (daemons/storaged.py). Only ADDITIONS
        happen here — evacuating dead hosts stays the balancer's job."""
        still_short = False
        for desc in self.list_spaces():
            alloc = self.get_parts_alloc(desc.space_id)
            changed = False
            for part, hosts in alloc.items():
                cur = [h for h in hosts if h != "local"]
                if host not in cur and len(cur) < desc.replica_factor:
                    cur = cur + [host]
                    self.update_part_alloc(desc.space_id, part, cur)
                    changed = True
                if len(cur) < desc.replica_factor:
                    still_short = True   # needs yet another host
            if changed:
                self.catalog_version += 1
                self._notify("parts_realloc", space_id=desc.space_id)
        # while any space stays under-replicated, keep sweeping on
        # every beat (another ALREADY-KNOWN host may re-enter the
        # liveness horizon and fill the gap); in the steady state the
        # flag is False and heartbeats stay O(1)
        self._needs_reconcile = still_short

    def note_web_port(self, host: str, ws_port: int,
                      role: str = "storage") -> None:
        """Record a daemon's HTTP admin port (heartbeat-carried for
        storaged; metad registers its own at boot). `host` is the
        daemon's RPC address — the scrape target is its hostname +
        ws_port."""
        self._web_ports[host] = (int(ws_port), role)

    def web_endpoints(self) -> List[Dict[str, Any]]:
        """Every registered daemon /metrics target for the cluster
        rollup: [{"host": rpc_addr, "role", "web": "host:ws_port",
        "alive": bool}]. graphd adds itself locally (it registers with
        heartbeat=False). A host whose heartbeat has EXPIRED past the
        liveness horizon is PRUNED from the registry — a crashed
        daemon scrapes as nebula_cluster_scrape 0 until the horizon,
        then stops haunting every scrape (a killed-and-replaced
        storaged must not add a fetch timeout to /cluster_metrics
        forever). metad's self-registration has no heartbeat and is
        never pruned."""
        now = time.time()
        alive_by_host = {}
        for _, v in self._scan(mk.P_HOST):
            info = HostInfo.from_json(v)
            alive_by_host[info.host] = \
                now - info.last_hb < self._expired_threshold
        out = []
        for host, (port, role) in sorted(self._web_ports.items()):
            alive = alive_by_host.get(host, role == "meta")
            if not alive:
                self._web_ports.pop(host, None)
                continue
            hostname = host.rsplit(":", 1)[0]
            out.append({"host": host, "role": role,
                        "web": f"{hostname}:{port}",
                        "alive": alive})
        return out

    def active_hosts(self, role: str = "storage") -> List[HostInfo]:
        now = time.time()
        out = []
        for _, v in self._scan(mk.P_HOST):
            info = HostInfo.from_json(v)
            if info.role == role and now - info.last_hb < self._expired_threshold:
                out.append(info)
        return out

    def all_hosts(self) -> List[Tuple[HostInfo, bool]]:
        now = time.time()
        out = []
        for _, v in self._scan(mk.P_HOST):
            info = HostInfo.from_json(v)
            out.append((info, now - info.last_hb < self._expired_threshold))
        return out

    # ------------------------------------------------------------------
    # cluster overview (SHOW HOSTS / SHOW PARTS data; ref: the
    # ListHostsProcessor joining ActiveHostsMan liveness, the leader
    # view and the part allocation into one table)
    # ------------------------------------------------------------------
    def hosts_overview(self) -> List[Dict[str, Any]]:
        """Per-host liveness + leader/partition distribution rows +
        the heartbeat-carried leader-heat rollup (600s score summed
        over the parts this host leads; workload observatory)."""
        spaces = self.list_spaces()
        name_of = {d.space_id: d.name for d in spaces}
        allocs = {d.space_id: self.get_parts_alloc(d.space_id)
                  for d in spaces}
        out = []
        for info, alive in self.all_hosts():
            if info.role != "storage":
                continue
            led = self._leader_view.get(info.host, {}) if alive else {}
            leader_dist = {name_of[s]: len(ps) for s, ps in led.items()
                           if s in name_of and ps}
            part_dist = {}
            for sid, alloc in allocs.items():
                n = sum(1 for hosts in alloc.values()
                        if info.host in hosts)
                if n:
                    part_dist[name_of[sid]] = n
            hv = self._heat_view.get(info.host) if alive else None
            leader_heat = 0.0
            if hv:
                for sid, parts in hv.get("parts", {}).items():
                    for pid, f in parts.items():
                        leader_heat += float(f.get("score", 0.0))
            out.append({"host": info.host,
                        "status": "online" if alive else "offline",
                        "leader_count": sum(leader_dist.values()),
                        "leader_dist": leader_dist,
                        "part_dist": part_dist,
                        "leader_heat": round(leader_heat, 1)})
        return out

    def heat_overview(self) -> Dict[str, Any]:
        """The heartbeat-carried heat view, advisor-shaped:
        {"hosts": {host: {"parts": {(sid, pid) serialized as
        "sid:pid": score}, "total": float}}, "staleness": [{space,
        part, host, max_ms}]} — consumed by the heat-aware BALANCE
        advisor (meta/balancer.py) and metad's /balance?heat=1."""
        alive = {h.host for h in self.active_hosts()}
        hosts: Dict[str, Any] = {}
        staleness: List[Dict[str, Any]] = []
        for host, hv in self._heat_view.items():
            if host not in alive:
                continue
            parts = {}
            total = 0.0
            for sid, ps in hv.get("parts", {}).items():
                for pid, f in ps.items():
                    s = float(f.get("score", 0.0))
                    parts[f"{sid}:{pid}"] = s
                    total += s
            hosts[host] = {"parts": parts, "total": round(total, 1),
                           "ts": hv.get("ts")}
            for sid, ps in hv.get("staleness", {}).items():
                for pid, f in ps.items():
                    staleness.append({"space": sid, "part": pid,
                                      "host": host,
                                      "max_ms": f.get("max_ms", 0.0)})
        return {"hosts": hosts, "staleness": staleness}

    def parts_overview(self, space_id: int) -> List[List]:
        """[part, leader, peers, losts, heat, staleness_ms] per part:
        leader from the heartbeat-carried view (validated against the
        allocation), losts = allocated hosts outside the liveness
        horizon, heat = the leader's 600s heat score for the part and
        staleness_ms = the max replica staleness watermark (both from
        the heartbeat heat payload; 0 when the leader doesn't carry
        heat — disarmed or unreplicated without telemetry)."""
        alive = {h.host for h in self.active_hosts()}
        leader_of: Dict[int, str] = {}
        for host, by_space in self._leader_view.items():
            if host not in alive:
                continue
            for p in by_space.get(space_id, []):
                leader_of[p] = host
        rows = []
        for part, hosts in sorted(self.get_parts_alloc(space_id).items()):
            leader = leader_of.get(part, "")
            if leader and leader not in hosts:
                leader = ""          # stale heartbeat from a moved part
            losts = [h for h in hosts if h != "local" and h not in alive]
            heat_score = 0.0
            stale_ms = 0.0
            hv = self._heat_view.get(leader) if leader else None
            if hv:
                f = (hv.get("parts", {}).get(space_id) or {}).get(part)
                if f:
                    heat_score = float(f.get("score", 0.0))
                sf = (hv.get("staleness", {}).get(space_id)
                      or {}).get(part)
                if sf:
                    stale_ms = float(sf.get("max_ms", 0.0))
            rows.append([part, leader, list(hosts), losts,
                         round(heat_score, 1), round(stale_ms, 1)])
        return rows

    # ------------------------------------------------------------------
    # balancer facade (ref: BalanceProcessor — BALANCE statements reach
    # the meta-hosted Balancer through the meta RPC surface)
    # ------------------------------------------------------------------
    def attach_balancer(self, balancer) -> None:
        self._balancer = balancer

    def _bal(self):
        return getattr(self, "_balancer", None)

    def balance_data(self, remove_hosts: List[str] = ()) -> StatusOr[int]:
        b = self._bal()
        if b is None:
            return StatusOr.err(ErrorCode.E_UNSUPPORTED,
                                "balancer not available")
        ready = getattr(b.admin, "ready", None)
        if ready is not None:
            st = ready()
            if not st.ok():
                return StatusOr.from_status(st)
        return b.balance(remove_hosts=tuple(remove_hosts))

    def balance_advise_heat(self) -> StatusOr[Dict]:
        """Heat-aware BALANCE advisor (BALANCE DATA heat /
        /balance?heat=1): the current vs post-plan MODELED per-host
        heat spread — advisory only, nothing moves
        (docs/manual/12-replication.md, "Heat-aware BALANCE
        advisor")."""
        b = self._bal()
        if b is None:
            return StatusOr.err(ErrorCode.E_UNSUPPORTED,
                                "balancer not available")
        return StatusOr.of(b.advise_heat())

    def balance_leader(self) -> Status:
        b = self._bal()
        if b is None:
            return Status.error(ErrorCode.E_UNSUPPORTED,
                                "balancer not available")
        return b.leader_balance()

    def balance_show(self, plan_id: Optional[int] = None) -> List[List]:
        b = self._bal()
        return [] if b is None else b.show_plan(plan_id)

    def balance_progress(self) -> Dict[str, Any]:
        """Latest plan's task-FSM progress (observability surface:
        graphd /tpu_stats cluster block + metad /metrics)."""
        b = self._bal()
        if b is None:
            return {"plan": 0, "running": False, "tasks": {}}
        return b.progress()

    def balance_stop(self) -> Status:
        b = self._bal()
        if b is None:
            return Status.error(ErrorCode.E_UNSUPPORTED,
                                "balancer not available")
        return b.stop()


def _pw_hash(password: str) -> str:
    import hashlib
    return hashlib.sha256(("nebula_tpu$" + password).encode()).hexdigest()
