"""ctypes bindings for the native C++ runtime library.

The native layer holds the components the reference implements in C++
below the Python-visible seams: the segmented WAL (ref
kvstore/wal/FileBasedWal.{h,cpp}) and, as it grows, the KV engine and
codec hot paths. The library is built on demand from `native/` with the
system toolchain and cached; call `load()` to get the bound CDLL or
raise if the toolchain is unavailable.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO, "native")
# NEBULA_NATIVE_LIB points tests at an alternate build — e.g. the
# asan/ubsan .so (make -C native asan + LD_PRELOAD libasan), the role
# of the reference's whole-suite sanitizer builds (CMakeLists:31-33)
_LIB_PATH = os.environ.get(
    "NEBULA_NATIVE_LIB",
    os.path.join(_NATIVE_DIR, "build", "libnebula_native.so"))

_lock = threading.Lock()
_lib = None


class NativeBuildError(RuntimeError):
    pass


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for sub in ("src", "include"):
        d = os.path.join(_NATIVE_DIR, sub)
        for name in os.listdir(d):
            if os.path.getmtime(os.path.join(d, name)) > lib_mtime:
                return True
    return False


def _build() -> None:
    proc = subprocess.run(
        ["make", "-C", _NATIVE_DIR, "-j4"],
        capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed:\n{proc.stdout}\n{proc.stderr}")


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, i32, u8p = ctypes.c_int64, ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8)
    vp = ctypes.c_void_p

    lib.nwal_open.restype = vp
    lib.nwal_open.argtypes = [ctypes.c_char_p, i64, i64, i32]
    lib.nwal_close.restype = None
    lib.nwal_close.argtypes = [vp]
    for fn in ("nwal_first_log_id", "nwal_last_log_id", "nwal_last_log_term"):
        getattr(lib, fn).restype = i64
        getattr(lib, fn).argtypes = [vp]
    lib.nwal_log_term.restype = i64
    lib.nwal_log_term.argtypes = [vp, i64]
    lib.nwal_append.restype = i32
    lib.nwal_append.argtypes = [vp, i64, i64, i64, ctypes.c_char_p, i64]
    lib.nwal_rollback.restype = i32
    lib.nwal_rollback.argtypes = [vp, i64]
    lib.nwal_reset.restype = i32
    lib.nwal_reset.argtypes = [vp]
    lib.nwal_clean_ttl.restype = i32
    lib.nwal_clean_ttl.argtypes = [vp]
    lib.nwal_clean_ttl_before.restype = i32
    lib.nwal_clean_ttl_before.argtypes = [vp, i64]
    lib.nwal_clean_before.restype = i32
    lib.nwal_clean_before.argtypes = [vp, i64]
    lib.nwal_sync.restype = i32
    lib.nwal_sync.argtypes = [vp]

    lib.nwal_iter_new.restype = vp
    lib.nwal_iter_new.argtypes = [vp, i64, i64]
    lib.nwal_iter_valid.restype = i32
    lib.nwal_iter_valid.argtypes = [vp]
    for fn in ("nwal_iter_log_id", "nwal_iter_term", "nwal_iter_cluster"):
        getattr(lib, fn).restype = i64
        getattr(lib, fn).argtypes = [vp]
    lib.nwal_iter_data.restype = i64
    lib.nwal_iter_data.argtypes = [vp, ctypes.POINTER(u8p)]
    lib.nwal_iter_next.restype = None
    lib.nwal_iter_next.argtypes = [vp]
    lib.nwal_iter_free.restype = None
    lib.nwal_iter_free.argtypes = [vp]

    # ------------------------------------------------------------ KV
    lib.nkv_open.restype = vp
    lib.nkv_open.argtypes = [ctypes.c_char_p]
    lib.nkv_close.restype = None
    lib.nkv_close.argtypes = [vp]
    for fn in ("nkv_count", "nkv_version", "nkv_approx_size"):
        getattr(lib, fn).restype = i64
        getattr(lib, fn).argtypes = [vp]
    lib.nkv_run_count.restype = i32
    lib.nkv_run_count.argtypes = [vp]
    lib.nkv_set_option.restype = i32
    lib.nkv_set_option.argtypes = [vp, ctypes.c_char_p, i64]
    lib.nkv_get_option.restype = i64
    lib.nkv_get_option.argtypes = [vp, ctypes.c_char_p]
    lib.nkv_put.restype = i32
    lib.nkv_put.argtypes = [vp, ctypes.c_char_p, i64, ctypes.c_char_p, i64]
    lib.nkv_get.restype = i64
    lib.nkv_get.argtypes = [vp, ctypes.c_char_p, i64, ctypes.POINTER(u8p)]
    lib.nkv_remove.restype = i32
    lib.nkv_remove.argtypes = [vp, ctypes.c_char_p, i64]
    lib.nkv_remove_range.restype = i32
    lib.nkv_remove_range.argtypes = [vp, ctypes.c_char_p, i64,
                                     ctypes.c_char_p, i64]
    lib.nkv_remove_prefix.restype = i32
    lib.nkv_remove_prefix.argtypes = [vp, ctypes.c_char_p, i64]
    lib.nkv_multi_put.restype = i32
    lib.nkv_multi_put.argtypes = [vp, ctypes.c_char_p, i64, i32]
    lib.nkv_ingest_sorted.restype = i64
    lib.nkv_ingest_sorted.argtypes = [vp, ctypes.c_char_p, i64, i64]
    lib.nkv_multi_remove.restype = i32
    lib.nkv_multi_remove.argtypes = [vp, ctypes.c_char_p, i64, i32]
    lib.nkv_scan_prefix.restype = i64
    lib.nkv_scan_prefix.argtypes = [vp, ctypes.c_char_p, i64,
                                    ctypes.POINTER(u8p), ctypes.POINTER(i64)]
    lib.nkv_scan_range.restype = i64
    lib.nkv_scan_range.argtypes = [vp, ctypes.c_char_p, i64,
                                   ctypes.c_char_p, i64,
                                   ctypes.POINTER(u8p), ctypes.POINTER(i64)]
    lib.nkv_scan_prefix_dedup.restype = i64
    lib.nkv_scan_prefix_dedup.argtypes = [vp, ctypes.c_char_p, i64, i32,
                                          ctypes.POINTER(u8p),
                                          ctypes.POINTER(i64)]
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.nkv_scan_prefix_cols.restype = i64
    lib.nkv_scan_prefix_cols.argtypes = [vp, ctypes.c_char_p, i64,
                                         ctypes.POINTER(u8p),
                                         ctypes.POINTER(i64),
                                         ctypes.POINTER(u8p),
                                         ctypes.POINTER(i64),
                                         ctypes.POINTER(u32p),
                                         ctypes.POINTER(u32p)]
    lib.nkv_multi_get.restype = i64
    lib.nkv_multi_get.argtypes = [vp, ctypes.c_char_p, i64, i32,
                                  ctypes.POINTER(u8p),
                                  ctypes.POINTER(i64)]
    lib.nkv_buf_free.restype = None
    lib.nkv_buf_free.argtypes = [u8p]
    lib.nkv_checkpoint.restype = i32
    lib.nkv_checkpoint.argtypes = [vp, ctypes.c_char_p]

    # ----------------------------------------------------------- CSR
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.ncsr_build.restype = vp
    lib.ncsr_build.argtypes = [vp, i32, i32]
    lib.ncsr_free.restype = None
    lib.ncsr_free.argtypes = [vp]
    lib.ncsr_vids.restype = i64
    lib.ncsr_vids.argtypes = [vp, i32, ctypes.POINTER(i64p)]
    lib.ncsr_edges.restype = i64
    lib.ncsr_edges.argtypes = [vp, i32] + [ctypes.POINTER(i32p)] * 2 + \
        [ctypes.POINTER(i64p)] * 2 + [ctypes.POINTER(i32p)] * 2
    lib.ncsr_edge_vals.restype = i64
    lib.ncsr_edge_vals.argtypes = [vp, i32, ctypes.POINTER(u8p),
                                   ctypes.POINTER(i64),
                                   ctypes.POINTER(i64p),
                                   ctypes.POINTER(i32p)]
    lib.ncsr_vert_rows.restype = i64
    lib.ncsr_vert_rows.argtypes = [vp, i32, ctypes.POINTER(i32p),
                                   ctypes.POINTER(i32p)]
    lib.ncsr_vert_vals.restype = i64
    lib.ncsr_vert_vals.argtypes = [vp, i32, ctypes.POINTER(u8p),
                                   ctypes.POINTER(i64),
                                   ctypes.POINTER(i64p),
                                   ctypes.POINTER(i32p)]

    # --------------------------------------------------------- codec
    lib.nbc_decode_batch.restype = i64
    lib.nbc_decode_batch.argtypes = [
        u8p, i32,                     # field_types, n_fields
        u8p, i64,                     # rows_blob, blob_len
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(i32),  # row_off/len
        ctypes.POINTER(i32), i64, i64,                        # row_idx, n, cap
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        u8p]
    lib.nbc_encode_rows.restype = i64
    lib.nbc_encode_rows.argtypes = [
        u8p, i32,                                    # field_types, n_fields
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
        u8p,                                         # nulls
        u8p, i64,                                    # str_blob, len
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint32),
        i64, i32, i64,                               # n_rows, ver_len, ver
        u8p, i64,                                    # out, out_cap
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(i32)]  # row_off/len

    # ---------------------------------------------------------- sort
    lib.nsort_counting_u32.restype = i32
    lib.nsort_counting_u32.argtypes = [
        ctypes.POINTER(ctypes.c_uint32), i64, i64,
        ctypes.POINTER(ctypes.c_int64), i32]
    return lib


def load() -> ctypes.CDLL:
    """Build (if stale) and load the native library. Thread-safe."""
    global _lib
    with _lock:
        if _lib is None:
            if _needs_build():
                _build()
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        return _lib


def available() -> bool:
    try:
        load()
        return True
    except (NativeBuildError, OSError):
        return False


class CsrExtract:
    """Handle over a native pass-1 CSR build (ncsr_build). Accessors
    COPY into numpy arrays (the native buffers die with the handle)."""

    def __init__(self, lib, handle, num_parts: int):
        self._lib = lib
        self._h = handle
        self.num_parts = num_parts

    def close(self) -> None:
        if self._h:
            self._lib.ncsr_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @staticmethod
    def _np(ptr, n, dtype):
        import numpy as np
        if n == 0:
            return np.empty(0, dtype)
        return np.ctypeslib.as_array(ptr, shape=(int(n),)).copy()

    def vids(self, part0: int):
        p = ctypes.POINTER(ctypes.c_int64)()
        n = self._lib.ncsr_vids(self._h, part0, ctypes.byref(p))
        import numpy as np
        return self._np(p, n, np.int64)

    def edges(self, part0: int):
        import numpy as np
        i64p, i32p = ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32)
        src, et, dp, dl = i32p(), i32p(), i32p(), i32p()
        rank, dst = i64p(), i64p()
        n = self._lib.ncsr_edges(self._h, part0, ctypes.byref(src),
                                 ctypes.byref(et), ctypes.byref(rank),
                                 ctypes.byref(dst), ctypes.byref(dp),
                                 ctypes.byref(dl))
        return (self._np(src, n, np.int32), self._np(et, n, np.int32),
                self._np(rank, n, np.int64), self._np(dst, n, np.int64),
                self._np(dp, n, np.int32), self._np(dl, n, np.int32))

    def _vals(self, fn, part0: int):
        import numpy as np
        blob = ctypes.POINTER(ctypes.c_uint8)()
        blen = ctypes.c_int64()
        offs = ctypes.POINTER(ctypes.c_int64)()
        lens = ctypes.POINTER(ctypes.c_int32)()
        n = fn(self._h, part0, ctypes.byref(blob), ctypes.byref(blen),
               ctypes.byref(offs), ctypes.byref(lens))
        if n == 0:
            return None
        raw = ctypes.string_at(blob, blen.value) if blen.value else b""
        return raw, self._np(offs, n, np.int64), self._np(lens, n, np.int32)

    def edge_vals(self, part0: int):
        return self._vals(self._lib.ncsr_edge_vals, part0)

    def vert_rows(self, part0: int):
        import numpy as np
        i32p = ctypes.POINTER(ctypes.c_int32)
        local, tag = i32p(), i32p()
        n = self._lib.ncsr_vert_rows(self._h, part0, ctypes.byref(local),
                                     ctypes.byref(tag))
        return self._np(local, n, np.int32), self._np(tag, n, np.int32)

    def vert_vals(self, part0: int):
        return self._vals(self._lib.ncsr_vert_vals, part0)


def extract_csr(engine_handle, num_parts: int,
                want_values: bool) -> CsrExtract:
    """Run the native pass-1 CSR build over an nkv engine handle."""
    lib = load()
    h = lib.ncsr_build(engine_handle, num_parts, 1 if want_values else 0)
    if not h:
        raise NativeBuildError("ncsr_build failed")
    return CsrExtract(lib, h, num_parts)


def decode_rows(field_types, blob, row_off, row_len, row_idx, cap):
    """Batch-decode fixed-slot rows of one schema into columns via the
    native codec (nbc_decode_batch) — zero per-row Python.

    field_types: list of PropType int values per schema field.
    blob: concatenated encoded rows; row_off (i64) / row_len (i32) per
    row; row_idx (i32): destination slot per row. cap: column length.

    Returns (vals_i64, vals_f64, str_off, str_len, nulls, blob) — numpy
    arrays shaped [n_fields, cap] (nulls: True = null) plus the blob
    str_off/str_len point into. Raises if the native library is
    unavailable (callers fall back to the Python codec).
    """
    import numpy as np
    lib = load()
    n_fields = len(field_types)
    n = len(row_idx)
    row_off = np.ascontiguousarray(row_off, np.int64)
    row_len = np.ascontiguousarray(row_len, np.int32)
    row_idx = np.ascontiguousarray(row_idx, np.int32)
    ft = np.asarray(field_types, np.uint8)
    vals_i64 = np.zeros((n_fields, cap), np.int64)
    vals_f64 = np.zeros((n_fields, cap), np.float64)
    str_off = np.zeros((n_fields, cap), np.uint32)
    str_len = np.zeros((n_fields, cap), np.uint32)
    nulls = np.ones((n_fields, cap), np.uint8)

    c_u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = lib.nbc_decode_batch(
        ft.ctypes.data_as(c_u8p), n_fields,
        ctypes.cast(ctypes.c_char_p(blob), c_u8p), len(blob),
        row_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        row_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        row_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, cap,
        vals_i64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        vals_f64.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        str_off.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        str_len.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        nulls.ctypes.data_as(c_u8p))
    if rc < 0:
        raise NativeBuildError(f"nbc_decode_batch failed ({rc})")
    return vals_i64, vals_f64, str_off, str_len, nulls.astype(bool), blob


def _encode_sizes(field_types, nulls, str_len, n, ver_len):
    """(out_cap, fixed_bytes_per_row) for the fixed-slot row layout."""
    import numpy as np
    n_fields = len(field_types)
    slot_total = sum(1 if t == 1 else 8 for t in field_types)  # BOOL=1
    fixed = 1 + ver_len + (n_fields + 7) // 8 + slot_total
    var = 0
    if str_len is not None:
        live = np.where(nulls, 0, str_len.astype(np.int64))
        for f, t in enumerate(field_types):
            if t == 6:                                         # STRING
                var += int(live[f].sum())
    return n * fixed + var, fixed


def _min_ver_bytes(version: int) -> int:
    ver_len = 0
    while version > 0:
        version >>= 8
        ver_len += 1
    return ver_len


def encode_rows(field_types, vals_i64, vals_f64, nulls, str_blob=b"",
                str_off=None, str_len=None, schema_version: int = 0):
    """Batch-encode column-major values into the fixed-slot row layout
    via the native codec (nbc_encode_rows) — the inverse of
    decode_rows, byte-identical to codec/row.py RowWriter, with the
    GIL released for the duration of the call.

    field_types: PropType int values per column. vals_i64 [n_fields,
    n] carries BOOL(0/1)/INT/VID/TIMESTAMP, vals_f64 DOUBLE, STRING
    columns reference (str_off i64, str_len u32) slices of str_blob.
    nulls [n_fields, n]: truthy = null cell.

    Returns (blob bytes, row_off int64[n], row_len int32[n]). Raises
    if the native library is unavailable (callers fall back to
    encode_rows_py, which produces identical bytes — the same
    degradation the "encode.rows" fault point exercises)."""
    import numpy as np
    from .common.faults import faults
    faults.fire("encode.rows")
    lib = load()
    ft = np.ascontiguousarray(field_types, np.uint8)
    n_fields = len(ft)
    vals_i64 = np.ascontiguousarray(vals_i64, np.int64)
    vals_f64 = np.ascontiguousarray(vals_f64, np.float64)
    nulls_u8 = np.ascontiguousarray(
        np.asarray(nulls, bool).astype(np.uint8))
    n = vals_i64.shape[1] if vals_i64.ndim == 2 else 0
    ver_len = _min_ver_bytes(schema_version)
    if str_off is None:
        str_off = np.zeros((n_fields, n), np.int64)
        str_len = np.zeros((n_fields, n), np.uint32)
    str_off = np.ascontiguousarray(str_off, np.int64)
    str_len = np.ascontiguousarray(str_len, np.uint32)
    out_cap, _ = _encode_sizes(ft, nulls_u8, str_len, n, ver_len)
    out = np.empty(max(out_cap, 1), np.uint8)
    row_off = np.empty(max(n, 1), np.int64)
    row_len = np.empty(max(n, 1), np.int32)
    c_u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = lib.nbc_encode_rows(
        ft.ctypes.data_as(c_u8p), n_fields,
        vals_i64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        vals_f64.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        nulls_u8.ctypes.data_as(c_u8p),
        ctypes.cast(ctypes.c_char_p(bytes(str_blob)), c_u8p),
        len(str_blob),
        str_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        str_len.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        n, ver_len, schema_version,
        out.ctypes.data_as(c_u8p), out_cap,
        row_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        row_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc < 0:
        raise NativeBuildError(f"nbc_encode_rows failed ({rc})")
    return out[:rc].tobytes(), row_off[:n], row_len[:n]


def encode_rows_py(field_types, vals_i64, vals_f64, nulls, str_blob=b"",
                   str_off=None, str_len=None, schema_version: int = 0):
    """Pure-Python twin of encode_rows: same signature, byte-identical
    output (the fallback when the native toolchain is unavailable —
    and the identity oracle encode tests compare against)."""
    import struct
    import numpy as np
    ft = list(int(t) for t in field_types)
    n_fields = len(ft)
    vals_i64 = np.asarray(vals_i64, np.int64)
    vals_f64 = np.asarray(vals_f64, np.float64)
    nulls = np.asarray(nulls, bool)
    n = vals_i64.shape[1] if vals_i64.ndim == 2 else 0
    ver_len = _min_ver_bytes(schema_version)
    hdr = bytes([ver_len]) + schema_version.to_bytes(ver_len, "little")
    null_bytes = (n_fields + 7) // 8
    out = bytearray()
    row_off = np.empty(max(n, 1), np.int64)
    row_len = np.empty(max(n, 1), np.int32)
    blob = bytes(str_blob)
    for r in range(n):
        nullmap = bytearray(null_bytes)
        slots = bytearray()
        var = bytearray()
        for f, t in enumerate(ft):
            if nulls[f, r]:
                nullmap[f >> 3] |= 1 << (f & 7)
                slots += b"\0" * (1 if t == 1 else 8)
                continue
            if t == 1:                                         # BOOL
                slots.append(1 if vals_i64[f, r] else 0)
            elif t == 5:                                       # DOUBLE
                slots += struct.pack("<d", float(vals_f64[f, r]))
            elif t == 6:                                       # STRING
                so, sl = int(str_off[f, r]), int(str_len[f, r])
                slots += struct.pack("<II", len(var), sl)
                var += blob[so:so + sl]
            else:                              # INT/VID/TIMESTAMP
                slots += struct.pack("<q", int(vals_i64[f, r]))
        row = hdr + bytes(nullmap) + bytes(slots) + bytes(var)
        row_off[r] = len(out)
        row_len[r] = len(row)
        out += row
    return bytes(out), row_off[:n], row_len[:n]


def usable_cpus() -> int:
    """CPUs this process may actually run on (affinity/cgroup limit,
    not the host count — containers often pin far below cpu_count)."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def stable_counting_sort(keys, n_keys: int, threads: int = 0):
    """Stable argsort of small-range non-negative int keys via the
    native parallel counting sort — O(E) vs numpy's comparison sort
    (the device kernel layouts sort ~10^8 edges by destination slot
    with a key range of only ~10^6). Returns int64 order such that
    keys[order] is non-decreasing with ties in input order.
    Falls back to None when the native library is unavailable."""
    import numpy as np
    if not available():
        return None
    keys = np.asarray(keys)
    if n_keys > (1 << 24):
        # the native sort allocates threads * n_keys * 8B of
        # histograms (16 threads at 2^24 keys = 2 GiB; unbounded, a
        # 2^32 range would ask for ~512 GiB and die in malloc rather
        # than falling back). Past this range the counting strategy
        # loses to a comparison sort anyway — numpy fallback.
        return None
    if keys.dtype.itemsize > 4 and len(keys) and (
            int(keys.max()) >= (1 << 32) or int(keys.min()) < 0):
        # values beyond uint32 would WRAP in the cast below and dodge
        # the native range check -> silently wrong permutation; make
        # the caller raise/fall back instead (one cheap O(E) pass)
        raise ValueError("stable_counting_sort: key out of uint32 range")
    lib = load()
    k = np.ascontiguousarray(keys, np.uint32)
    n = len(k)
    order = np.empty(n, np.int64)
    if threads <= 0:
        threads = min(usable_cpus(), 16)
    rc = lib.nsort_counting_u32(
        k.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), n, n_keys,
        order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), threads)
    if rc != 0:
        raise ValueError("nsort_counting_u32: key out of range")
    return order
