from .parser import GQLParser, ParseError  # noqa: F401
from . import ast  # noqa: F401
